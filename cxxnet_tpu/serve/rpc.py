"""Length-prefixed binary RPC for the serving fleet (serve/fleet.py).

One small, dependency-free wire protocol carries every fleet verb —
submit / result / adopt / drain / health / metrics — plus the KV
migration payloads (the crc32-checksummed engine swap records, moved
verbatim: the checksum that guards host-RAM preemption round trips
guards the socket round trip for free).

Frame layout (all integers network byte order)::

    +------+---------+------+-------+----------+---------------+
    | CXRP | version | kind | seq   | length   | payload bytes |
    | 4 B  | 1 B     | 1 B  | 4 B   | 8 B      | `length` B    |
    +------+---------+------+-------+----------+---------------+

``kind`` is REQUEST (0) / REPLY (1) / ERROR (2); ``seq`` matches a
reply to its request so one connection multiplexes concurrent calls
(the server dispatches every request on its own handler thread — a
blocking ``result`` verb never serializes the connection). Payloads
are pickled dicts: the fleet runs the SAME code tree on both ends of
every socket (the router spawns its own workers), which is the one
situation pickle's schema-free numpy transport is the right tool —
this port must never be exposed beyond the fleet's loopback/rack.

Malformed frames get a TYPED death, never a hang: bad magic, an
unsupported version, an oversized length, or a mid-frame EOF raise
:class:`FrameError` (the server best-effort replies with an ERROR
frame, then closes that connection — the worker itself survives).
A handler exception crosses back as :class:`RpcError` carrying the
remote type name; a dead peer — heartbeat timeout or connection loss —
fails every pending and future call with :class:`WorkerLostError`, the
signal the fleet router's journal replay triggers on.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading

from ..analysis.concurrency import make_lock
from typing import Callable, Dict, Optional

__all__ = ["FrameError", "RpcError", "WorkerLostError", "RpcServer",
           "RpcClient", "MAGIC", "VERSION", "MAX_FRAME"]

MAGIC = b"CXRP"
VERSION = 1
KIND_REQUEST = 0
KIND_REPLY = 1
KIND_ERROR = 2
_HEADER = struct.Struct("!4sBBIQ")      # magic, version, kind, seq, len
# KV swap records for a long row run to a few MB; 1 GiB is far above
# any real frame while still rejecting a garbage length field instantly
MAX_FRAME = 1 << 30


class FrameError(RuntimeError):
    """A malformed wire frame (bad magic / bad version / oversized /
    truncated); ``reason`` is the short machine-readable kind."""

    def __init__(self, msg: str, reason: str = ""):
        super().__init__(msg)
        self.reason = reason


class RpcError(RuntimeError):
    """The remote handler raised; ``remote_type`` is the exception's
    type name and ``payload`` the full error record (back-off hints
    and tenancy fields included), so the caller can re-raise typed."""

    def __init__(self, msg: str, remote_type: str = "",
                 payload: Optional[dict] = None):
        super().__init__(msg)
        self.remote_type = remote_type
        self.payload = payload or {}


class WorkerLostError(RuntimeError):
    """The peer is gone — connection closed/reset, or no heartbeat
    within the timeout. Every call pending on the connection fails
    with this, which is the fleet router's replay trigger."""


def _recv_exact(sock: socket.socket, n: int, what: str) -> bytes:
    """Read exactly ``n`` bytes; EOF raises ConnectionError when
    nothing was read yet (a clean close between frames) and FrameError
    when a frame was cut mid-flight."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            if not buf:
                raise ConnectionError("connection closed")
            raise FrameError("truncated %s: got %d of %d bytes"
                             % (what, len(buf), n), reason="truncated")
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket, max_frame: int = MAX_FRAME):
    """Read one frame -> (kind, seq, payload object). Raises
    ConnectionError on a clean close, FrameError on garbage."""
    hdr = _recv_exact(sock, _HEADER.size, "header")
    magic, ver, kind, seq, length = _HEADER.unpack(hdr)
    if magic != MAGIC:
        raise FrameError("bad frame magic %r (want %r)" % (magic, MAGIC),
                         reason="bad-magic")
    if ver != VERSION:
        raise FrameError("unsupported frame version %d (speak %d)"
                         % (ver, VERSION), reason="bad-version")
    if length > max_frame:
        raise FrameError("frame length %d exceeds the %d-byte cap"
                         % (length, max_frame), reason="oversized")
    body = _recv_exact(sock, length, "payload") if length else b""
    try:
        payload = pickle.loads(body) if body else None
    except Exception as e:
        raise FrameError("undecodable frame payload: %s" % e,
                         reason="bad-payload")
    return kind, seq, payload


def write_frame(sock: socket.socket, lock: threading.Lock, kind: int,
                seq: int, payload) -> None:
    body = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    hdr = _HEADER.pack(MAGIC, VERSION, kind, seq, len(body))
    with lock:
        sock.sendall(hdr + body)


class RpcServer:
    """Accept loop + per-connection reader threads over one handler:
    ``handler(verb, payload_dict) -> result``. Every REQUEST frame is
    dispatched on its own thread so blocking verbs (``result``,
    ``fetch_migrated``) never stall other calls multiplexed on the same
    connection; replies are serialized by a per-connection write lock.

    A FrameError on a connection answers with one best-effort ERROR
    frame (seq 0) and closes THAT connection; the listener and every
    other connection stay up — a fuzzing client cannot take a worker
    down."""

    def __init__(self, handler: Callable[[str, dict], object],
                 host: str = "127.0.0.1", port: int = 0,
                 max_frame: int = MAX_FRAME, name: str = "rpc"):
        self._handler = handler
        self._max_frame = max_frame
        self._name = name
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(64)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._closing = False           # guarded_by: self._lock
        self._conns: list = []          # guarded_by: self._lock
        self._lock = make_lock("RpcServer._lock")
        self._accept_t: Optional[threading.Thread] = None

    def start(self) -> "RpcServer":
        self._accept_t = threading.Thread(
            target=self._accept_loop,
            name="cxn-fleet-%s-accept" % self._name, daemon=True)
        self._accept_t.start()
        return self

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return                  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._closing:
                    conn.close()
                    return
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="cxn-fleet-%s-conn" % self._name,
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        wlock = make_lock("RpcServer.conn_wlock")
        try:
            while True:
                try:
                    kind, seq, payload = read_frame(conn,
                                                    self._max_frame)
                except ConnectionError:
                    return
                except FrameError as e:
                    # typed rejection, then hang up THIS connection —
                    # the frame boundary is untrustworthy now, so
                    # resynchronization is not attempted
                    try:
                        write_frame(conn, wlock, KIND_ERROR, 0,
                                    {"type": "FrameError",
                                     "msg": str(e),
                                     "reason": e.reason})
                    except OSError:
                        pass
                    return
                if kind != KIND_REQUEST or not isinstance(payload, dict):
                    try:
                        write_frame(conn, wlock, KIND_ERROR, seq,
                                    {"type": "FrameError",
                                     "msg": "expected a request frame",
                                     "reason": "bad-kind"})
                    except OSError:
                        pass
                    return
                threading.Thread(
                    target=self._dispatch, args=(conn, wlock, seq,
                                                 payload),
                    name="cxn-fleet-%s-h" % self._name,
                    daemon=True).start()
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, wlock, seq: int, payload: dict) -> None:
        verb = payload.pop("verb", "")
        try:
            result = self._handler(verb, payload)
            frame = (KIND_REPLY, {"ok": result})
        except Exception as e:         # crosses back typed, not fatal
            err = {"type": type(e).__name__, "msg": str(e)}
            for attr in ("retry_after_ms", "tenant", "kind", "reason"):
                v = getattr(e, attr, None)
                if v is not None and not isinstance(v, type):
                    err[attr] = v
            frame = (KIND_ERROR, err)
        try:
            write_frame(conn, wlock, frame[0], seq, frame[1])
        except OSError:
            pass                        # caller hung up; nothing to do

    def close(self) -> None:
        with self._lock:
            if self._closing:
                return
            self._closing = True
            conns, self._conns = self._conns, []
        # a thread parked in accept() does not reliably wake when the
        # listener fd closes under it — nudge it with a self-connect
        try:
            socket.create_connection((self.host, self.port),
                                     timeout=1).close()
        except OSError:
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        for c in conns:
            # shutdown BEFORE close: close() alone neither wakes this
            # process's blocked readers nor (until they exit recv)
            # sends the FIN a peer's waiters are released by
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._accept_t is not None:
            self._accept_t.join(timeout=5)


class RpcClient:
    """One connection to a worker, shared by any number of caller
    threads: calls are seq-matched by a reader thread, writes serialize
    on a lock. ``call`` raises the typed remote error (re-raised by the
    fleet layer), TimeoutError past ``timeout``, and WorkerLostError
    the moment the connection dies — which also fails every call still
    pending, so a SIGKILL'd worker releases its waiters immediately
    instead of leaking them into their timeouts."""

    def __init__(self, host: str, port: int, connect_timeout: float = 30.0,
                 max_frame: int = MAX_FRAME, name: str = "rpc"):
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = make_lock("RpcClient._wlock")
        self._lock = make_lock("RpcClient._lock")
        self._seq = 0                   # guarded_by: self._lock
        self._pending: Dict[int, dict] = {}  # guarded_by: self._lock
        # why the connection died (read lockless on the fast path —
        # a stale None only costs one extra write_frame OSError)
        self._lost: Optional[str] = None  # guarded_by: self._lock
        self._reader = threading.Thread(
            target=self._read_loop, name="cxn-fleet-%s-reader" % name,
            daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                kind, seq, payload = read_frame(self._sock)
                with self._lock:
                    slot = self._pending.pop(seq, None)
                if slot is None:
                    continue            # caller timed out and left
                slot["kind"] = kind
                slot["payload"] = payload
                slot["event"].set()
        except (ConnectionError, FrameError, OSError) as e:
            self._fail_all("worker connection lost: %s" % e)

    def _fail_all(self, why: str) -> None:
        with self._lock:
            if self._lost is None:
                self._lost = why
            pending, self._pending = self._pending, {}
        for slot in pending.values():
            slot["kind"] = None
            slot["event"].set()

    @property
    def lost(self) -> Optional[str]:
        return self._lost

    def call(self, verb: str, timeout: Optional[float] = None,
             **payload):
        if self._lost is not None:
            raise WorkerLostError(self._lost)
        slot = {"event": threading.Event(), "kind": None,
                "payload": None}
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._pending[seq] = slot
        payload["verb"] = verb
        try:
            write_frame(self._sock, self._wlock, KIND_REQUEST, seq,
                        payload)
        except OSError as e:
            with self._lock:
                self._pending.pop(seq, None)
            self._fail_all("worker connection lost: %s" % e)
            raise WorkerLostError(self._lost)
        if not slot["event"].wait(timeout):
            with self._lock:
                self._pending.pop(seq, None)
            raise TimeoutError("rpc %r: no reply within %.1fs"
                               % (verb, timeout))
        if slot["kind"] is None:
            raise WorkerLostError(self._lost or "worker connection lost")
        if slot["kind"] == KIND_ERROR:
            err = slot["payload"] or {}
            raise RpcError("%s: %s" % (err.get("type", "RemoteError"),
                                       err.get("msg", "")),
                           remote_type=err.get("type", ""),
                           payload=err)
        return (slot["payload"] or {}).get("ok")

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._fail_all("client closed")
