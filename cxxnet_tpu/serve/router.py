"""Replicated serving: N data-parallel engine replicas behind one
prefix- and health-aware front door (doc/serving.md "Sharded &
replicated serving").

One :class:`~cxxnet_tpu.serve.server.InferenceServer` is one engine —
one scheduler thread, one KV pool, one prefix trie, one failure domain.
The :class:`ServeRouter` runs ``replicas`` of them over the SAME
``(cfg, params)`` export (the TensorFlow paper's replicated-dataflow
regime, arxiv 1605.08695; each replica may itself be TP-sharded over a
mesh — ``tp`` in ``server_kw`` composes) and keeps the single-server
submit/result surface:

* **routing** weighs prefix-cache AFFINITY against load: the router
  keeps a chunk-granular fingerprint trie of the prompts it sent to
  each replica (crc32 of each chunk-aligned prefix — a hash hit can
  only misroute, never corrupt, so fingerprints beat storing tokens),
  and scores candidates by longest-prefix match first, then by the
  health-derived load signal (``health()``: degradation rung +
  admission-queue fraction — exactly the gauges ``cxn_serve_state`` /
  ``cxn_serve_degrade_rung`` export). Same-prefix traffic converges on
  the replica whose KV trie already holds the prefix (the zero-copy hit
  serves from shared blocks), while an overloaded or degraded replica
  sheds new traffic to its peers. ``policy="rr"`` replaces the scoring
  with plain round-robin (the A/B baseline).

* **failover** reuses PR 9's replay machinery verbatim: every live
  request is tracked in a :class:`~cxxnet_tpu.serve.resilience
  .ReplayJournal`; when a replica goes FAILED (restart budget
  exhausted), each of its in-flight requests is rewound with
  :func:`~cxxnet_tpu.serve.resilience.reset_for_replay` — the greedy
  token prefix it already emitted becomes the ``replay_expect`` pin —
  and re-admitted on a healthy replica via
  :meth:`~cxxnet_tpu.serve.server.InferenceServer.adopt`. The
  deterministic per-request ``fold_in`` key schedule makes the
  regenerated stream bit-identical (greedy; sampled resumes on the
  pinned schedule), and the survivor's ``_emit`` verifies the pin token
  by token — a divergent replay fails typed, never silently. The
  caller's handle never changes: :meth:`result` chases the migration.

* **drain** is the same path run deliberately: :meth:`drain_replica`
  stops routing to a replica, abort-stops it, and migrates its live
  requests to the survivors — live-request migration as a maintenance
  verb, not just a failure response.

* **observability**: :meth:`metrics_text` is ONE scrape payload —
  every per-replica ``cxn_serve_*`` series gains a ``replica=`` label
  (names unchanged), and the latency histograms additionally emit an
  aggregate series merged with ``Histogram.merge`` (fixed log-spaced
  buckets, so the merged payload equals the union of per-replica
  observations — the property obs/metrics.py was built for, pinned in
  tests/test_obs.py).

Thread-safety: the router's own state (tries, journal, handle map,
routing counters) is lock-guarded; each replica keeps its own internal
discipline. ``submit``/``result`` may be called from any thread, like
the single server's.
"""

from __future__ import annotations

import collections
import itertools
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..analysis.concurrency import make_rlock
from ..obs import metrics as obs_metrics
from .resilience import (STATE_DRAINING, STATE_FAILED, EngineFailedError,
                         ReplayJournal, reset_for_replay)
from .scheduler import Request, SamplingParams
from .server import (AdmissionError, InferenceServer, QueueFullError,
                     QuotaExceededError)

__all__ = ["ServeRouter", "RouterHandle", "rewind_request"]


def rewind_request(req: Request) -> Request:
    """A fresh Request carrying everything a bit-exact replay needs
    (serve/resilience.py): prompt, params (seed included), tenant
    label, LoRA adapter name, and the emitted-token prefix as the
    ``replay_expect`` pin. Shared by the in-process router's
    failover/drain migration and the cross-process fleet's worker-loss
    replay (serve/fleet.py) — one rewind contract, not two."""
    new = Request(req.rid, req.prompt, req.params, req.submit_t,
                  tenant=req.tenant, adapter=req.adapter)
    new.tokens = list(req.tokens)
    new.replay_expect = req.replay_expect
    reset_for_replay(new)
    return new


class RouterHandle:
    """The router's request handle: stable across migrations. ``req``
    points at the CURRENT replica-owned Request (re-pointed by a
    failover/drain migration); ``rid`` is the process-unique request
    id, shared by every incarnation."""

    __slots__ = ("prompt", "params", "req", "replica", "migrations")

    def __init__(self, req: Request, replica: int):
        self.prompt = req.prompt
        self.params = req.params
        self.req = req
        self.replica = replica
        self.migrations = 0

    @property
    def rid(self) -> int:
        return self.req.rid


class _AffinityTrie:
    """Chunk-granular prompt-prefix fingerprints for ONE replica: crc32
    of every chunk-aligned prefix of every prompt routed there, LRU-
    bounded. ``match`` returns the longest chunk-aligned prefix (in
    tokens) this replica has seen — the router's affinity score. A
    crc collision can only inflate a score (misroute one request);
    nothing downstream trusts it, so fingerprints beat storing token
    tuples at O(n^2) bytes per prompt. The running crc is SEEDED with
    the request's LoRA adapter name: adapted K/V differs from base
    K/V, so the replicas' prefix tries key on (adapter, prefix)
    (serve/prefix_cache.py) and affinity must too — the same prompt
    under two adapters is two disjoint fingerprint chains, while the
    base-model seed (adapter "") leaves pre-LoRA fingerprints
    untouched."""

    def __init__(self, chunk: int, cap: int = 4096):
        self.chunk = max(1, int(chunk))
        self.cap = int(cap)
        self._keys: "collections.OrderedDict" = collections.OrderedDict()

    def _crcs(self, prompt, adapter: str = ""):
        # running crc over successive chunks: crc32(p[:end]) chained as
        # crc32(chunk, prev) — identical values to hashing each prefix
        # from scratch, but O(n) bytes total instead of O(n^2) per
        # note/match call (this runs per candidate replica per submit)
        p = np.ascontiguousarray(np.asarray(prompt, np.int32))
        crc = zlib.crc32(adapter.encode("utf-8")) if adapter else 0
        for end in range(self.chunk, p.size + 1, self.chunk):
            crc = zlib.crc32(p[end - self.chunk:end].tobytes(), crc)
            yield end, crc

    def note(self, prompt, adapter: str = "") -> None:
        for _, crc in self._crcs(prompt, adapter):
            self._keys[crc] = None
            self._keys.move_to_end(crc)
        while len(self._keys) > self.cap:
            self._keys.popitem(last=False)

    def match(self, prompt, adapter: str = "") -> int:
        n = 0
        for end, crc in self._crcs(prompt, adapter):
            if crc not in self._keys:
                break
            self._keys.move_to_end(crc)
            n = end
        return n


class ServeRouter:
    """N engine replicas behind one submit/result API (module
    docstring). ``server_kw`` is forwarded to every replica's
    :class:`InferenceServer` (slots, prefill_chunk, paged, spec, tp,
    chaos, ... — ``chaos`` may also be a per-replica sequence, which is
    how the chaos tests kill exactly one replica). Each replica owns
    its metrics registry; passing ``registry`` is rejected — scrape
    the merged payload via :meth:`metrics_text`."""

    def __init__(self, cfg, params, *, replicas: int = 2,
                 policy: str = "prefix", affinity_cap: int = 4096,
                 chaos: Union[str, Sequence[str]] = "", **server_kw):
        if replicas < 1:
            raise ValueError("serve_replicas must be >= 1, got %d"
                             % replicas)
        if policy not in ("prefix", "rr"):
            raise ValueError("serve_router policy must be 'prefix' or "
                             "'rr', got %r" % (policy,))
        if "registry" in server_kw:
            raise ValueError("ServeRouter replicas own their registries "
                             "(per-replica label sets); scrape the "
                             "merged payload via metrics_text()")
        if isinstance(chaos, str):
            chaos_list = [chaos] * replicas
        else:
            chaos_list = list(chaos)
            if len(chaos_list) != replicas:
                raise ValueError(
                    "per-replica chaos spec list has %d entries for %d "
                    "replicas" % (len(chaos_list), replicas))
        self.policy = policy
        chunk = int(server_kw.get("prefill_chunk", 64)) or 64
        # per-replica device placement: with enough local devices for
        # disjoint blocks, replica i serves from devices
        # [i*tp, (i+1)*tp) — its own mesh (tensor-parallel when tp > 1,
        # placement-only otherwise), so N replicas actually occupy N
        # device blocks instead of all defaulting onto device 0. With
        # fewer devices the replicas share (the CPU CI regime, where
        # one core backs everything anyway); an explicit ``mesh`` in
        # server_kw is respected verbatim for every replica.
        if "mesh" not in server_kw:
            import jax as _jax

            from ..parallel.mesh import make_mesh
            tp = int(server_kw.pop("tp", 0) or 0)
            need = max(1, tp)
            devs = _jax.devices()
            if len(devs) >= replicas * need:
                srv_args = [dict(server_kw, mesh=make_mesh(
                    devices=devs[i * need:(i + 1) * need],
                    model_parallel=need)) for i in range(replicas)]
            else:
                srv_args = [dict(server_kw, tp=tp)] * replicas
        else:
            srv_args = [dict(server_kw)] * replicas
        self._servers: List[InferenceServer] = []
        try:
            for i in range(replicas):
                self._servers.append(InferenceServer(
                    cfg, params, chaos=chaos_list[i], **srv_args[i]))
        except Exception:
            for s in self._servers:
                s.shutdown(drain=False)
            raise
        # one lock guards ALL router state: routing tables, journal,
        # handles, and the counters below — submit/result/failover run
        # on arbitrary caller threads (cxn-lint CXN3xx, doc/lint.md)
        self._lock = make_rlock("ServeRouter._lock")
        self._tries = [_AffinityTrie(chunk, affinity_cap)  # guarded_by: self._lock
                       for _ in range(replicas)]
        self._routable = [True] * replicas  # guarded_by: self._lock
        self._swept = [False] * replicas    # guarded_by: self._lock
        # rid -> current Request / RouterHandle: the router's OWN
        # replay journal (PR 9's class — the conftest leak check sees
        # it, so a router that abandons admitted requests fails tests
        # the same way a server would)
        self._journal = ReplayJournal()     # guarded_by: self._lock
        self._handles: Dict[int, RouterHandle] = {}  # guarded_by: self._lock
        self._rr = itertools.count()        # guarded_by: self._lock
        # counters: submits sent to replica i / routed by a prefix
        # match / failed-replica migrations / drain-initiated
        # migrations / tenant-quota rejections spilled to a peer
        self.routed = [0] * replicas        # guarded_by: self._lock
        self.affinity_hits = 0              # guarded_by: self._lock
        self.failovers = 0                  # guarded_by: self._lock
        self.drain_migrations = 0           # guarded_by: self._lock
        self.quota_spills = 0               # guarded_by: self._lock

    # ------------------------------------------------------------ routing
    @property
    def replicas(self) -> int:
        return len(self._servers)

    @property
    def servers(self) -> List[InferenceServer]:
        """The replica servers (read-only use: tests, metrics)."""
        return list(self._servers)

    def _load(self, i: int) -> float:
        """The health-derived load signal: admission-queue fraction
        plus the degradation rung (a DEGRADED replica is shedding
        optional work — new traffic belongs on its peers first)."""
        s = self._servers[i]
        h = s.health()
        return (h["queue_depth"] / float(max(1, s.queue_capacity))
                + h["rung"])

    def _candidates(self, exclude=()) -> List[int]:
        out = []
        for i, s in enumerate(self._servers):
            if i in exclude or not self._routable[i]:
                continue
            if s.health()["state"] in (STATE_FAILED, STATE_DRAINING):
                continue
            out.append(i)
        return out

    def _route(self, prompt, exclude=(),
               adapter: str = "") -> Optional[int]:
        """Pick a replica for ``prompt`` (None = nobody healthy).
        Policy "prefix": longest affinity match wins, load breaks ties
        (and decides for cold prompts); "rr": round-robin over the
        healthy set. Affinity is (adapter, prefix)-keyed — LoRA traffic
        lands where its adapter pages (and adapted prefixes) already
        are. Caller holds ``_lock``."""
        cands = self._candidates(exclude)
        if not cands:
            return None
        if self.policy == "rr" or len(cands) == 1:
            return cands[next(self._rr) % len(cands)]
        scored = []
        for i in cands:
            scored.append((-self._tries[i].match(prompt, adapter),
                           self._load(i), i))
        scored.sort()
        best = scored[0]
        if -best[0] > 0:
            self.affinity_hits += 1
        return best[2]

    # ------------------------------------------------------------- submit
    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, **overrides) -> RouterHandle:
        """Route one request to a replica; returns a RouterHandle for
        :meth:`result`. A replica answering with backpressure
        (QueueFullError) — or a tenant-quota rejection
        (QuotaExceededError; per-replica quota/rate state, so a peer
        may well have budget) — spills to the next-best healthy
        replica; the error is re-raised only when EVERY healthy
        replica refuses, and then with the MINIMUM ``retry_after_ms``
        across the rejecting peers (plus that replica's id in the
        reason) — not whichever peer happened to answer last, whose
        hint may be arbitrarily pessimistic. Raises EngineFailedError
        when no healthy replica remains."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        adapter = str(overrides.get("adapter", "") or "")
        self._sweep_failed()
        tried: set = set()
        last_err: Optional[Exception] = None
        rejects = []            # (retry_after_ms, replica, error)
        while True:
            with self._lock:
                idx = self._route(prompt, exclude=tried,
                                  adapter=adapter)
            if idx is None:
                if rejects:
                    raise self._aggregate_rejection(rejects)
                if isinstance(last_err, AdmissionError):
                    raise last_err
                raise EngineFailedError(
                    "no healthy replica left to route to (%d replicas: "
                    "failed/draining/refusing)" % len(self._servers))
            try:
                req = self._servers[idx].submit(prompt, params,
                                                block=block, **overrides)
            except QueueFullError as e:
                tried.add(idx)
                last_err = e
                rejects.append((e.retry_after_ms, idx, e))
                if isinstance(e, QuotaExceededError):
                    with self._lock:
                        self.quota_spills += 1
                continue
            except EngineFailedError as e:
                tried.add(idx)
                last_err = e
                self._sweep_failed()
                continue
            except AdmissionError as e:
                # a replica that started draining/closing between the
                # routing decision and the submit refuses with a plain
                # AdmissionError — spill to a peer like backpressure. A
                # VALIDATION rejection (bad prompt/params) re-raises:
                # every replica would refuse it for the same reason,
                # and retrying elsewhere only masks the message.
                if self._servers[idx].health()["state"] \
                        != STATE_DRAINING:
                    raise
                tried.add(idx)
                last_err = e
                continue
            handle = RouterHandle(req, idx)
            with self._lock:
                self._tries[idx].note(prompt, adapter)
                self.routed[idx] += 1
                self._journal.add(req)
                self._handles[req.rid] = handle
            return handle

    def result(self, handle: RouterHandle, timeout=None):
        """Block for the handle's terminal ServeResult, chasing
        failover/drain migrations: a request whose replica died (typed
        ``error`` from a FAILED engine) or was drained out from under
        it (``cancelled`` by a replica the router took out of rotation)
        is replayed on a survivor and this call keeps waiting on the
        new incarnation — the caller never sees the intermediate
        failure. A waiter that wakes DURING drain_replica (the abort
        resolves its request before the drain's own migration sweep
        runs) migrates the request itself; _failover's lock + the
        replica-changed check make the two paths race-safe (whoever
        gets the lock first migrates, the other chases)."""
        while True:
            req, idx = handle.req, handle.replica
            res = self._servers[idx].result(req, timeout=timeout)
            if handle.req is not req:
                continue                    # migrated while we waited
            if res.status == "error" \
                    and self._servers[idx].health()["state"] \
                    == STATE_FAILED and self._failover(handle, idx):
                continue
            if res.status == "cancelled" and not self._routable[idx] \
                    and self._failover(handle, idx):
                continue                    # drained out from under us
            with self._lock:
                self._journal.remove(handle.req)
                self._handles.pop(handle.req.rid, None)
            return res

    @staticmethod
    def _aggregate_rejection(rejects):
        """Every healthy replica rejected the submit: aggregate the
        hints instead of parroting the last answer. The raised error
        carries the MINIMUM ``retry_after_ms`` across peers and names
        the replica it came from — the honest fleet-wide back-off (the
        soonest any replica expects room). A quota rejection stays
        typed QuotaExceededError so callers keep the per-tenant
        signal."""
        ms, idx, err = min(rejects, key=lambda t: (t[0], t[1]))
        reason = ("all %d replica(s) rejected the submit; earliest "
                  "capacity at replica %d" % (len(rejects), idx))
        if isinstance(err, QuotaExceededError):
            return QuotaExceededError(reason, retry_after_ms=ms,
                                      tenant=err.tenant, kind=err.kind)
        return QueueFullError(reason, retry_after_ms=ms)

    # ----------------------------------------------------------- failover
    def _rewind(self, req: Request) -> Request:
        """Module-level :func:`rewind_request` — kept as a method for
        the pinned tests and subclass hooks."""
        return rewind_request(req)

    def _failover(self, handle: RouterHandle, from_idx: int) -> bool:
        """Migrate one live request off ``from_idx`` (failed or
        draining). False = nowhere to go (the caller returns the typed
        error)."""
        with self._lock:
            if handle.replica != from_idx \
                    or handle.migrations >= len(self._servers):
                return handle.replica != from_idx
            target = self._route(handle.prompt, exclude={from_idx},
                                 adapter=handle.req.adapter)
            if target is None:
                return False
            new = self._rewind(handle.req)
            try:
                self._servers[target].adopt(new)
            except (AdmissionError, EngineFailedError):
                return False
            self._journal.remove(handle.req)
            self._journal.add(new)
            self._handles.pop(handle.req.rid, None)
            self._handles[new.rid] = handle
            handle.req = new
            handle.replica = target
            handle.migrations += 1
            self._tries[target].note(handle.prompt, new.adapter)
            self.failovers += 1
            return True

    def _sweep_failed(self) -> None:
        """Proactively migrate every live handle off a replica that
        went FAILED (its _finalize already resolved them all with the
        typed error — terminal, so the rewind pin is complete). Waiters
        inside result() would migrate lazily anyway; the sweep covers
        handles nobody is waiting on yet."""
        with self._lock:
            stale = [i for i, s in enumerate(self._servers)
                     if not self._swept[i]
                     and s.health()["state"] == STATE_FAILED]
            victims = [(i, h) for i in stale
                       for h in list(self._handles.values())
                       if h.replica == i]
            for i in stale:
                self._swept[i] = True
        for i, h in victims:
            if h.req.done.is_set() and h.req.status == "error":
                self._failover(h, i)

    def drain_replica(self, idx: int, migrate: bool = True) -> int:
        """Take replica ``idx`` out of rotation and migrate its live
        requests to the survivors (the deliberate-maintenance twin of
        failover). The replica is abort-stopped — its in-flight work
        resolves ``cancelled`` — and every router-tracked request is
        replayed elsewhere from its journal pin. Returns the number of
        requests migrated."""
        if not 0 <= idx < len(self._servers):
            raise ValueError("no replica %d (have %d)"
                             % (idx, len(self._servers)))
        with self._lock:
            self._routable[idx] = False
            victims = [h for h in self._handles.values()
                       if h.replica == idx]
        self._servers[idx].shutdown(drain=False)
        moved = 0
        if migrate:
            for h in victims:
                # only requests the ABORT interrupted are replayed:
                # 'cancelled' (the abort's own status) and 'error'. A
                # request that already reached 'ok'/'timeout'/'shed'
                # keeps its terminal outcome — resurrecting a timed-out
                # request would re-run it with its deadline stripped.
                if h.req.done.is_set() \
                        and h.req.status in ("cancelled", "error") \
                        and self._failover(h, idx):
                    moved += 1
                    with self._lock:
                        # re-attributed under the lock: a waiter's
                        # concurrent _failover increments race here
                        self.drain_migrations += 1
                        self.failovers -= 1
        return moved

    # ------------------------------------------------------------ surface
    def health(self) -> Dict:
        """Aggregate + per-replica health: ``state`` is SERVING while
        any routable replica serves, DEGRADED when every survivor is
        degraded, FAILED when none is left."""
        per = [s.health() for s in self._servers]
        live = [h for i, h in enumerate(per)
                if self._routable[i]
                and h["state"] not in (STATE_FAILED, STATE_DRAINING)]
        if not live:
            state = STATE_FAILED
        elif all(h["state"] == "DEGRADED" for h in live):
            state = "DEGRADED"
        else:
            state = "SERVING"
        return {"state": state, "replicas": per,
                "routable": list(self._routable),
                "failovers": self.failovers,
                "drain_migrations": self.drain_migrations}

    def metrics(self) -> Dict:
        """Aggregate serving snapshot: summed request counters and
        token counts, per-replica snapshots, and the router's own
        routing/failover accounting."""
        per = [s.metrics() for s in self._servers]
        counts: Dict[str, int] = {}
        for m in per:
            for k, v in m["requests"].items():
                counts[k] = counts.get(k, 0) + v
        return {
            "requests": counts,
            "tokens_generated": sum(m["tokens_generated"] for m in per),
            "ticks": sum(m["ticks"] for m in per),
            "routed": list(self.routed),
            "affinity_hits": self.affinity_hits,
            "failovers": self.failovers,
            "drain_migrations": self.drain_migrations,
            "quota_spills": self.quota_spills,
            "replicas": per,
        }

    def metrics_text(self) -> str:
        """The merged Prometheus scrape payload: per-replica series
        labeled ``replica=``, histograms additionally aggregated via
        ``Histogram.merge`` (obs/metrics.py:merged_prometheus)."""
        return obs_metrics.merged_prometheus(
            {str(i): s.registry for i, s in enumerate(self._servers)})

    def reset_metrics(self) -> None:
        """Zero the measurement window on every replica AND the
        router's own routing/failover accounting, so a post-reset
        snapshot is internally consistent (bench warm-pass
        isolation)."""
        for s in self._servers:
            s.reset_metrics()
        with self._lock:
            self.routed = [0] * len(self._servers)
            self.affinity_hits = 0
            self.failovers = 0
            self.drain_migrations = 0
            self.quota_spills = 0

    def drain(self, timeout=None) -> None:
        """Finish everything in flight on every replica, then stop
        (shutdown(drain=True) — the single server's contract)."""
        self.shutdown(drain=True, timeout=timeout)

    def shutdown(self, drain: bool = True, timeout=None) -> None:
        """Stop every replica (idempotent); ``drain=True`` finishes
        queued + in-flight work first."""
        for s in self._servers:
            s.shutdown(drain=drain, timeout=timeout)
        with self._lock:
            self._journal.clear()
            self._handles.clear()

    def close(self) -> None:
        self.shutdown(drain=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=not any(exc))
