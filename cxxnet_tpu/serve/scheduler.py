"""Continuous-batching scheduler: slot bookkeeping between device calls.

Per scheduler pass (driven by serve/server.py's loop):

1. **admit** — pop queued requests FIFO (skipping any whose deadline
   already passed — they finish as ``timeout``) into free slots; each
   admit restores the longest prefix-cache match into its row and
   enqueues the rest of the prompt as chunk-prefill work (with
   ``serve_prefill_chunk = 0``, the legacy path runs one whole-prompt
   prefill here instead);
2. **prefill** — up to ``serve_prefill_budget`` chunk steps of the
   OLDEST still-prefilling request (``prefill_step``), so a long prompt
   advances without stalling the decode tick for more than one chunk's
   duration; the final (padded) chunk returns the request's first token
   and activates the row;
3. **tick** — one batched decode step across all slots; decoding rows
   append their token, free and still-prefilling rows run on parked
   dummy state (position row_len - 1, outside every pending row's
   prefix; the spot is safe to dirty because a decode row always writes
   its own position before attending to it) and are ignored;
4. **retire** — rows that hit EOS, their token budget, or the sequence
   length offer their complete prompt chunks to the prefix cache and
   free their slot immediately, so the NEXT pass can admit into it —
   short requests leave the batch the moment they finish instead of
   convoying behind long ones.

**Paged mode** (the engine owns a block pool instead of dense rows,
serve/paged.py) adds block policy on top of the same loop:

* every device write is preceded by ``engine.reserve_window`` — block
  allocation plus copy-on-write faults for shared blocks — wrapped in
  :meth:`SlotScheduler._reserve`, which on pool exhaustion first evicts
  prefix-trie blocks (LRU, cheapest — they are a cache) and then
  **preempts** the youngest-admitted other row: its blocks are swapped
  to a host buffer, its slot freed, and the request parked on a resume
  list. Speculative verifies never preempt (speculation is optional
  work — the row just ticks instead this pass);
* prefix donation moves from retire to PREFILL COMPLETION
  (``donate_from_row``), so live rows share blocks with concurrent
  same-prefix traffic at zero copies;
* swapped requests RESUME with strict priority over new admissions
  (``resume_swapped``, oldest admit first) the moment a slot and their
  blocks are available — the swap-in restore is bit-exact, so a
  preempted request's tokens are identical to an undisturbed run;
* admission is gated on block headroom (``admissible``): the queue head
  only claims a slot when its prompt's blocks (minus the prefix-cache
  hit it would get) fit in free + trie-reclaimable blocks, so thousands
  of queued requests degrade into orderly waiting instead of admit/
  preempt thrash.

The scheduler is single-threaded by design (only the server's scheduler
thread calls it); cross-thread state (the admission queue, completion
events) lives in the server.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import List, Optional

import numpy as np

from ..obs.trace import TID_ENGINE, request_tid
from ..utils import profiler
from .resilience import InjectedFault, SupersededError, SwapCorruptionError

__all__ = ["SamplingParams", "Request", "SlotScheduler"]

# speculative back-off: a SERVING verify is one dispatch per slot while
# the tick amortizes every slot in one forward — so with SEVERAL rows
# decoding, a request whose drafts don't stick pays the full verify
# overhead for ~1 token per forward. After SPEC_BACKOFF_PROBE drafted
# tokens, a request accepting below SPEC_BACKOFF_MIN stops speculating
# for its remaining lifetime (a fresh admit re-probes); identity is
# untouched — the row just ticks like a spec-off request. The trip only
# arms while MORE than one row is decoding: a lone row's verify has the
# offline path's economics (it costs about one batch-1 tick and emits
# >= 1 token, so even a ~15% accept rate wins there — measured in
# doc/serving.md's round-10 cells).
SPEC_BACKOFF_PROBE = 8
SPEC_BACKOFF_MIN = 0.3

# drafter fault containment (serve/resilience.py): a drafter exception
# skips speculation for the pass (identity is untouched — greedy
# speculative output equals the plain tick stream), and a drafter that
# fails this many passes IN A ROW is disabled for the server's lifetime
# — a persistently-broken draft model must not cost a try + warn on
# every pass forever
DRAFTER_FAULT_LIMIT = 3


@dataclasses.dataclass
class SamplingParams:
    """Per-request generation parameters (defaults come from the server's
    config). ``seed`` feeds ``jax.random.PRNGKey`` exactly like
    ``gpt_decode(rng=PRNGKey(seed))``, so a served request reproduces the
    offline path token for token. ``timeout_ms`` bounds QUEUE time: a
    request still waiting when it expires finishes as ``timeout``
    (0 = no deadline); once admitted a request always runs to
    completion. ``eos``: stop early when this token is produced (it is
    included in the output); None = run to max_tokens.

    ``spec_mode`` / ``spec_len`` override the server's speculative
    decoding defaults per request: None inherits the server mode,
    ``"off"`` disables speculation for this request, ``"ngram"`` /
    ``"model"`` select a drafter the server has available (rejected at
    submit otherwise). ``spec_len`` 0 inherits; a positive value caps
    the draft window BELOW the server's (the verify program's shape is
    fixed server-wide — a per-request cap only lowers the traced draft
    count, so it cannot add a compiled signature)."""
    max_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos: Optional[int] = None
    timeout_ms: float = 0.0
    spec_mode: Optional[str] = None
    spec_len: int = 0


class Request:
    """One in-flight generation request: prompt + params + lifecycle
    timestamps. ``status`` walks queued -> prefill (chunked admit;
    legacy admits jump straight on) -> active -> terminal; ``done`` is
    set exactly once, when ``status`` reaches a terminal value
    (ok / timeout / rejected / cancelled)."""

    __slots__ = ("rid", "prompt", "params", "submit_t", "deadline",
                 "admit_t", "first_token_t", "done_t", "tokens", "status",
                 "error", "done", "slot", "traced", "replay_expect",
                 "retry_after_ms", "tenant", "migrate", "adapter")

    def __init__(self, rid: int, prompt: np.ndarray,
                 params: SamplingParams, submit_t: float,
                 tenant: str = "", adapter: str = ""):
        self.rid = rid
        # multi-tenant SLOs (serve/tenancy.py): the RESOLVED tenant
        # label ("" on an untenanted server) — keys the scheduler's
        # quota accounting, the priority ordering, and the tenant=
        # metric labels; survives recovery replay and router failover
        self.tenant = tenant
        # batched multi-LoRA (serve/lora.py): the adapter NAME this
        # request decodes under ("" = base model, adapter id 0). The
        # name — not the pool slot, which can change across a
        # preempt/resume cycle — is the identity that survives replay,
        # failover, and fleet migration; it also keys the prefix-cache
        # tries (LoRA changes K/V, so prefixes only match within one
        # adapter).
        self.adapter = adapter
        self.traced = False     # span recording on for this request
        #                         (set once at admit: tracer sampling)
        self.prompt = prompt
        self.params = params
        self.submit_t = submit_t
        self.deadline = (submit_t + params.timeout_ms / 1e3
                         if params.timeout_ms > 0 else None)
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.tokens: List[int] = []
        self.status = "queued"
        self.error = ""
        self.done = threading.Event()
        self.slot: Optional[int] = None
        # crash recovery (serve/resilience.py): the verified token
        # prefix a replayed request must regenerate bit-identically
        # (None = never replayed), and the back-off hint a shed /
        # rejected request carries out through its ServeResult
        self.replay_expect: Optional[List[int]] = None
        self.retry_after_ms = 0.0
        # disaggregated serving (serve/fleet.py): True = this request's
        # KV row leaves for a decode-tier worker the moment prefill
        # completes (_migrate_out), instead of decoding here. Default
        # False keeps every non-fleet submit on the exact pre-fleet
        # path.
        self.migrate = False

    def finish(self, status: str, error: str = "") -> None:
        """First terminal state wins: a request failed by the recovery
        supervisor must not be re-finished as `cancelled` when the
        shutdown sweep later walks the same rows — the waiter in
        result() has already been released with the typed error."""
        if self.done.is_set():
            return
        self.status = status
        self.error = error
        self.done_t = time.perf_counter()
        self.done.set()


class SlotScheduler:
    """Owns the per-slot host state mirroring the engine's cache rows."""

    def __init__(self, engine, stats: Optional[profiler.StepStats] = None,
                 on_finish=None, prefix_cache=None, drafters=None,
                 spec_mode: str = "off", spec_len: int = 0, tracer=None,
                 injector=None, on_swap_corrupt=None, tenancy=None):
        self.engine = engine
        self.paged = bool(getattr(engine, "paged", False))
        self.stats = stats or profiler.StepStats()
        # request-scoped span recording (obs/trace.py): None = off.
        # Per-request spans go on the request's own track; work shared
        # across rows (the batched tick, a drafter pass) goes on
        # TID_ENGINE — one span per tick, NOT one per row, so the tick
        # loop stays free of per-token allocation.
        self.tracer = tracer
        self.on_finish = on_finish      # called with each request that
        #                                 reaches a terminal state here
        self.chunk = int(engine.chunk)  # 0 = legacy whole-prompt
        self.prefix = prefix_cache if self.chunk > 0 else None
        # speculative decoding (serve/speculative.py): available drafter
        # objects by name, the server-default mode, and the verify
        # window (the engine's compiled spec_len — per-request overrides
        # can only lower the draft count inside it). The dict is SHARED
        # with the server (not copied): disabling a persistently-faulty
        # drafter here must also flip the server's spec gate off, or it
        # would keep dispatching no-op spec passes forever
        self.drafters = drafters if drafters is not None else {}
        self.spec_mode = spec_mode if self.drafters else "off"
        self.spec_len = min(int(spec_len), engine.spec_len) \
            if engine.spec_len else 0
        n = engine.slots
        self._req: List[Optional[Request]] = [None] * n
        self._free = list(range(n - 1, -1, -1))     # pop() -> lowest slot
        # chunk-prefill work: per-slot in-progress state + FIFO of slots
        # still prefilling (the front request's chunks run first, so
        # prefill completion order follows admission order)
        self._pending: List[Optional[dict]] = [None] * n
        self._prefill_q: collections.deque = collections.deque()
        # device-call argument rows; free and still-prefilling rows keep
        # harmless dummies (temperature 0 — greedy over garbage,
        # discarded) PARKED at the row's last position: the batched tick
        # writes every row's K/V at its position unconditionally, so the
        # park spot must be one no later reader can see stale. Chunk
        # masks stop at the prompt (< seq_len <= row_len), which leaves
        # only a decode step at pos row_len - 1 (reachable when seq_len
        # == row_len) — safe because the tick ALWAYS writes a row's own
        # position before attending to it, the invariant every reuse
        # argument here leans on. A parked write can therefore never
        # corrupt a pending row's already-prefilled prefix.
        self._park = engine.row_len - 1
        self._tok = np.zeros(n, np.int32)
        self._pos = np.full(n, self._park, np.int32)
        self._fold = np.zeros(n, np.int32)
        self._keys = np.zeros((n, 2), np.uint32)
        self._temp = np.zeros(n, np.float32)
        self._topk = np.zeros(n, np.int32)
        self._topp = np.ones(n, np.float32)
        # gauges
        self.ticks = 0
        self.active_row_ticks = 0       # sum of decoding counts over ticks
        self.tokens_generated = 0
        self.prefill_chunks = 0         # chunk steps run (chunked path)
        self.requests_prefilled = 0     # requests whose prefill completed
        # speculative gauges: verify forwards run, draft tokens proposed
        # vs accepted, tokens a verify actually APPENDED (EOS / the token
        # budget can retire a request mid-window, discarding the rest of
        # an accepted prefix — spec_tokens_per_forward must not count
        # those), and forwards that rolled back a rejected suffix
        self.spec_forwards = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self.spec_rollbacks = 0
        self.spec_backoffs = 0          # requests that stopped speculating
        # per-request accept probe for the back-off (reset at admit)
        self._spec_try = np.zeros(n, np.int64)
        self._spec_hit = np.zeros(n, np.int64)
        self._spec_off = [False] * n
        # request ids in admission order (bounded: diagnostic window, not
        # a full history — a hot server admits forever)
        self.admit_order: collections.deque = collections.deque(maxlen=4096)
        # paged preemption/swap state: records of swapped-out rows
        # awaiting resume ({"req", "phase", host K/V buffers, decode or
        # prefill cursor}), plus the traffic counters the obs registry
        # reads. swap_host_bytes tracks the LIVE host buffer footprint
        # (the `swap_host` ledger pool), not a cumulative total.
        self._swapped: List[dict] = []
        self.swaps_out = 0
        self.swaps_in = 0
        self.swap_host_bytes = 0
        # disaggregated serving (serve/fleet.py): completed-prefill rows
        # parked for export to a decode-tier worker (rid -> swap record
        # in exactly the resume_swapped format, host numpy only), plus
        # the tier traffic counters. The dict is written here on the
        # scheduler thread and popped by the server's export hook on an
        # RPC thread — single get/pop operations only, never iterated
        # cross-thread.
        self.migrated: dict = {}
        self.migrations_out = 0
        self.migrations_in = 0
        # resilience (serve/resilience.py): the chaos injector (None =
        # off), the server's swap-corruption replay hook, the
        # degradation ladder's prefix-admission switch (rung 2), the
        # superseded flag a recovery sets on the OLD scheduler so an
        # abandoned (previously hung) loop thread unwinds instead of
        # mutating replayed requests, and the fault-containment counters
        self._inj = injector
        self.on_swap_corrupt = on_swap_corrupt
        self.prefix_admission = True
        self.dead = False
        self._owner = None      # thread allowed past the dead flag
        self.swap_corruptions = 0
        self.drafter_faults = 0
        self.prefix_restore_faults = 0
        self.replay_mismatches = 0
        self._drafter_streak: dict = {}     # name -> consecutive faults
        # multi-tenant SLOs (serve/tenancy.py): the TenantRegistry (None
        # = untenanted, every branch below short-circuits), live
        # per-tenant accounting — slots occupied and blocks CHARGED
        # (one admission_claim per admitted row, credited back at
        # retire/abort/preempt, re-charged at resume) — and the
        # per-slot charge memo that makes the credit exact however the
        # row leaves its slot. Scheduler-thread only, like every other
        # host gauge here.
        self.tenancy = tenancy
        self.tenant_slots: dict = {}
        self.tenant_blocks: dict = {}
        self._slot_charge = [0] * n
        # batched multi-LoRA (serve/lora.py): the engine's adapter pool
        # (None = unarmed, every branch below short-circuits) and the
        # per-slot adapter-id row the batched tick consumes. A row's id
        # is the POOL SLOT its adapter currently occupies — re-resolved
        # at resume (eviction may have moved it); parked/free rows sit
        # at 0 (base, the pinned all-zero slot), so the one-signature
        # tick stays correct across any occupancy mix.
        self.lora = getattr(engine, "lora_pool", None)
        self._aid = np.zeros(n, np.int32)

    # ----------------------------------------------------------- tenancy
    def _rank(self, req: Request) -> int:
        """Sacrifice rank (higher = preempted/shed first): every
        request ranks `standard` on an untenanted server, so every
        (rank, age) ordering below degenerates to the original
        age-only order — the pinned no-op."""
        if self.tenancy is None:
            return 1
        return self.tenancy.rank_of(req.tenant)

    def _tenant_charge(self, req: Request, blocks: int) -> None:
        if self.tenancy is None:
            return
        t = req.tenant
        self._slot_charge[req.slot] = blocks
        self.tenant_slots[t] = self.tenant_slots.get(t, 0) + 1
        self.tenant_blocks[t] = self.tenant_blocks.get(t, 0) + blocks

    def _tenant_credit(self, req: Request, slot: int) -> None:
        if self.tenancy is None:
            return
        t = req.tenant
        self.tenant_slots[t] = self.tenant_slots.get(t, 0) - 1
        self.tenant_blocks[t] = self.tenant_blocks.get(t, 0) \
            - self._slot_charge[slot]
        self._slot_charge[slot] = 0

    def tenant_usage(self, name: str):
        """(occupied slots, charged blocks) for one tenant — the quota
        accounting the exactness tests pin (both return to 0 when the
        tenant's last request retires, aborts, or is preempted)."""
        return (self.tenant_slots.get(name, 0),
                self.tenant_blocks.get(name, 0))

    def tenant_blocked(self, req: Request, claims: dict) -> bool:
        """Would admitting ``req`` NOW exceed its tenant's slot or
        block quota? ``claims`` maps tenant -> (slots, blocks) already
        promised to requests popped earlier in the same scheduler pass
        (their charges land later, outside the admission lock — the
        same over-admit hazard ``admissible``'s ``claimed`` guards
        globally). A blocked tenant's request is SKIPPED by the pop
        loop, never blocking other tenants queued behind it."""
        if self.tenancy is None:
            return False
        pol = self.tenancy.policy_for(req.tenant)
        cs, cb = claims.get(req.tenant, (0, 0))
        if pol.slots > 0 and \
                self.tenant_slots.get(req.tenant, 0) + cs + 1 > pol.slots:
            return True
        if self.paged:
            limit = pol.block_limit(self.engine.num_blocks - 1)
            if limit > 0 and self.tenant_blocks.get(req.tenant, 0) + cb \
                    + self.admission_claim(req) > limit:
                return True
        return False

    # ------------------------------------------------------------- state
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active(self) -> int:
        """Occupied slots (decoding + still prefilling)."""
        return self.engine.slots - len(self._free)

    @property
    def prefilling(self) -> int:
        """Admitted requests whose prefill has not finished yet."""
        return len(self._prefill_q)

    @property
    def decoding(self) -> int:
        """Rows the next tick advances (prefill complete, not retired)."""
        return sum(r is not None for r in self._req)

    def occupancy(self) -> float:
        return self.active / float(self.engine.slots)

    def batch_efficiency(self) -> float:
        """Mean fraction of slot rows doing useful work per tick — the
        continuous-batching quality gauge (1.0 = every tick fully
        batched)."""
        if not self.ticks:
            return 0.0
        return self.active_row_ticks / float(self.ticks * self.engine.slots)

    @property
    def swapped_pending(self) -> int:
        """Preempted requests waiting to resume (paged mode)."""
        return len(self._swapped)

    def live_tokens(self) -> int:
        """Cache positions written and still live across occupied rows
        (decoding rows' current position + prefilling rows' consumed
        prompt) — the numerator of token-level KV utilization."""
        t = 0
        for slot, req in enumerate(self._req):
            if req is not None:
                t += int(self._pos[slot])
        for slot in self._prefill_q:
            st = self._pending[slot]
            if st is not None:
                t += int(st["next"])
        return t

    def kv_token_utilization(self) -> float:
        """Token-level KV utilization in [0, 1]. Paged: PHYSICAL —
        allocated blocks / allocatable pool (shared blocks counted
        once, however many rows' tables reference them; trie-retained
        blocks count as used — they hold real K/V). Dense:
        live_tokens / (slots * row_len), which reads LOW by
        construction — every admitted row pins row_len positions
        regardless of its length — exactly the waste paging removes
        (doc/serving.md). A logical-token numerator would double-count
        shared prefixes and read over 1.0 under heavy sharing."""
        eng = self.engine
        if self.paged:
            usable = eng.num_blocks - 1
            used = usable - eng.manager.free_count
            return used / float(max(1, usable))
        return self.live_tokens() / float(max(1, eng.slots * eng.row_len))

    # ------------------------------------------------------- resilience
    def supersede(self) -> None:
        """Mark this scheduler dead to every thread but the CALLER: a
        recovery (or the budget-exhausted finalizer) abandons the loop
        thread that may still be inside a device call here — when that
        thread finally returns it must unwind without appending tokens
        (the requests were rewound for replay) or touching slots it no
        longer owns — while the superseding thread itself may still
        drive the terminal cancel/fail sweep through the same
        scheduler."""
        self._owner = threading.get_ident()
        self.dead = True

    def _check_live(self) -> None:
        """Raise :class:`SupersededError` on a dead scheduler unless
        the calling thread is the one that superseded it (see
        :meth:`supersede`). Called at every state-mutation entry point
        that follows a device call."""
        if self.dead and threading.get_ident() != self._owner:
            raise SupersededError(
                "scheduler superseded by engine recovery")

    def _emit(self, slot: int, req: Request, tok: int) -> Optional[str]:
        """Append one generated token to ``req``, verifying it against
        the replay journal's expected prefix when the request is being
        replayed after a crash (serve/resilience.py): the deterministic
        fold_in key schedule makes regeneration bit-exact, so any
        divergence means corrupted replay state — the request must fail
        typed, never silently continue on a forked stream. Returns the
        error message on divergence, None otherwise."""
        self._check_live()
        exp = req.replay_expect
        i = len(req.tokens)
        req.tokens.append(tok)
        self.tokens_generated += 1
        if exp is not None and i < len(exp) and int(exp[i]) != int(tok):
            self.replay_mismatches += 1
            return ("deterministic replay diverged at token %d: "
                    "expected %d, regenerated %d (request %d)"
                    % (i, int(exp[i]), int(tok), req.rid))
        return None

    # ----------------------------------------------------- block policy
    def admission_need(self, req: Request) -> int:
        """Blocks this request's admission will ALLOCATE: its prompt
        (plus one decode block), minus the prefix-cache hit it would
        get RIGHT NOW (same-prefix requests popped in one burst get no
        credit for each other's not-yet-donated chunks — conservative,
        which is the safe direction for a gate)."""
        if not self.paged:
            return 0
        eng = self.engine
        need = eng.blocks_for(len(req.prompt) + 1)
        if self.prefix is not None:
            need -= self.prefix.match_tokens(req.prompt) \
                // eng.block_size
        return max(0, need)

    def admission_claim(self, req: Request) -> int:
        """Credit this admission consumes from the gate's free +
        reclaimable pot: allocations AND borrowed prefix-hit blocks —
        a hit pins its trie chain (refcounts rise past 1), so those
        blocks stop being reclaimable the moment the admit runs. The
        full prompt block count is exactly need + hit."""
        if not self.paged:
            return 0
        return self.engine.blocks_for(len(req.prompt) + 1)

    def admissible(self, req: Request, claimed: int = 0) -> bool:
        """Paged admission gate: can ``req`` be backed by free +
        trie-reclaimable blocks, AFTER subtracting ``claimed`` — the
        credit (admission_claim) already promised to requests popped
        earlier in the same scheduler pass? Their allocations happen
        later, outside the admission lock, and their prefix hits pin
        trie blocks that reclaimable_blocks still counts — so without
        ``claimed`` a burst would over-admit against a pot that hasn't
        moved yet and preempt-thrash the just-admitted rows. Dense
        mode admits on slots alone (the dense pool pre-pays every
        row). FIFO is preserved — the server stops popping at the
        first inadmissible head rather than searching the queue for
        smaller requests."""
        if not self.paged:
            return True
        need = self.admission_need(req)
        if need <= 0:
            return True
        avail = self.engine.manager.free_count - int(claimed)
        if avail < need and self.prefix is not None:
            avail += self.prefix.reclaimable_blocks()
        return avail >= need

    def _reserve(self, slot: int, p0: int, p1: int,
                 allow_preempt: bool = True,
                 what: str = "write window") -> bool:
        """Make [p0, p1) of ``slot``'s row writable, creating room by
        (1) evicting prefix-trie blocks, then (2) preempting the
        youngest-admitted OTHER row, until the engine's reserve_window
        succeeds. Terminates: every retry either freed trie blocks or
        removed a row, both finite. Returns False only when the pool
        cannot hold the window at all (with num_blocks >= bpr + 1 that
        means allow_preempt=False and no trie headroom)."""
        if not self.paged:
            return True
        from .paged import BlockPoolExhausted
        while True:
            try:
                self.engine.reserve_window(slot, p0, p1, what=what)
                return True
            except BlockPoolExhausted as e:
                if self.prefix is not None \
                        and self.prefix.evict_blocks(e.short) > 0:
                    continue
                if allow_preempt and self._preempt_one(exclude=slot):
                    continue
                return False

    def _preempt_one(self, exclude: int) -> bool:
        """Swap out the lowest-priority occupied row, never
        ``exclude``: victims order by (priority class, age) — every
        best-effort row goes before any standard row before any
        guaranteed row, youngest admit first within a class (it has
        done the least work and re-queues behind the least history).
        Untenanted, every row ranks equal and the order degenerates to
        the original youngest-admit rule. Decoding and still-
        prefilling rows are both fair game; returns False when no
        victim exists."""
        victim, key = None, (-1, -1.0)
        for slot, req in enumerate(self._req):
            if req is not None and slot != exclude \
                    and (self._rank(req), req.admit_t) > key:
                victim, key = slot, (self._rank(req), req.admit_t)
        for slot in self._prefill_q:
            st = self._pending[slot]
            if st is not None and slot != exclude \
                    and (self._rank(st["req"]), st["req"].admit_t) > key:
                victim, key = slot, (self._rank(st["req"]),
                                     st["req"].admit_t)
        if victim is None:
            return False
        self._preempt(victim)
        return True

    def _preempt(self, slot: int) -> None:
        """Swap ``slot``'s blocks to host and park its request on the
        resume list. The record carries everything a bit-exact resume
        needs: the decode cursor (pos / fold / last token) or the
        prefill cursor (next), the PRNG key, and the blocks' contents."""
        st = self._pending[slot]
        if st is not None:                  # mid-prefill victim
            req, key = st["req"], st["key"]
            rec = {"req": req, "key": key, "phase": "prefill",
                   "next": st["next"]}
            self._pending[slot] = None
            self._prefill_q.remove(slot)
        else:
            req = self._req[slot]
            rec = {"req": req, "key": self._keys[slot].copy(),
                   "phase": "decode", "tok": int(self._tok[slot]),
                   "pos": int(self._pos[slot]),
                   "fold": int(self._fold[slot])}
            self._req[slot] = None
        rec["spec"] = (int(self._spec_try[slot]),
                       int(self._spec_hit[slot]), self._spec_off[slot])
        # a preempted row releases its adapter pin (the NAME rides on
        # the request; the pool slot is re-resolved at resume — eviction
        # may reassign it, which is invisible to the request's identity)
        if self.lora is not None and req.adapter:
            self.lora.release(req.adapter)
        self._aid[slot] = 0
        # tenancy: a preempted row's slot/block charge is RETURNED (its
        # blocks leave the device pool for the host buffer); the charge
        # rides the record so the resume re-applies exactly what was
        # credited here
        rec["charge"] = self._slot_charge[slot]
        self._tenant_credit(req, slot)
        # the engine's swap record is carried OPAQUELY: under
        # serve_kv_dtype=int8 it holds the stored int8 payloads plus
        # scale planes ("ks"/"vs") at roughly half the bytes — the
        # nbytes/crc bookkeeping below is layout-agnostic
        swap = self.engine.swap_out_row(slot)
        rec.update(swap)
        req.status = "swapped"
        req.slot = None
        self._tok[slot] = 0
        self._pos[slot] = self._park
        self._fold[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._free.append(slot)
        self._swapped.append(rec)
        self.swaps_out += 1
        self.swap_host_bytes += rec["nbytes"]

    def resume_swapped(self) -> int:
        """Swap preempted requests back in — oldest admit first, one per
        free slot, as soon as their blocks fit (evicting trie blocks if
        that closes the gap). Called by the server each pass BEFORE new
        admissions, so a preempted request can never be starved by
        fresh traffic. Returns how many resumed."""
        n = 0
        while self._swapped and self._free:
            self._check_live()
            # (priority class, age): a preempted guaranteed row resumes
            # before any standard row before any best-effort row,
            # oldest admit first within a class (untenanted: the
            # original oldest-admit order, ranks all equal)
            rec = min(self._swapped,
                      key=lambda r: (self._rank(r["req"]),
                                     r["req"].admit_t))
            need = rec["n"]
            m = self.engine.manager
            if need > m.free_count:
                short = need - m.free_count
                if self.prefix is not None \
                        and self.prefix.evict_blocks(short) > 0:
                    continue
                break                       # wait for retires
            if self.lora is not None and rec["req"].adapter \
                    and not self.lora.can_acquire(rec["req"].adapter):
                # adapter pool exhausted (every slot pinned by active
                # rows): wait for retires, like the block shortfall
                break
            self._swapped.remove(rec)
            slot = self._free.pop()
            try:
                self.engine.swap_in_row(slot, rec)
            except SwapCorruptionError as e:
                # the host buffer failed its checksum: resuming would
                # replay garbage bits. Fail CONTAINED — drop the swap
                # record, give the slot back, and route the request to
                # a deterministic journal replay (the server hook); the
                # engine and every other row are untouched.
                self._free.append(slot)
                self.swap_host_bytes -= rec["nbytes"]
                self.swap_corruptions += 1
                profiler.warn("serve: %s" % e)
                req = rec["req"]
                if self.on_swap_corrupt is not None:
                    self.on_swap_corrupt(req)
                else:
                    req.finish("error", str(e))
                    if self.on_finish is not None:
                        self.on_finish(req)
                continue
            self.swaps_in += 1
            self.swap_host_bytes -= rec["nbytes"]
            req = rec["req"]
            req.slot = slot
            self._tenant_charge(req, rec["charge"])
            if self.lora is not None and req.adapter:
                # re-acquire by NAME: the pool slot may differ from the
                # pre-preemption one (eviction churn) — the delta math
                # only ever indexes by the CURRENT slot, so identity
                # is unaffected
                self._aid[slot] = self.lora.acquire(req.adapter)
            for d in self.drafters.values():
                d.reset(slot)
            self._spec_try[slot], self._spec_hit[slot], \
                self._spec_off[slot] = rec["spec"]
            self._keys[slot] = rec["key"]
            p = req.params
            if rec["phase"] == "prefill":
                req.status = "prefill"
                self._pending[slot] = {"req": req, "key": rec["key"],
                                       "next": rec["next"]}
                self._prefill_q.append(slot)
            else:
                req.status = "active"
                self._tok[slot] = rec["tok"]
                self._pos[slot] = rec["pos"]
                self._fold[slot] = rec["fold"]
                self._temp[slot] = p.temperature
                self._topk[slot] = p.top_k
                self._topp[slot] = p.top_p
                self._req[slot] = req
            n += 1
        return n

    # ------------------------------------------------------------- admit
    def admit(self, req: Request) -> None:
        """Claim a free slot for ``req`` (caller checked free_slots).
        Chunked path: restore the longest prefix-cache match into the
        row and enqueue the remaining chunks (prefill_step runs them).
        Legacy path (chunk 0): one whole-prompt prefill, may retire
        immediately (max_tokens == 1, or the first token is EOS)."""
        import jax

        self._check_live()
        slot = self._free.pop()
        p = req.params
        req.slot = slot
        req.admit_t = time.perf_counter()
        if self.lora is not None and req.adapter:
            # residency IS the admission gate: the server's pop loop
            # checked can_acquire, so this swap-in (if the adapter is
            # not already resident) succeeds; the row then pins its
            # pool slot until retire/preempt/migrate releases it
            self._aid[slot] = self.lora.acquire(req.adapter)
        # tenancy: charge the tenant its admission claim (slots always,
        # blocks in paged mode) — credited back wherever the row leaves
        # its slot (retire, abort, preempt)
        self._tenant_charge(req, self.admission_claim(req))
        for d in self.drafters.values():
            d.reset(slot)               # new occupant: drop mirror state
        self._spec_try[slot] = self._spec_hit[slot] = 0
        self._spec_off[slot] = False
        self.stats.record(profiler.QUEUE_WAIT, req.admit_t - req.submit_t)
        tr = self.tracer
        if tr is not None and tr.should_sample(req.rid):
            req.traced = True
            tr.add(profiler.QUEUE_WAIT, req.submit_t,
                   req.admit_t - req.submit_t, request_tid(req.rid),
                   cat="serve")
        self.admit_order.append(req.rid)
        key = np.asarray(jax.random.PRNGKey(p.seed), np.uint32)
        if self.chunk <= 0:
            t0 = time.perf_counter()
            with self.stats.phase(profiler.PREFILL):
                tok = self.engine.prefill(slot, req.prompt, key,
                                          p.temperature, p.top_k, p.top_p)
            if req.traced:
                tr.add(profiler.PREFILL, t0, time.perf_counter() - t0,
                       request_tid(req.rid), cat="serve",
                       args={"n_prompt": len(req.prompt)})
            # commit this admit's QUEUE_WAIT/PREFILL as their own stats
            # step: folding them into the next tick's end_step would sum
            # every admit since the last tick into one sample (skewing
            # the percentiles) and lose them entirely for requests that
            # retire at admit (max_tokens 1 / instant EOS — no tick runs)
            self.stats.end_step()
            self.requests_prefilled += 1
            self._activate(req, key, tok)
            return
        start = 0
        if self.prefix is not None:
            t0 = time.perf_counter()
            with self.stats.phase(profiler.PREFIX_COPY):
                try:
                    if self._inj is not None \
                            and self._inj.fire("prefix_restore"):
                        raise InjectedFault("chaos point "
                                            "'prefix_restore'")
                    start = self.prefix.copy_into(slot, req.prompt,
                                                  adapter=req.adapter)
                except SupersededError:
                    raise
                except Exception as e:
                    # a failed restore is a MISS, not a fatality: start
                    # the chunk prefill from position 0, which rewrites
                    # (COW-faulting first, in paged mode) whatever the
                    # partial restore left in the row
                    self.prefix_restore_faults += 1
                    profiler.warn("serve: prefix restore failed for "
                                  "request %d (%s); prefilling from "
                                  "scratch" % (req.rid, e))
                    start = 0
            if req.traced:
                tr.add("prefix_restore", t0, time.perf_counter() - t0,
                       request_tid(req.rid), cat="serve",
                       args={"restored_tokens": start})
        self.stats.end_step()       # commit QUEUE_WAIT (+ PREFIX_COPY)
        req.status = "prefill"
        self._pending[slot] = {"req": req, "key": key, "next": start}
        self._prefill_q.append(slot)

    def prefill_step(self) -> bool:
        """Run ONE chunk of prefill work for the oldest still-prefilling
        request; returns False when none is pending. The final (padded)
        chunk samples the request's first token and activates the row
        for ticking."""
        if not self._prefill_q:
            return False
        slot = self._prefill_q[0]
        st = self._pending[slot]
        req = st["req"]
        p = req.params
        n = len(req.prompt)
        start = st["next"]
        end = min(start + self.chunk, n)
        toks = np.zeros(self.chunk, np.int32)
        toks[:end - start] = req.prompt[start:end]
        # paged: allocate (and COW-privatize) the chunk's full write
        # window first — the program writes chunk tokens at start even
        # when fewer are valid (the padded final chunk). The window is
        # clamped to row_len: after a partial-tail prefix hit, start is
        # NOT chunk-aligned, so the final window can run past the row —
        # the chunk program clamps those pad writes to the row's last
        # position (engine._prefill_chunk_paged_fn), and the reserve
        # must not ask for blocks beyond the table either.
        if self.paged and not self._reserve(
                slot, start,
                min(start + self.chunk, self.engine.row_len),
                what="prefill chunk"):
            # unreachable with num_blocks >= bpr + 1 (a lone row always
            # fits once the trie is evicted and every other row swapped)
            raise RuntimeError("block pool cannot hold one prefill "
                               "window; serve_num_blocks is too small")
        t0 = time.perf_counter()
        with self.stats.phase(profiler.PREFILL_CHUNK):
            tok = self.engine.prefill_chunk(slot, toks, start, end - start,
                                            st["key"], p.temperature,
                                            p.top_k, p.top_p,
                                            aid=int(self._aid[slot]))
            if end >= n:
                # the request's first token: only the FINAL chunk's
                # sample is fetched — mid-prompt chunks stay async so
                # they pipeline on device
                tok = int(tok)
        self._check_live()
        if req.traced:
            self.tracer.add(profiler.PREFILL_CHUNK, t0,
                            time.perf_counter() - t0,
                            request_tid(req.rid), cat="serve",
                            args={"start": start, "n": end - start})
        self.stats.end_step()       # one chunk = one stats step
        self.prefill_chunks += 1
        st["next"] = end
        if end < n:
            return True
        self._prefill_q.popleft()
        self._pending[slot] = None
        self.requests_prefilled += 1
        self._activate(req, st["key"], tok)
        return True

    def _activate(self, req: Request, key: np.ndarray, tok: int) -> None:
        """Prefill finished: record TTFT, take the first token, and arm
        the row for decode ticks (or retire on the spot — max_tokens 1 /
        instant EOS)."""
        slot = req.slot
        p = req.params
        req.first_token_t = time.perf_counter()
        req.status = "active"
        err = self._emit(slot, req, tok)
        if err is not None:
            self._retire(req, "error", err)
            return
        if self.paged and self.prefix is not None \
                and self.prefix_admission:
            # eager donation: the row's complete prompt chunks join the
            # trie NOW (zero-copy ownership refs), so concurrent
            # same-prefix requests share this LIVE row's blocks instead
            # of waiting for it to retire. Degradation rung 2 switches
            # prefix_admission off — under pool pressure new donations
            # only pin blocks the make-room loop then has to evict.
            with self.stats.phase(profiler.PREFIX_COPY):
                self.prefix.donate_from_row(slot, req.prompt,
                                            adapter=req.adapter)
            self.stats.end_step()
        if self._finished(req, tok):
            self._retire(req, "ok")
            return
        if req.migrate and self.paged:
            # disaggregated fleet (serve/fleet.py): this worker only
            # prefills — the row's blocks leave for a decode worker.
            # Runs AFTER the prefix donation above, so the trie keeps
            # serving this prompt's prefix to later same-prefix traffic
            # (swap-out copies content; the trie's refs survive the
            # row release).
            self._migrate_out(req, key, tok)
            return
        n = len(req.prompt)
        self._tok[slot] = tok
        self._pos[slot] = n            # position the NEXT tick processes
        self._fold[slot] = 1           # next token's fold_in index
        self._keys[slot] = key
        self._temp[slot] = p.temperature
        self._topk[slot] = p.top_k
        self._topp[slot] = p.top_p
        self._req[slot] = req

    def _migrate_out(self, req: Request, key: np.ndarray,
                     tok: int) -> None:
        """Park a just-prefilled row for adoption by a decode-tier
        worker (serve/fleet.py): the record is exactly what
        :meth:`resume_swapped` restores — decode cursor armed at the
        first token, PRNG key, and the row's block contents via the
        crc-checksummed engine swap record — so the adopting worker's
        ``inject_swapped`` + resume path replays the existing bit-exact
        preemption contract over the wire. The request finishes here
        with the non-terminal-looking ``migrated`` status WITHOUT the
        ``on_finish`` hook: it did not complete on this worker, so the
        completion counters (and the journal, which the export hook
        clears) must not see it as done."""
        slot = req.slot
        rec = {"req": req, "key": np.array(key, np.uint32, copy=True),
               "phase": "decode", "tok": int(tok),
               "pos": len(req.prompt), "fold": 1,
               "spec": (int(self._spec_try[slot]),
                        int(self._spec_hit[slot]),
                        self._spec_off[slot]),
               "charge": self._slot_charge[slot]}
        self._tenant_credit(req, slot)
        if self.lora is not None and req.adapter:
            # the decode-tier adoptee re-acquires by name at resume
            self.lora.release(req.adapter)
        self._aid[slot] = 0
        swap = self.engine.swap_out_row(slot)
        rec.update(swap)
        req.slot = None
        self._req[slot] = None
        self._tok[slot] = 0
        self._pos[slot] = self._park
        self._fold[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._free.append(slot)
        self.migrations_out += 1
        self.migrated[req.rid] = rec
        req.finish("migrated")

    def pop_migrated(self, rid: int) -> Optional[dict]:
        """Claim (and remove) one parked migration record; None when
        the record is gone — an engine recovery between park and export
        dropped it, and the fleet router then replays the request from
        its own journal instead."""
        return self.migrated.pop(rid, None)

    def inject_swapped(self, rec: dict) -> None:
        """Adopt a migrated row from another worker: the wire record
        joins the resume list exactly like a locally-preempted row, so
        ``resume_swapped`` restores it (crc verified first — a
        corrupted wire payload routes to the swap-corruption replay
        hook, never into the pool). Scheduler-thread only: the server
        drains its adoption queue into here at the top of each pass."""
        req = rec["req"]
        req.status = "swapped"
        req.slot = None
        self._swapped.append(rec)
        self.swap_host_bytes += rec["nbytes"]
        self.migrations_in += 1

    def _finished(self, req: Request, tok: int) -> bool:
        p = req.params
        cap = min(p.max_tokens, self.engine.cfg.seq_len - len(req.prompt))
        if len(req.tokens) >= cap:
            return True
        return p.eos is not None and tok == p.eos

    def _retire(self, req: Request, status: str, error: str = "") -> None:
        self._check_live()
        slot = req.slot
        t_retire = time.perf_counter()
        if self._pending[slot] is not None:     # cancelled mid-prefill
            # _pending and _prefill_q are always mutated together on the
            # scheduler thread, so membership is an invariant — a
            # ValueError here is a real bug, not a race to paper over
            self._pending[slot] = None
            self._prefill_q.remove(slot)
        elif status == "ok" and self.prefix is not None \
                and not self.paged and self.prefix_admission:
            # dense path: offer the row's complete prompt chunks to the
            # prefix cache BEFORE the slot is recycled (the copy-out
            # reads the row). Paged rows donated at prefill completion.
            with self.stats.phase(profiler.PREFIX_COPY):
                self.prefix.insert_from_row(slot, req.prompt,
                                            adapter=req.adapter)
            self.stats.end_step()
        if self.paged:
            # drop the row's block refs; blocks donated to the trie (or
            # shared with other live rows) survive through their refs
            self.engine.release_row(slot)
        if self.lora is not None and req.adapter:
            self.lora.release(req.adapter)
        self._aid[slot] = 0
        self._tenant_credit(req, slot)
        self._req[slot] = None
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._tok[slot] = 0
        self._pos[slot] = self._park
        self._fold[slot] = 0
        self._free.append(slot)
        req.finish(status, error)
        if req.traced:
            tid = request_tid(req.rid)
            tr = self.tracer
            if req.first_token_t is not None:
                # ONE span covering every tick the request decoded
                # through (args carry the token count) — the per-request
                # record stays O(1) in tokens, the per-tick detail lives
                # on the shared TID_ENGINE track
                tr.add("decode", req.first_token_t,
                       t_retire - req.first_token_t, tid, cat="serve",
                       args={"tokens": len(req.tokens)})
            tr.add("retire", t_retire, req.done_t - t_retire, tid,
                   cat="serve", args={"status": status})
            tr.add("request", req.submit_t, req.done_t - req.submit_t,
                   tid, cat="serve",
                   args={"rid": req.rid, "status": status,
                         "prompt_tokens": len(req.prompt),
                         "tokens": len(req.tokens)})
        if self.on_finish is not None:
            self.on_finish(req)

    # ------------------------------------------------------- speculative
    def _spec_mode_for(self, req: Request) -> str:
        """Effective drafter name for ``req`` ("off" = no speculation):
        the per-request override when set, else the server default; a
        mode with no available drafter degrades to off (submit already
        rejected explicitly-unavailable overrides)."""
        mode = req.params.spec_mode or self.spec_mode
        return mode if mode in self.drafters else "off"

    def spec_steps(self) -> int:
        """One draft-and-verify pass: draft for every eligible decoding
        row (host n-gram lookup, or the draft model's catch-up + batched
        greedy ticks), then run one ``serve_verify_chunk`` per row with
        a non-empty draft — each emits between 1 (all drafts rejected:
        the correction token alone) and ``spec_len + 1`` tokens. Returns
        the number of verify forwards run. Rows are eligible when their
        request speculates (mode != off), at least 2 tokens of budget
        remain (with 1 left a plain tick finishes cheaper than a
        verify), and the verify window fits the row
        (``pos + spec_len + 1 <= row_len`` — the program writes the full
        window regardless of the draft hit length). The decode tick runs
        AFTER this in the same pass; just-verified rows tick too (the
        tick writes its own position's K/V before attending — the
        standard write-before-attend invariant)."""
        if self.spec_mode == "off" and not any(
                r is not None and r.params.spec_mode not in (None, "off")
                for r in self._req):
            return 0
        K = self.spec_len
        if K < 1 or not self.drafters:
            return 0
        want: dict = {}                 # slot -> (mode, k_eff)
        for slot, req in enumerate(self._req):
            if req is None or self._spec_off[slot]:
                continue
            mode = self._spec_mode_for(req)
            if mode == "off":
                continue
            p = req.params
            cap = min(p.max_tokens,
                      self.engine.cfg.seq_len - len(req.prompt))
            remaining = cap - len(req.tokens)
            k_eff = min(K, remaining - 1)
            if p.spec_len > 0:
                k_eff = min(k_eff, p.spec_len)
            if k_eff < 1 or remaining < 2:
                continue
            if int(self._pos[slot]) + K + 1 > self.engine.row_len:
                continue
            if self.paged and not self._reserve(
                    slot, int(self._pos[slot]),
                    int(self._pos[slot]) + K + 1, allow_preempt=False,
                    what="speculative verify window"):
                # speculation is optional work: under block pressure the
                # row just ticks this pass instead of preempting a
                # neighbor to make room for drafts
                continue
            want[slot] = (mode, k_eff)
        if not want:
            return 0
        drafts: dict = {}
        disabled = []
        t_draft = time.perf_counter()
        with self.stats.phase(profiler.SPEC_DRAFT):
            for name, drafter in self.drafters.items():
                slots = {s for s, (m, _) in want.items() if m == name}
                if not slots:
                    continue
                ctxs = {s: np.concatenate(
                    [self._req[s].prompt,
                     np.asarray(self._req[s].tokens, np.int32)])
                    for s in slots}
                try:
                    if self._inj is not None \
                            and self._inj.fire("drafter"):
                        raise InjectedFault("chaos point 'drafter'")
                    drafts.update(drafter.draft(
                        ctxs, {s: want[s][1] for s in slots}))
                    self._drafter_streak[name] = 0
                except SupersededError:
                    raise
                except Exception as e:
                    # a drafter is OPTIONAL work: contain the fault —
                    # the rows just tick plain this pass (identity is
                    # untouched; only tokens-per-forward drops) — and
                    # resync the drafter's per-slot mirror state, which
                    # a mid-catch-up failure may have desynchronized
                    self.drafter_faults += 1
                    streak = self._drafter_streak.get(name, 0) + 1
                    self._drafter_streak[name] = streak
                    profiler.warn("serve: %s drafter failed (%s); "
                                  "rows tick plain this pass"
                                  % (name, e))
                    for s in slots:
                        drafter.reset(s)
                    if streak >= DRAFTER_FAULT_LIMIT:
                        disabled.append(name)
        for name in disabled:
            profiler.warn("serve: %s drafter disabled after %d "
                          "consecutive faults" % (name,
                                                  DRAFTER_FAULT_LIMIT))
            drafter = self.drafters.pop(name, None)
            if drafter is not None:
                try:
                    # release its resources NOW (a ModelDrafter pins a
                    # whole mirror-engine KV pool on device) — it will
                    # never draft again; close() is idempotent, so the
                    # server's shutdown sweep re-closing it is harmless
                    drafter.close()
                except Exception as e:
                    profiler.warn("serve: closing disabled %s drafter "
                                  "failed (%s)" % (name, e))
            if self.spec_mode == name:
                self.spec_mode = "off"
        if self.tracer is not None and self.tracer.enabled:
            # one engine-track span per drafter pass (it is batched
            # across rows), mirroring the tick's shared-span discipline
            self.tracer.add(profiler.SPEC_DRAFT, t_draft,
                            time.perf_counter() - t_draft, TID_ENGINE,
                            cat="serve", args={"rows": len(want)})
        n = 0
        for slot, d in drafts.items():
            nd = len(d)
            req = self._req[slot]
            if nd < 1 or req is None:
                continue
            p = req.params
            buf = np.zeros(K + 1, np.int32)
            buf[0] = self._tok[slot]
            buf[1:1 + nd] = d
            t0 = time.perf_counter()
            with self.stats.phase(profiler.SPEC_VERIFY):
                n_acc, emit = self.engine.verify_chunk(
                    slot, buf, int(self._pos[slot]), nd,
                    self._keys[slot], int(self._fold[slot]),
                    p.temperature, p.top_k, p.top_p,
                    aid=int(self._aid[slot]))
            if req.traced:
                # a verify forward is a per-slot dispatch emitting up to
                # K+1 tokens, so one span per FORWARD is O(1)/token-
                # batch, not per-token
                self.tracer.add(profiler.SPEC_VERIFY, t0,
                                time.perf_counter() - t0,
                                request_tid(req.rid), cat="serve",
                                args={"drafted": nd, "accepted": n_acc})
            self.spec_forwards += 1
            self.spec_drafted += nd
            self.spec_accepted += n_acc
            if n_acc < nd:
                self.spec_rollbacks += 1
            n += 1
            self._spec_try[slot] += nd
            self._spec_hit[slot] += n_acc
            if self.decoding > 1 \
                    and self._spec_try[slot] >= SPEC_BACKOFF_PROBE \
                    and self._spec_hit[slot] \
                    < SPEC_BACKOFF_MIN * self._spec_try[slot]:
                self._spec_off[slot] = True
                self.spec_backoffs += 1
            self.spec_emitted += self._append_spec(
                slot, req, [int(t) for t in d[:n_acc]] + [int(emit)])
        self.stats.end_step()           # one spec pass = one stats step
        return n

    def _append_spec(self, slot: int, req: Request, emitted) -> int:
        """Take the verify's emitted tokens one at a time — EOS or the
        token budget can land mid-window, in which case the request
        retires there and the remaining emitted tokens are DISCARDED
        (exactly what the tick-by-tick path would never have generated;
        their K/V rows sit beyond the retired row's position and are
        plain recycled-slot stale data). Returns the count actually
        appended — what the per-forward emission gauge may count."""
        for i, tok in enumerate(emitted):
            err = self._emit(slot, req, tok)
            self._tok[slot] = tok
            self._pos[slot] += 1
            self._fold[slot] += 1
            if err is not None:
                self._retire(req, "error", err)
                return i + 1
            if self._finished(req, tok):
                self._retire(req, "ok")
                return i + 1
        return len(emitted)

    # -------------------------------------------------------------- tick
    def tick(self) -> int:
        """One batched decode step; returns the number of still-decoding
        slots afterwards. Rows still in chunk prefill are skipped (their
        device rows are parked dummies)."""
        if self.paged:
            # every decoding row writes its position's K/V this tick:
            # allocate boundary-crossing blocks and COW-privatize shared
            # ones up front, preempting the youngest other row under
            # pool pressure (a preempted victim drops out of this tick)
            for slot in [s for s, r in enumerate(self._req)
                         if r is not None]:
                if self._req[slot] is None:
                    continue            # preempted by an earlier reserve
                pos = int(self._pos[slot])
                if not self._reserve(slot, pos, pos + 1,
                                     what="decode tick"):
                    raise RuntimeError("block pool cannot hold one "
                                       "decode position; "
                                       "serve_num_blocks is too small")
        decoding = self.decoding
        if decoding == 0:
            return 0
        t0 = time.perf_counter()
        with self.stats.phase(profiler.DECODE_TICK):
            nxt = self.engine.tick(self._tok, self._pos, self._keys,
                                   self._fold, self._temp, self._topk,
                                   self._topp, aid=self._aid)
        if self.tracer is not None and self.tracer.enabled:
            # ONE span per batched tick on the shared engine track —
            # per-request tick spans would be a per-token allocation in
            # the hot loop, exactly what the obs cost budget forbids
            self.tracer.add(profiler.DECODE_TICK, t0,
                            time.perf_counter() - t0, TID_ENGINE,
                            cat="serve", args={"decoding": decoding})
        self.ticks += 1
        self.active_row_ticks += decoding
        for slot, req in enumerate(self._req):
            if req is None:
                continue
            tok = int(nxt[slot])
            err = self._emit(slot, req, tok)
            if err is not None:
                self._retire(req, "error", err)
            elif self._finished(req, tok):
                self._retire(req, "ok")
            else:
                self._tok[slot] = tok
                self._pos[slot] += 1
                self._fold[slot] += 1
        self.stats.end_step()
        return self.decoding

    # ------------------------------------------------------------- drain
    def cancel_active(self, status: str = "cancelled",
                      error: str = "server shutdown") -> int:
        """Finish every in-flight request — decoding AND mid-prefill —
        with the given terminal status (non-drain shutdown cancels; a
        permanently-failed engine fails them typed, serve/resilience.py
        EngineFailedError); returns how many were finished."""
        n = 0
        for req in list(self._req):
            if req is not None:
                self._retire(req, status, error)
                n += 1
        for slot in list(self._prefill_q):
            st = self._pending[slot]
            if st is not None:
                self._retire(st["req"], status, error)
                n += 1
        for rec in self._swapped:           # swapped-out requests hold
            req = rec["req"]                # no slot — finish directly
            req.finish(status, error)
            if self.on_finish is not None:
                self.on_finish(req)
            n += 1
        self._swapped = []
        self.swap_host_bytes = 0
        # un-exported migration records: the requests already finished
        # ("migrated") and the buffers are host-only — just drop them
        # (the fleet router replays from its own journal if it still
        # wants them)
        self.migrated.clear()
        return n
