"""Continuous-batching scheduler: slot bookkeeping between device calls.

Per scheduler pass (driven by serve/server.py's loop):

1. **admit** — pop queued requests FIFO (skipping any whose deadline
   already passed — they finish as ``timeout``) into free slots; each
   admit runs one prefill (the request's TTFT token comes back with it);
2. **tick** — one batched decode step across all slots; active rows
   append their token, free rows are ignored;
3. **retire** — rows that hit EOS, their token budget, or the sequence
   length free their slot immediately, so the NEXT pass can admit into
   it — short requests leave the batch the moment they finish instead of
   convoying behind long ones.

The scheduler is single-threaded by design (only the server's scheduler
thread calls it); cross-thread state (the admission queue, completion
events) lives in the server.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import List, Optional

import numpy as np

from ..utils import profiler

__all__ = ["SamplingParams", "Request", "SlotScheduler"]


@dataclasses.dataclass
class SamplingParams:
    """Per-request generation parameters (defaults come from the server's
    config). ``seed`` feeds ``jax.random.PRNGKey`` exactly like
    ``gpt_decode(rng=PRNGKey(seed))``, so a served request reproduces the
    offline path token for token. ``timeout_ms`` bounds QUEUE time: a
    request still waiting when it expires finishes as ``timeout``
    (0 = no deadline); once admitted a request always runs to
    completion. ``eos``: stop early when this token is produced (it is
    included in the output); None = run to max_tokens."""
    max_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos: Optional[int] = None
    timeout_ms: float = 0.0


class Request:
    """One in-flight generation request: prompt + params + lifecycle
    timestamps. ``done`` is set exactly once, when ``status`` reaches a
    terminal value (ok / timeout / rejected / cancelled)."""

    __slots__ = ("rid", "prompt", "params", "submit_t", "deadline",
                 "admit_t", "first_token_t", "done_t", "tokens", "status",
                 "error", "done", "slot")

    def __init__(self, rid: int, prompt: np.ndarray,
                 params: SamplingParams, submit_t: float):
        self.rid = rid
        self.prompt = prompt
        self.params = params
        self.submit_t = submit_t
        self.deadline = (submit_t + params.timeout_ms / 1e3
                         if params.timeout_ms > 0 else None)
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.tokens: List[int] = []
        self.status = "queued"
        self.error = ""
        self.done = threading.Event()
        self.slot: Optional[int] = None

    def finish(self, status: str, error: str = "") -> None:
        self.status = status
        self.error = error
        self.done_t = time.perf_counter()
        self.done.set()


class SlotScheduler:
    """Owns the per-slot host state mirroring the engine's cache rows."""

    def __init__(self, engine, stats: Optional[profiler.StepStats] = None,
                 on_finish=None):
        self.engine = engine
        self.stats = stats or profiler.StepStats()
        self.on_finish = on_finish      # called with each request that
        #                                 reaches a terminal state here
        n = engine.slots
        self._req: List[Optional[Request]] = [None] * n
        self._free = list(range(n - 1, -1, -1))     # pop() -> lowest slot
        # device-call argument rows; free rows keep harmless dummies
        # (tok 0 / pos 0 / temperature 0 — greedy over garbage, discarded)
        self._tok = np.zeros(n, np.int32)
        self._pos = np.zeros(n, np.int32)
        self._fold = np.zeros(n, np.int32)
        self._keys = np.zeros((n, 2), np.uint32)
        self._temp = np.zeros(n, np.float32)
        self._topk = np.zeros(n, np.int32)
        self._topp = np.ones(n, np.float32)
        # gauges
        self.ticks = 0
        self.active_row_ticks = 0       # sum of active counts over ticks
        self.tokens_generated = 0
        # request ids in admission order (bounded: diagnostic window, not
        # a full history — a hot server admits forever)
        self.admit_order: collections.deque = collections.deque(maxlen=4096)

    # ------------------------------------------------------------- state
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active(self) -> int:
        return self.engine.slots - len(self._free)

    def occupancy(self) -> float:
        return self.active / float(self.engine.slots)

    def batch_efficiency(self) -> float:
        """Mean fraction of slot rows doing useful work per tick — the
        continuous-batching quality gauge (1.0 = every tick fully
        batched)."""
        if not self.ticks:
            return 0.0
        return self.active_row_ticks / float(self.ticks * self.engine.slots)

    # ------------------------------------------------------------- admit
    def admit(self, req: Request) -> None:
        """Prefill ``req`` into a free slot (caller checked free_slots).
        May retire immediately (max_tokens == 1, or the first token is
        EOS)."""
        import jax

        slot = self._free.pop()
        p = req.params
        req.slot = slot
        req.admit_t = time.perf_counter()
        self.stats.record(profiler.QUEUE_WAIT, req.admit_t - req.submit_t)
        self.admit_order.append(req.rid)
        key = np.asarray(jax.random.PRNGKey(p.seed), np.uint32)
        with self.stats.phase(profiler.PREFILL):
            tok = self.engine.prefill(slot, req.prompt, key,
                                      p.temperature, p.top_k, p.top_p)
        # commit this admit's QUEUE_WAIT/PREFILL as their own stats step:
        # folding them into the next tick's end_step would sum every
        # admit since the last tick into one sample (skewing the
        # percentiles) and lose them entirely for requests that retire
        # at admit (max_tokens 1 / instant EOS — no tick ever runs)
        self.stats.end_step()
        req.first_token_t = time.perf_counter()
        req.status = "active"
        req.tokens.append(tok)
        self.tokens_generated += 1
        if self._finished(req, tok):
            self._retire(req, "ok")
            return
        n = len(req.prompt)
        self._tok[slot] = tok
        self._pos[slot] = n            # position the NEXT tick processes
        self._fold[slot] = 1           # next token's fold_in index
        self._keys[slot] = key
        self._temp[slot] = p.temperature
        self._topk[slot] = p.top_k
        self._topp[slot] = p.top_p
        self._req[slot] = req

    def _finished(self, req: Request, tok: int) -> bool:
        p = req.params
        cap = min(p.max_tokens, self.engine.cfg.seq_len - len(req.prompt))
        if len(req.tokens) >= cap:
            return True
        return p.eos is not None and tok == p.eos

    def _retire(self, req: Request, status: str, error: str = "") -> None:
        slot = req.slot
        self._req[slot] = None
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._tok[slot] = 0
        self._pos[slot] = 0
        self._fold[slot] = 0
        self._free.append(slot)
        req.finish(status, error)
        if self.on_finish is not None:
            self.on_finish(req)

    # -------------------------------------------------------------- tick
    def tick(self) -> int:
        """One batched decode step; returns the number of still-active
        slots afterwards."""
        if self.active == 0:
            return 0
        with self.stats.phase(profiler.DECODE_TICK):
            nxt = self.engine.tick(self._tok, self._pos, self._keys,
                                   self._fold, self._temp, self._topk,
                                   self._topp)
        self.ticks += 1
        self.active_row_ticks += self.active
        for slot, req in enumerate(self._req):
            if req is None:
                continue
            tok = int(nxt[slot])
            req.tokens.append(tok)
            self.tokens_generated += 1
            if self._finished(req, tok):
                self._retire(req, "ok")
            else:
                self._tok[slot] = tok
                self._pos[slot] += 1
                self._fold[slot] += 1
        self.stats.end_step()
        return self.active

    # ------------------------------------------------------------- drain
    def cancel_active(self) -> int:
        """Abort every in-flight request (non-drain shutdown); returns
        how many were cancelled."""
        n = 0
        for req in list(self._req):
            if req is not None:
                self._retire(req, "cancelled", "server shutdown")
                n += 1
        return n
