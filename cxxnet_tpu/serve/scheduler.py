"""Continuous-batching scheduler: slot bookkeeping between device calls.

Per scheduler pass (driven by serve/server.py's loop):

1. **admit** — pop queued requests FIFO (skipping any whose deadline
   already passed — they finish as ``timeout``) into free slots; each
   admit restores the longest prefix-cache match into its row and
   enqueues the rest of the prompt as chunk-prefill work (with
   ``serve_prefill_chunk = 0``, the legacy path runs one whole-prompt
   prefill here instead);
2. **prefill** — up to ``serve_prefill_budget`` chunk steps of the
   OLDEST still-prefilling request (``prefill_step``), so a long prompt
   advances without stalling the decode tick for more than one chunk's
   duration; the final (padded) chunk returns the request's first token
   and activates the row;
3. **tick** — one batched decode step across all slots; decoding rows
   append their token, free and still-prefilling rows run on parked
   dummy state (position row_len - 1, outside every pending row's
   prefix; the spot is safe to dirty because a decode row always writes
   its own position before attending to it) and are ignored;
4. **retire** — rows that hit EOS, their token budget, or the sequence
   length offer their complete prompt chunks to the prefix cache and
   free their slot immediately, so the NEXT pass can admit into it —
   short requests leave the batch the moment they finish instead of
   convoying behind long ones.

The scheduler is single-threaded by design (only the server's scheduler
thread calls it); cross-thread state (the admission queue, completion
events) lives in the server.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import List, Optional

import numpy as np

from ..utils import profiler

__all__ = ["SamplingParams", "Request", "SlotScheduler"]


@dataclasses.dataclass
class SamplingParams:
    """Per-request generation parameters (defaults come from the server's
    config). ``seed`` feeds ``jax.random.PRNGKey`` exactly like
    ``gpt_decode(rng=PRNGKey(seed))``, so a served request reproduces the
    offline path token for token. ``timeout_ms`` bounds QUEUE time: a
    request still waiting when it expires finishes as ``timeout``
    (0 = no deadline); once admitted a request always runs to
    completion. ``eos``: stop early when this token is produced (it is
    included in the output); None = run to max_tokens."""
    max_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos: Optional[int] = None
    timeout_ms: float = 0.0


class Request:
    """One in-flight generation request: prompt + params + lifecycle
    timestamps. ``status`` walks queued -> prefill (chunked admit;
    legacy admits jump straight on) -> active -> terminal; ``done`` is
    set exactly once, when ``status`` reaches a terminal value
    (ok / timeout / rejected / cancelled)."""

    __slots__ = ("rid", "prompt", "params", "submit_t", "deadline",
                 "admit_t", "first_token_t", "done_t", "tokens", "status",
                 "error", "done", "slot")

    def __init__(self, rid: int, prompt: np.ndarray,
                 params: SamplingParams, submit_t: float):
        self.rid = rid
        self.prompt = prompt
        self.params = params
        self.submit_t = submit_t
        self.deadline = (submit_t + params.timeout_ms / 1e3
                         if params.timeout_ms > 0 else None)
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.tokens: List[int] = []
        self.status = "queued"
        self.error = ""
        self.done = threading.Event()
        self.slot: Optional[int] = None

    def finish(self, status: str, error: str = "") -> None:
        self.status = status
        self.error = error
        self.done_t = time.perf_counter()
        self.done.set()


class SlotScheduler:
    """Owns the per-slot host state mirroring the engine's cache rows."""

    def __init__(self, engine, stats: Optional[profiler.StepStats] = None,
                 on_finish=None, prefix_cache=None):
        self.engine = engine
        self.stats = stats or profiler.StepStats()
        self.on_finish = on_finish      # called with each request that
        #                                 reaches a terminal state here
        self.chunk = int(engine.chunk)  # 0 = legacy whole-prompt
        self.prefix = prefix_cache if self.chunk > 0 else None
        n = engine.slots
        self._req: List[Optional[Request]] = [None] * n
        self._free = list(range(n - 1, -1, -1))     # pop() -> lowest slot
        # chunk-prefill work: per-slot in-progress state + FIFO of slots
        # still prefilling (the front request's chunks run first, so
        # prefill completion order follows admission order)
        self._pending: List[Optional[dict]] = [None] * n
        self._prefill_q: collections.deque = collections.deque()
        # device-call argument rows; free and still-prefilling rows keep
        # harmless dummies (temperature 0 — greedy over garbage,
        # discarded) PARKED at the row's last position: the batched tick
        # writes every row's K/V at its position unconditionally, so the
        # park spot must be one no later reader can see stale. Chunk
        # masks stop at the prompt (< seq_len <= row_len), which leaves
        # only a decode step at pos row_len - 1 (reachable when seq_len
        # == row_len) — safe because the tick ALWAYS writes a row's own
        # position before attending to it, the invariant every reuse
        # argument here leans on. A parked write can therefore never
        # corrupt a pending row's already-prefilled prefix.
        self._park = engine.row_len - 1
        self._tok = np.zeros(n, np.int32)
        self._pos = np.full(n, self._park, np.int32)
        self._fold = np.zeros(n, np.int32)
        self._keys = np.zeros((n, 2), np.uint32)
        self._temp = np.zeros(n, np.float32)
        self._topk = np.zeros(n, np.int32)
        self._topp = np.ones(n, np.float32)
        # gauges
        self.ticks = 0
        self.active_row_ticks = 0       # sum of decoding counts over ticks
        self.tokens_generated = 0
        self.prefill_chunks = 0         # chunk steps run (chunked path)
        self.requests_prefilled = 0     # requests whose prefill completed
        # request ids in admission order (bounded: diagnostic window, not
        # a full history — a hot server admits forever)
        self.admit_order: collections.deque = collections.deque(maxlen=4096)

    # ------------------------------------------------------------- state
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active(self) -> int:
        """Occupied slots (decoding + still prefilling)."""
        return self.engine.slots - len(self._free)

    @property
    def prefilling(self) -> int:
        """Admitted requests whose prefill has not finished yet."""
        return len(self._prefill_q)

    @property
    def decoding(self) -> int:
        """Rows the next tick advances (prefill complete, not retired)."""
        return sum(r is not None for r in self._req)

    def occupancy(self) -> float:
        return self.active / float(self.engine.slots)

    def batch_efficiency(self) -> float:
        """Mean fraction of slot rows doing useful work per tick — the
        continuous-batching quality gauge (1.0 = every tick fully
        batched)."""
        if not self.ticks:
            return 0.0
        return self.active_row_ticks / float(self.ticks * self.engine.slots)

    # ------------------------------------------------------------- admit
    def admit(self, req: Request) -> None:
        """Claim a free slot for ``req`` (caller checked free_slots).
        Chunked path: restore the longest prefix-cache match into the
        row and enqueue the remaining chunks (prefill_step runs them).
        Legacy path (chunk 0): one whole-prompt prefill, may retire
        immediately (max_tokens == 1, or the first token is EOS)."""
        import jax

        slot = self._free.pop()
        p = req.params
        req.slot = slot
        req.admit_t = time.perf_counter()
        self.stats.record(profiler.QUEUE_WAIT, req.admit_t - req.submit_t)
        self.admit_order.append(req.rid)
        key = np.asarray(jax.random.PRNGKey(p.seed), np.uint32)
        if self.chunk <= 0:
            with self.stats.phase(profiler.PREFILL):
                tok = self.engine.prefill(slot, req.prompt, key,
                                          p.temperature, p.top_k, p.top_p)
            # commit this admit's QUEUE_WAIT/PREFILL as their own stats
            # step: folding them into the next tick's end_step would sum
            # every admit since the last tick into one sample (skewing
            # the percentiles) and lose them entirely for requests that
            # retire at admit (max_tokens 1 / instant EOS — no tick runs)
            self.stats.end_step()
            self.requests_prefilled += 1
            self._activate(req, key, tok)
            return
        start = 0
        if self.prefix is not None:
            with self.stats.phase(profiler.PREFIX_COPY):
                start = self.prefix.copy_into(slot, req.prompt)
        self.stats.end_step()       # commit QUEUE_WAIT (+ PREFIX_COPY)
        req.status = "prefill"
        self._pending[slot] = {"req": req, "key": key, "next": start}
        self._prefill_q.append(slot)

    def prefill_step(self) -> bool:
        """Run ONE chunk of prefill work for the oldest still-prefilling
        request; returns False when none is pending. The final (padded)
        chunk samples the request's first token and activates the row
        for ticking."""
        if not self._prefill_q:
            return False
        slot = self._prefill_q[0]
        st = self._pending[slot]
        req = st["req"]
        p = req.params
        n = len(req.prompt)
        start = st["next"]
        end = min(start + self.chunk, n)
        toks = np.zeros(self.chunk, np.int32)
        toks[:end - start] = req.prompt[start:end]
        with self.stats.phase(profiler.PREFILL_CHUNK):
            tok = self.engine.prefill_chunk(slot, toks, start, end - start,
                                            st["key"], p.temperature,
                                            p.top_k, p.top_p)
            if end >= n:
                # the request's first token: only the FINAL chunk's
                # sample is fetched — mid-prompt chunks stay async so
                # they pipeline on device
                tok = int(tok)
        self.stats.end_step()       # one chunk = one stats step
        self.prefill_chunks += 1
        st["next"] = end
        if end < n:
            return True
        self._prefill_q.popleft()
        self._pending[slot] = None
        self.requests_prefilled += 1
        self._activate(req, st["key"], tok)
        return True

    def _activate(self, req: Request, key: np.ndarray, tok: int) -> None:
        """Prefill finished: record TTFT, take the first token, and arm
        the row for decode ticks (or retire on the spot — max_tokens 1 /
        instant EOS)."""
        slot = req.slot
        p = req.params
        req.first_token_t = time.perf_counter()
        req.status = "active"
        req.tokens.append(tok)
        self.tokens_generated += 1
        if self._finished(req, tok):
            self._retire(req, "ok")
            return
        n = len(req.prompt)
        self._tok[slot] = tok
        self._pos[slot] = n            # position the NEXT tick processes
        self._fold[slot] = 1           # next token's fold_in index
        self._keys[slot] = key
        self._temp[slot] = p.temperature
        self._topk[slot] = p.top_k
        self._topp[slot] = p.top_p
        self._req[slot] = req

    def _finished(self, req: Request, tok: int) -> bool:
        p = req.params
        cap = min(p.max_tokens, self.engine.cfg.seq_len - len(req.prompt))
        if len(req.tokens) >= cap:
            return True
        return p.eos is not None and tok == p.eos

    def _retire(self, req: Request, status: str, error: str = "") -> None:
        slot = req.slot
        if self._pending[slot] is not None:     # cancelled mid-prefill
            # _pending and _prefill_q are always mutated together on the
            # scheduler thread, so membership is an invariant — a
            # ValueError here is a real bug, not a race to paper over
            self._pending[slot] = None
            self._prefill_q.remove(slot)
        elif status == "ok" and self.prefix is not None:
            # offer the row's complete prompt chunks to the prefix cache
            # BEFORE the slot is recycled (the copy-out reads the row)
            with self.stats.phase(profiler.PREFIX_COPY):
                self.prefix.insert_from_row(slot, req.prompt)
            self.stats.end_step()
        self._req[slot] = None
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._tok[slot] = 0
        self._pos[slot] = self._park
        self._fold[slot] = 0
        self._free.append(slot)
        req.finish(status, error)
        if self.on_finish is not None:
            self.on_finish(req)

    # -------------------------------------------------------------- tick
    def tick(self) -> int:
        """One batched decode step; returns the number of still-decoding
        slots afterwards. Rows still in chunk prefill are skipped (their
        device rows are parked dummies)."""
        decoding = self.decoding
        if decoding == 0:
            return 0
        with self.stats.phase(profiler.DECODE_TICK):
            nxt = self.engine.tick(self._tok, self._pos, self._keys,
                                   self._fold, self._temp, self._topk,
                                   self._topp)
        self.ticks += 1
        self.active_row_ticks += decoding
        for slot, req in enumerate(self._req):
            if req is None:
                continue
            tok = int(nxt[slot])
            req.tokens.append(tok)
            self.tokens_generated += 1
            if self._finished(req, tok):
                self._retire(req, "ok")
            else:
                self._tok[slot] = tok
                self._pos[slot] += 1
                self._fold[slot] += 1
        self.stats.end_step()
        return self.decoding

    # ------------------------------------------------------------- drain
    def cancel_active(self) -> int:
        """Abort every in-flight request — decoding AND mid-prefill
        (non-drain shutdown); returns how many were cancelled."""
        n = 0
        for req in list(self._req):
            if req is not None:
                self._retire(req, "cancelled", "server shutdown")
                n += 1
        for slot in list(self._prefill_q):
            st = self._pending[slot]
            if st is not None:
                self._retire(st["req"], "cancelled", "server shutdown")
                n += 1
        return n
