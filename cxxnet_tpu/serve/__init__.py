"""Online inference serving: continuous batching over the KV-cache
decode path (doc/serving.md).

The offline surface (``task=generate`` / ``gpt_decode``) batches
equal-length prompts once and exits; this package keeps the model hot
behind a request queue: a fixed pool of KV-cache slots, per-tick
admission of queued prompts into free slots, one batched decode step
across all active slots, and immediate retirement of finished sequences
— so mixed-length traffic interleaves instead of convoying.

Surfaces: ``InferenceServer`` (programmatic), ``wrapper.Net.serve_*``
(reference-style API), and CLI ``task = serve`` (cli.py). Scale-out:
``serve_tp`` shards one engine over a model-axis mesh (gather-form TP,
bit-identical tokens — engine.py module docstring), and ``ServeRouter``
(router.py) runs N engine replicas behind one prefix- and health-aware
submit API with replay-based failover and merged metrics. Cross-process:
``FleetRouter`` (fleet.py) spawns disaggregated prefill/decode worker
processes behind the binary RPC of rpc.py, migrating KV rows between
tiers over checksummed sockets with journal-replay failover.
"""

from .engine import (DecodeEngine, assert_fused_allclose, auto_num_blocks,
                     fused_attn_tolerance, kv_int8_tolerance)
from .paged import BlockManager, BlockPoolExhausted
from .prefix_cache import PagedPrefixCache, PrefixCache
from .resilience import (DegradationLadder, EngineFailedError,
                         FaultInjector, InjectedFault,
                         SwapCorruptionError)
from .fleet import FleetRouter, parse_tiers
from .lora import (AdapterPool, load_adapter, lora_delta, make_adapter,
                   parse_lora_spec, save_adapter)
from .router import RouterHandle, ServeRouter
from .rpc import FrameError, RpcError, WorkerLostError
from .scheduler import Request, SamplingParams, SlotScheduler
from .server import (AdmissionError, InferenceServer, QueueFullError,
                     QuotaExceededError, ServeResult)
from .speculative import ModelDrafter, NgramDrafter, SpeculativeDecoder
from .tenancy import TenantPolicy, TenantRegistry, TokenBucket

__all__ = ["InferenceServer", "SamplingParams", "ServeResult", "Request",
           "SlotScheduler", "DecodeEngine", "PrefixCache",
           "PagedPrefixCache", "BlockManager", "BlockPoolExhausted",
           "auto_num_blocks", "fused_attn_tolerance",
           "assert_fused_allclose", "kv_int8_tolerance",
           "AdmissionError", "QueueFullError",
           "QuotaExceededError", "NgramDrafter", "ModelDrafter",
           "SpeculativeDecoder", "FaultInjector", "DegradationLadder",
           "InjectedFault", "SwapCorruptionError", "EngineFailedError",
           "ServeRouter", "RouterHandle", "TenantPolicy",
           "TenantRegistry", "TokenBucket", "FleetRouter",
           "parse_tiers", "FrameError", "RpcError", "WorkerLostError",
           "AdapterPool", "parse_lora_spec", "make_adapter",
           "save_adapter", "load_adapter", "lora_delta"]
