"""Command-line runner — equivalent of the reference CLI
(/root/reference/src/cxxnet_main.cpp:16-478).

Usage: ``python -m cxxnet_tpu <config> [k=v ...]``

Tasks (``task = ...``): train (default) / finetune / pred / extract /
generate (autoregressive decode from a GPT-shaped net — prompt_file in,
token ids out; the fused whole-step decode kernel auto-engages).
Config sections: ``data = <name> ... iter = end`` (training set),
``eval = <name> ... iter = end`` (eval sets), ``pred = <path> ... iter = end``
(prediction input). Global pairs outside sections are broadcast to the trainer
and every iterator, as in CreateIterators (cxxnet_main.cpp:214-264).

Behavioral parity: round loop with progress to stdout and eval lines to stderr
in ``[round]\\tname-metric:value`` format (cxxnet_main.cpp:390-403); snapshots
``{model_dir}/%04d.model`` every ``save_model`` rounds; ``continue = 1`` scans
model_dir for the newest snapshot; ``test_io = 1`` exercises the input pipeline
without touching the net.
"""

from __future__ import annotations

import contextlib
import glob
import os
import re
import sys
import time
from typing import List, Optional, Tuple

import numpy as np

from .io import create_iterator
from .nnet.net import Net
from .utils import profiler
from .utils.config import ConfigError, load_config, tokenize

Pairs = List[Tuple[str, str]]


class LearnTask:
    def __init__(self) -> None:
        self.cfg: Pairs = []
        self.task = "train"
        self.net_type = 0
        self.print_step = 100
        self.continue_training = 0
        self.save_period = 1      # reference default: snapshot every round
        self.start_counter = 1
        self.model_in = "NULL"
        self.model_dir = "./"
        self.num_round = 10
        self.max_round = 1 << 30
        self.silent = 0
        self.test_io = 0
        self.prefetch_to_device = 2   # async feed queue depth; 0 = sync path
        self.profile_dir = ""     # 'profile = <dir>': xplane trace dir
        self.step_stats = 0       # 'step_stats = 1': per-round phase timing
        self.nan_check = 0        # 'nan_check = N': check loss every N steps
        self.nan_recover = 0      # 'nan_recover = 1': reload newest snapshot
        self.loss_bound = 0.0     # 'loss_bound = X': |loss| > X also diverged
        self.check_consistency = 0      # per-round replica weight check
        self.save_on_preempt = 1        # SIGTERM -> snapshot + clean exit
        self._preempted = 0  # per-round replica weight check
        self.extract_node_name = ""
        self.output_format = 1
        self.name_pred = "pred.txt"
        self.prompt_file = ""     # task=generate: token-id prompts, one
        #                           space-separated sequence per line
        self.num_gen = 32         # task=generate: tokens to generate
        self.temperature = 0.0    # 0 = greedy, else categorical sampling
        self.generate_out = "gen.txt"
        self.generate_bench = 0   # 1: print warm ms/token after a warmup
        self.generate_int8 = 0    # 1: int8 weight-streaming decode
        self.generate_topk = 0    # sampling: keep k most likely (0 = off)
        self.generate_topp = 1.0  # sampling: nucleus mass (1.0 = off)
        self.serve_slots = 8      # task=serve: KV-cache slot pool size
        self.serve_queue = 32     # task=serve: admission queue bound
        self.serve_timeout_ms = 0.0   # task=serve: per-request queue
        #                               deadline (0 = none)
        self.serve_eos = -1       # task=serve: stop token (-1 = none)
        self.serve_prefill_chunk = 64   # task=serve: chunked-prefill unit
        #                                 (tokens/jitted step; 0 = legacy
        #                                 whole-prompt prefill)
        self.serve_prefill_budget = 1   # task=serve: max prefill chunks
        #                                 interleaved per decode tick
        self.serve_prefix_mb = 32.0     # task=serve: shared-prefix KV
        #                                 cache budget in MiB (0 = off)
        self.serve_paged = 1      # task=serve: paged KV cache — block
        #                           pool + per-row block tables, COW
        #                           prefix sharing, preemption/swap
        #                           (0 = dense slot rows; forced dense
        #                           when serve_prefill_chunk = 0)
        self.serve_block_size = 0   # KV block width in tokens (0 = the
        #                             prefill chunk; must divide it;
        #                             "auto"/-1 = load the persisted
        #                             task=autotune winner from the AOT
        #                             cache, chunk default when none)
        self.serve_num_blocks = 0   # block-pool size (0 = auto: dense-
        #                             equivalent rows + trie headroom,
        #                             or serve_kv_mb when set)
        self.serve_kv_mb = 0.0    # block-pool MiB budget for auto-
        #                           sizing (0 = slots-equivalent formula)
        self.serve_fused_attn = 1   # fused Pallas paged-attention for
        #                             the tick/verify programs where the
        #                             backend supports it (0 = the XLA
        #                             gather formulation, the
        #                             bit-reference; CXN_FUSED_ATTN=0
        #                             env force-disables too)
        self.serve_int8_weights = 0     # stream the serve programs'
        #                                 block matmul weights int8-
        #                                 quantized (per-out-column,
        #                                 quantized once at engine
        #                                 build; speculative verify
        #                                 included; 0 = full-precision
        #                                 weights, a pinned no-op)
        self.serve_int4_weights = 0     # stream them PACKED int4
        #                                 instead: two nibbles per byte,
        #                                 group-wise symmetric scales,
        #                                 fused Pallas dequant-matmul
        #                                 where the geometry gate
        #                                 passes (doc/serving.md "Int4
        #                                 weights"; exclusive with
        #                                 serve_int8_weights; 0 = a
        #                                 pinned no-op)
        self.serve_int4_group = 64      # scale-group size in in-rows
        #                                 for serve_int4_weights (0 =
        #                                 one group = per-out-column
        #                                 scales)
        self.serve_kv_dtype = ""  # KV block-pool stored dtype: "" =
        #                           the compute dtype; "int8" = per-
        #                           block-scaled int8 (values, scales)
        #                           pairs — ~2x tokens per serve_kv_mb,
        #                           halved swap bandwidth; paged only
        #                           (doc/serving.md "Quantized
        #                           serving")
        self.serve_lora = ""      # batched multi-LoRA adapter registry:
        #                           "name:path.npz;name2:path2.npz" —
        #                           per-request adapters served in ONE
        #                           batched tick through a paged device
        #                           pool of factor pages (serve/lora.py,
        #                           doc/serving.md "Batched multi-LoRA");
        #                           paged engine only; "" = a pinned
        #                           STRUCTURAL no-op (no adapter operand
        #                           in the serve programs)
        self.serve_lora_rank = 8  # adapter rank r (must match the
        #                           registered adapter files)
        self.serve_lora_pool_mb = 0.0   # device budget for the adapter
        #                                 pool in MiB (0 = size the pool
        #                                 for the whole registry; smaller
        #                                 budgets page adapters LRU like
        #                                 KV blocks)
        self.serve_chaos = ""     # fault-injection spec (chaos harness;
        #                           grammar in serve/resilience.py, e.g.
        #                           "tick_raise:0.01,seed:7"; the
        #                           CXN_CHAOS env var overrides; empty =
        #                           true no-op)
        self.serve_max_restarts = 3     # engine rebuild budget: faults
        #                                 beyond it fail in-flight
        #                                 requests typed
        self.serve_watchdog_ms = 0.0    # stalled-loop watchdog: no
        #                                 scheduler pass for this long ->
        #                                 teardown + replay restart
        #                                 (0 = off; must exceed the
        #                                 worst-case compile of one pass)
        self.serve_tp = 0         # task=serve: tensor-parallel shard
        #                           count for the decode engine (0/1 =
        #                           single device; needs n_head % tp ==
        #                           0, chunked prefill, and tp local
        #                           devices — gather-form TP, served
        #                           tokens bit-identical;
        #                           doc/serving.md "Sharded &
        #                           replicated serving")
        self.serve_replicas = 1   # task=serve: data-parallel engine
        #                           replicas behind the prefix- and
        #                           health-aware router (serve/router
        #                           .py); 1 = plain single server
        self.serve_router = "prefix"    # router policy: "prefix"
        #                           (longest prefix-affinity match,
        #                           load breaks ties) or "rr"
        #                           (round-robin)
        self.serve_fleet = ""     # task=serve: CROSS-PROCESS fleet tier
        #                           spec, "prefill=N,decode=M" (or a bare
        #                           worker count = decode-only replica
        #                           pool); "" = in-process serving.
        #                           Spawns worker processes behind the
        #                           RPC router (serve/fleet.py)
        self.aot_relabel = -1     # AOT executable device relabeling:
        #                           1 = key executables on positional
        #                           device ids so one persisted artifact
        #                           serves every replica worker of a
        #                           tier; 0 = off; -1 = auto (on for
        #                           fleet workers when aot_cache is set)
        self.fleet_spec = ""      # task=fleet-worker: path of the
        #                           pickled worker spec the router wrote
        self.fleet_tier = ""      # task=fleet-worker: tier name whose
        #                           per-tier kwargs overlay server_kw
        self.serve_degrade = 1    # graceful-degradation ladder: under
        #                           sustained overload disable spec ->
        #                           stop prefix admission -> shed
        #                           deadline-doomed queued requests with
        #                           retry_after_ms hints (0 = off)
        self.serve_tenants = ""   # multi-tenant SLO policies (serve/
        #                           tenancy.py): "name:prio=G,
        #                           blocks=40%,qps=50;..." — priority
        #                           classes, queue/slot/KV-block
        #                           quotas, token-bucket rate limits,
        #                           default deadlines; tenant-aware
        #                           degradation ladder with emergency
        #                           rung 4. Empty = untenanted (a
        #                           pinned no-op).
        self.spec_mode = "off"    # speculative decoding draft source:
        #                           off | ngram (prompt lookup) | model
        self.spec_len = 4         # draft tokens verified per forward
        self.spec_model_netconfig = ""  # spec_mode=model: netconfig file
        #                                 of the small draft model
        self.spec_model_in = ""   # spec_mode=model: draft model snapshot
        #                           (empty = random init — testing only)
        self.lint_compile = 0     # task=lint: also lower/compile-audit the
        #                           jitted steps (pass 2; needs init_model)
        self.lint_threads = 0     # task=lint: also run the CXN3xx
        #                           concurrency pass over the package
        #                           source (pass 3; pure AST, no devices)
        self.aot_cache = ""       # AOT executable cache dir (analysis/
        #                           aot_cache.py; CXN_AOT_CACHE env is
        #                           the fallback): serve/train/decode
        #                           programs load their persisted
        #                           executables instead of compiling on
        #                           a warm start; cxn-lint --compile
        #                           validates the artifacts (CXN210).
        #                           Empty = off (a pinned no-op).
        self.obs_trace = 1        # span tracing (obs/trace.py): cheap
        #                           enough to stay on; 0 disables
        self.obs_trace_buffer = 65536   # span ring capacity (old spans
        #                                 fall off; memory stays bounded)
        self.obs_slow_ms = 0.0    # slow-request exemplar threshold:
        #                           auto-dump the span tree of any
        #                           request over this TTFT/total latency
        #                           (0 = off)
        self.obs_export = ""      # path PREFIX for telemetry dumps:
        #                           <prefix>.metrics.jsonl (periodic
        #                           snapshots), <prefix>.trace.json
        #                           (Chrome trace), <prefix>.spans.jsonl
        #                           (raw spans), <prefix>.prom (final
        #                           exposition); empty = no files
        self.obs_export_interval_s = 10.0   # JSONL snapshot period
        self.prof_every = 64      # device/compiler observatory cadence
        #                           for task=serve: one blocking device-
        #                           time sample per program per N
        #                           executions (live MFU / bandwidth
        #                           gauges; 0 = off). The TRAINER reads
        #                           its own `prof_every` config key
        #                           (default 0 — a sample costs the
        #                           async feed a device sync).
        self.prof_reps = 3        # task=prof: timed executions per
        #                           program (best-of) for the roofline
        #                           table's measured column
        self.net: Optional[Net] = None
        self.itr_train = None
        self._train_feed = None   # DevicePrefetcher over itr_train (async)
        self.itr_evals = []
        self.eval_names = []
        self.itr_pred = None

    def set_param(self, name: str, val: str) -> None:
        if val == "default":
            return
        if name == "print_step":
            self.print_step = int(val)
        elif name == "continue":
            self.continue_training = int(val)
        elif name == "save_model":
            self.save_period = int(val)
        elif name == "start_counter":
            self.start_counter = int(val)
        elif name == "model_in":
            self.model_in = val
        elif name == "model_dir":
            self.model_dir = val
        elif name == "num_round":
            self.num_round = int(val)
        elif name == "max_round":
            self.max_round = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "task":
            self.task = val
        elif name == "test_io":
            self.test_io = int(val)
        elif name == "prefetch_to_device":
            self.prefetch_to_device = int(val)
        elif name == "profile":
            self.profile_dir = val
        elif name == "step_stats":
            self.step_stats = int(val)
        elif name == "nan_check":
            self.nan_check = int(val)
        elif name == "nan_recover":
            self.nan_recover = int(val)
        elif name == "loss_bound":
            self.loss_bound = float(val)
        elif name == "check_consistency":
            self.check_consistency = int(val)
        elif name == "save_on_preempt":
            self.save_on_preempt = int(val)
        elif name == "extract_node_name":
            self.extract_node_name = val
        elif name == "prompt_file":
            self.prompt_file = val
        elif name == "num_gen":
            self.num_gen = int(val)
        elif name == "temperature":
            self.temperature = float(val)
        elif name == "generate_out":
            self.generate_out = val
        elif name == "generate_bench":
            self.generate_bench = int(val)
        elif name == "generate_int8":
            self.generate_int8 = int(val)
        elif name == "generate_topk":
            self.generate_topk = int(val)
        elif name == "generate_topp":
            self.generate_topp = float(val)
        elif name == "serve_slots":
            self.serve_slots = int(val)
        elif name == "serve_queue":
            self.serve_queue = int(val)
        elif name == "serve_timeout_ms":
            self.serve_timeout_ms = float(val)
        elif name == "serve_eos":
            self.serve_eos = int(val)
        elif name == "serve_prefill_chunk":
            self.serve_prefill_chunk = int(val)
        elif name == "serve_prefill_budget":
            self.serve_prefill_budget = int(val)
        elif name == "serve_prefix_mb":
            self.serve_prefix_mb = float(val)
        elif name == "serve_paged":
            self.serve_paged = int(val)
        elif name == "serve_block_size":
            # "auto" is the -1 sentinel: the engine build resolves it
            # through the persisted geometry-autotune winner
            self.serve_block_size = (-1 if str(val).strip().lower()
                                     == "auto" else int(val))
        elif name == "serve_num_blocks":
            self.serve_num_blocks = int(val)
        elif name == "serve_kv_mb":
            self.serve_kv_mb = float(val)
        elif name == "serve_fused_attn":
            self.serve_fused_attn = int(val)
        elif name == "serve_int8_weights":
            self.serve_int8_weights = int(val)
        elif name == "serve_int4_weights":
            self.serve_int4_weights = int(val)
        elif name == "serve_int4_group":
            self.serve_int4_group = int(val)
        elif name == "serve_kv_dtype":
            self.serve_kv_dtype = val
        elif name == "serve_lora":
            self.serve_lora = val
        elif name == "serve_lora_rank":
            self.serve_lora_rank = int(val)
        elif name == "serve_lora_pool_mb":
            self.serve_lora_pool_mb = float(val)
        elif name == "serve_chaos":
            self.serve_chaos = val
        elif name == "serve_max_restarts":
            self.serve_max_restarts = int(val)
        elif name == "serve_watchdog_ms":
            self.serve_watchdog_ms = float(val)
        elif name == "serve_degrade":
            self.serve_degrade = int(val)
        elif name == "serve_tenants":
            self.serve_tenants = val
        elif name == "serve_tp":
            self.serve_tp = int(val)
        elif name == "serve_replicas":
            self.serve_replicas = int(val)
        elif name == "serve_router":
            self.serve_router = val
        elif name == "serve_fleet":
            self.serve_fleet = val
        elif name == "aot_relabel":
            self.aot_relabel = int(val)
        elif name == "fleet_spec":
            self.fleet_spec = val
        elif name == "fleet_tier":
            self.fleet_tier = val
        elif name == "spec_mode":
            self.spec_mode = val
        elif name == "spec_len":
            self.spec_len = int(val)
        elif name == "spec_model_netconfig":
            self.spec_model_netconfig = val
        elif name == "spec_model_in":
            self.spec_model_in = val
        elif name == "name_pred":
            # output path for pred/extract; the `pred = <path>` section
            # marker also sets it (reference cxxnet_main.cpp honors both —
            # the missing branch here was found by cxn-lint dogfooding)
            self.name_pred = val
        elif name == "lint_compile":
            self.lint_compile = int(val)
        elif name == "lint_threads":
            self.lint_threads = int(val)
        elif name == "aot_cache":
            self.aot_cache = val
        elif name == "obs_trace":
            self.obs_trace = int(val)
        elif name == "obs_trace_buffer":
            self.obs_trace_buffer = int(val)
        elif name == "obs_slow_ms":
            self.obs_slow_ms = float(val)
        elif name == "obs_export":
            self.obs_export = val
        elif name == "obs_export_interval_s":
            self.obs_export_interval_s = float(val)
        elif name == "prof_every":
            self.prof_every = int(val)
        elif name == "prof_reps":
            self.prof_reps = int(val)
        elif name == "output_format":
            self.output_format = 1 if val == "txt" else 0
        self.cfg.append((name, val))

    # ------------------------------------------------------------------
    def run(self, argv: List[str]) -> int:
        if len(argv) < 1:
            print("Usage: python -m cxxnet_tpu <config> [k=v ...]")
            return 0
        if not os.path.exists(argv[0]):
            print("cannot open config file %r" % argv[0], file=sys.stderr)
            return 1
        try:
            pairs = load_config(argv[0])
        except ConfigError:
            # the config cannot even tokenize: report it through the lint
            # formatter (file:line finding) instead of a traceback —
            # whatever the task, this is the CXN100 surface
            from .analysis import lint_config_file
            print(lint_config_file(argv[0]).report.format(),
                  file=sys.stderr)
            return 1
        for name, val in pairs:
            self.set_param(name, val)
        cli_overrides = []
        for arg in argv[1:]:
            m = re.match(r"^([^=]+)=(.*)$", arg)
            if m:
                self.set_param(m.group(1), m.group(2))
                cli_overrides.append((m.group(1), m.group(2)))
        if self.task == "lint":
            # lint-and-exit: pass 1 needs no devices and no data files;
            # `lint_compile = 1` additionally builds the net and audits
            # the compiled steps (pass 2)
            return self.task_lint(argv[0], cli_overrides)
        if self.task == "fleet-worker":
            # serving-fleet worker process (serve/fleet.py): the pickled
            # spec carries config + host params + server kwargs, so no
            # netconfig / data plumbing is built here
            if not self.fleet_spec:
                raise ValueError("task=fleet-worker needs fleet_spec=")
            from .serve.fleet import worker_main
            return worker_main(self.fleet_spec, self.fleet_tier)
        lint_level = int(os.environ.get("CXN_LINT", "0") or 0)
        if lint_level:
            # runtime hook: graph/config lint before anything is built,
            # and a default recompilation guard on the trainer's hot
            # steps (explicit lint_recompile_limit in the config wins)
            self._run_startup_lint(argv[0], cli_overrides, lint_level)
            if not any(k == "lint_recompile_limit" for k, _ in self.cfg):
                self.set_param("lint_recompile_limit", "8")
                if lint_level < 2:
                    # level 1 is log-only: a guard trip logs CXN205
                    # through the profiler instead of aborting the run
                    self.set_param("lint_recompile_strict", "0")
        # observability knobs land on the process-global tracer before
        # any task work records a span (doc/observability.md)
        from .obs import trace as obs_trace
        obs_trace.configure(
            enabled=bool(self.obs_trace),
            capacity=self.obs_trace_buffer,
            slow_dir=(self.obs_export + ".slow")
            if self.obs_export and self.obs_slow_ms > 0 else "")
        self.init()
        if lint_level and self.net is not None:
            self._run_step_audit(lint_level)
        if not self.silent:
            print("initializing end, start working")
        if self.task == "serve":
            # serve exports its server-private registry; the wrapping
            # happens inside task_serve where that registry exists
            self.task_serve()
            return 0
        from .obs.metrics import default_registry
        with self._obs_run(default_registry()):
            if self.task in ("train", "finetune"):
                self.task_train()
            elif self.task == "pred":
                self.task_predict()
            elif self.task == "extract":
                self.task_extract()
            elif self.task == "generate":
                self.task_generate()
            elif self.task == "prof":
                self.task_prof()
            elif self.task == "autotune":
                self.task_autotune()
            else:
                raise ValueError("unknown task %r" % self.task)
        return 0

    @contextlib.contextmanager
    def _obs_run(self, registry):
        """Telemetry export around one task when ``obs_export`` is set:
        a background JSONL flusher (cxn-obs-flusher thread) during the
        task, then the end-of-task dump — Chrome trace + raw spans +
        final Prometheus text under the ``obs_export`` prefix."""
        if not self.obs_export:
            yield
            return
        from .obs import MetricsFlusher, export_run
        from .obs import trace as obs_trace
        flusher = MetricsFlusher(registry,
                                 self.obs_export + ".metrics.jsonl",
                                 self.obs_export_interval_s,
                                 extra=lambda: {"task": self.task})
        try:
            yield
        finally:
            flusher.close()
            try:
                paths = export_run(self.obs_export, registry,
                                   obs_trace.get_tracer())
                profiler.log("obs: telemetry written to %s"
                             % ", ".join(paths))
            except OSError as e:
                # same discipline as flusher.close(): a telemetry write
                # failure in a finally must not mask the task's own
                # exception (or crash an otherwise-successful run)
                profiler.warn("obs: end-of-task telemetry dump under %r "
                              "failed (%s)" % (self.obs_export, e))

    # ------------------------------------------------------------- lint
    def task_lint(self, config_path: str, overrides: Pairs) -> int:
        """``task=lint``: run the static analyzer on the config and exit
        nonzero on errors (doc/lint.md). Pass 1 (graph/config) always;
        ``lint_compile = 1`` also builds the net and audits the compiled
        steps (pass 2); ``lint_threads = 1`` also runs the CXN3xx
        concurrency pass over the package source (pass 3)."""
        from .analysis import audit_net, format_step_info, lint_config_file
        t0 = profiler.get_time()
        result = lint_config_file(config_path, extra_pairs=overrides)
        report = result.report
        if self.lint_compile and report.ok():
            self.net = Net(self._trainer_cfg())
            self.net.init_model()
            audit_report, infos = audit_net(self.net)
            report.extend(audit_report.findings)
            for info in infos:
                print("lint: %s" % format_step_info(info))
        if self.lint_threads:
            from .analysis import lint_threads
            lint_threads(report=report)
        print(report.format())
        print("lint: %s in %.0f ms" % (
            "clean" if report.ok() else "FAILED",
            (profiler.get_time() - t0) * 1e3))
        return report.exit_code()

    def _run_startup_lint(self, config_path: str, overrides: Pairs,
                          level: int) -> None:
        """CXN_LINT pass 1 at startup: findings through the profiler log;
        level >= 2 turns lint errors fatal."""
        from .analysis import lint_config_file
        t0 = profiler.get_time()
        with profiler.annotate("cxn-lint/graph"):
            report = lint_config_file(config_path,
                                      extra_pairs=overrides).report
        self._log_lint_report("graph lint", report, t0, level)

    def _run_step_audit(self, level: int) -> None:
        """CXN_LINT pass 2 after init: audit the compiled steps."""
        from .analysis import audit_net, format_step_info
        t0 = profiler.get_time()
        with profiler.annotate("cxn-lint/steps"):
            report, infos = audit_net(self.net)
        for info in infos:
            profiler.log("cxn-lint: %s" % format_step_info(info))
        self._log_lint_report("step audit", report, t0, level)

    @staticmethod
    def _log_lint_report(what: str, report, t0: float, level: int) -> None:
        from .analysis import LintError
        for f in report.findings:
            profiler.log("cxn-lint: %s" % f.format())
        profiler.log("cxn-lint: %s %s (%d error(s), %d warning(s), "
                     "%.0f ms)" % (what,
                                   "clean" if report.ok() else "FAILED",
                                   len(report.errors()),
                                   len(report.warnings()),
                                   (profiler.get_time() - t0) * 1e3))
        if level >= 2 and not report.ok():
            raise LintError("CXN_LINT=2: %s failed with %d error(s)"
                            % (what, len(report.errors())))

    # ------------------------------------------------------------------
    def _trainer_cfg(self) -> Pairs:
        """Global pairs outside iterator sections."""
        out, flag = [], 0
        for name, val in self.cfg:
            if name in ("data", "eval", "pred"):
                flag = 1
                continue
            if name == "iter" and val == "end":
                flag = 0
                continue
            if flag == 0 and name != "iter":
                out.append((name, val))
        return out

    def init(self) -> None:
        if self.task == "train" and self.continue_training:
            if self._sync_latest_model():
                print("Init: continue training from round %d"
                      % self.start_counter)
                self._create_iterators()
                return
            self.continue_training = 0
        if self.model_in == "NULL":
            # prof/autotune run fine on random init: cost/memory/
            # compile/tick time are properties of the program geometry,
            # not the weights
            assert self.task in ("train", "prof", "autotune"), \
                "must specify model_in if not training"
            self.net = Net(self._trainer_cfg())
            self.net.init_model()
        elif self.task == "finetune":
            old = Net()
            old.load_model(self.model_in)
            self.net = Net(self._trainer_cfg())
            self.net.init_model()
            self.net.copy_model_from(old)
        else:
            self.net = Net(self._trainer_cfg())
            self.net.load_model(self.model_in)
        self._create_iterators()

    def _sync_latest_model(self) -> bool:
        """Scan model_dir for the newest %04d.model (cxxnet_main.cpp:135-157)."""
        best = -1
        if os.path.isdir(self.model_dir):
            for f in os.listdir(self.model_dir):
                m = re.match(r"^(\d{4})\.model$", f)
                if m:
                    best = max(best, int(m.group(1)))
        if best < 0:
            return False
        self.net = Net(self._trainer_cfg())
        self.net.load_model(os.path.join(self.model_dir, "%04d.model" % best))
        self.start_counter = best + 1
        return True

    def _create_iterators(self) -> None:
        flag = 0
        evname = ""
        itcfg: Pairs = []
        defcfg: Pairs = []
        sections = []   # (flag, evname, itcfg)
        for name, val in self.cfg:
            if name == "data":
                flag = 1
                continue
            if name == "eval":
                evname = val
                flag = 2
                continue
            if name == "pred":
                flag = 3
                self.name_pred = val
                continue
            if name == "iter" and val == "end":
                assert flag != 0, "wrong configuration file"
                sections.append((flag, evname, list(itcfg)))
                flag = 0
                itcfg = []
                continue
            (itcfg if flag else defcfg).append((name, val))
        # bf16 nets get compute-dtype batches from every pipeline (train,
        # eval, and pred sections) by default — conversion in the prefetch
        # producer thread, half the host->device bytes; an explicit
        # data_dtype in the config wins
        extra: Pairs = []
        if any(k == "precision" and v == "bfloat16" for k, v in defcfg) \
                and not any(k == "data_dtype"
                            for k, _ in defcfg + sum(
                                [s[2] for s in sections], [])):
            extra = [("data_dtype", "bfloat16")]
        for sflag, sname, scfg in sections:
            # section config first, then globals — matching the reference's
            # CreateIterator-then-InitIter(defcfg) order (cxxnet_main.cpp:254-262)
            full = scfg + defcfg + extra
            if sflag == 1 and self.task not in ("pred", "generate", "serve",
                                                "prof"):
                assert self.itr_train is None, "can only have one data section"
                self.itr_train = create_iterator(full)
            elif sflag == 2 and self.task not in ("pred", "generate",
                                                  "serve", "prof"):
                self.itr_evals.append(create_iterator(full))
                self.eval_names.append(sname)
            elif sflag == 3 and self.task in ("pred", "extract"):
                assert self.itr_pred is None, "can only have one pred section"
                self.itr_pred = create_iterator(full)

    # ------------------------------------------------------------------
    def save_model(self) -> None:
        if self.save_period == 0 or (self.start_counter % self.save_period):
            return
        os.makedirs(self.model_dir, exist_ok=True)
        self.net.save_model(os.path.join(self.model_dir,
                                         "%04d.model" % self.start_counter))

    def task_train(self) -> None:
        # preemption-safe training (save_on_preempt=1, default): SIGTERM —
        # what a TPU-pod scheduler sends before reclaiming the slice — sets
        # a flag; the train loop snapshots at the next step boundary and
        # exits cleanly so `continue = 1` resumes. The reference's only
        # failure story was exit(-1) + continue (SURVEY §5.3).
        import signal

        def _on_term(signum, frame):
            self._preempted = signum

        old_handler = None
        if self.save_on_preempt:
            try:
                old_handler = signal.signal(signal.SIGTERM, _on_term)
            except ValueError:          # not the main thread
                old_handler = None
        try:
            # real tracing is the SURVEY §5.1 upgrade over the reference's
            # wall-clock prints: 'profile = <dir>' captures an xplane trace
            # of the training task, viewable in TensorBoard/XProf
            with profiler.trace(self.profile_dir):
                self._task_train()
        finally:
            if old_handler is not None:
                signal.signal(signal.SIGTERM, old_handler)
        if self.profile_dir:
            print("profile: xplane trace written to %s" % self.profile_dir)

    def _diverged(self, loss: float) -> bool:
        """Non-finite loss always counts; saturating nets can diverge to a
        huge-but-finite loss, so 'loss_bound = X' flags |loss| > X too."""
        if not np.isfinite(loss):
            return True
        return self.loss_bound > 0 and abs(loss) > self.loss_bound

    def _recover_from_divergence(self, step: int) -> bool:
        """nan_recover=1: non-finite loss → reload the newest snapshot
        (checkpoint-based recovery is the reference's only failure story,
        cxxnet_main.cpp:135-157; we add the *detection*, SURVEY §5.3)."""
        sys.stderr.write("[%d] step %d: divergent loss detected\n"
                         % (self.start_counter, step))
        if not self.nan_recover or not self._sync_latest_model():
            raise RuntimeError("training diverged at round "
                               "%d step %d" % (self.start_counter, step))
        sys.stderr.write("[%d] recovered from snapshot, resuming at round %d\n"
                         % (self.start_counter, self.start_counter))
        return True

    def _train_feed_iter(self):
        """The round loop's batch source: a DevicePrefetcher over the host
        chain when ``prefetch_to_device > 0`` (placement on a background
        thread, batch k+1's transfer overlapped with step k — see
        io/device_prefetch.py), else the host iterator itself (the old
        synchronous path). ``test_io = 1`` never prefetches: there is no
        net to place onto."""
        if self.prefetch_to_device <= 0 or self.test_io:
            return self.itr_train
        if self._train_feed is None:
            from .io.device_prefetch import DevicePrefetcher
            self._train_feed = DevicePrefetcher(
                self.net.place_batch, self.itr_train,
                depth=self.prefetch_to_device)
        return self._train_feed

    def _close_train_feed(self) -> None:
        if self._train_feed is not None:
            self._train_feed.close()
            self._train_feed = None

    def _task_train(self) -> None:
        try:
            self._task_train_rounds()
        finally:
            self._close_train_feed()

    def _task_train_rounds(self) -> None:
        start = time.time()
        if self.continue_training == 0 and self.model_in == "NULL":
            pass      # fresh start
        else:
            for itr, name in zip(self.itr_evals, self.eval_names):
                sys.stderr.write(self.net.evaluate(itr, name))
            sys.stderr.write("\n")
            sys.stderr.flush()
        if self.itr_train is None:
            return
        if self.test_io:
            print("start I/O test")
        cc = self.max_round
        while self.start_counter <= self.num_round and cc > 0:
            cc -= 1
            if not self.silent:
                print("update round %d" % (self.start_counter - 1))
            sample_counter = 0
            self.net.start_round(self.start_counter)
            feed = self._train_feed_iter()
            feed.before_first()
            t_round = time.perf_counter()
            stats = profiler.StepStats(batch_size=self.net.batch_size) \
                if self.step_stats else None
            restart_round = False
            while True:
                if stats:
                    with stats.phase(profiler.FEED_WAIT):
                        has_next = feed.next()
                else:
                    has_next = feed.next()
                if not has_next:
                    break
                if self.test_io == 0:
                    with contextlib.ExitStack() as es:
                        if stats:
                            es.enter_context(
                                stats.phase(profiler.STEP_DISPATCH))
                        if self.profile_dir:
                            es.enter_context(
                                profiler.step_annotation(self.net.epoch_counter))
                        self.net.update(feed.value())
                    if self.nan_check and \
                            (sample_counter + 1) % self.nan_check == 0 and \
                            self._diverged(self.net.last_loss()):
                        restart_round = self._recover_from_divergence(
                            sample_counter + 1)
                        break
                sample_counter += 1
                if self._preempted:
                    os.makedirs(self.model_dir, exist_ok=True)
                    path = os.path.join(self.model_dir,
                                        "%04d.model" % self.start_counter)
                    self.net.save_model(path)
                    sys.stderr.write(
                        "[%d] preempted (signal %d) at step %d: snapshot "
                        "saved to %s; continue=1 resumes at round %d (the "
                        "partial round is recorded as complete — its "
                        "remaining batches are skipped, unlike the "
                        "reference which loses the whole round)\n"
                        % (self.start_counter, self._preempted,
                           sample_counter, path, self.start_counter + 1))
                    sys.stderr.flush()
                    return
                if stats:
                    stats.end_step()
                if sample_counter % self.print_step == 0 and not self.silent:
                    elapsed = int(time.time() - start)
                    sys.stdout.write("\r%-63s\r" % "")
                    sys.stdout.write("round %8d:[%8d] %d sec elapsed"
                                     % (self.start_counter - 1, sample_counter,
                                        elapsed))
                    sys.stdout.flush()
            if restart_round:
                # recovery replaced self.net — the old feed's place_batch
                # is bound to the dead trainer; rebuild it next round
                self._close_train_feed()
                continue
            if self.check_consistency and self.test_io == 0:
                diff, worst = self.net.check_replica_consistency()
                sys.stderr.write("[%d] replica-consistency max|Δ|=%g%s\n"
                                 % (self.start_counter, diff,
                                    " at %s.%s" % worst if worst else ""))
            if self.test_io == 0:
                with contextlib.ExitStack() as es:
                    if stats:
                        # the round's single train-metric fold + the eval
                        # passes — the only device->host metric syncs
                        es.enter_context(stats.phase(profiler.METRIC_SYNC))
                    sys.stderr.write("[%d]" % self.start_counter)
                    if not self.itr_evals:
                        sys.stderr.write(self.net.evaluate(None, "train"))
                    for itr, name in zip(self.itr_evals, self.eval_names):
                        sys.stderr.write(self.net.evaluate(itr, name))
                    sys.stderr.write("\n")
                    sys.stderr.flush()
            if stats and not self.silent:
                print("\nround %d: %s" % (self.start_counter - 1,
                                          stats.summary()))
            self._record_round_spans(t_round, stats, sample_counter)
            self.save_model()
            self.start_counter += 1
        if not self.silent:
            print("\nupdating end, %d sec in all" % int(time.time() - start))

    def _record_round_spans(self, t0: float, stats, steps: int) -> None:
        """Per-round training spans on the obs tracer's TID_TRAIN
        track: one ``train_round`` span, plus (when ``step_stats = 1``
        timed the phases) aggregate ``feed_wait`` / ``step_dispatch`` /
        ``metric_sync`` child spans laid end to end inside it — each is
        the round's phase TOTAL, not an exact interval (the per-step
        intervals would be a per-step allocation for no new
        information; the totals are what the feed-overlap question
        needs)."""
        from .obs import trace as obs_trace
        tr = obs_trace.get_tracer()
        if not tr.enabled:
            return
        now = time.perf_counter()
        tid = obs_trace.TID_TRAIN
        tr.add("train_round", t0, now - t0, tid, cat="train",
               args={"round": self.start_counter, "steps": steps})
        if stats is None:
            return
        cur = t0
        totals = stats.phase_totals()
        for phase in (profiler.FEED_WAIT, profiler.STEP_DISPATCH,
                      profiler.METRIC_SYNC):
            dur = totals.get(phase, 0.0)
            if dur > 0:
                tr.add(phase, cur, dur, tid, cat="train",
                       args={"aggregate": True})
                cur += dur

    def task_generate(self) -> None:
        """Autoregressive generation from a GPT-shaped model (the inference
        twin of ``pred`` for sequence models — no reference counterpart,
        SURVEY §5.7): reads ``prompt_file`` (one space-separated token-id
        sequence per line, equal lengths batch together), generates
        ``num_gen`` tokens each (``temperature`` 0 = greedy), writes the
        full sequences to ``generate_out``. ``generate_bench = 1`` also
        prints the warm per-token latency (the fused whole-step decode
        kernel auto-engages on one chip, ops/pallas_kernels.py)."""
        import jax

        from .nnet.lm import net_generate, net_gpt_export
        assert self.prompt_file, "task=generate needs prompt_file=<path>"
        prompts = []
        with open(self.prompt_file) as f:
            for line in f:
                line = line.strip()
                if line:
                    prompts.append([int(t) for t in line.split()])
        assert prompts, "prompt_file %r is empty" % self.prompt_file
        if len({len(p) for p in prompts}) != 1:
            raise ValueError(
                "task=generate: all prompt lines must have equal length "
                "(got lengths %s) so they batch into one decode"
                % sorted({len(p) for p in prompts}))
        batch = np.asarray(prompts, np.int32)
        rng = (jax.random.PRNGKey(int(time.time()))
               if self.temperature > 0 else None)
        print("start generating (%d prompts, %d tokens each)..."
              % (batch.shape[0], self.num_gen))
        # export the weight tree ONCE: repeated net_generate calls (the
        # warm-timing pass below) must time the decode, not the export
        export = net_gpt_export(self.net)
        spec = None
        if self.spec_mode != "off":
            # offline draft-and-verify (gpt_decode(speculative=...)):
            # greedy output stays bit-identical, the drafter only
            # changes how many forwards the stream costs
            spec = {"mode": self.spec_mode, "spec_len": self.spec_len,
                    "model": self._spec_model_export(), "stats": {}}
        t0 = time.time()
        out = net_generate(self.net, batch, self.num_gen,
                           temperature=self.temperature, rng=rng,
                           export=export, int8=bool(self.generate_int8),
                           top_k=self.generate_topk,
                           top_p=self.generate_topp, speculative=spec)
        dt = time.time() - t0
        with open(self.generate_out, "w") as fo:
            for row in out:
                fo.write(" ".join(str(int(t)) for t in row) + "\n")
        print("finished generation, write into %s (%.1fs incl. compile)"
              % (self.generate_out, dt))
        if spec is not None:
            print("speculative (%s x%d): accept %.0f%%, %.1f tokens/"
                  "forward" % (self.spec_mode, self.spec_len,
                               100.0 * spec["stats"]["accept_rate"],
                               spec["stats"]["spec_tokens_per_forward"]))
        if self.generate_bench:
            t0 = time.time()
            net_generate(self.net, batch, self.num_gen,
                         temperature=self.temperature, rng=rng,
                         export=export, int8=bool(self.generate_int8),
                         top_k=self.generate_topk,
                         top_p=self.generate_topp, speculative=spec)
            warm = time.time() - t0
            print("generate_bench: %.4f ms/token warm (batch %d, %d new "
                  "tokens)" % (warm * 1e3 / self.num_gen, batch.shape[0],
                               self.num_gen))

    def _spec_model_export(self):
        """(draft_cfg, draft_params) for ``spec_mode = model``: build the
        draft Net from ``spec_model_netconfig`` (a netconfig file with
        the same GPT shape at reduced depth/width), load its snapshot
        from ``spec_model_in`` when given (a random-init draft model is
        a valid but useless drafter — identity never depends on it, only
        accept_rate does). None for the other modes."""
        if self.spec_mode != "model":
            return None
        assert self.spec_model_netconfig, \
            "spec_mode=model needs spec_model_netconfig=<config>"
        sub = LearnTask()
        for name, val in load_config(self.spec_model_netconfig):
            sub.set_param(name, val)
        from .nnet.lm import net_gpt_export
        dnet = Net(sub._trainer_cfg())
        if self.spec_model_in:
            dnet.load_model(self.spec_model_in)
        else:
            dnet.init_model()
        return net_gpt_export(dnet)

    def task_prof(self) -> None:
        """``task=prof``: the device & compiler observatory's offline
        report (doc/observability.md, ``tools/cxn_prof.py`` is the CI
        wrapper). Extracts the XLA cost/memory model of every compiled
        program the config would run — the trainer's four jitted steps,
        plus the serve engine's prefill-chunk / verify-chunk / tick for
        GPT-shaped configs — times each AOT executable ``prof_reps``
        times on zero-filled inputs, and prints the per-program
        roofline table (FLOPs, bytes, arithmetic intensity, peak
        memory, compile seconds, measured time, MFU, achieved-bandwidth
        fraction) followed by the device-memory ledger and per-label
        compile-time totals. The metric gauges land in the process
        registry, so ``obs_export`` snapshots them like any task."""
        from .obs import devprof
        from .obs.metrics import default_registry
        reg = default_registry()
        table = devprof.profile_net(self.net, registry=reg,
                                    time_reps=self.prof_reps)
        from .utils.config import ConfigError
        try:
            from .nnet.lm import net_gpt_export
            gcfg, gparams = net_gpt_export(self.net)
        except ConfigError as e:
            print("prof: serve programs skipped (not GPT-shaped: %s)" % e)
        else:
            from .serve.engine import DecodeEngine, auto_num_blocks
            # a real (2-slot) engine so the serve programs can be TIMED,
            # not just costed; spec_len > 0 always — prof reports the
            # verify program whether or not serving would arm it. The
            # engine mirrors the serving mode: paged (block pool sized
            # for the 2 prof slots) unless serve_paged=0 / chunk=0.
            nb = 0
            if self.serve_paged and self.serve_prefill_chunk > 0:
                nb = (self.serve_num_blocks or auto_num_blocks(
                    gcfg, 2, self.serve_prefill_chunk,
                    block_size=self.serve_block_size,
                    kv_mb=self.serve_kv_mb,
                    kv_dtype=self.serve_kv_dtype))
            eng = DecodeEngine(gcfg, gparams, slots=2,
                               prefill_chunk=self.serve_prefill_chunk,
                               spec_len=max(1, self.spec_len),
                               num_blocks=nb,
                               block_size=self.serve_block_size,
                               fused_attn=bool(self.serve_fused_attn),
                               int8_weights=bool(self.serve_int8_weights),
                               int4_weights=bool(self.serve_int4_weights),
                               int4_group=int(self.serve_int4_group),
                               kv_dtype=self.serve_kv_dtype,
                               aot=self.aot_cache or None)
            # the weight pool the serve programs actually stream — the
            # PACKED byte count under int8/int4 (nibbles + scale
            # planes), exactly what cxn_device_bytes{pool=params}
            # prices, so a quantization knob that silently failed to
            # shrink the pool is visible on the first prof line
            wtag = ("int4(group=%d)" % eng.int4_group
                    if eng.int4_weights else
                    "int8" if eng.int8_weights else
                    ("bf16" if gcfg.dtype == "bfloat16" else "f32"))
            wb = devprof.tree_nbytes((eng._blocks, eng._outer))
            print("serve weight pool: dtype=%s, %.2f MiB resident "
                  "(formulation=%s)"
                  % (wtag, wb / (1 << 20),
                     (eng.int4_formulation or "reference")
                     if eng.int4_weights else "n/a"))
            table.merge(devprof.profile_engine(
                eng, registry=reg, time_reps=self.prof_reps))
            if self.aot_cache:
                # cached-vs-compiled per program: which executables a
                # production startup over this config would LOAD vs pay
                # XLA for (doc/performance.md "AOT executable cache")
                from .analysis.aot_cache import get_cache
                st = eng.aot_status()
                stats = get_cache(self.aot_cache).stats()
                print("aot cache (%s): %s | hits %d, misses %d, stale "
                      "%d, %.1f KiB moved"
                      % (self.aot_cache,
                         ", ".join("%s=%s" % kv for kv in sorted(
                             st.items())) or "no programs",
                         stats["hits"], stats["misses"], stats["stale"],
                         stats["bytes"] / 1024.0))
            eng.close()
        print(table.format_roofline())
        ledger = devprof.register_net_pools(self.net)
        rec = ledger.reconcile()
        print("device memory: " + ", ".join(
            "%s %.1f MiB" % (k, v / (1 << 20))
            for k, v in list(rec["pools"].items())
            + [("live_total", rec["live_total"]),
               ("unaccounted", rec["unaccounted"])]))
        totals = devprof.compile_watch().totals
        if totals:
            print("compile seconds: " + ", ".join(
                "%s %.2fs" % (k, v) for k, v in sorted(totals.items())))

    def task_autotune(self) -> None:
        """``task=autotune``: geometry search for the paged serve
        engine (doc/performance.md "Geometry autotuning"). Sweeps
        ``serve_block_size`` over the divisors of the (seq_len-clamped)
        prefill chunk — each candidate is a different blocks-per-row x
        per-block VMEM footprint, and with it a different
        resident-vs-streaming crossover for the fused kernel — builds
        the real engine per candidate (production ``serve_slots``,
        the same auto-sized pool a server would build), times the AOT
        executables on zero-filled inputs (the ``task=prof`` harness,
        ``prof_reps`` best-of reps), and picks the winner by decode
        tick time (the steady-state cost serving is bound by; prefill
        time is reported for the record). With an ``aot_cache`` armed
        the winner persists under the device-kind + model-geometry key
        (analysis/aot_cache.py:tuned_components) and the WINNER's
        executables stay warm in the cache (losing candidates' files
        are pruned after the pick, so a later ``cxn-lint --compile
        aot_cache=`` CXN210 scan stays clean) — tuning runs ONCE per
        fleet, and a later ``serve_block_size=auto`` build loads the
        winner AND its compiled programs with zero XLA work."""
        import dataclasses
        from .analysis import aot_cache as aot_mod
        from .nnet.lm import net_gpt_export
        from .obs import devprof
        from .obs.metrics import default_registry
        from .serve.engine import DecodeEngine, auto_num_blocks
        if not (self.serve_paged and self.serve_prefill_chunk > 0):
            raise ConfigError(
                "task=autotune tunes the PAGED serve engine: set "
                "serve_paged=1 and serve_prefill_chunk > 0")
        t0 = time.perf_counter()
        gcfg, gparams = net_gpt_export(self.net)
        cache = None
        cache_path = str(self.aot_cache or "") or os.environ.get(
            "CXN_AOT_CACHE", "")
        if cache_path:
            cache = aot_mod.get_cache(cache_path)
        mesh = None
        if self.serve_tp > 1:
            import jax as _jax
            from .parallel.mesh import make_mesh
            devs = _jax.devices()
            if len(devs) < self.serve_tp:
                raise ConfigError(
                    "serve_tp=%d needs %d devices, found %d"
                    % (self.serve_tp, self.serve_tp, len(devs)))
            mesh = make_mesh(devices=devs[:self.serve_tp],
                             model_parallel=self.serve_tp)
        reg = default_registry()
        chunk = min(self.serve_prefill_chunk, gcfg.seq_len)
        cands = [d for d in range(1, chunk + 1) if chunk % d == 0]
        spec = self.spec_len if self.spec_mode != "off" else 0
        reps = max(1, self.prof_reps)

        def _cache_files():
            if not cache_path:
                return set()
            return set(glob.glob(os.path.join(cache_path, "*", "*")))

        rows = []
        created = {}                # bs -> artifact files this sweep wrote
        seen = _cache_files()
        for bs in cands:
            nb = self.serve_num_blocks or auto_num_blocks(
                gcfg, self.serve_slots, self.serve_prefill_chunk,
                block_size=bs, prefix_mb=self.serve_prefix_mb,
                kv_mb=self.serve_kv_mb, kv_dtype=self.serve_kv_dtype)
            eng = DecodeEngine(
                gcfg, gparams, slots=self.serve_slots,
                prefill_chunk=self.serve_prefill_chunk,
                num_blocks=nb, block_size=bs, spec_len=spec,
                fused_attn=bool(self.serve_fused_attn), mesh=mesh,
                int8_weights=bool(self.serve_int8_weights),
                int4_weights=bool(self.serve_int4_weights),
                int4_group=int(self.serve_int4_group),
                kv_dtype=self.serve_kv_dtype, aot=cache)
            table = devprof.profile_engine(eng, registry=reg,
                                           time_reps=reps)
            tick = table.get("serve_tick")
            pre = table.get("serve_prefill_chunk")
            rows.append({
                "block_size": bs, "bpr": eng.bpr,
                "num_blocks": eng.num_blocks,
                "formulation": eng.fused_formulation or "gather",
                "tick_ms": tick.measured_s * 1e3,
                "prefill_chunk_ms":
                    pre.measured_s * 1e3 if pre is not None else 0.0,
            })
            eng.close()
            now = _cache_files()
            created[bs] = now - seen
            seen = now
            if not self.silent:
                r = rows[-1]
                print("autotune: bs=%-4d bpr=%-4d %-9s tick %8.3f ms, "
                      "prefill_chunk %8.3f ms"
                      % (r["block_size"], r["bpr"], r["formulation"],
                         r["tick_ms"], r["prefill_chunk_ms"]))
        winner = min(rows, key=lambda r: r["tick_ms"])
        wall_ms = (time.perf_counter() - t0) * 1e3
        record = dict(winner)
        record["candidates"] = rows
        record["wall_ms"] = wall_ms
        print("autotune: winner serve_block_size=%d (%s, %.3f ms/tick; "
              "%d candidates in %.0f ms)"
              % (winner["block_size"], winner["formulation"],
                 winner["tick_ms"], len(rows), wall_ms))
        if cache is not None:
            from .serve.engine import weight_stream_tag
            comp = aot_mod.tuned_components(
                aot_mod.config_hash(dataclasses.astuple(gcfg)), chunk,
                self.serve_kv_dtype, self.serve_tp if mesh else 1,
                weight_stream_tag(bool(self.serve_int8_weights),
                                  bool(self.serve_int4_weights),
                                  int(self.serve_int4_group)))
            if cache.store_tuned(comp, record):
                print("autotune: winner persisted to %s (load it with "
                      "serve_block_size=auto)" % cache_path)
            # losing candidates' executables are dead weight a CXN210
            # scan (cxn-lint --compile aot_cache=) would flag as stale
            # against the winner geometry: prune ONLY the files this
            # sweep created for non-winner block sizes — pre-existing
            # artifacts (other configs sharing the cache) untouched
            pruned = 0
            for bs, files in created.items():
                if bs == winner["block_size"]:
                    continue
                for f in files:
                    try:
                        os.remove(f)
                        pruned += 1
                    except OSError:
                        pass
            if pruned:
                print("autotune: pruned %d losing-candidate artifact "
                      "file(s) — the cache holds the winner's "
                      "executables only" % pruned)
        else:
            print("autotune: no aot_cache armed — winner NOT persisted "
                  "(set aot_cache=DIR or CXN_AOT_CACHE to let "
                  "serve_block_size=auto load it)")

    def task_serve(self) -> None:
        """Online serving: keep the model hot behind a request queue (the
        continuous-batching scheduler, doc/serving.md). Line-oriented
        loop: each stdin line is one prompt (space-separated token ids,
        lengths may differ — requests are multiplexed onto KV-cache
        slots, NOT batched by length like ``task=generate``); each stdout
        line is the corresponding full sequence, emitted in SUBMISSION
        order ("ERR <status>: <detail>" for requests that timed out or
        were rejected). ``num_gen``/``temperature``/``generate_topk``/
        ``generate_topp``/``serve_eos`` set the per-request defaults;
        ``serve_slots``/``serve_queue``/``serve_timeout_ms`` size the
        scheduler; ``serve_prefill_chunk``/``serve_prefill_budget``/
        ``serve_prefix_mb`` shape the chunked prefill + prefix-reuse path
        (doc/serving.md); ``serve_paged``/``serve_block_size``/
        ``serve_num_blocks``/``serve_kv_mb`` shape the paged KV cache
        (block tables, zero-copy prefix sharing, preemption/swap —
        on by default; ``serve_paged=0`` restores the dense slot pool).
        An explicit ``lint_recompile_limit`` (or the
        CXN_LINT default) extends the recompilation guard to the serve
        engine's prefill/chunk programs. A final metrics summary
        (p50/p95/p99 TTFT, tokens/s, batch efficiency, prefix hit rate)
        goes to stderr."""
        from .nnet.lm import net_gpt_export
        from .serve import InferenceServer, SamplingParams

        cfg, params = net_gpt_export(self.net)
        defaults = SamplingParams(
            max_tokens=self.num_gen, temperature=self.temperature,
            top_k=self.generate_topk, top_p=self.generate_topp,
            eos=self.serve_eos if self.serve_eos >= 0 else None,
            timeout_ms=self.serve_timeout_ms)
        # the trainer's recompile-guard keys (already parsed by Net from
        # the same config pairs, including the CXN_LINT-injected limit 8
        # / non-strict defaults) also govern the serve engine's compiled
        # prefill/chunk signature count
        server_kw = dict(slots=self.serve_slots,
                         queue=self.serve_queue, defaults=defaults,
                         prefill_chunk=self.serve_prefill_chunk,
                         prefill_budget=self.serve_prefill_budget,
                         prefix_mb=self.serve_prefix_mb,
                         paged=bool(self.serve_paged),
                         block_size=self.serve_block_size,
                         num_blocks=self.serve_num_blocks,
                         kv_mb=self.serve_kv_mb,
                         fused_attn=bool(self.serve_fused_attn),
                         int8_weights=bool(self.serve_int8_weights),
                         int4_weights=bool(self.serve_int4_weights),
                         int4_group=int(self.serve_int4_group),
                         kv_dtype=self.serve_kv_dtype,
                         lora=self.serve_lora,
                         lora_rank=int(self.serve_lora_rank),
                         lora_pool_mb=float(self.serve_lora_pool_mb),
                         recompile_limit=self.net.lint_recompile_limit,
                         recompile_strict=bool(
                             self.net.lint_recompile_strict),
                         spec_mode=self.spec_mode,
                         spec_len=self.spec_len,
                         spec_model=self._spec_model_export(),
                         slow_ms=self.obs_slow_ms,
                         prof_every=self.prof_every,
                         chaos=self.serve_chaos,
                         max_restarts=self.serve_max_restarts,
                         watchdog_ms=self.serve_watchdog_ms,
                         degrade=bool(self.serve_degrade),
                         tp=self.serve_tp,
                         tenants=self.serve_tenants,
                         aot_cache=self.aot_cache)
        fleet = bool(self.serve_fleet.strip())
        routed = self.serve_replicas > 1 and not fleet
        if fleet:
            # cross-process fleet: disaggregated prefill/decode worker
            # processes behind the out-of-process RPC router — same
            # stdin/stdout contract; KV rows migrate between tiers over
            # checksummed sockets (serve/fleet.py)
            from .serve import FleetRouter, parse_tiers
            tiers = parse_tiers(self.serve_fleet)
            srv = FleetRouter(cfg, params, prefill=tiers["prefill"],
                              decode=tiers["decode"],
                              aot_relabel=(None if self.aot_relabel < 0
                                           else bool(self.aot_relabel)),
                              **server_kw)
        elif routed:
            # replicated serving: N engines behind the prefix- and
            # health-aware router — same stdin/stdout contract, requests
            # spread (and failed over) across replicas (serve/router.py)
            from .serve import ServeRouter
            srv = ServeRouter(cfg, params,
                              replicas=self.serve_replicas,
                              policy=self.serve_router, **server_kw)
        else:
            srv = InferenceServer(cfg, params, **server_kw)
        if fleet and not self.silent:
            profiler.log(
                "serving: cross-process fleet, %d prefill + %d decode "
                "workers, %d slots/worker, queue %d%s (one prompt per "
                "line; EOF drains and exits)"
                % (tiers["prefill"], tiers["decode"], self.serve_slots,
                   self.serve_queue,
                   ", aot cache " + self.aot_cache
                   if self.aot_cache else ""))
        if not self.silent and not fleet:
            if self.serve_prefill_chunk > 0:
                mode = "prefill chunk %d, prefix cache %s" % (
                    self.serve_prefill_chunk,
                    "%g MiB" % self.serve_prefix_mb
                    if self.serve_prefix_mb > 0 else "off")
                if self.serve_paged:
                    eng = (srv.servers[0] if routed else srv)._engine
                    mode += (", paged KV (%d blocks x %d tokens, "
                             "%.1f MiB %s, %s attention)"
                             % (eng.num_blocks, eng.block_size,
                                eng.cache_bytes() / 2.0 ** 20,
                                eng.kv_dtype,
                                "fused" if eng.fused_attn
                                else "gather"))
            else:
                mode = "whole-prompt prefill, prefix cache off"
            if self.serve_tp > 1:
                mode += ", tp=%d (KV head-sharded)" % self.serve_tp
            if self.serve_int8_weights:
                mode += ", int8 weights"
            if self.serve_int4_weights:
                mode += ", int4 weights (group %d)" % self.serve_int4_group
            if self.serve_lora:
                lp = (srv.servers[0] if routed else srv).lora_pool
                mode += (", lora r%d (%d adapters, %d pool slots)"
                         % (lp.rank, len(lp.registry), lp.size))
            if routed:
                mode += ", %d replicas (%s router)" % (
                    self.serve_replicas, self.serve_router)
            if self.spec_mode != "off":
                mode += ", speculative %s x%d" % (self.spec_mode,
                                                  self.spec_len)
            ten = (srv.servers[0] if routed else srv).tenancy
            if ten is not None:
                mode += ", tenants [%s]" % ", ".join(
                    "%s=%s" % (t, ten.policy_for(t).priority[0].upper())
                    for t in ten.label_names())
            if self.aot_cache:
                st = (srv.servers[0] if routed else srv)._engine \
                    .aot_status()
                loaded = sum(1 for v in st.values() if v == "aot_load")
                mode += ", aot cache %s (%d/%d programs loaded)" % (
                    self.aot_cache, loaded, len(st))
            inj = (srv.servers[0] if routed else srv).fault_injector
            if inj is not None:
                mode += ", CHAOS armed (%s)" % inj.spec
            if self.serve_watchdog_ms > 0:
                mode += ", watchdog %.0f ms" % self.serve_watchdog_ms
            # through the leveled logger, not a bare stderr print: the
            # serve path's human lines carry timestamps so they
            # interleave coherently with the obs JSONL snapshots
            profiler.log("serving: %d slots, queue %d, %s (one prompt "
                         "per line; EOF drains and exits)"
                         % (self.serve_slots, self.serve_queue, mode))
        import collections
        import threading

        from .serve import AdmissionError
        # pending results in submission order, drained by a dedicated
        # printer thread: each response is emitted the moment ITS request
        # finishes — an interactive client waiting on one reply must not
        # have it gated on the arrival of the next stdin line. Printed
        # entries are popped, so a long-lived serve process does not
        # retain every request.
        handles: collections.deque = collections.deque()
        feed = threading.Condition()
        eof = [False]

        def printer() -> None:
            while True:
                with feed:
                    while not handles and not eof[0]:
                        feed.wait()
                    if not handles:
                        return
                    h = handles.popleft()
                if isinstance(h, str):          # pre-rejected line
                    sys.stdout.write(h + "\n")
                else:
                    res = srv.result(h)         # blocks until THIS one
                    if res.status == "ok":
                        sys.stdout.write(" ".join(
                            str(int(t)) for t in res.tokens) + "\n")
                    else:
                        sys.stdout.write("ERR %s: %s\n"
                                         % (res.status, res.error))
                sys.stdout.flush()

        out_thread = threading.Thread(target=printer,
                                      name="cxn-serve-printer",
                                      daemon=True)
        out_thread.start()

        def emit(h) -> None:
            with feed:
                handles.append(h)
                feed.notify()

        # graceful preemption (save_on_preempt=1, default — the
        # trainer's SIGTERM discipline applied to serving): SIGTERM —
        # what a pod scheduler sends before reclaiming the slice —
        # stops ADMISSION (later submits are rejected with
        # retry_after_ms hints while the server reports DRAINING),
        # finishes every queued + in-flight request instead of killing
        # live streams mid-token, flushes the obs exports, and exits 0.
        import signal

        class _ServePreempt(Exception):
            pass

        # the handler raises ONLY while armed (the stdin loop): a
        # SIGTERM landing after EOF — or a scheduler RE-sending the
        # signal while the drain below already runs — must not abort
        # the drain it asked for; it just (re)records the flag
        armed = [True]

        def _on_term(signum, frame):
            self._preempted = signum
            if armed[0]:
                armed[0] = False
                raise _ServePreempt()

        old_handler = None
        if self.save_on_preempt:
            try:
                old_handler = signal.signal(signal.SIGTERM, _on_term)
            except ValueError:          # not the main thread
                old_handler = None
        try:
            es = contextlib.ExitStack()
            # telemetry export follows replica 0 when routed (one JSONL
            # stream; the MERGED cross-replica payload is
            # srv.metrics_text() — doc/observability.md)
            es.enter_context(self._obs_run(
                srv.servers[0].registry if routed else srv.registry))
            try:
                for line in sys.stdin:
                    line = line.strip()
                    if not line:
                        continue
                    # one bad line must not take down the serving loop:
                    # it gets its ERR output slot and the stream
                    # continues
                    try:
                        ids = [int(t) for t in line.split()]
                        # block=True: the stdin loop IS the
                        # backpressure — a full queue pauses reading
                        # instead of dropping
                        emit(srv.submit(ids, block=True))
                    except ValueError:
                        emit("ERR rejected: unparseable prompt line "
                             "(want space-separated ints)")
                    except AdmissionError as e:
                        emit("ERR rejected: %s" % e.reason)
            except _ServePreempt:
                profiler.log(
                    "serve: SIGTERM — graceful preemption: admission "
                    "closing, draining in-flight requests (rejections "
                    "during the drain carry retry_after_ms hints)")
            armed[0] = False            # EOF path: later SIGTERMs only
            #                             set the flag, the drain runs
            srv.drain()
            with feed:
                eof[0] = True
                feed.notify()
            out_thread.join()
            m = srv.metrics()
            if fleet and not self.silent:
                fl = m["fleet"]
                profiler.log(
                    "serve: %d ok / %d timeout / %d rejected over %d "
                    "worker(s) (%d prefill + %d decode); %d "
                    "migration(s), %d KV wire bytes, %d replay(s), %d "
                    "restart(s); %d tokens"
                    % (m["requests"]["completed"],
                       m["requests"]["timeout"],
                       m["requests"]["rejected"], fl["live"],
                       fl["prefill"], fl["decode"], fl["migrations"],
                       fl["kv_wire_bytes"], fl["replays"],
                       fl["restarts"], m["tokens_generated"]))
            if routed and not self.silent:
                # aggregate summary: the per-replica detail lives in the
                # merged scrape payload (metrics_text)
                p95s = ", ".join(
                    "%.1f" % r["ttft_ms"]["p95"] for r in m["replicas"])
                profiler.log(
                    "serve: %d ok / %d timeout / %d rejected over %d "
                    "replicas (routed %s, %d affinity hits, %d "
                    "failovers); ttft p95 per replica [%s] ms; %d "
                    "tokens" % (m["requests"]["completed"],
                                m["requests"]["timeout"],
                                m["requests"]["rejected"],
                                self.serve_replicas, m["routed"],
                                m["affinity_hits"], m["failovers"],
                                p95s, m["tokens_generated"]))
            if not routed and not fleet and not self.silent:
                # gauge text follows the serving mode, so a legacy run
                # reads "prefix cache off" instead of a misleading
                # "prefix hit 0%" (disabled, not ineffective)
                if self.serve_prefill_chunk > 0:
                    extra = "%.1f prefill chunks/req, prefix %s" % (
                        m["prefill_chunks_per_req"],
                        "hit %.0f%%" % (100.0 * m["prefix_hit_rate"])
                        if m["prefix_cache"] is not None else "cache off")
                    if m["paged"] is not None:
                        extra += ("; paged: %d/%d blocks free, "
                                  "%d swaps, %d COW faults"
                                  % (m["paged"]["blocks"]["free"],
                                     m["paged"]["num_blocks"],
                                     m["paged"]["swaps_out"],
                                     m["paged"]["cow_faults"]))
                else:
                    extra = "whole-prompt prefill"
                if self.spec_mode != "off":
                    extra += ("; spec accept %.0f%% (%.1f tok/fwd, "
                              "rollback %.0f%%)"
                              % (100.0 * m["accept_rate"],
                                 m["spec_tokens_per_forward"],
                                 100.0 * m["spec_rollback_rate"]))
                res = m["resilience"]
                if res["restarts"] or res["replayed"] or res["shed"] \
                        or res["faults_injected"]:
                    extra += ("; resilience: %d restart(s), %d "
                              "replayed, %d shed, faults %s"
                              % (res["restarts"], res["replayed"],
                                 res["shed"],
                                 {k: v for k, v in
                                  res["faults_injected"].items()
                                  if v} or "none"))
                profiler.log(
                    "serve: %d ok / %d timeout / %d rejected; "
                    "ttft p50 %.1f / p95 %.1f / p99 %.1f ms; "
                    "batch efficiency %.2f over %d ticks; %s"
                    % (m["requests"]["completed"],
                       m["requests"]["timeout"],
                       m["requests"]["rejected"],
                       m["ttft_ms"]["p50"], m["ttft_ms"]["p95"],
                       m["ttft_ms"]["p99"], m["batch_efficiency"],
                       m["ticks"], extra))
        finally:
            if old_handler is not None:
                signal.signal(signal.SIGTERM, old_handler)
            srv.shutdown(drain=False)       # idempotent after drain()
            try:
                with feed:                  # wake the printer on the
                    eof[0] = True           # error path too (shutdown
                    feed.notify()           # resolved every handle)
                out_thread.join(timeout=10)
            finally:
                es.close()                  # final flush + trace dump
                #                             LAST (after shutdown the
                #                             gauges report the drained
                #                             state) so a telemetry
                #                             write error can't skip
                #                             the printer wakeup/join

    def task_predict(self) -> None:
        assert self.itr_pred is not None, "must specify a pred iterator"
        print("start predicting...")
        with open(self.name_pred, "w") as fo:
            # double-buffered: each batch's forward dispatches before the
            # previous batch's outputs are fetched (Net.forward_iter)
            for out in self.net.forward_iter(self.itr_pred):
                out = out.reshape(out.shape[0], -1)
                vals = out[:, 0] if out.shape[1] == 1 \
                    else np.argmax(out, axis=1).astype(np.float32)
                for v in vals:
                    fo.write("%g\n" % v)
        print("finished prediction, write into %s" % self.name_pred)

    def task_extract(self) -> None:
        assert self.itr_pred is not None, "must specify a pred iterator"
        node = self.extract_node_name
        assert node, "must set extract_node_name"
        print("start extracting...")
        rows = []
        for out in self.net.forward_iter(self.itr_pred, node):
            rows.append(out.reshape(out.shape[0], -1))
        feats = np.concatenate(rows, axis=0) if rows else np.zeros((0, 0))
        if self.output_format == 1:
            with open(self.name_pred, "w") as fo:
                for row in feats:
                    fo.write(" ".join("%g" % v for v in row) + "\n")
        else:
            feats.astype("<f4").tofile(self.name_pred)
            with open(self.name_pred + ".meta", "w") as fo:
                fo.write("%d %d" % (feats.shape[0], feats.shape[1]))
        print("finished extraction, write into %s" % self.name_pred)


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    return LearnTask().run(argv)


if __name__ == "__main__":
    sys.exit(main())
