"""Tracing & profiling — the SURVEY §5.1 first-class upgrade.

The reference's observability is a wall-clock elapsed-seconds print every
``print_step`` batches (/root/reference/src/cxxnet_main.cpp:371-387) plus a
bare ``GetTime()`` helper (/root/reference/src/utils/timer.h:16-31). The TPU
build provides three levels:

1. **StepStats** — host-side per-step phase timers (data wait vs. step
   dispatch) with percentile summaries and throughput. Cheap enough to stay
   on by default; surfaces the classic "input-bound vs compute-bound"
   question the reference answered with ``test_io=1``.
2. **XPlane tracing** — :func:`trace` wraps ``jax.profiler`` so a whole task
   (or any region) is captured for TensorBoard/XProf, with per-step
   boundaries marked via :func:`step_annotation`.
3. **Annotations** — :func:`annotate` names host regions so custom pipeline
   stages show up in the trace alongside XLA ops.

Host-side step times measure *dispatch* latency, not device execution — JAX
dispatch is async. Round-level wall time (which amortizes the final sync)
and the XPlane trace are the ground truth for device time; StepStats'
data-wait fraction is accurate because the iterator runs on the host.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

__all__ = ["StepStats", "trace", "annotate", "step_annotation", "get_time",
           "percentiles", "log", "warn", "FEED_WAIT", "STEP_DISPATCH",
           "METRIC_SYNC", "PREFILL", "PREFILL_CHUNK", "PREFIX_COPY",
           "DECODE_TICK", "QUEUE_WAIT", "SPEC_DRAFT", "SPEC_VERIFY",
           "LINT"]

# canonical phase names of the training hot loop (round 6, async feed):
#   FEED_WAIT     — blocked on the next batch (host iterator, or the async
#                   device feed's queue; ~0 when prefetch hides placement)
#   STEP_DISPATCH — Net.update dispatch (async; device time is NOT here)
#   METRIC_SYNC   — round-boundary metric fold + eval passes (the only
#                   device->host syncs of a round on the device-metric path)
FEED_WAIT = "feed_wait"
STEP_DISPATCH = "step_dispatch"
METRIC_SYNC = "metric_sync"

# canonical phase names of the serving hot loop (serve/ scheduler):
#   PREFILL       — admit: full-prompt forward filling the request's KV slot
#                   (legacy whole-prompt path, serve_prefill_chunk = 0)
#   PREFILL_CHUNK — one fixed-size chunk of prefill work (the chunked
#                   path's unit: the scheduler interleaves these with
#                   decode ticks instead of stalling on a whole prompt)
#   PREFIX_COPY   — prefix-cache traffic at admit/retire: cached-chunk
#                   K/V copied into a fresh row, or a retired row's
#                   prompt chunks copied out into the trie
#   DECODE_TICK   — one batched decode step across all active slots
#   QUEUE_WAIT    — time a request sat in the admission queue before a slot
#                   freed up (recorded at admit via StepStats.record)
#   SPEC_DRAFT    — speculative-decoding draft generation (host n-gram
#                   lookup, or the draft model's catch-up + greedy ticks)
#   SPEC_VERIFY   — one draft-and-verify forward (serve_verify_chunk):
#                   up to spec_len + 1 tokens banked per sample
PREFILL = "prefill"
PREFILL_CHUNK = "prefill_chunk"
PREFIX_COPY = "prefix_copy"
DECODE_TICK = "decode_tick"
QUEUE_WAIT = "queue_wait"
SPEC_DRAFT = "spec_draft"
SPEC_VERIFY = "spec_verify"

# phases counted as "waiting on input" for the wait-fraction line ("data"
# is the pre-round-6 name, kept so external callers' stats still summarize)
_WAIT_PHASES = (FEED_WAIT, "data")


# one-shot phase of the CXN_LINT startup audit (analysis/): recorded via
# StepStats.record so linter cost stays visible next to the hot-loop phases
LINT = "lint"


def get_time() -> float:
    """High-resolution wall clock (GetTime, timer.h:16-31)."""
    return time.perf_counter()


def log(msg: str, level: str = "info") -> None:
    """Timestamped, leveled host-side log line on stderr — the runtime
    channel for subsystem findings (the CXN_LINT startup audit, the
    serve path's banners and fallback notices, and the obs slow-request
    exemplars all route through here, so human logs carry the same
    wall timestamps as the obs JSONL snapshot lines and the two streams
    interleave coherently). ``level`` is ``"info"`` (default) or
    ``"warn"``; warnings are tagged ``[WARN]`` so they grep apart."""
    import sys
    if level not in ("info", "warn"):
        raise ValueError("log level must be 'info' or 'warn', got %r"
                         % (level,))
    tag = " [WARN]" if level == "warn" else ""
    sys.stderr.write("[%s]%s %s\n" % (time.strftime("%H:%M:%S"), tag, msg))
    sys.stderr.flush()


def warn(msg: str) -> None:
    """``log(msg, level="warn")`` shorthand."""
    log(msg, level="warn")


class StepStats:
    """Accumulates named per-step phase durations; summarizes a round.

    Usage::

        stats = StepStats(batch_size=128)
        with stats.phase("data"):
            has_next = itr.next()
        with stats.phase("step"):
            net.update(itr.value())
        stats.end_step()
        ...
        print(stats.summary())   # then stats.clear() for the next round
    """

    def __init__(self, batch_size: int = 0, max_steps: int = 100000,
                 observer=None) -> None:
        """``observer``: optional ``(phase_name, seconds)`` callable
        invoked once per phase at each ``end_step`` — how StepStats
        feeds the obs metrics registry (the server wires it to
        per-phase histograms, obs/metrics.py) instead of callers
        reaching into the private sample dicts."""
        self.batch_size = batch_size
        self.max_steps = max_steps
        self.observer = observer
        self._phases: Dict[str, List[float]] = {}
        self._current: Dict[str, float] = {}
        self._round_start = get_time()
        self.num_steps = 0

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = get_time()
        try:
            yield
        finally:
            self._current[name] = self._current.get(name, 0.0) + get_time() - t0

    def record(self, name: str, seconds: float) -> None:
        """Add an externally measured duration to a phase — for spans the
        context manager cannot bracket (e.g. QUEUE_WAIT: the wait ends in
        the scheduler thread but started at submit in the caller's)."""
        self._current[name] = self._current.get(name, 0.0) + seconds

    def end_step(self) -> None:
        for name, dt in self._current.items():
            lst = self._phases.setdefault(name, [])
            if len(lst) < self.max_steps:
                lst.append(dt)
            if self.observer is not None:
                self.observer(name, dt)
        self._current.clear()
        self.num_steps += 1

    def samples(self, name: str) -> List[float]:
        """Per-step durations recorded for a phase (empty when it never
        ran) — the public read surface; summaries should go through
        this or :meth:`percentiles`, not the private dicts."""
        return list(self._phases.get(name, []))

    def clear(self) -> None:
        self._phases.clear()
        self._current.clear()
        self.num_steps = 0
        self._round_start = get_time()

    # ------------------------------------------------------------- summary
    @staticmethod
    def _pct(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
        return sorted_vals[i]

    def phase_totals(self) -> Dict[str, float]:
        """Per-phase accumulated seconds — including round-level phases
        still pending in the current step (e.g. METRIC_SYNC recorded after
        the last end_step())."""
        totals = {k: sum(v) for k, v in self._phases.items()}
        for k, v in self._current.items():
            totals[k] = totals.get(k, 0.0) + v
        return totals

    def percentiles(self, name: str, qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
        """{p50, p95, p99, ...} of a phase's per-step durations (seconds);
        zeros when the phase never ran. The serving scheduler summarizes
        its PREFILL/DECODE_TICK/QUEUE_WAIT phases through this."""
        return percentiles(self._phases.get(name, []), qs)

    def wait_fraction(self) -> float:
        """Fraction of the round's wall time spent blocked on input
        (FEED_WAIT / legacy "data") — the feed-overlap complement:
        ``overlap = 1 - wait_fraction()`` is ~1 when the async device
        feed fully hides host->device placement behind compute."""
        wall = get_time() - self._round_start
        totals = self.phase_totals()
        return sum(totals.get(p, 0.0) for p in _WAIT_PHASES) / max(wall, 1e-9)

    def summary(self) -> str:
        """One human line: wall, throughput, per-phase mean/p95, feed-wait %."""
        wall = get_time() - self._round_start
        if self.num_steps == 0:
            return "no steps recorded"
        parts = ["%d steps in %.1fs (%.1f steps/s"
                 % (self.num_steps, wall, self.num_steps / max(wall, 1e-9))]
        if self.batch_size:
            parts[-1] += ", %.0f samples/s" % (self.num_steps * self.batch_size
                                               / max(wall, 1e-9))
        parts[-1] += ")"
        totals = self.phase_totals()
        for name in sorted(self._phases):
            vals = sorted(self._phases[name])
            mean = sum(vals) / len(vals)
            parts.append("%s %.1fms/p95 %.1fms"
                         % (name, mean * 1e3, self._pct(vals, 0.95) * 1e3))
        for name in sorted(self._current):
            if name not in self._phases:    # round-level phase (METRIC_SYNC)
                parts.append("%s %.1fms/round" % (name,
                                                  self._current[name] * 1e3))
        for p in _WAIT_PHASES:
            if p in totals and wall > 0:
                parts.append("%s-wait %.0f%%"
                             % (p.split("_")[0],
                                100.0 * totals[p] / wall))
                break
        return "; ".join(parts)


def percentiles(vals: List[float], qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
    """Nearest-rank percentile summary of a sample list: {"p50": ..,
    "p95": .., "p99": ..} (keys follow ``qs``). An EMPTY window — a
    server summarized before any tick ran, a phase that never fired —
    yields consistent zeros rather than raising, and non-finite samples
    are dropped so a poisoned entry can never surface NaN in a stats
    line (the empty-window contract, pinned by tests/test_profiler.py)."""
    import math
    s = sorted(v for v in vals if math.isfinite(v))
    return {"p%g" % (q * 100): StepStats._pct(s, q) for q in qs}


@contextlib.contextmanager
def trace(logdir: Optional[str]):
    """Capture an XPlane trace of the enclosed region into ``logdir``
    (viewable in TensorBoard / XProf). No-op when logdir is falsy."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host region, visible in the XPlane trace."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def step_annotation(step: int):
    """Mark a training-step boundary so XProf groups device ops per step."""
    import jax

    return jax.profiler.StepTraceAnnotation("train", step_num=step)
