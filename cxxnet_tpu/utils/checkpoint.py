"""Sharded checkpointing for mesh-distributed state (SURVEY §5.4 upgrade).

The reference's checkpoint is a single-host binary blob (nnet_impl-inl.hpp:
82-99), which `Net.save_model` mirrors for config-DSL nets. For the modern
stack (GPT flagship with ZeRO/tensor-parallel shardings) gathering to one
host defeats the point of sharding — so this module wraps orbax: every host
writes its own shards, and restore places each leaf directly onto its target
sharding (including *resharding* restores onto a different mesh layout).

API:
    save(path, tree)                      # blocking, atomic directory write
    restore(path, like=tree)              # target shardings = like's
    restore(path, shardings=tree_of_NamedSharding, dtypes=...)

``like`` may be the live state tree (arrays) or a tree of
jax.ShapeDtypeStruct with `.sharding` set.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

__all__ = ["save", "restore"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save(path: str, tree: Any) -> None:
    """Write ``tree`` (pytree of jax.Array / np.ndarray / scalars) to the
    directory ``path``. Atomic: a partial write never looks like a valid
    checkpoint. Multi-host: every process must call this collectively; each
    writes only its addressable shards."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(os.fspath(path)), tree, force=True)
    ckptr.wait_until_finished()


def restore(path: str, like: Any = None, shardings: Any = None) -> Any:
    """Read a checkpoint written by :func:`save`.

    - ``like=tree``: restore with each leaf's shape/dtype/sharding taken
      from the corresponding leaf of ``tree`` (live arrays or
      ShapeDtypeStruct). This is also how you *reshard* on restore: pass a
      target tree placed on the new mesh.
    - ``shardings=tree``: restore with stored shapes/dtypes but the given
      jax.sharding.Sharding per leaf.
    - neither: restore fully replicated on the default device order.
    """
    ckptr = _checkpointer()
    apath = os.path.abspath(os.fspath(path))
    if like is not None:
        target = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding",
                                                            None)), like)
        return ckptr.restore(apath, target)
    if shardings is not None:
        import orbax.checkpoint as ocp

        meta = ckptr.metadata(apath)
        target = jax.tree.map(
            lambda m, s: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=s),
            meta, shardings)
        return ckptr.restore(apath, target)
    return ckptr.restore(apath)
