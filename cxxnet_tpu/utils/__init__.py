"""Foundation utilities (config tokenizer, etc.)."""

from .config import ConfigError, load_config, tokenize

__all__ = ["ConfigError", "load_config", "tokenize"]
