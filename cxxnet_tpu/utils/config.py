"""Config tokenizer: ``name = value`` pairs with comments and quoted values.

Capability parity with the reference tokenizer (/root/reference/src/utils/config.h:40-189):
- ``#`` starts a comment running to end of line (outside quotes)
- tokens are split on ``=`` with arbitrary whitespace
- values may be single- or double-quoted; quoted values may span multiple
  lines and contain ``=``/whitespace/escapes (\\" \\' \\\\ \\n \\t)
- later occurrences of a key do NOT override earlier ones at the tokenizer
  level: the config is an ordered list of (name, value) pairs, because order
  is meaningful to the netconfig DSL (scoped layer/iterator blocks).
"""

from __future__ import annotations

from typing import Iterator, List, Tuple


class ConfigError(ValueError):
    pass


_ESCAPES = {'"': '"', "'": "'", "\\": "\\", "n": "\n", "t": "\t", "r": "\r"}


def tokenize(text: str) -> List[Tuple[str, str]]:
    """Tokenize config text into an ordered list of (name, value) pairs."""
    pairs: List[Tuple[str, str]] = []
    i, n = 0, len(text)

    def skip_ws_comments(i: int) -> int:
        while i < n:
            c = text[i]
            if c == "#":
                while i < n and text[i] != "\n":
                    i += 1
            elif c.isspace():
                i += 1
            else:
                break
        return i

    def read_token(i: int, stop_at_eq: bool) -> Tuple[str, int]:
        c = text[i]
        if c in "\"'":
            quote = c
            i += 1
            out = []
            while True:
                if i >= n:
                    raise ConfigError("unterminated quoted string in config")
                c = text[i]
                if c == "\\" and i + 1 < n and text[i + 1] in _ESCAPES:
                    out.append(_ESCAPES[text[i + 1]])
                    i += 2
                elif c == quote:
                    i += 1
                    break
                else:
                    out.append(c)
                    i += 1
            return "".join(out), i
        out = []
        while i < n:
            c = text[i]
            if c.isspace() or c == "#" or (stop_at_eq and c == "="):
                break
            out.append(c)
            i += 1
        return "".join(out), i

    while True:
        i = skip_ws_comments(i)
        if i >= n:
            break
        name, i = read_token(i, stop_at_eq=True)
        i = skip_ws_comments(i)
        if i >= n or text[i] != "=":
            raise ConfigError("expected '=' after config key %r" % name)
        i += 1
        i = skip_ws_comments(i)
        if i >= n:
            raise ConfigError("expected value after '%s ='" % name)
        value, i = read_token(i, stop_at_eq=False)
        pairs.append((name, value))
    return pairs


def load_config(path: str) -> List[Tuple[str, str]]:
    with open(path, "r") as f:
        return tokenize(f.read())


def iter_config(path: str) -> Iterator[Tuple[str, str]]:
    yield from load_config(path)
