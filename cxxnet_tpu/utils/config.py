"""Config tokenizer: ``name = value`` pairs with comments and quoted values.

Capability parity with the reference tokenizer (/root/reference/src/utils/config.h:40-189):
- ``#`` starts a comment running to end of line (outside quotes)
- tokens are split on ``=`` with arbitrary whitespace
- values may be single- or double-quoted; quoted values may span multiple
  lines and contain ``=``/whitespace/escapes (\\" \\' \\\\ \\n \\t)
- later occurrences of a key do NOT override earlier ones at the tokenizer
  level: the config is an ordered list of (name, value) pairs, because order
  is meaningful to the netconfig DSL (scoped layer/iterator blocks).

Locations: every :class:`ConfigError` raised here carries the 1-based source
line on ``.line`` (and in the message), and ``tokenize(text, with_lines=True)``
returns ``(name, value, line)`` triples — the static analyzer
(:mod:`cxxnet_tpu.analysis`) reports findings as ``file:line`` through these.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple, Union

Pair = Tuple[str, str]
PairLine = Tuple[str, str, int]


class ConfigError(ValueError):
    """Config/graph error. ``line`` is the 1-based source line when the
    failing pair's location is known (tokenizer errors always know it;
    graph errors know it when the caller tokenized ``with_lines``)."""

    def __init__(self, msg: str, line: Optional[int] = None) -> None:
        self.line = line
        super().__init__("line %d: %s" % (line, msg) if line else msg)


_ESCAPES = {'"': '"', "'": "'", "\\": "\\", "n": "\n", "t": "\t", "r": "\r"}


def tokenize(text: str, with_lines: bool = False
             ) -> Union[List[Pair], List[PairLine]]:
    """Tokenize config text into an ordered list of (name, value) pairs,
    or (name, value, line) triples when ``with_lines`` is set (line is the
    1-based line the key starts on)."""
    pairs: list = []
    i, n = 0, len(text)
    line = 1          # advanced incrementally per consumed span (O(n) total)

    def skip_ws_comments(i: int) -> int:
        while i < n:
            c = text[i]
            if c == "#":
                while i < n and text[i] != "\n":
                    i += 1
            elif c.isspace():
                i += 1
            else:
                break
        return i

    def read_token(i: int, stop_at_eq: bool, line0: int) -> Tuple[str, int]:
        c = text[i]
        if c in "\"'":
            quote = c
            i += 1
            out = []
            while True:
                if i >= n:
                    raise ConfigError("unterminated quoted string in config "
                                      "(opened here)", line=line0)
                c = text[i]
                if c == "\\" and i + 1 < n and text[i + 1] in _ESCAPES:
                    out.append(_ESCAPES[text[i + 1]])
                    i += 2
                elif c == quote:
                    i += 1
                    break
                else:
                    out.append(c)
                    i += 1
            return "".join(out), i
        out = []
        while i < n:
            c = text[i]
            if c.isspace() or c == "#" or (stop_at_eq and c == "="):
                break
            out.append(c)
            i += 1
        return "".join(out), i

    def advance(j: int) -> int:
        nonlocal line
        line += text.count("\n", i, j)
        return j

    while True:
        i = advance(skip_ws_comments(i))
        if i >= n:
            break
        key_line = line
        name, j = read_token(i, stop_at_eq=True, line0=line)
        i = advance(j)
        i = advance(skip_ws_comments(i))
        if i >= n or text[i] != "=":
            raise ConfigError("expected '=' after config key %r" % name,
                              line=key_line)
        i += 1
        i = advance(skip_ws_comments(i))
        if i >= n:
            raise ConfigError("expected value after '%s ='" % name,
                              line=key_line)
        value, j = read_token(i, stop_at_eq=False, line0=line)
        i = advance(j)
        pairs.append((name, value, key_line) if with_lines
                     else (name, value))
    return pairs


def load_config(path: str, with_lines: bool = False
                ) -> Union[List[Pair], List[PairLine]]:
    with open(path, "r") as f:
        return tokenize(f.read(), with_lines=with_lines)


def iter_config(path: str) -> Iterator[Pair]:
    yield from load_config(path)
