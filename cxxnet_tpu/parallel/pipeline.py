"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

No reference counterpart (cxxnet predates pipeline parallelism; SURVEY §2.7
lists it as to-be-designed-fresh). TPU-first design: the repeated block's
parameters are *stacked* along a leading layer dim and sharded over the
``pipe`` axis — each device owns ``L/P`` consecutive blocks. Microbatches
flow through the ring with ``ppermute``; each tick every stage applies its
local blocks (a ``lax.scan`` over the stacked params, so the block body
compiles once) and hands its activation to the next stage. The classic GPipe
bubble is ``(P-1)/(M+P-1)``; gradients flow through the schedule because
``scan``/``ppermute``/``where`` are all differentiable — no special backward
schedule is needed under XLA.

Composition: the body runs inside ``shard_map`` spanning ALL mesh axes, so
block functions may freely use collectives over the other axes — e.g.
``ring_attention_inner`` (sequence parallelism) or ``psum`` over ``model``
(megatron-style tensor parallelism) — giving dp x pp x sp x tp in one jitted
step.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS, PIPE_AXIS


def _gpipe_body(params_local, x_local, block_fn: Callable, n_microbatch: int,
                axis_name: str):
    my = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    b_local = x_local.shape[0]
    mb = b_local // n_microbatch
    xs = x_local.reshape((n_microbatch, mb) + x_local.shape[1:])

    def run_local(h):
        return lax.scan(lambda a, p: (block_fn(p, a), None),
                        h, params_local)[0]

    # the zeros inherit xs's varying axes (data/seq); only the pipe axis —
    # over which xs is replicated but the carries diverge — needs casting
    state = lax.pcast(jnp.zeros_like(xs[0]), axis_name, to="varying")
    outbuf = lax.pcast(jnp.zeros_like(xs), axis_name, to="varying")

    def tick(carry, t):
        state, outbuf = carry
        # stage 0 injects microbatch t (clamped: post-M injections never
        # reach the output buffer before the schedule ends)
        inp = jnp.where(my == 0, xs[jnp.minimum(t, n_microbatch - 1)], state)
        out = run_local(inp)
        idx = t - (n_stage - 1)
        valid = (my == n_stage - 1) & (idx >= 0)
        safe = jnp.clip(idx, 0, n_microbatch - 1)
        outbuf = outbuf.at[safe].set(jnp.where(valid, out, outbuf[safe]))
        state = lax.ppermute(out, axis_name,
                             [(i, i + 1) for i in range(n_stage - 1)])
        return (state, outbuf), None

    n_tick = n_microbatch + n_stage - 1
    (state, outbuf), _ = lax.scan(tick, (state, outbuf), jnp.arange(n_tick))
    # only the last stage wrote outputs; share them around the ring
    outbuf = lax.psum(outbuf, axis_name)
    return outbuf.reshape((b_local,) + x_local.shape[1:])


def gpipe(block_fn: Callable, stacked_params, x: jnp.ndarray, mesh: Mesh,
          n_microbatch: int, axis_name: str = PIPE_AXIS,
          batch_axis: Optional[str] = DATA_AXIS,
          extra_spec_axes=(), param_specs=None) -> jnp.ndarray:
    """Run ``x`` through ``L`` stacked blocks pipelined over ``axis_name``.

    ``block_fn(params_one_block, h) -> h`` must preserve ``h``'s shape.
    ``stacked_params`` leaves have leading dim ``L`` divisible by the axis
    size. ``x`` is ``(batch, ...)`` with batch divisible by ``n_microbatch``
    (after data-axis sharding). ``extra_spec_axes`` optionally assigns mesh
    axes to trailing activation dims, e.g. ``("seq",)`` to shard dim 1 for
    ring attention inside the blocks. ``param_specs`` optionally gives a
    pytree (matching ``stacked_params`` or a prefix) of PartitionSpecs whose
    first entry must be the pipe axis — used to additionally shard weight
    dims over ``model`` for megatron-style tensor parallelism inside blocks.
    """
    n_stage = mesh.shape.get(axis_name, 1)
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead % n_stage:
        raise ValueError("gpipe: %d blocks not divisible by %r axis size %d"
                         % (lead, axis_name, n_stage))
    batch_ax = batch_axis if (batch_axis and
                              mesh.shape.get(batch_axis, 1) > 1 and
                              x.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    b_local = x.shape[0] // (mesh.shape[batch_ax] if batch_ax else 1)
    if b_local % n_microbatch:
        raise ValueError(
            "gpipe: per-data-shard batch %d not divisible by n_microbatch %d"
            % (b_local, n_microbatch))

    x_spec = P(batch_ax, *extra_spec_axes)
    if param_specs is None:
        param_specs = P(axis_name)
    body = functools.partial(
        _gpipe_body, block_fn=block_fn, n_microbatch=n_microbatch,
        axis_name=axis_name)
    # check_vma=False: pallas_call inside the body (flash attention for
    # long sequences) trips shard_map's varying-mesh-axes checker (JAX 0.9
    # errors out and itself suggests this flag); semantics are unchanged
    return jax.shard_map(body, mesh=mesh,
                         in_specs=(param_specs, x_spec),
                         out_specs=x_spec,
                         check_vma=False)(stacked_params, x)


__all__ = ["gpipe", "PIPE_AXIS"]
