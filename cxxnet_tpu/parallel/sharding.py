"""Parameter/optimizer-state sharding resolution (GSPMD partitioning).

TPU-native replacement for the reference's two weight-distribution mechanisms
(/root/reference/src/updater/async_updater-inl.hpp):

- ``fullc_gather`` (async_updater-inl.hpp:67-92) — sharding huge FC layers'
  *work* across devices — becomes true tensor parallelism: weight matrices are
  sharded over the ``model`` mesh axis via ``NamedSharding`` and XLA GSPMD
  inserts the all-gather/reduce-scatter pattern automatically.
- ``update_on_server`` (async_updater-inl.hpp:200-205) — optimizer state living
  on parameter servers — becomes ZeRO-style optimizer-state sharding over the
  ``data`` axis (``shard_optimizer = 1``): each data-parallel rank updates a
  slice of the momentum/variance tensors; XLA partitions the update op along
  the sharded dim and re-gathers the (replicated) weights.

Layers declare *logical* axis names per weight tag via ``Layer.param_axes``;
this module checks divisibility against the actual mesh and degrades to
replication per-dimension when a shard would not divide evenly, so the same
model config runs on any mesh shape.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS

AxesSpec = Optional[Tuple[Optional[str], ...]]


def _fit_spec(axes: AxesSpec, shape: Sequence[int], mesh: Mesh) -> list:
    """Drop requested mesh axes that don't exist / don't divide the dim.

    A per-dim entry may be a tuple of axis names, meaning "the first axis
    that is present (>1) and divides the dim" — e.g. the MoE expert dim
    declares ``("expert", "model")``: shard over a dedicated expert axis
    when the mesh has one, else fall back to the model axis."""
    out = [None] * len(shape)
    if axes is None:
        return out
    for d, ax in enumerate(axes[:len(shape)]):
        if ax is None:
            continue
        for cand in (ax if isinstance(ax, tuple) else (ax,)):
            size = mesh.shape.get(cand, 1)
            if size > 1 and shape[d] % size == 0:
                out[d] = cand
                break
    return out


def param_sharding(mesh: Mesh, axes: AxesSpec,
                   shape: Sequence[int]) -> NamedSharding:
    """NamedSharding for one weight tensor from its layer-declared axes."""
    return NamedSharding(mesh, P(*_fit_spec(axes, shape, mesh)))


def _data_shard_spec(spec: list, shape: Sequence[int], mesh: Mesh) -> list:
    """Additionally shard the first free (unsharded, divisible) dim over
    the ``data`` axis."""
    nd = mesh.shape.get(DATA_AXIS, 1)
    if nd > 1:
        for d, cur in enumerate(spec):
            if cur is None and shape[d] % nd == 0:
                spec[d] = DATA_AXIS
                break
    return spec


def opt_state_sharding(mesh: Mesh, axes: AxesSpec, shape: Sequence[int],
                       zero: int) -> NamedSharding:
    """Sharding for optimizer-state tensors mirroring ``w``. With ``zero``
    >= 1, additionally shard over the ``data`` axis — each DP rank owns a
    slice of momentum/variance (ZeRO-1; levels 2/3 change the gradient
    and parameter placement, not this one)."""
    spec = _fit_spec(axes, shape, mesh)
    if zero >= 1:
        spec = _data_shard_spec(spec, shape, mesh)
    return NamedSharding(mesh, P(*spec))


def resolve_shardings(mesh: Mesh, graph, layers,
                      params: Dict[str, Dict],
                      zero: int) -> Tuple[Dict, Dict]:
    """Per-tensor shardings for the params / opt-state pytrees.

    ``zero`` (the ``shard_optimizer`` config level):
      0 — nothing sharded over ``data``;
      1 — optimizer state sharded (ZeRO-1);
      2 — + gradients reduce-scattered instead of all-reduced (ZeRO-2;
          applied by the train step via a sharding constraint on grads);
      3 — + parameters themselves sharded over ``data`` (ZeRO-3 / FSDP:
          XLA all-gathers each weight at its use sites).

    Returns ``(param_sh, opt_sh)`` keyed ``[layer_key][tag]``. ``opt_sh`` is a
    per-weight sharding applied to every tensor of that weight's optimizer
    state (momentum, m/v, ...) — they all have the weight's shape.
    """
    param_sh: Dict[str, Dict] = {}
    opt_sh: Dict[str, Dict] = {}
    for spec, layer in zip(graph.layers, layers):
        if spec.type == "share":
            continue
        lkey = spec.key()
        if lkey not in params or lkey in param_sh:
            continue
        param_sh[lkey] = {}
        opt_sh[lkey] = {}
        for tag, w in params[lkey].items():
            axes = layer.param_axes(tag)
            if zero >= 3:
                # ZeRO-3: params placed exactly like their optimizer
                # state (one shard-selection code path, layouts cannot
                # drift)
                param_sh[lkey][tag] = opt_state_sharding(
                    mesh, axes, w.shape, zero)
            else:
                param_sh[lkey][tag] = param_sharding(mesh, axes, w.shape)
            opt_sh[lkey][tag] = opt_state_sharding(mesh, axes, w.shape, zero)
    return param_sh, opt_sh


__all__ = ["param_sharding", "opt_state_sharding", "resolve_shardings",
           "DATA_AXIS", "MODEL_AXIS"]
