"""Device mesh + sharding utilities — the TPU-native replacement for the
reference's device lists and mshadow-ps parameter server (SURVEY §5.8).

The reference's ``dev = gpu:0-3`` (nnet_impl-inl.hpp:32-51) becomes a 1-D
``jax.sharding.Mesh`` over a ``data`` axis; batch tensors are sharded along it
and parameters are replicated, so XLA inserts the gradient all-reduce (psum)
that Push/PullReq used to perform, overlapping it with backprop automatically.
Higher-dimensional meshes (data x model) are built here too for the tensor/
pipeline-parallel paths.
"""

from .mesh import (DATA_AXIS, MODEL_AXIS, batch_sharding, make_mesh,
                   replicated_sharding)

__all__ = ["DATA_AXIS", "MODEL_AXIS", "batch_sharding", "make_mesh",
           "replicated_sharding"]
