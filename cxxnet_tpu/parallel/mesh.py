"""Mesh construction and common shardings.

``parse_devices`` understands the reference's device-list syntax
(``dev = gpu:0-3`` / ``dev = gpu:0,1,2``, nnet_impl-inl.hpp:32-51) mapped onto
TPU: ``dev = tpu`` (all chips), ``dev = tpu:0-3``, ``dev = cpu``. The device
count becomes the size of the 1-D ``data`` mesh axis; an optional
``model_parallel = k`` splits a second ``model`` axis for tensor-parallel
layers (the fullc_gather descendant).
"""

from __future__ import annotations

import re
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def parse_devices(dev: str) -> Sequence[jax.Device]:
    """Device-list string -> list of jax devices."""
    dev = dev.strip()
    if dev in ("", "cpu", "gpu", "tpu"):
        return jax.devices()
    m = re.match(r"^[a-z]+:([\d,\-]+)$", dev)
    if not m:
        raise ValueError("invalid device spec %r" % dev)
    ids = []
    for part in m.group(1).split(","):
        if "-" in part:
            a, b = part.split("-")
            ids.extend(range(int(a), int(b) + 1))
        else:
            ids.append(int(part))
    all_devices = jax.devices()
    if max(ids) >= len(all_devices):
        raise ValueError("device id %d out of range (%d devices available)"
                         % (max(ids), len(all_devices)))
    return [all_devices[i] for i in ids]


def make_mesh(dev: str = "", model_parallel: int = 1, seq_parallel: int = 1,
              pipeline_parallel: int = 1, expert_parallel: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, pipe, seq, expert, model) mesh; size-1 axes cost
    nothing.

    Axis order is outermost-to-innermost communication intensity (the
    scaling-book ordering): ``pipe`` stages exchange one activation per tick,
    ``seq`` rings K/V shards, ``expert`` all-to-alls token blocks per MoE
    layer, ``model`` all-reduces every layer — so the chattiest axes map to
    the most adjacent chips.
    """
    if devices is None:
        devices = parse_devices(dev)
    n = len(devices)
    for name, k in (("model_parallel", model_parallel),
                    ("seq_parallel", seq_parallel),
                    ("pipeline_parallel", pipeline_parallel),
                    ("expert_parallel", expert_parallel)):
        if k <= 0:
            raise ValueError("%s must be >= 1, got %d" % (name, k))
    prod = model_parallel * seq_parallel * pipeline_parallel * expert_parallel
    if n % prod:
        raise ValueError(
            "pipeline_parallel=%d * seq_parallel=%d * expert_parallel=%d * "
            "model_parallel=%d must divide device count %d"
            % (pipeline_parallel, seq_parallel, expert_parallel,
               model_parallel, n))
    arr = np.asarray(devices).reshape(
        n // prod, pipeline_parallel, seq_parallel, expert_parallel,
        model_parallel)
    return Mesh(arr, (DATA_AXIS, PIPE_AXIS, SEQ_AXIS, EXPERT_AXIS,
                      MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis; replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
