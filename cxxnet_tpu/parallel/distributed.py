"""Multi-host (multi-process) runtime — the dist-PS replacement.

Reference counterpart: the ps-lite worker/server deployment
(/root/reference/src/nnet/nnet_ps_server.cpp, mpi.conf) where each worker
process trained on its dataset shard and gradients met on parameter servers.
TPU-native shape: one JAX process per host, all chips joined into one global
mesh by ``jax.distributed``; gradients meet in XLA collectives over ICI/DCN
(no servers). The data side keeps the reference's contract — each process
reads only its shard (``dist_worker_rank``/``dist_num_worker``, imgbin.py) —
and per-host batches are assembled into one global sharded array with
``jax.make_array_from_process_local_data``.

Environment variables (launcher-agnostic, the mpi.conf analogue):
  CXXNET_COORDINATOR  host:port of process 0
  CXXNET_NUM_WORKER   total process count
  CXXNET_RANK         this process's index
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

_initialized = False


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join the global runtime. No-op when single-process (nothing
    configured) or already initialized. Arguments fall back to the
    CXXNET_* environment variables."""
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("CXXNET_COORDINATOR", "")
    if num_processes is None:
        num_processes = int(os.environ.get("CXXNET_NUM_WORKER", "0") or 0)
    if process_id is None:
        pid = os.environ.get("CXXNET_RANK", "")
        process_id = int(pid) if pid else None
    if not coordinator or num_processes <= 1:
        return                      # single-host run, nothing to join
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_multi_host() -> bool:
    return jax.process_count() > 1


def global_batch(mesh: Mesh, sharding: NamedSharding,
                 host_local: np.ndarray) -> jax.Array:
    """Assemble one *global* array from this process's local batch slice.

    Single-process: plain ``device_put``. Multi-host: each process passes its
    local rows (global batch = concat over processes in process order) and
    gets back a handle to the global array, with only local shards resident
    — the input-pipeline contract of the reference's per-rank .lst shards
    (iter_thread_imbin_x-inl.hpp:119-130) mapped onto jax process semantics.
    """
    if not is_multi_host():
        return jax.device_put(host_local, sharding)
    return jax.make_array_from_process_local_data(sharding, host_local)


def local_rows(arr) -> np.ndarray:
    """This process's rows of a batch-sharded global array, in global row
    order. Single-process: the whole array. Multi-host: a global array's
    value cannot be fetched (its shards span other processes); each process
    reads back exactly the rows it contributed via ``global_batch``, so
    per-process metrics/predictions line up with its local labels — the
    per-worker accounting of the reference's dist mode."""
    if not is_multi_host():
        return np.asarray(arr)
    # one shard per distinct dim-0 slice: replicas across other mesh axes
    # (model/pipe) or GSPMD replication hold duplicate rows
    by_start = {}
    for s in arr.addressable_shards:
        by_start.setdefault(s.index[0].start or 0, s)
    return np.concatenate(
        [np.asarray(by_start[st].data) for st in sorted(by_start)], axis=0)


def _allgather_f64(arr: np.ndarray) -> np.ndarray:
    """process_allgather that preserves f64 exactly: JAX's x32 default
    would silently downcast f64 payloads to f32 (which breaks both the
    checksum ids and the metric sums), so the payload crosses the wire
    bit-packed as uint32 pairs."""
    from jax.experimental import multihost_utils
    a = np.ascontiguousarray(arr, np.float64)
    packed = a.view(np.uint32).reshape(a.shape[:-1] + (a.shape[-1] * 2,))
    gathered = np.asarray(multihost_utils.process_allgather(packed),
                          np.uint32)
    return gathered.view(np.float64)


def host_psum(values: np.ndarray) -> np.ndarray:
    """Sum a small host-side array across all processes (identity when
    single-process). The cross-process reduction the reference's
    per-worker metric accounting lacked: with it every rank can print the
    *global* eval line instead of its own shard's
    (utils/metric.h:175-236 kept per-worker sums)."""
    if not is_multi_host():
        return np.asarray(values)
    return _allgather_f64(np.atleast_2d(np.asarray(values, np.float64))) \
        .reshape((process_count(),) + np.asarray(values).shape).sum(axis=0)


def host_allgather_rows(rows: np.ndarray) -> np.ndarray:
    """All-gather a small (n, k) f64 host array across processes ->
    (n_processes * n, k), value-exact (see _allgather_f64).
    Single-process: identity. Requires every process to contribute the
    same shape (true for symmetric meshes)."""
    if not is_multi_host():
        return np.asarray(rows)
    return _allgather_f64(np.asarray(rows, np.float64)) \
        .reshape(-1, rows.shape[-1])


def multihost_assert_equal(row, what: str) -> None:
    """Raise if ``row`` (a small list/array of floats) differs on any
    process. Collective: every process must call it at the same point
    (like the save/get paths, the callers are SPMD round boundaries).
    Used by the async device feed to verify the per-epoch batch count —
    a mismatch means the processes' feeds diverged, and the next epoch's
    ``global_batch`` placements would pair wrong slices. No-op
    single-process."""
    if not is_multi_host():
        return
    mine = np.atleast_2d(np.asarray(row, np.float64))
    rows = host_allgather_rows(mine).reshape(process_count(), -1)
    if not np.all(rows == rows[0]):
        raise RuntimeError(
            "%s differs across processes: %s (rank %d has %s) — the SPMD "
            "contract requires every process to run the same sequence"
            % (what, rows.tolist(), process_index(), mine.ravel().tolist()))


__all__ = ["init_distributed", "process_index", "process_count",
           "is_multi_host", "global_batch", "local_rows", "host_psum",
           "host_allgather_rows", "multihost_assert_equal"]
