"""1F1B pipeline schedule over a ``pipe`` mesh axis.

Round-5 answer to the gpipe schedule's two structural costs
(parallel/pipeline.py, VERDICT r4 weak #1): every stage computed every
tick (the ``(P-1)/(M+P-1)`` bubble was *garbage compute*, in the forward
and again in its autodiff), and the whole output buffer was psum'd over
the pipe axis although only the last stage wrote it.

This module runs the classic one-forward-one-backward schedule instead —
with the loss computed IN the last stage, so nothing larger than a scalar
(plus the entry cotangent the embedding backward needs) ever crosses the
pipe axis:

- tick ``t``, stage ``s`` forwards microbatch ``t - s`` and backwards
  microbatch ``t - 2(P-1) + s`` (the last stage backwards a microbatch the
  same tick it forwards it); invalid slots are ``lax.cond``-skipped, not
  computed on garbage.
- backward slots rebuild the stage's VJP from the stashed stage-INPUT
  activation (``jax.vjp`` recompute — activation checkpointing at stage
  granularity, the same recompute the gpipe path paid via
  ``jax.checkpoint``); the stash holds at most ``min(M, 2P-1)``
  microbatch inputs per stage, so activation memory is **O(P)**,
  independent of the microbatch count (gpipe's differentiated scan held
  O(M) plus every tick's carries).
- block-parameter gradients accumulate per stage and stay pipe-sharded
  (zero collectives); the loss/head gradients and the scalar loss psum
  over ``pipe`` + ``data``; the entry cotangent psums over ``pipe`` only
  (it lives on stage 0).

Scheduling math: forward of (s, m) at tick ``m + s`` consumes the
activation stage s-1 ppermuted at tick ``m + s - 1``; backward of (s, m)
at ``m + 2(P-1) - s`` consumes the cotangent stage s+1 ppermuted at
``m + 2(P-1) - s - 1``; total ticks ``M + 2(P-1)``. Per-stage in-flight
stash: forwards done minus backwards done = ``2(P-1-s) + 1`` slots (stage
0 worst), all < ``2P-1``, so slot ``m mod K`` with ``K = min(M, 2P-1)``
never collides.

Composition: dp x pp x tp (the block body's megatron psum over ``model``
works unchanged — the shard_map spans all axes). Sequence/expert
parallelism stay on the gpipe path; ``models/gpt.py`` routes by
``GPTConfig.pipeline_schedule``. No reference counterpart (SURVEY §2.7:
pipeline parallelism is a designed-fresh axis).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS, PIPE_AXIS


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_in(x, axis_name: str):
    """Megatron's ``f`` operator: identity forward, psum backward. Marks
    the ENTRY of a tensor-parallel region inside a manually-VJP'd body
    (this schedule backwards with ``jax.vjp`` per stage, where shard_map's
    automatic replication-aware transposes are unavailable): the same
    replicated activation is consumed by every model shard's partial
    compute, so its cotangent is the SUM of the per-shard partials."""
    return x


def _tp_in_fwd(x, axis_name):
    return x, None


def _tp_in_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


tp_region_in.defvjp(_tp_in_fwd, _tp_in_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_out(x, axis_name: str):
    """Megatron's ``g`` operator: psum forward, identity backward — the
    EXIT of a tensor-parallel region (row-sharded partials summed into a
    replicated activation; the replicated cotangent passes straight to
    each shard's partial)."""
    return lax.psum(x, axis_name)


def _tp_out_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _tp_out_bwd(axis_name, _, g):
    return (g,)


tp_region_out.defvjp(_tp_out_fwd, _tp_out_bwd)


def _1f1b_body(params_local, loss_params, x_local, tgt_local, *,
               block_fn: Callable, loss_fn: Callable, n_microbatch: int,
               axis_name: str, data_axis: Optional[str]):
    my = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    m_total = n_microbatch
    b_local = x_local.shape[0]
    mb = b_local // m_total
    xs = x_local.reshape((m_total, mb) + x_local.shape[1:])
    tgts = tgt_local.reshape((m_total, mb) + tgt_local.shape[1:])

    def run_local(p, h):
        return lax.scan(lambda a, pp: (block_fn(pp, a), None), h, p)[0]

    def stack_loss(p, lp, h, tgt):
        """Last stage's joint block-stack + head/loss forward (one VJP
        yields dp, dlp, dh with a single recompute)."""
        return loss_fn(lp, run_local(p, h), tgt)

    # the global loss is the mean over microbatches AND data shards, so
    # every gradient seed carries 1/(M * n_dp); the loss accumulator
    # applies the same normalization separately
    n_dp = lax.psum(1, data_axis) if data_axis else 1
    seed = 1.0 / (m_total * n_dp)

    k_slots = min(m_total, 2 * n_stage - 1)
    zero_mb = jnp.zeros((mb,) + x_local.shape[1:], x_local.dtype)
    # stash/carries diverge across the pipe axis (xs is replicated over
    # it): pcast keeps the varying-axes bookkeeping consistent
    vary = lambda t: lax.pcast(t, axis_name, to="varying")
    carry0 = (
        vary(zero_mb),                                    # fwd_state
        vary(zero_mb),                                    # bwd_state
        vary(jnp.zeros((k_slots,) + zero_mb.shape, x_local.dtype)),
        jax.tree.map(lambda a: vary(jnp.zeros_like(a)), params_local),
        jax.tree.map(lambda a: vary(jnp.zeros_like(a)), loss_params),
        vary(jnp.zeros_like(xs)),                         # dxs buffer
        vary(jnp.zeros((), jnp.float32)),                 # loss acc
    )

    last = n_stage - 1

    def tick(carry, t):
        fwd_state, bwd_state, stash, gacc, lpacc, dxs, loss_acc = carry

        # ---- forward slot: stage s forwards microbatch t - s ----------
        m_f = t - my
        f_valid = (m_f >= 0) & (m_f < m_total)
        slot_f = jnp.clip(m_f, 0, m_total - 1) % k_slots
        h_in = jnp.where(my == 0, xs[jnp.clip(m_f, 0, m_total - 1)],
                         fwd_state)
        stash = stash.at[slot_f].set(jnp.where(f_valid, h_in,
                                               stash[slot_f]))
        # stages < last forward-and-send; the last stage's forward is
        # folded into its backward VJP below (no double compute)
        h_out = lax.cond(f_valid & (my != last),
                         lambda h: run_local(params_local, h),
                         lambda h: jnp.zeros_like(h), h_in)

        # ---- backward slot: stage s backwards t - 2(P-1) + s ----------
        m_b = t - 2 * (n_stage - 1) + my
        b_valid = (m_b >= 0) & (m_b < m_total)
        m_bc = jnp.clip(m_b, 0, m_total - 1)
        stash_in = stash[m_bc % k_slots]

        def bwd_last(args):
            h0, _cot, tgt = args
            loss_m, vjp = jax.vjp(
                lambda p, lp, h: stack_loss(p, lp, h, tgt),
                params_local, loss_params, h0)
            dp, dlp, dh = vjp(jnp.full((), seed, loss_m.dtype))
            return dp, dlp, dh, loss_m

        def bwd_mid(args):
            h0, cot, _tgt = args
            _, vjp = jax.vjp(lambda p, h: run_local(p, h),
                             params_local, h0)
            dp, dh = vjp(cot)
            zlp = jax.tree.map(jnp.zeros_like, loss_params)
            return dp, zlp, dh, jnp.zeros((), jnp.float32)

        def bwd_skip(args):
            h0, _cot, _tgt = args
            return (jax.tree.map(jnp.zeros_like, params_local),
                    jax.tree.map(jnp.zeros_like, loss_params),
                    jnp.zeros_like(h0), jnp.zeros((), jnp.float32))

        cot_in = bwd_state
        branch = jnp.where(b_valid, jnp.where(my == last, 2, 1), 0)
        dp, dlp, dh, loss_m = lax.switch(
            branch, [bwd_skip, bwd_mid, bwd_last],
            (stash_in, cot_in, tgts[m_bc]))

        gacc = jax.tree.map(jnp.add, gacc, dp)
        lpacc = jax.tree.map(jnp.add, lpacc, dlp)
        loss_acc = loss_acc + loss_m / m_total
        # stage 0's dh is the entry cotangent (for the embedding bwd)
        dxs = dxs.at[m_bc].set(jnp.where((my == 0) & b_valid, dh,
                                         dxs[m_bc]))

        # ---- ring exchanges ------------------------------------------
        fwd_state = lax.ppermute(h_out, axis_name,
                                 [(i, i + 1) for i in range(n_stage - 1)])
        bwd_state = lax.ppermute(dh, axis_name,
                                 [(i + 1, i) for i in range(n_stage - 1)])
        return (fwd_state, bwd_state, stash, gacc, lpacc, dxs,
                loss_acc), None

    n_tick = m_total + 2 * (n_stage - 1)
    (_, _, _, gacc, lpacc, dxs, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(n_tick))

    axes_dp = (data_axis,) if data_axis else ()
    # block grads stay pipe-sharded; sum data-parallel contributions
    if axes_dp:
        gacc = jax.tree.map(lambda g: lax.psum(g, axes_dp), gacc)
    # loss/head grads + the scalar loss live on the last stage only;
    # the entry cotangent lives on stage 0 only — psum over pipe
    # replicates them (everything else contributed zeros)
    lpacc = jax.tree.map(lambda g: lax.psum(g, (axis_name,) + axes_dp),
                         lpacc)
    loss = lax.psum(loss_acc, (axis_name,) + axes_dp) / n_dp
    dxs = lax.psum(dxs, axis_name)
    return (gacc, lpacc,
            dxs.reshape((b_local,) + x_local.shape[1:]), loss)


def pipeline_1f1b(block_fn: Callable, stacked_params, loss_fn: Callable,
                  loss_params, x: jnp.ndarray, targets: jnp.ndarray,
                  mesh: Mesh, n_microbatch: int,
                  axis_name: str = PIPE_AXIS,
                  batch_axis: Optional[str] = DATA_AXIS,
                  param_specs=None):
    """Run the 1F1B schedule; returns ``(loss, block_grads, loss_param_
    grads, d_x)``.

    ``block_fn(params_one_block, h) -> h`` (shape-preserving);
    ``stacked_params`` leaves lead with ``L`` divisible by the pipe axis;
    ``loss_fn(loss_params, h, targets_mb) -> scalar mean loss`` runs in
    the LAST stage per microbatch; ``x`` is ``(batch, ...)`` activations
    entering the block stack; ``targets`` is ``(batch, ...)`` per-sample
    targets. The returned loss is the mean over microbatches and data
    shards; ``d_x`` is d(loss)/d(x) (feed it to the embedding VJP);
    ``block_grads`` match ``stacked_params``' sharding (``param_specs``,
    first axis the pipe axis); ``loss_param_grads`` are replicated.
    """
    n_stage = mesh.shape.get(axis_name, 1)
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead % n_stage:
        raise ValueError("pipeline_1f1b: %d blocks not divisible by %r "
                         "axis size %d" % (lead, axis_name, n_stage))
    batch_ax = batch_axis if (batch_axis and
                              mesh.shape.get(batch_axis, 1) > 1 and
                              x.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    if batch_ax is None and batch_axis and \
            mesh.shape.get(batch_axis, 1) > 1:
        # the result stays correct (every data shard recomputes the full
        # batch), but the user just lost data parallelism — say so
        import warnings
        warnings.warn(
            "pipeline_1f1b: batch %d is not divisible by the %r axis "
            "size %d — falling back to batch_ax=None (batch replicated, "
            "every data shard recomputes the full batch; data "
            "parallelism is OFF for this step). Pad the batch or resize "
            "the mesh to restore it." % (x.shape[0], batch_axis,
                                         mesh.shape[batch_axis]),
            stacklevel=2)
    b_local = x.shape[0] // (mesh.shape[batch_ax] if batch_ax else 1)
    if b_local % n_microbatch:
        raise ValueError(
            "pipeline_1f1b: per-data-shard batch %d not divisible by "
            "n_microbatch %d" % (b_local, n_microbatch))

    x_spec = P(batch_ax)
    tgt_spec = P(batch_ax)
    if param_specs is None:
        param_specs = P(axis_name)
    body = functools.partial(
        _1f1b_body, block_fn=block_fn, loss_fn=loss_fn,
        n_microbatch=n_microbatch, axis_name=axis_name,
        data_axis=batch_ax)
    gacc, lpacc, dxs, loss = jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(), x_spec, tgt_spec),
        out_specs=(param_specs, P(), x_spec, P()),
        check_vma=False)(stacked_params, loss_params, x, targets)
    return loss, gacc, lpacc, dxs


__all__ = ["pipeline_1f1b", "tp_region_in", "tp_region_out"]
