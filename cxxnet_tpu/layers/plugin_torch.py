"""External-framework plugin layer: wrap a ``torch.nn`` module as a Layer.

Capability parity with the reference's caffe adapter plugin
(/root/reference/src/plugin/caffe_adapter-inl.hpp:26-231): there, a
``caffe::Layer`` built from a prototxt config string runs inside an ILayer
with node data copied into caffe Blobs and its weights exposed as ``blob%d``
through the visitor, so an external framework's op can serve as a production
layer or as a pairtest oracle. Here the external framework is torch (CPU):
the module is built from a ``module = <expr>`` config string evaluated in
the ``torch.nn`` namespace, its forward/backward run on the host through
``jax.pure_callback`` under a ``custom_vjp`` (backward = ``torch.autograd``),
and its parameters surface in the param tree as ``blob0..blobN``.

Layout bridging: runtime nodes are NHWC (matrix nodes ``(b,1,1,len)``); the
adapter hands torch NCHW (or 2-D) tensors and converts back, like the
adapter's Blob copies (caffe_adapter-inl.hpp:96-148).

Pairtest interop (§4.1/§4.2 of SURVEY.md — external oracle): by default
parameters are named ``blob%d``; ``param_names = wmat,bias`` renames them in
``named_parameters()`` order and ``hwio = 1`` exposes 4-D weights in HWIO
(converting to torch's OIHW internally), so ``pairtest-fullc-torch`` and
``pairtest-conv-torch`` share one parameter set with the native layer.

Limits (documented deviations): the module must be deterministic for
training (torch's own RNG is invisible to JAX, and backward re-runs the
forward — modules like nn.Dropout would resample); buffers (e.g. BN running
stats) live as host-side module state, not in the functional state tree.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.config import ConfigError
from .base import ApplyContext, Layer, Params, Shape3, register_layer

_TORCH_LOCK = threading.Lock()


def _import_torch():
    try:
        import torch
        return torch
    except Exception as e:                      # pragma: no cover
        raise ConfigError("torch plugin layer requires torch: %s" % e)


def _build_module(expr: str):
    torch = _import_torch()
    ns = {"torch": torch, "nn": torch.nn}
    ns.update({k: v for k, v in vars(torch.nn).items()
               if not k.startswith("_")})
    try:
        module = eval(expr, {"__builtins__": {}}, ns)   # config-author's code,
    except Exception as e:                              # like the prototxt string
        raise ConfigError("torch plugin: cannot build module from %r: %s"
                          % (expr, e))
    if not isinstance(module, torch.nn.Module):
        raise ConfigError("torch plugin: %r is not an nn.Module" % expr)
    return module.float()


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _torch_call(layer, train, x, *blobs):
    y, _ = _torch_call_fwd(layer, train, x, *blobs)
    return y


def _torch_call_fwd(layer, train, x, *blobs):
    out_sd = jax.ShapeDtypeStruct(
        (x.shape[0],) + layer._out_torch_tail, jnp.float32)
    y = jax.pure_callback(partial(layer._host_forward, train), out_sd,
                          x, *blobs, vmap_method="sequential")
    return y, (x, blobs)


def _torch_call_bwd(layer, train, res, gy):
    x, blobs = res
    out_sd = tuple(jax.ShapeDtypeStruct(t.shape, jnp.float32)
                   for t in (x,) + blobs)
    grads = jax.pure_callback(partial(layer._host_backward, train), out_sd,
                              x, gy, *blobs, vmap_method="sequential")
    return grads


_torch_call.defvjp(_torch_call_fwd, _torch_call_bwd)


@register_layer
class TorchPluginLayer(Layer):
    type_name = "torch"

    def __init__(self, spec, cfg):
        self.module_expr = ""
        self.custom_names: List[str] = []
        self.hwio = 0
        super().__init__(spec, cfg)
        if not self.module_expr:
            raise ConfigError("torch layer %r: must set module" % spec.key())
        self.module = _build_module(self.module_expr)
        self._names = [n for n, _ in self.module.named_parameters()]
        if self.custom_names:
            if len(self.custom_names) != len(self._names):
                raise ConfigError(
                    "torch layer %r: param_names has %d names, module has %d "
                    "parameters" % (spec.key(), len(self.custom_names),
                                    len(self._names)))
            self._exposed = list(self.custom_names)
        else:
            self._exposed = ["blob%d" % i for i in range(len(self._names))]

    def set_param(self, name: str, val: str) -> None:
        if name == "module":
            self.module_expr = val
        elif name == "param_names":
            self.custom_names = [s.strip() for s in val.split(",") if s.strip()]
        elif name == "hwio":
            self.hwio = int(val)

    # ------------------------------------------------------------ shapes
    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        torch = _import_torch()
        c, y, x = self.check_one_to_one(in_shapes)
        # matrix nodes are logically (1, 1, len) (layer.h:30-71 convention)
        self._matrix_in = (c == 1 and y == 1)
        tin = (x,) if self._matrix_in else (c, y, x)
        with torch.no_grad():
            self.module.eval()
            try:
                out = self.module(torch.zeros((2,) + tin))
            except Exception as e:
                raise ConfigError(
                    "torch layer %r: dry forward on input %r failed: %s"
                    % (self.spec.key(), (2,) + tin, e))
        if out.dim() == 4:
            _, oc, oy, ox = out.shape
            out_shape = (int(oc), int(oy), int(ox))
        elif out.dim() == 2:
            out_shape = (1, 1, int(out.shape[1]))
        else:
            raise ConfigError("torch layer %r: unsupported output rank %d"
                              % (self.spec.key(), out.dim()))
        self._matrix_out = out.dim() == 2
        # callback-result tail in torch layout (batch prepended at trace time)
        self._out_torch_tail = ((out_shape[2],) if self._matrix_out
                                else tuple(int(d) for d in out.shape[1:]))
        return [out_shape]

    def init_params(self, key: jax.Array, in_shapes: List[Shape3]) -> Params:
        torch = _import_torch()
        seed = int(np.asarray(jax.random.randint(key, (), 0, 2**31 - 1)))
        with _TORCH_LOCK:
            torch.manual_seed(seed)
            for m in self.module.modules():
                if hasattr(m, "reset_parameters"):
                    m.reset_parameters()
            blobs = [p.detach().numpy().copy()
                     for _, p in self.module.named_parameters()]
        out: Params = {}
        for name, b in zip(self._exposed, blobs):
            if self.hwio and b.ndim == 4:
                b = b.transpose(2, 3, 1, 0)      # OIHW -> HWIO exposure
            out[name] = jnp.asarray(b, jnp.float32)
        return out

    # ------------------------------------------------------------ forward
    def apply(self, params: Params, inputs: List[jnp.ndarray],
              ctx: ApplyContext) -> List[jnp.ndarray]:
        x = inputs[0]
        dtype = x.dtype
        if self._matrix_in:
            tx = x.reshape(x.shape[0], -1).astype(jnp.float32)
        else:
            tx = jnp.transpose(x, (0, 3, 1, 2)).astype(jnp.float32)  # NHWC->NCHW
        blobs = []
        for name in self._exposed:
            b = params[name].astype(jnp.float32)
            if self.hwio and b.ndim == 4:
                b = jnp.transpose(b, (3, 2, 0, 1))   # HWIO -> torch OIHW
            blobs.append(b)
        y = _torch_call(self, bool(ctx.train), tx, *blobs)
        if self._matrix_out:
            y = y.reshape(y.shape[0], 1, 1, -1)
        else:
            y = jnp.transpose(y, (0, 2, 3, 1))       # NCHW -> NHWC
        return [y.astype(dtype)]

    # ------------------------------------------------------------ host side
    def _functional_forward(self, train: bool, x_np, blob_nps, need_grad: bool):
        torch = _import_torch()
        xt = torch.from_numpy(np.ascontiguousarray(x_np, np.float32))
        xt.requires_grad_(need_grad)
        pdict = {}
        for name, b in zip(self._names, blob_nps):
            t = torch.from_numpy(np.ascontiguousarray(b, np.float32))
            t.requires_grad_(need_grad)
            pdict[name] = t
        self.module.train(bool(train))
        y = torch.func.functional_call(self.module, pdict, (xt,))
        return xt, pdict, y

    def _host_forward(self, train, x_np, *blob_nps):
        torch = _import_torch()
        with _TORCH_LOCK, torch.no_grad():
            _, _, y = self._functional_forward(train, x_np, blob_nps, False)
        return np.asarray(y.detach().numpy(), np.float32)

    def _host_backward(self, train, x_np, gy_np, *blob_nps):
        torch = _import_torch()
        with _TORCH_LOCK:
            xt, pdict, y = self._functional_forward(train, x_np, blob_nps, True)
            gy = torch.from_numpy(np.ascontiguousarray(gy_np, np.float32))
            leaves = [xt] + list(pdict.values())
            grads = torch.autograd.grad(y, leaves, grad_outputs=gy,
                                        allow_unused=True)
        return tuple(np.zeros(l.shape, np.float32) if g is None
                     else np.asarray(g.detach().numpy(), np.float32)
                     for l, g in zip(leaves, grads))
