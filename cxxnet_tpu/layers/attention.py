"""Sequence-model layers: attention, layer_norm, add (residual), embedding.

The reference is a pure CNN/MLP framework with no attention (SURVEY §5.7);
these layers extend the same config DSL to transformer-style networks, with
long-context support built in: when the trainer's mesh has a ``seq`` axis
(``seq_parallel = k``), the attention layer automatically switches from exact
attention to ring attention (K/V rotation over ICI, online softmax — see
cxxnet_tpu/ops/attention.py).

Sequence node convention: a sequence of length N with F features is the node
shape (batch, y=N, x=1, c=F) — logical (F, N, 1) in config terms. Token-id
inputs for ``embedding`` are matrix nodes (batch, 1, 1, N) holding float ids,
as produced by the standard label/data pipeline.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import (local_attention, local_attention_bhnd,
                             ring_attention, ring_attention_bhnd,
                             ulysses_attention, ulysses_attention_bhnd)
from ..parallel.mesh import DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, SEQ_AXIS
from ..utils.config import ConfigError
from .base import ApplyContext, Layer, Params, Shape3, register_layer


@register_layer
class LayerNormLayer(Layer):
    """Per-position layer norm over the feature (channel) dim; learned
    scale ("wmat") and shift ("bias"), same tag names as batch_norm."""
    type_name = "layer_norm"

    def __init__(self, spec, cfg):
        self.eps = 1e-5
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "eps":
            self.eps = float(val)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        shape = self.check_one_to_one(in_shapes)
        self.channel = shape[0]
        return [shape]

    def init_params(self, key, in_shapes):
        return {"wmat": jnp.ones((self.channel,), jnp.float32),
                "bias": jnp.zeros((self.channel,), jnp.float32)}

    def apply(self, params, inputs, ctx):
        x = inputs[0]
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=-1, keepdims=True)
        var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        out = out * params["wmat"] + params["bias"]
        return [out.astype(x.dtype)]


@register_layer
class AddLayer(Layer):
    """N->1 elementwise sum — the residual connection. Dual of ``split``."""
    type_name = "add"

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        for s in in_shapes[1:]:
            if s != in_shapes[0]:
                raise ConfigError("add: mismatched input shapes %r" % in_shapes)
        return [in_shapes[0]]

    def apply(self, params, inputs, ctx):
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return [out]


@register_layer
class EmbeddingLayer(Layer):
    """Token + learned positional embedding: (b,1,1,N) float ids ->
    (b, N, 1, nhidden). Weights: "wmat" (vocab, nhidden), "pos" (N, nhidden).
    """
    type_name = "embedding"

    def __init__(self, spec, cfg):
        self.vocab_size = 0
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "vocab_size":
            self.vocab_size = int(val)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        c, y, x = self.check_one_to_one(in_shapes)
        if self.vocab_size <= 0 or self.param.num_hidden <= 0:
            raise ConfigError("embedding %r: set vocab_size and nhidden"
                              % self.spec.key())
        self.seq_len = c * y * x
        return [(self.param.num_hidden, self.seq_len, 1)]

    def init_params(self, key, in_shapes):
        kw, kp = jax.random.split(key)
        f = self.param.num_hidden
        return {
            "wmat": self.param.rand_init(kw, (self.vocab_size, f),
                                         in_num=self.vocab_size, out_num=f),
            "pos": self.param.rand_init(kp, (self.seq_len, f),
                                        in_num=self.seq_len, out_num=f),
        }

    def param_axes(self, tag):
        return {"wmat": (None, MODEL_AXIS), "pos": (None, MODEL_AXIS)}.get(tag)

    def apply(self, params, inputs, ctx):
        ids = inputs[0].reshape(inputs[0].shape[0], -1).astype(jnp.int32)
        emb = jnp.take(params["wmat"], ids, axis=0) + params["pos"]
        # the net's precision applies from here: the id entry node stays
        # exact f32 (bf16 ids would corrupt vocab > 256), the embedded
        # activations carry the compute dtype downstream
        return [emb.astype(ctx.compute_dtype)[:, :, None, :]]   # (b,N,1,F)


@register_layer
class MoELayer(Layer):
    """Switch-MoE position-wise FFN on (b, N, 1, F) nodes (ops/moe.py).

    Config: ``nexpert``, ``nhidden`` (per-expert hidden width),
    ``capacity_factor``, ``moe_aux_weight`` (load-balance loss weight),
    ``moe_dispatch`` (auto | sort | dense | ragged, the single-logical-
    shard strategy — doc/performance.md measures the sort/dense
    crossover; ragged is the DROPLESS variant: no capacity limit, every
    token is served via a ragged grouped matmul), ``moe_topk`` (1 =
    switch top-1; 2 = GShard top-2, renormalized gates, first choices
    win capacity).
    Weights: "gate" (F, E), "w_up" (E, F, H), "w_down" (E, H, F) — the
    expert dim is sharded over the dedicated ``expert`` mesh axis
    (``expert_parallel = k``) when present, else over ``model``.

    With ``expert_parallel > 1`` the layer runs the explicit all-to-all
    dispatch (ops/moe.py:switch_moe_alltoall) inside a shard_map over the
    expert axis: tokens shard over (data, expert), capacity applies per
    (source shard, expert) group — GShard's grouped dispatch. Otherwise
    the GSPMD path partitions the einsum/scatter formulation from the
    weight shardings alone.
    """
    type_name = "moe"
    emits_aux_loss = True      # appends the load-balance loss to ctx.losses

    def __init__(self, spec, cfg):
        self.nexpert = 0
        self.capacity_factor = 1.25
        self.aux_weight = 0.01
        self.moe_dispatch = "auto"
        self._warned_dispatch = False
        self.moe_topk = 1
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "nexpert":
            self.nexpert = int(val)
        elif name == "capacity_factor":
            self.capacity_factor = float(val)
        elif name == "moe_aux_weight":
            self.aux_weight = float(val)
        elif name == "moe_dispatch":
            if val not in ("auto", "sort", "dense", "ragged"):
                raise ConfigError("moe_dispatch must be auto|sort|dense|"
                                  "ragged, got %r" % val)
            self.moe_dispatch = val
        elif name == "moe_topk":
            self.moe_topk = int(val)
            if self.moe_topk < 1:
                raise ConfigError("moe_topk must be >= 1")

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        c, y, x = self.check_one_to_one(in_shapes)
        if self.nexpert <= 0 or self.param.num_hidden <= 0:
            raise ConfigError("moe %r: set nexpert and nhidden"
                              % self.spec.key())
        if self.moe_topk > self.nexpert:
            raise ConfigError("moe %r: moe_topk %d exceeds nexpert %d"
                              % (self.spec.key(), self.moe_topk,
                                 self.nexpert))
        if self.moe_dispatch == "dense" and self.moe_topk != 1:
            raise ConfigError("moe %r: moe_dispatch=dense supports "
                              "moe_topk=1 only" % self.spec.key())
        self.feat = c
        return [(c, y, x)]

    def init_params(self, key, in_shapes):
        kg, ku, kd = jax.random.split(key, 3)
        f, e, hid = self.feat, self.nexpert, self.param.num_hidden
        return {
            "gate": self.param.rand_init(kg, (f, e), in_num=f, out_num=e),
            "w_up": self.param.rand_init(ku, (e, f, hid), in_num=f,
                                         out_num=hid),
            "w_down": self.param.rand_init(kd, (e, hid, f), in_num=hid,
                                           out_num=f),
        }

    def param_axes(self, tag):
        # prefer a dedicated expert axis; degrade to the model axis on
        # meshes without one (resolver picks the first present+dividing)
        return {"w_up": ((EXPERT_AXIS, MODEL_AXIS), None, None),
                "w_down": ((EXPERT_AXIS, MODEL_AXIS), None, None)}.get(tag)

    def apply(self, params, inputs, ctx: ApplyContext):
        from ..ops.moe import switch_moe, switch_moe_alltoall
        x = inputs[0]
        b, n, _, f = x.shape
        mesh = ctx.mesh
        ep = mesh.shape.get(EXPERT_AXIS, 1) if mesh is not None else 1
        nd = mesh.shape.get(DATA_AXIS, 1) if mesh is not None else 1
        if ep > 1 and (b * n) % (ep * nd) == 0 and self.nexpert % ep == 0:
            if self.moe_dispatch == "ragged":
                # ragged is a SEMANTIC choice (dropless), not a strategy
                # hint: the all-to-all path groups capacity per source
                # shard and DROPS overflow tokens, so silently honoring
                # ep>1 would reintroduce exactly the drops the user opted
                # out of — fail loudly instead (ADVICE r4)
                raise ConfigError(
                    "moe %s: moe_dispatch=ragged (dropless) cannot run "
                    "under expert_parallel>1 — the all-to-all dispatch "
                    "drops tokens over capacity; use moe_dispatch=auto/"
                    "sort/dense with expert_parallel, or expert_parallel=1 "
                    "for dropless" % self.spec.key())
            if self.moe_dispatch != "auto" and not self._warned_dispatch:
                # the expert-parallel all-to-all path groups capacity per
                # source shard (GShard semantics), which differs from the
                # global grouping of the single-device sort/dense paths —
                # an explicit moe_dispatch cannot be honored here
                import sys
                print("moe %s: expert_parallel>1 uses the all-to-all "
                      "dispatch; explicit moe_dispatch=%s is ignored "
                      "(capacity grouped per source shard, not globally)"
                      % (self.spec.key(), self.moe_dispatch),
                      file=sys.stderr)
                self._warned_dispatch = True
            from jax import lax
            from jax.sharding import PartitionSpec as P

            def body(xs, g, wu, wd):
                o, a = switch_moe_alltoall(
                    xs, g, wu, wd, axis_name=EXPERT_AXIS,
                    capacity_factor=self.capacity_factor,
                    top_k=self.moe_topk)
                # aux is psum-averaged over expert inside; averaging over
                # data too makes it a genuinely replicated scalar (the
                # P() out_spec below relies on that, check_vma is off)
                return o, lax.psum(a, DATA_AXIS) / nd

            tok = P((DATA_AXIS, EXPERT_AXIS), None)
            # check_vma off: the varying-axes checker rejects the psum
            # composition across two axes here (JAX 0.9), but the specs
            # are replication-correct by construction
            out, aux = jax.shard_map(
                body, mesh=mesh,
                in_specs=(tok, P(None, None), P(EXPERT_AXIS, None, None),
                          P(EXPERT_AXIS, None, None)),
                out_specs=(tok, P()), check_vma=False)(
                    x.reshape(b * n, f), params["gate"], params["w_up"],
                    params["w_down"])
        else:
            dispatch = self.moe_dispatch
            if dispatch == "auto":
                # measured (doc/performance.md round 3): sort-based sparse
                # dispatch beats the dense one-hot einsums 2.4-3x at every
                # E on one chip. Dense remains the choice when the expert
                # weights are actually GSPMD-sharded on their expert dim
                # (einsums partition into clean all-to-alls where
                # scatter/gather would force gathers) — decided with the
                # same resolver rule that placed the weights, so the two
                # cannot diverge.
                expert_sharded = False
                if mesh is not None:
                    from ..parallel.sharding import _fit_spec
                    spec = _fit_spec(self.param_axes("w_up"),
                                     params["w_up"].shape, mesh)
                    expert_sharded = spec[0] is not None
                # dense supports top-1 only; top-k forces the sort path
                dispatch = ("dense" if expert_sharded
                            and self.moe_topk == 1 else "sort")
            out, aux = switch_moe(x.reshape(b * n, f), params["gate"],
                                  params["w_up"], params["w_down"],
                                  self.capacity_factor, dispatch=dispatch,
                                  top_k=self.moe_topk)
        if ctx.train and self.aux_weight > 0:
            # divide by update_period so gradient accumulation keeps the
            # aux:data loss ratio fixed (the CE loss carries the same factor,
            # loss_layer_base-inl.hpp:61-63 parity in loss.py)
            ctx.losses.append(self.aux_weight * aux
                              / max(ctx.update_period, 1))
        return [out.reshape(b, n, 1, f)]


@register_layer
class AttentionLayer(Layer):
    """Multi-head self-attention on (b, N, 1, F) nodes.

    Weights: "qkv" (3F, F), "proj" (F, F) (+ "qkv_bias"/"proj_bias" unless
    no_bias). ``nhead`` heads; ``causal = 1`` for autoregressive masking.
    Ring attention engages when the trainer mesh's ``seq`` axis is > 1.

    ``attn_layout`` (auto | bnhd | bhnd) picks the flash-kernel-boundary
    layout, the same measured rule as the models/gpt.py flagship
    (gpt.py GPTConfig.attn_layout): ``bhnd`` projects straight into the
    kernels' head-major (b, heads, n, head_dim) layout via per-head
    einsums so XLA inserts no transpose at the kernel boundary — a win
    when head_dim >= 128 (lane-native), a loss below (measured round
    2/3, doc/performance.md); ``auto`` applies that rule. Composes with
    both sequence-parallel modes (the sp cores are head-major).
    """
    type_name = "attention"
    uses_rng = False

    def __init__(self, spec, cfg):
        self.nhead = 1
        self.causal = 0
        self.seq_parallel_mode = "ring"
        self.attn_layout = "auto"
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "nhead":
            self.nhead = int(val)
        elif name == "causal":
            self.causal = int(val)
        elif name == "seq_parallel_mode":
            if val not in ("ring", "ulysses"):
                raise ConfigError("seq_parallel_mode must be ring|ulysses, "
                                  "got %r" % val)
            self.seq_parallel_mode = val
        elif name == "attn_layout":
            if val not in ("auto", "bnhd", "bhnd"):
                raise ConfigError("attn_layout must be auto|bnhd|bhnd, "
                                  "got %r" % val)
            self.attn_layout = val

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        c, y, x = self.check_one_to_one(in_shapes)
        if x != 1:
            raise ConfigError("attention %r: expects (feat, seq, 1) nodes, "
                              "got %r" % (self.spec.key(), (c, y, x)))
        if c % self.nhead:
            raise ConfigError("attention %r: nhead %d must divide feature "
                              "dim %d" % (self.spec.key(), self.nhead, c))
        self.feat = c
        return [(c, y, x)]

    def init_params(self, key, in_shapes):
        kq, kp = jax.random.split(key)
        f = self.feat
        p: Params = {
            "qkv": self.param.rand_init(kq, (3 * f, f), in_num=f, out_num=f),
            "proj": self.param.rand_init(kp, (f, f), in_num=f, out_num=f),
        }
        if not self.param.no_bias:
            p["qkv_bias"] = jnp.zeros((3 * f,), jnp.float32)
            p["proj_bias"] = jnp.zeros((f,), jnp.float32)
        return p

    def param_axes(self, tag):
        return {"qkv": (MODEL_AXIS, None), "qkv_bias": (MODEL_AXIS,),
                "proj": (None, MODEL_AXIS)}.get(tag)

    def apply(self, params, inputs, ctx: ApplyContext):
        x = inputs[0]                       # (b, N, 1, F)
        b, n, _, f = x.shape
        h = self.nhead
        layout = self.attn_layout
        if layout == "auto":
            # measured rule shared with the gpt.py flagship
            # (gpt_logits, doc/performance.md round 3): head-major iff
            # the per-head projection width is lane-native
            layout = "bhnd" if f // h >= 128 else "bnhd"
        xs = x.reshape(b, n, f)
        mesh = ctx.mesh
        sp = mesh is not None and mesh.shape.get(SEQ_AXIS, 1) > 1
        if layout == "bhnd":
            # project straight into the kernels' head-major layout:
            # qkv rows are [q; k; v] blocks of F, each row j mapping to
            # (head j//d, dim j%d) — reshape (3F, F) -> (3, h, d, F)
            w = params["qkv"].astype(xs.dtype).reshape(3, h, f // h, f)
            qh = jnp.einsum("bnf,hdf->bhnd", xs, w[0])
            kh = jnp.einsum("bnf,hdf->bhnd", xs, w[1])
            vh = jnp.einsum("bnf,hdf->bhnd", xs, w[2])
            if "qkv_bias" in params:
                bias = params["qkv_bias"].astype(qh.dtype).reshape(
                    3, h, f // h)
                qh = qh + bias[0][None, :, None, :]
                kh = kh + bias[1][None, :, None, :]
                vh = vh + bias[2][None, :, None, :]
            if sp:
                sp_attn = (ulysses_attention_bhnd
                           if self.seq_parallel_mode == "ulysses"
                           else ring_attention_bhnd)
                att = sp_attn(qh, kh, vh, mesh, axis_name=SEQ_AXIS,
                              causal=bool(self.causal))
            else:
                att = local_attention_bhnd(qh, kh, vh,
                                           causal=bool(self.causal))
            wp = params["proj"].astype(x.dtype).reshape(f, h, f // h)
            out = jnp.einsum("bhnd,fhd->bnf", att, wp)
        else:
            qkv = xs @ params["qkv"].astype(xs.dtype).T
            if "qkv_bias" in params:
                qkv = qkv + params["qkv_bias"].astype(qkv.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, n, h, f // h)
            k = k.reshape(b, n, h, f // h)
            v = v.reshape(b, n, h, f // h)
            if sp:
                sp_attn = (ulysses_attention
                           if self.seq_parallel_mode == "ulysses"
                           else ring_attention)
                out = sp_attn(q, k, v, mesh, axis_name=SEQ_AXIS,
                              causal=bool(self.causal))
            else:
                out = local_attention(q, k, v, causal=bool(self.causal))
            out = out.reshape(b, n, f) @ params["proj"].astype(x.dtype).T
        if "proj_bias" in params:
            out = out + params["proj_bias"].astype(out.dtype)
        return [out.reshape(b, n, 1, f)]
