"""Layer library: functional TPU-native equivalents of the reference layer zoo
(/root/reference/src/layer/). Importing this package populates the registry."""

from .base import (ApplyContext, Layer, LayerParam, LAYER_REGISTRY,
                   create_layer, register_layer)
from . import simple   # noqa: F401  (registers dense/activation/structural layers)
from . import conv     # noqa: F401  (registers conv/pooling/lrn/batch_norm)
from . import loss     # noqa: F401  (registers softmax/l2_loss/multi_logistic)
from . import pairtest  # noqa: F401  (registers the differential-test layer)
from . import attention  # noqa: F401  (registers attention/layer_norm/add/embedding)
from . import plugin_torch  # noqa: F401  (registers the torch adapter plugin;
#                             torch itself is imported lazily on first use)

__all__ = ["ApplyContext", "Layer", "LayerParam", "LAYER_REGISTRY",
           "create_layer", "register_layer"]
