"""PairTest layer — differential testing of two layer implementations.

Reference (/root/reference/src/layer/pairtest_layer-inl.hpp:14-200): config
``layer[...] = pairtest-master-slave`` wraps two implementations; the master
drives the real nodes while the slave runs on shadow state with weights synced
from the master, and every Forward/Backprop compares outputs within tolerance,
reporting the max-diff element. This is how the custom conv was validated
against cuDNN/Caffe.

Functional redesign: both layers share one parameter set (their param shapes
must agree — e.g. ``pairtest-conv-conv``, or an XLA layer vs. its Pallas
variant). ``apply`` computes both outputs inside the jitted graph, emits the
max abs diff via ``jax.debug.print`` when it exceeds ``pairtest_tol``, and
returns the master's outputs; because autodiff flows only through the master's
result, training behavior is identical to running the master alone.
``master:`` / ``slave:`` config-key prefixes scope settings to one side
(pairtest_layer-inl.hpp:127-135).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..graph import LayerSpec
from ..utils.config import ConfigError
from .base import ApplyContext, Layer, Params, Shape3, register_layer


def _scoped_cfg(cfg, side: str):
    """Split ``master:``/``slave:`` prefixed keys; unprefixed go to both."""
    out = []
    for k, v in cfg:
        if k.startswith("master:"):
            if side == "master":
                out.append((k[len("master:"):], v))
        elif k.startswith("slave:"):
            if side == "slave":
                out.append((k[len("slave:"):], v))
        else:
            out.append((k, v))
    return out


@register_layer
class PairTestLayer(Layer):
    type_name = "pairtest"
    uses_rng = True

    def __init__(self, spec: LayerSpec, cfg):
        from .base import LAYER_REGISTRY       # late: registry fully populated
        self.tol = 1e-5
        super().__init__(spec, cfg)
        if spec.pairtest is None:
            raise ConfigError("pairtest layer missing master/slave types")
        mtype, stype = spec.pairtest
        for t in (mtype, stype):
            if t not in LAYER_REGISTRY:
                raise ConfigError("pairtest: unknown layer type %r" % t)
        mspec = LayerSpec(mtype, spec.name, spec.inputs, spec.outputs)
        sspec = LayerSpec(stype, spec.name, spec.inputs, spec.outputs)
        self.master = LAYER_REGISTRY[mtype](mspec, _scoped_cfg(cfg, "master"))
        self.slave = LAYER_REGISTRY[stype](sspec, _scoped_cfg(cfg, "slave"))
        if self.master.is_loss or self.slave.is_loss:
            # pairing loss layers would double-count ctx.losses and route
            # gradient through both copies
            raise ConfigError("pairtest cannot wrap loss layers")

    def set_param(self, name, val):
        if name == "pairtest_tol":
            self.tol = float(val)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        mshape = self.master.infer_shapes(in_shapes)
        sshape = self.slave.infer_shapes(in_shapes)
        if mshape != sshape:
            raise ConfigError(
                "pairtest: master %r and slave %r disagree on output shape "
                "(%r vs %r)" % (self.master.type_name, self.slave.type_name,
                                mshape, sshape))
        return mshape

    def init_params(self, key: jax.Array, in_shapes: List[Shape3]) -> Params:
        mp = self.master.init_params(key, in_shapes)
        sp = self.slave.init_params(key, in_shapes)
        if jax.tree.structure(mp) != jax.tree.structure(sp) or any(
                mp[t].shape != sp[t].shape for t in mp):
            raise ConfigError(
                "pairtest: master and slave parameter shapes differ — pair "
                "only implementations of the same op")
        return mp        # single shared parameter set (slave "synced" by construction)

    def init_state(self):
        if hasattr(self.master, "init_state"):
            return self.master.init_state()
        return {}

    def apply(self, params: Params, inputs: List[jnp.ndarray],
              ctx: ApplyContext) -> List[jnp.ndarray]:
        mouts = self.master.apply(params, inputs, ctx)
        # the slave runs in an isolated context (own rng stream, discarded
        # losses/state) so the master's behavior is bit-identical to running
        # it alone; stop_gradient keeps autodiff on the master path only
        slave_ctx = ApplyContext(
            train=ctx.train,
            rng=ctx.next_key() if self.slave.uses_rng and ctx.train else None,
            labels=ctx.labels, sample_mask=ctx.sample_mask,
            batch_size=ctx.batch_size, update_period=ctx.update_period,
            epoch=ctx.epoch, states=ctx.states, mesh=ctx.mesh)
        souts = self.slave.apply(params, [jax.lax.stop_gradient(x)
                                          for x in inputs], slave_ctx)
        for i, (m, s) in enumerate(zip(mouts, souts)):
            # relative-absolute error as in CmpResult (pairtest:172-199)
            err = jax.lax.stop_gradient(
                jnp.max(jnp.abs(m - s) / (jnp.abs(m) + 1e-6)))
            jax.lax.cond(
                err > self.tol,
                lambda e: jax.debug.print(
                    "PairTest[" + self.spec.key() + " out" + str(i) +
                    "]: max rel-abs diff {e} exceeds tol", e=e),
                lambda e: None,
                err)
        return mouts
