"""Dense, activation, and structural layers.

Reference semantics (file:line cites are into /root/reference):
- fullc     src/layer/fullc_layer-inl.hpp:13-146
- act fns   src/layer/activation_layer-inl.hpp:11-41 + op.h:13-101
- xelu      src/layer/xelu_layer-inl.hpp:14-55 (leaky: a>0 ? a : a/b)
- insanity  src/layer/insanity_layer-inl.hpp:13-106 (RReLU, random divisor in [lb,ub])
- prelu     src/layer/prelu_layer-inl.hpp:45-177 (learned per-channel slope)
- dropout   src/layer/dropout_layer-inl.hpp:11-66 (self-loop, mask/pkeep)
- flatten   src/layer/flatten_layer-inl.hpp ((b,c,y,x)->(b,1,1,cyx))
- split     src/layer/split_layer-inl.hpp:12-47 (1->N copy; autodiff sums grads)
- concat    src/layer/concat_layer-inl.hpp:11-80 (dim 3 features / dim 1 channels)
- bias      src/layer/bias_layer-inl.hpp:14-86 (self-loop add bias)
- fixconn   src/layer/fixconn_layer-inl.hpp:14-96 (fixed sparse weight matmul)

All matmuls run in the MXU-friendly path: inputs flattened to (b, d) 2-D and
kept in float32 params with optional bf16 compute (see nnet.precision).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.config import ConfigError
from .base import (ApplyContext, Layer, Params, Shape3, flat_dim,
                   register_layer)


def _flatten2d(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape(x.shape[0], -1)


def _as_matrix_node(x: jnp.ndarray) -> jnp.ndarray:
    """(b, d) -> (b, 1, 1, d) node form."""
    return x.reshape(x.shape[0], 1, 1, x.shape[1])


@register_layer
class FullcLayer(Layer):
    """out = in @ W.T + bias; W is (nhidden, in_dim) as in the reference."""
    type_name = "fullc"

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        self.check_one_to_one(in_shapes)
        if self.param.num_hidden <= 0:
            raise ConfigError("fullc %r: must set nhidden" % self.spec.key())
        self.in_dim = flat_dim(in_shapes[0])
        return [(1, 1, self.param.num_hidden)]

    def init_params(self, key: jax.Array, in_shapes: List[Shape3]) -> Params:
        kw, _ = jax.random.split(key)
        p: Params = {
            "wmat": self.param.rand_init(
                kw, (self.param.num_hidden, self.in_dim),
                in_num=self.in_dim, out_num=self.param.num_hidden),
        }
        if not self.param.no_bias:
            p["bias"] = jnp.full((self.param.num_hidden,), self.param.init_bias,
                                 jnp.float32)
        return p

    def param_axes(self, tag):
        # tensor parallelism: shard the output-feature dim over the `model`
        # mesh axis (the fullc_gather descendant, async_updater-inl.hpp:67-92)
        from ..parallel.mesh import MODEL_AXIS
        return {"wmat": (MODEL_AXIS, None), "bias": (MODEL_AXIS,)}.get(tag)

    def apply(self, params: Params, inputs: List[jnp.ndarray],
              ctx: ApplyContext) -> List[jnp.ndarray]:
        x = _flatten2d(inputs[0])
        out = x @ params["wmat"].astype(x.dtype).T
        if "bias" in params:
            out = out + params["bias"].astype(out.dtype)
        return [_as_matrix_node(out)]


@register_layer
class FixconnLayer(Layer):
    """fullc with a fixed (non-learned) sparse weight from a text file:
    each line ``row col value``; first line ``nrow ncol nnz``."""
    type_name = "fixconn"

    def set_param(self, name: str, val: str) -> None:
        if name == "weight_file":
            self.weight_file = val

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        self.check_one_to_one(in_shapes)
        if not hasattr(self, "weight_file"):
            raise ConfigError("fixconn: must set weight_file")
        rows = []
        with open(self.weight_file) as f:
            header = f.readline().split()
            nrow, ncol = int(header[0]), int(header[1])
            for line in f:
                parts = line.split()
                if len(parts) >= 3:
                    rows.append((int(parts[0]), int(parts[1]), float(parts[2])))
        w = np.zeros((nrow, ncol), np.float32)
        for r, c, v in rows:
            w[r, c] = v
        self.wmat = jnp.asarray(w)   # (out, in), constant — closed over, not a param
        if flat_dim(in_shapes[0]) != ncol:
            raise ConfigError("fixconn: weight ncol %d != input dim %d"
                              % (ncol, flat_dim(in_shapes[0])))
        return [(1, 1, nrow)]

    def apply(self, params: Params, inputs: List[jnp.ndarray],
              ctx: ApplyContext) -> List[jnp.ndarray]:
        x = _flatten2d(inputs[0])
        out = x @ self.wmat.astype(x.dtype).T
        return [_as_matrix_node(out)]


class _ActLayer(Layer):
    """Elementwise activation; shape preserved."""

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        return [self.check_one_to_one(in_shapes)]

    def fn(self, x: jnp.ndarray, ctx: ApplyContext) -> jnp.ndarray:
        raise NotImplementedError

    def apply(self, params, inputs, ctx):
        return [self.fn(inputs[0], ctx)]


@register_layer
class ReluLayer(_ActLayer):
    type_name = "relu"

    def fn(self, x, ctx):
        return jnp.maximum(x, 0.0)


@register_layer
class SigmoidLayer(_ActLayer):
    type_name = "sigmoid"

    def fn(self, x, ctx):
        return jax.nn.sigmoid(x)


@register_layer
class TanhLayer(_ActLayer):
    type_name = "tanh"

    def fn(self, x, ctx):
        return jnp.tanh(x)


@register_layer
class SoftplusLayer(_ActLayer):
    # enum exists in the reference (layer.h:290) but its factory case is missing;
    # we implement it properly rather than reproducing the dead-enum error.
    type_name = "softplus"

    def fn(self, x, ctx):
        return jax.nn.softplus(x)


def xelu(x: jnp.ndarray, b) -> jnp.ndarray:
    """op.h xelu: a > 0 ? a : a / b  (divisor-form leaky relu)."""
    return jnp.where(x > 0, x, x / b)


@register_layer
class XeluLayer(_ActLayer):
    type_name = "xelu"

    def __init__(self, spec, cfg):
        self.b = 5.0
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "b":
            self.b = float(val)

    def fn(self, x, ctx):
        return xelu(x, self.b)


@register_layer
class InsanityLayer(_ActLayer):
    """Randomized leaky ReLU: divisor drawn uniform in [lb, ub] per element at
    train time, mean divisor at eval. Slope annealing via calm_start/calm_end
    narrows [lb, ub] toward the midpoint over training steps."""
    type_name = "insanity"
    uses_rng = True

    def __init__(self, spec, cfg):
        self.lb, self.ub = 5.0, 10.0
        self.calm_start, self.calm_end = 0, 0
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "lb":
            self.lb = float(val)
        elif name == "ub":
            self.ub = float(val)
        elif name == "calm_start":
            self.calm_start = int(val)
        elif name == "calm_end":
            self.calm_end = int(val)

    def _bounds(self, ctx: ApplyContext):
        lb, ub = self.lb, self.ub
        if self.calm_end > self.calm_start:
            mid = (lb + ub) / 2.0
            frac = jnp.clip(
                (jnp.asarray(ctx.epoch, jnp.float32) - self.calm_start)
                / (self.calm_end - self.calm_start), 0.0, 1.0)
            return lb + (mid - lb) * frac, ub - (ub - mid) * frac
        return lb, ub

    def fn(self, x, ctx):
        if ctx.train:
            lb, ub = self._bounds(ctx)
            u = jax.random.uniform(ctx.next_key(), x.shape, x.dtype)
            return xelu(x, u * (ub - lb) + lb)
        return xelu(x, (self.lb + self.ub) / 2.0)


@register_layer
class PReluLayer(Layer):
    """Learned per-channel negative slope (multiplier form: a>0 ? a : slope*a);
    optional multiplicative uniform noise on the slope at train time."""
    type_name = "prelu"
    uses_rng = True

    def __init__(self, spec, cfg):
        self.init_slope = 0.25
        self.init_random = 0
        self.random = 0.0
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "init_slope":
            self.init_slope = float(val)
        elif name == "random_slope":
            self.init_random = int(val)
        elif name == "random":
            self.random = float(val)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        shape = self.check_one_to_one(in_shapes)
        c, y, x = shape
        # fc node (c==1, y==1): per-feature slope; conv node: per-channel slope
        self.channel = x if (c == 1 and y == 1) else c
        self.is_fc = (c == 1 and y == 1)
        return [shape]

    def init_params(self, key, in_shapes):
        if self.init_random:
            slope = self.init_slope * jax.random.uniform(
                key, (self.channel,), jnp.float32)
        else:
            slope = jnp.full((self.channel,), self.init_slope, jnp.float32)
        return {"bias": slope}   # exposed under tag "bias", as in the reference

    def apply(self, params, inputs, ctx):
        x = inputs[0]
        slope = params["bias"]
        # runtime layout NHWC: channel is the last axis for both fc and conv
        slope = slope.reshape((1,) * (x.ndim - 1) + (self.channel,))
        if ctx.train and self.random > 0:
            noise = 1.0 + (jax.random.uniform(ctx.next_key(), x.shape, x.dtype)
                           * 2.0 - 1.0) * self.random
            slope = slope * noise
        return [jnp.where(x > 0, x, slope * x)]


@register_layer
class FlattenLayer(Layer):
    type_name = "flatten"

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        return [(1, 1, flat_dim(self.check_one_to_one(in_shapes)))]

    def apply(self, params, inputs, ctx):
        return [_as_matrix_node(_flatten2d(inputs[0]))]


@register_layer
class DropoutLayer(Layer):
    """Self-loop; mask = (uniform < pkeep) / pkeep at train, identity at eval."""
    type_name = "dropout"
    uses_rng = True

    def __init__(self, spec, cfg):
        self.threshold = 0.0
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "threshold":
            self.threshold = float(val)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        shape = self.check_one_to_one(in_shapes)
        if self.spec.inputs != self.spec.outputs:
            raise ConfigError("dropout is a self-loop layer (layer[+0])")
        if not (0.0 <= self.threshold < 1.0):
            raise ConfigError("dropout: invalid threshold %g" % self.threshold)
        return [shape]

    def apply(self, params, inputs, ctx):
        x = inputs[0]
        if not ctx.train or self.threshold == 0.0:
            return [x]
        pkeep = 1.0 - self.threshold
        mask = jax.random.bernoulli(ctx.next_key(), pkeep, x.shape)
        return [x * mask.astype(x.dtype) / pkeep]


@register_layer
class SplitLayer(Layer):
    """1 -> N copy; gradients sum automatically under autodiff."""
    type_name = "split"

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        if len(in_shapes) != 1:
            raise ConfigError("split: takes exactly one input")
        return [in_shapes[0]] * len(self.spec.outputs)

    def apply(self, params, inputs, ctx):
        return [inputs[0]] * len(self.spec.outputs)


@register_layer
class ConcatLayer(Layer):
    """N -> 1 concat along the feature axis (reference dim 3)."""
    type_name = "concat"
    axis_logical = 2        # x of (c, y, x)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        if not in_shapes:
            raise ConfigError("concat: needs at least one input")
        base = list(in_shapes[0])
        total = 0
        for s in in_shapes:
            for d in range(3):
                if d != self.axis_logical and s[d] != base[d]:
                    raise ConfigError("%s: non-concat dims must agree"
                                      % self.type_name)
            total += s[self.axis_logical]
        base[self.axis_logical] = total
        return [tuple(base)]

    def apply(self, params, inputs, ctx):
        # NHWC runtime: feature/channel axis is -1 in both cases; y-axis concat
        # never occurs in the reference (only dim 3 and dim 1 variants exist).
        return [jnp.concatenate(inputs, axis=-1)]


@register_layer
class ChConcatLayer(ConcatLayer):
    """N -> 1 concat along channels (reference dim 1) — also axis -1 in NHWC."""
    type_name = "ch_concat"
    axis_logical = 0        # c of (c, y, x)


@register_layer
class BiasLayer(Layer):
    """Self-loop: adds a learned per-feature bias on the flattened node."""
    type_name = "bias"

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        shape = self.check_one_to_one(in_shapes)
        self.dim = flat_dim(shape)
        return [shape]

    def init_params(self, key, in_shapes):
        return {"bias": jnp.full((self.dim,), self.param.init_bias, jnp.float32)}

    def apply(self, params, inputs, ctx):
        x = inputs[0]
        return [(x.reshape(x.shape[0], -1) + params["bias"]).reshape(x.shape)]
