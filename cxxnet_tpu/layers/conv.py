"""Convolution, pooling, LRN, and batch-norm layers.

Reference semantics (file:line into /root/reference):
- conv      src/layer/convolution_layer-inl.hpp:12-228 — im2col GEMM with groups;
            here a single lax.conv_general_dilated (XLA lowers straight onto the
            MXU; feature_group_count replaces the per-group GEMM loop, and no
            im2col temp memory management (nstep_/temp_col_max) is needed)
- pooling   src/layer/pooling_layer-inl.hpp:11-117 — max/sum/avg with *ceil-mode*
            output shape  min(in - k + stride - 1, in - 1) // stride + 1
            and partial edge windows; avg always divides by ky*kx
- relu_max_pooling  fused pre-activation variant (layer_impl-inl.hpp:55-56)
- insanity_max_pooling  src/layer/insanity_pooling_layer-inl.hpp — randomized
            leaky pre-activation (divisor in [lb,ub]) + max pooling
- lrn       src/layer/lrn_layer-inl.hpp:11-93 — cross-channel:
            out = x * (knorm + alpha/n * sum_window(x^2))^-beta
- batch_norm src/layer/batch_norm_layer-inl.hpp:13-197 — per-channel batch stats,
            eps=1e-10; NOTE the reference uses *mini-batch statistics at eval
            time too* (doc/layer.md marks it experimental); we reproduce that by
            default and offer ``moving_average = 1`` as an opt-in modern mode
            with running statistics.

Runtime layout is NHWC (TPU-native); logical config shapes stay (c, y, x).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.config import ConfigError
from .base import ApplyContext, Layer, Params, Shape3, register_layer
from .simple import xelu


@register_layer
class ConvLayer(Layer):
    """Grouped 2-D convolution, stride/pad, optional bias."""
    type_name = "conv"

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        c, y, x = self.check_one_to_one(in_shapes)
        p = self.param
        if p.num_channel <= 0:
            raise ConfigError("conv %r: must set nchannel" % self.spec.key())
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ConfigError("conv: must set kernel_size")
        if c % p.num_group or p.num_channel % p.num_group:
            raise ConfigError("conv: channels must divide ngroup")
        if y + 2 * p.pad_y < p.kernel_height or x + 2 * p.pad_x < p.kernel_width:
            raise ConfigError("conv: kernel size exceeds padded input")
        self.in_channel = c
        oy = (y + 2 * p.pad_y - p.kernel_height) // p.stride + 1
        ox = (x + 2 * p.pad_x - p.kernel_width) // p.stride + 1
        return [(p.num_channel, oy, ox)]

    def init_params(self, key: jax.Array, in_shapes: List[Shape3]) -> Params:
        p = self.param
        kw, _ = jax.random.split(key)
        ich_g = self.in_channel // p.num_group
        # HWIO kernel; init fan-in/out match the reference's grouped wmat view
        # (convolution_layer-inl.hpp:32): in = ich/g*kh*kw, out = och/g
        wmat = p.rand_init(
            kw, (p.kernel_height, p.kernel_width, ich_g, p.num_channel),
            in_num=ich_g * p.kernel_height * p.kernel_width,
            out_num=p.num_channel // p.num_group)
        out: Params = {"wmat": wmat}
        if not p.no_bias:
            out["bias"] = jnp.full((p.num_channel,), p.init_bias, jnp.float32)
        return out

    def param_axes(self, tag):
        # shard output channels over the `model` axis (ungrouped convs only:
        # splitting grouped filters across shards would break group alignment)
        from ..parallel.mesh import MODEL_AXIS
        if self.param.num_group != 1:
            return None
        return {"wmat": (None, None, None, MODEL_AXIS),
                "bias": (MODEL_AXIS,)}.get(tag)

    def apply(self, params: Params, inputs: List[jnp.ndarray],
              ctx: ApplyContext) -> List[jnp.ndarray]:
        import os
        p = self.param
        x = inputs[0]
        w = params["wmat"].astype(x.dtype)
        # opt-in (CXN_S2D=1): measured a small LOSS on one v5e chip —
        # 17.4k img/s with vs 17.7k without on the AlexNet bench (r2
        # back-to-back A/B; r1 measured 17.8k vs 18.0k) — the
        # space-to-depth transpose of the 1024x227x227x3 input costs a
        # full HBM pass that the better-shaped stem convs don't win back.
        # XLA's own conv lowering handles the 3-channel stem well. Kept
        # as an exact, tested lever for other topologies.
        if (self.in_channel <= 4 and p.stride >= 2 and p.num_group == 1
                and os.environ.get("CXN_S2D", "") == "1"):
            out = self._space_to_depth_conv(x, w, p)
        else:
            out = jax.lax.conv_general_dilated(
                x, w,
                window_strides=(p.stride, p.stride),
                padding=[(p.pad_y, p.pad_y), (p.pad_x, p.pad_x)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=p.num_group)
        if "bias" in params:
            out = out + params["bias"].astype(out.dtype)
        return [out]

    @staticmethod
    def _space_to_depth_conv(x, w, p):
        """Stem convs with <=4 input channels starve the MXU's 128-deep
        contraction (and their dW pass was 7.4% of the AlexNet step in the
        op profile). Exact rewrite: stride-s conv == stride-1 conv on the
        space-to-depth input (s x s x C blocks -> one pixel of s^2*C
        channels) with the kernel rearranged the same way —
        out(y,x) = sum w[ps+a, qs+b, c] * in[ys+p*s+a, ...] regrouped over
        (p, q) x (a, b, c). Same sums, same order of magnitude better
        channel depth (3 -> 48 for AlexNet conv1)."""
        s = p.stride
        kh, kw, ic, oc = w.shape
        b, hh, ww_, _ = x.shape
        # explicit conv padding first, then right-pad H/W to block multiples
        # and the kernel taps to block multiples (zero taps read only the
        # zero-padded tail, so the result is unchanged)
        x = jnp.pad(x, ((0, 0), (p.pad_y, (-(hh + 2 * p.pad_y)) % s + p.pad_y),
                        (p.pad_x, (-(ww_ + 2 * p.pad_x)) % s + p.pad_x),
                        (0, 0)))
        kh2, kw2 = -(-kh // s), -(-kw // s)
        w = jnp.pad(w, ((0, kh2 * s - kh), (0, kw2 * s - kw), (0, 0), (0, 0)))
        hb, wb = x.shape[1] // s, x.shape[2] // s
        x = x.reshape(b, hb, s, wb, s, ic).transpose(0, 1, 3, 2, 4, 5) \
             .reshape(b, hb, wb, s * s * ic)
        w = w.reshape(kh2, s, kw2, s, ic, oc).transpose(0, 2, 1, 3, 4, 5) \
             .reshape(kh2, kw2, s * s * ic, oc)
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # ceil-padding can add one extra block row/col of pure padding;
        # crop to the true conv output size
        oy = (hh + 2 * p.pad_y - kh) // s + 1
        ox = (ww_ + 2 * p.pad_x - kw) // s + 1
        return out[:, :oy, :ox]


def _pool_out_dim(in_dim: int, k: int, stride: int, max_start: int) -> int:
    """Ceil-mode output size; ``max_start`` bounds the last window's start so
    every window overlaps real data (or at worst the left padding) — with
    pad=0 this reduces to the reference clamp ``min(..., in-1)``."""
    return min(in_dim - k + stride - 1, max_start) // stride + 1


class _PoolingLayer(Layer):
    """Shared machinery for the pooling trio (ceil-mode partial edge windows).

    Extension over the reference: ``pad`` / ``pad_y`` / ``pad_x`` apply
    symmetric identity-element padding before pooling (the reference pooling
    ignores pad; default 0 keeps exact parity). Needed for 'same'-size pooling
    branches in inception-style modules."""
    reducer = "max"          # "max" | "sum" | "avg"

    def pre_activation(self, x: jnp.ndarray, ctx: ApplyContext) -> jnp.ndarray:
        return x

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        c, y, x = self.check_one_to_one(in_shapes)
        p = self.param
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ConfigError("pooling: must set kernel_size")
        y_eff, x_eff = y + 2 * p.pad_y, x + 2 * p.pad_x
        if p.kernel_height > y_eff or p.kernel_width > x_eff:
            raise ConfigError("pooling: kernel size exceeds input")
        # last window must start at or before the last real row/col (in padded
        # coords: y + pad - 1), else a window could cover only padding and a
        # max pool would emit its -inf identity
        self.out_y = _pool_out_dim(y_eff, p.kernel_height, p.stride,
                                   y + p.pad_y - 1)
        self.out_x = _pool_out_dim(x_eff, p.kernel_width, p.stride,
                                   x + p.pad_x - 1)
        self.in_y, self.in_x = y_eff, x_eff
        return [(c, self.out_y, self.out_x)]

    def apply(self, params: Params, inputs: List[jnp.ndarray],
              ctx: ApplyContext) -> List[jnp.ndarray]:
        p = self.param
        x = self.pre_activation(inputs[0], ctx)
        pad_y = max(0, (self.out_y - 1) * p.stride + p.kernel_height - self.in_y)
        pad_x = max(0, (self.out_x - 1) * p.stride + p.kernel_width - self.in_x)
        window = (1, p.kernel_height, p.kernel_width, 1)
        strides = (1, p.stride, p.stride, 1)
        padding = ((0, 0), (p.pad_y, p.pad_y + pad_y),
                   (p.pad_x, p.pad_x + pad_x), (0, 0))
        if self.reducer == "max":
            init = -jnp.inf
            out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides,
                                        padding)
        else:
            out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                        padding)
            if self.reducer == "avg":
                out = out * (1.0 / (p.kernel_height * p.kernel_width))
        return [out]


@register_layer
class MaxPoolingLayer(_PoolingLayer):
    type_name = "max_pooling"
    reducer = "max"


@register_layer
class SumPoolingLayer(_PoolingLayer):
    type_name = "sum_pooling"
    reducer = "sum"


@register_layer
class AvgPoolingLayer(_PoolingLayer):
    type_name = "avg_pooling"
    reducer = "avg"


@register_layer
class ReluMaxPoolingLayer(MaxPoolingLayer):
    """max pooling with fused relu pre-activation; XLA fuses the two ops."""
    type_name = "relu_max_pooling"

    def pre_activation(self, x, ctx):
        return jnp.maximum(x, 0.0)


@register_layer
class InsanityMaxPoolingLayer(MaxPoolingLayer):
    """max pooling with randomized-leaky (insanity/RReLU) pre-activation."""
    type_name = "insanity_max_pooling"
    uses_rng = True

    def __init__(self, spec, cfg):
        self.lb, self.ub = 5.0, 10.0
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "lb":
            self.lb = float(val)
        elif name == "ub":
            self.ub = float(val)

    def pre_activation(self, x, ctx):
        if ctx.train:
            u = jax.random.uniform(ctx.next_key(), x.shape, x.dtype)
            return xelu(x, u * (self.ub - self.lb) + self.lb)
        return xelu(x, (self.lb + self.ub) / 2.0)


@register_layer
class LRNLayer(Layer):
    """Cross-channel local response normalization."""
    type_name = "lrn"

    def __init__(self, spec, cfg):
        self.nsize = 3
        self.alpha = 1e-4     # reference leaves alpha/beta uninitialized (bug);
        self.beta = 0.75      # configs always set them — these are Caffe defaults
        self.knorm = 1.0
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "local_size":
            self.nsize = int(val)
        elif name == "alpha":
            self.alpha = float(val)
        elif name == "beta":
            self.beta = float(val)
        elif name == "knorm":
            self.knorm = float(val)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        return [self.check_one_to_one(in_shapes)]

    def apply(self, params, inputs, ctx):
        # the Pallas fused LRN is opt-in (CXN_PALLAS_LRN=1): measured on one
        # v5e chip the XLA band-matmul path below still wins at every width
        # tried (fwd+bwd bf16: 10.9 vs 18.9 ms @ 1024x55x55x96, 8.0 vs 11.5
        # @ 1024x27x27x256, 5.4 vs 5.8 @ 256x14x14x1024) — sub-128 channel
        # widths halve the kernel's DMA efficiency, and XLA's pow/scale
        # fusion is already near the HBM floor
        import os
        from ..ops.pallas_kernels import (LRN_MAX_CHANNELS, lrn_fused,
                                          use_pallas)
        x = inputs[0]
        n = self.nsize
        if (use_pallas() and os.environ.get("CXN_PALLAS_LRN", "") == "1"
                and n <= x.shape[-1] <= LRN_MAX_CHANNELS):
            return [lrn_fused(x, n, self.alpha, self.beta, self.knorm)]
        c_dim = x.shape[-1]
        if (n <= c_dim <= 4096
                and os.environ.get("CXN_LRN_REDUCE_WINDOW", "") != "1"):
            # band-matmul windowed sum: the cross-channel window rides the
            # MXU as x^2 @ B (C x C 0/1 band), instead of a reduce_window
            # along the 128-lane minor dim (measured on one v5e chip, bf16
            # fwd+bwd, bit-identical output: 7.3ms vs 52.4ms @
            # 512x55x55x96, 11.3 vs 29.7 @ 512x27x27x256, and still ahead
            # at every width tried up to 6.1 vs 7.6 @ 64x7x7x4096). Beyond
            # C=4096 the O(C^2) dense band is unmeasured, so fall back;
            # CXN_LRN_REDUCE_WINDOW=1 forces the fallback at any width.
            sq_sum = jax.lax.dot_general(
                x * x, self._band_matrix(c_dim, x.dtype),
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(x.dtype)
        else:
            pad_lo = (n - 1) // 2
            sq_sum = jax.lax.reduce_window(
                x * x, 0.0, jax.lax.add, (1, 1, 1, n), (1, 1, 1, 1),
                ((0, 0), (0, 0), (0, 0), (pad_lo, n - 1 - pad_lo)))
        norm = self.knorm + (self.alpha / n) * sq_sum
        return [x * norm ** (-self.beta)]

    def _band_matrix(self, c_dim: int, dtype) -> jnp.ndarray:
        """(C, C) 0/1 matrix: B[j, c] = 1 iff channel j falls in the size-n
        window centered (reference-style, left-biased) on channel c."""
        n, pad_lo = self.nsize, (self.nsize - 1) // 2
        j = np.arange(c_dim)[:, None]
        c = np.arange(c_dim)[None, :]
        band = (j >= c - pad_lo) & (j <= c + n - 1 - pad_lo)
        return jnp.asarray(band, dtype)


@register_layer
class BatchNormLayer(Layer):
    """Per-channel batch normalization with learned slope ("wmat") and bias.

    Default reproduces the reference quirk: eval mode also normalizes with the
    current mini-batch statistics. ``moving_average = 1`` opts into running
    statistics for eval (modern behavior; running stats live in net state,
    not in params, so they are excluded from gradients).
    """
    type_name = "batch_norm"
    has_state = True

    def __init__(self, spec, cfg):
        self.init_slope = 1.0
        self.init_bias_bn = 0.0
        self.eps = 1e-10
        self.moving_average = 0
        self.bn_momentum = 0.9
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "init_slope":
            self.init_slope = float(val)
        elif name == "init_bias":
            self.init_bias_bn = float(val)
        elif name == "eps":
            self.eps = float(val)
        elif name == "moving_average":
            self.moving_average = int(val)
        elif name == "bn_momentum":
            self.bn_momentum = float(val)

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        shape = self.check_one_to_one(in_shapes)
        c, y, x = shape
        self.channel = x if (c == 1 and y == 1) else c
        return [shape]

    def init_params(self, key, in_shapes):
        return {
            "wmat": jnp.full((self.channel,), self.init_slope, jnp.float32),
            "bias": jnp.full((self.channel,), self.init_bias_bn, jnp.float32),
        }

    def init_state(self):
        if not self.moving_average:
            return {}
        return {"mean": jnp.zeros((self.channel,), jnp.float32),
                "var": jnp.ones((self.channel,), jnp.float32)}

    def apply(self, params, inputs, ctx):
        x = inputs[0]
        key = self.spec.key()
        axes = tuple(range(x.ndim - 1))     # all but channel (NHWC last)
        state = ctx.states.get(key)
        if ctx.train or not self.moving_average:
            # one fused pass over x: f32-accumulated sums of (x-c) and
            # (x-c)^2 where c is a per-channel sample (shifted-variance
            # algorithm). The naive mean(square(x - mean)) costs an extra
            # full-tensor pass and, for bf16 inputs, accumulates in bf16 —
            # measured 42% of a ResNet-50 step. The shift kills the
            # E[x^2]-E[x]^2 cancellation when |mean| >> std, and
            # stop_gradient(c) is exactly gradient-neutral (d mean/dc =
            # d var/dc = 0 analytically)
            n = 1
            for a in axes:
                n *= x.shape[a]
            c = jax.lax.stop_gradient(
                x[(0,) * (x.ndim - 1)].astype(jnp.float32))
            xs = x.astype(jnp.float32) - c
            s1 = jnp.sum(xs, axis=axes, dtype=jnp.float32)
            s2 = jnp.sum(jnp.square(xs), axis=axes, dtype=jnp.float32)
            mean = c + s1 / n
            var = jnp.maximum(s2 / n - jnp.square(s1 / n), 0.0)
            if ctx.train and self.moving_average and state:
                m = self.bn_momentum
                ctx.new_states[key] = {
                    "mean": m * state["mean"] + (1 - m) * jax.lax.stop_gradient(mean),
                    "var": m * state["var"] + (1 - m) * jax.lax.stop_gradient(var)}
        else:
            mean, var = state["mean"], state["var"]
        inv = jax.lax.rsqrt(var + self.eps)
        scale = (inv * params["wmat"]).astype(x.dtype)
        shift = (params["bias"] - mean * inv * params["wmat"]).astype(x.dtype)
        return [x * scale + shift]
