"""Layer base classes, parameter parsing, weight init, and the type registry.

TPU-first redesign of the reference layer system (/root/reference/src/layer/layer.h:161-279):
layers here are *pure functions* — ``apply(params, inputs, ctx) -> outputs`` — so the
whole graph executes inside one jitted, differentiable train step. There are no
gradient buffers and no Backprop methods: JAX autodiff replaces the hand-derived
backward passes, and XLA fuses what mshadow expression templates used to fuse.

Runtime node layout is **NHWC** ``(batch, y, x, channel)`` — the layout the TPU
MXU/XLA prefers — while config-level shapes remain the reference's logical
``(channel, y, x)`` triples (layer.h:30-71 uses NCHW). Matrix nodes are
``(batch, 1, 1, length)`` in both conventions.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph import LayerSpec
from ..utils.config import ConfigError

Shape3 = Tuple[int, int, int]          # logical (c, y, x)
Params = Dict[str, jnp.ndarray]


class LayerParam:
    """Common hyper-parameters, same names/defaults as the reference
    (/root/reference/src/layer/param.h:15-111)."""

    def __init__(self) -> None:
        self.init_sigma = 0.01
        self.init_uniform = -1.0
        self.init_sparse = 10
        self.init_bias = 0.0
        self.random_type = 0           # 0 gaussian, 1 uniform/xavier, 2 kaiming
        self.num_hidden = 0
        self.num_channel = 0
        self.num_group = 1
        self.kernel_width = 0
        self.kernel_height = 0
        self.stride = 1
        self.pad_x = 0
        self.pad_y = 0
        self.no_bias = 0
        self.silent = 0
        self.num_input_channel = 0
        self.num_input_node = 0

    def set_param(self, name: str, val: str) -> None:
        if name == "init_sigma":
            self.init_sigma = float(val)
        elif name == "init_uniform":
            self.init_uniform = float(val)
        elif name == "init_bias":
            self.init_bias = float(val)
        elif name == "init_sparse":
            self.init_sparse = int(val)
        elif name == "random_type":
            if val == "gaussian":
                self.random_type = 0
            elif val in ("uniform", "xavier"):
                self.random_type = 1
            elif val == "kaiming":
                self.random_type = 2
            else:
                raise ConfigError("invalid random_type %r" % val)
        elif name == "nhidden":
            self.num_hidden = int(val)
        elif name == "nchannel":
            self.num_channel = int(val)
        elif name == "ngroup":
            self.num_group = int(val)
        elif name == "kernel_size":
            self.kernel_width = self.kernel_height = int(val)
        elif name == "kernel_height":
            self.kernel_height = int(val)
        elif name == "kernel_width":
            self.kernel_width = int(val)
        elif name == "stride":
            self.stride = int(val)
        elif name == "pad":
            self.pad_x = self.pad_y = int(val)
        elif name == "pad_x":
            self.pad_x = int(val)
        elif name == "pad_y":
            self.pad_y = int(val)
        elif name == "no_bias":
            self.no_bias = int(val)
        elif name == "silent":
            self.silent = int(val)

    def rand_init(self, key: jax.Array, shape: Sequence[int],
                  in_num: int, out_num: int) -> jnp.ndarray:
        """Weight init with the reference's schemes (param.h:113-138):
        gaussian(init_sigma) | xavier-uniform sqrt(3/(in+out)) | kaiming."""
        if self.random_type == 0:
            return self.init_sigma * jax.random.normal(key, shape, jnp.float32)
        if self.random_type == 1:
            a = math.sqrt(3.0 / (in_num + out_num))
            if self.init_uniform > 0:
                a = self.init_uniform
            return jax.random.uniform(key, shape, jnp.float32, -a, a)
        if self.random_type == 2:
            if self.num_hidden > 0:
                sigma = math.sqrt(2.0 / self.num_hidden)
            else:
                sigma = math.sqrt(
                    2.0 / (self.num_channel * self.kernel_width * self.kernel_height))
            return sigma * jax.random.normal(key, shape, jnp.float32)
        raise ConfigError("unsupported random_type %d" % self.random_type)


class ApplyContext:
    """Per-step execution context threaded through layer ``apply`` calls.

    Replaces the reference's LabelInfo plumbing + per-layer RNG + loss-layer
    batch scaling (loss_layer_base-inl.hpp:61-63). ``losses`` collects scalar
    loss contributions; autodiff of their sum reproduces the reference's
    hand-written loss gradients.
    """

    def __init__(self, train: bool, rng: Optional[jax.Array],
                 labels: Optional[Dict[str, jnp.ndarray]] = None,
                 sample_mask: Optional[jnp.ndarray] = None,
                 batch_size: int = 0, update_period: int = 1,
                 epoch=0, states: Optional[dict] = None,
                 mesh=None, compute_dtype=jnp.float32) -> None:
        self.train = train
        self.mesh = mesh    # device mesh (static); lets layers pick
                            # sequence-parallel implementations
        # activation dtype (the net's `precision`): most layers derive it
        # from their input's dtype (the data node is cast on entry), but
        # integer-indexed entries (embedding ids) must stay exact f32, so
        # the embedding lookup reads the target dtype from here instead
        self.compute_dtype = compute_dtype
        self._rng = rng
        self._rng_count = 0
        self.labels = labels or {}
        self.sample_mask = sample_mask    # (batch,) 1.0 = real sample, 0.0 = pad
        self.batch_size = batch_size      # configured *global* batch size
        self.update_period = update_period
        self.epoch = epoch                # update-step counter (traced scalar ok)
        self.losses: List[jnp.ndarray] = []
        # mutable per-layer state (e.g. BN running stats), keyed by layer key:
        # read from `states`, updates land in `new_states` (functional pytree)
        self.states: dict = states or {}
        self.new_states: dict = dict(self.states)

    def next_key(self) -> jax.Array:
        if self._rng is None:
            raise RuntimeError("layer requested randomness but no rng was provided")
        self._rng_count += 1
        return jax.random.fold_in(self._rng, self._rng_count)

    def mask4(self, x: jnp.ndarray) -> jnp.ndarray:
        """Broadcast the sample mask against a (b, ...) tensor."""
        if self.sample_mask is None:
            return jnp.ones((x.shape[0],) + (1,) * (x.ndim - 1), x.dtype)
        return self.sample_mask.astype(x.dtype).reshape(
            (x.shape[0],) + (1,) * (x.ndim - 1))


class Layer:
    """Base class. Subclasses define shape inference, parameter init, and the
    pure forward function. ``cfg`` is the merged global+scoped config."""

    type_name: str = ""
    uses_rng = False          # needs ctx rng at train time
    is_loss = False
    has_state = False         # mutable per-layer state (BN running stats)

    def __init__(self, spec: LayerSpec, cfg: Sequence[Tuple[str, str]]):
        self.spec = spec
        self.param = LayerParam()
        self.cfg = list(cfg)
        for k, v in self.cfg:
            self.param.set_param(k, v)
            self.set_param(k, v)

    # hooks ------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        pass

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        raise NotImplementedError

    def init_params(self, key: jax.Array, in_shapes: List[Shape3]) -> Params:
        return {}

    def param_axes(self, tag: str) -> Optional[Tuple[Optional[str], ...]]:
        """Logical mesh-axis names per dim of weight ``tag`` for tensor
        parallelism (None = replicate). The sharding resolver degrades any
        axis that doesn't divide evenly back to replication, so layers can
        declare intent unconditionally. Default: fully replicated."""
        return None

    def apply(self, params: Params, inputs: List[jnp.ndarray],
              ctx: ApplyContext) -> List[jnp.ndarray]:
        raise NotImplementedError

    # helpers ----------------------------------------------------------
    def check_one_to_one(self, in_shapes: List[Shape3]) -> Shape3:
        if len(in_shapes) != 1:
            raise ConfigError("%s: only supports 1-1 connection" % self.type_name)
        return in_shapes[0]


# ----------------------------------------------------------------------------
# registry
LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls: type) -> type:
    LAYER_REGISTRY[cls.type_name] = cls
    return cls


def create_layer(spec: LayerSpec, global_cfg: Sequence[Tuple[str, str]]) -> Layer:
    """Factory (layer_impl-inl.hpp:36-76 analogue). Config merge order mirrors
    the reference: global defcfg first, then the layer-scoped block."""
    if spec.type not in LAYER_REGISTRY:
        raise ConfigError("unknown or unsupported layer type %r" % spec.type)
    merged = list(global_cfg) + list(spec.cfg)
    return LAYER_REGISTRY[spec.type](spec, merged)


def logical_to_runtime(shape: Shape3) -> Tuple[int, int, int]:
    """(c, y, x) logical -> (y, x, c) runtime NHWC order."""
    c, y, x = shape
    return (y, x, c)


def flat_dim(shape: Shape3) -> int:
    c, y, x = shape
    return c * y * x
