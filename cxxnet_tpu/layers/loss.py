"""Loss layers: softmax, l2_loss, multi_logistic.

Reference semantics (/root/reference/src/layer/loss/):
- loss layers are *self-loop* layers whose forward writes the prediction into
  the node and whose backward writes the loss gradient scaled by
  ``grad_scale / (batch_size * update_period)`` (loss_layer_base-inl.hpp:61-63)
  — the global-batch normalization happens in the loss, not the updater.
- ``target`` selects a named label field (loss_layer_base-inl.hpp:31-45).

Here each loss layer both emits its forward output (so prediction/extraction
see probabilities, as in the reference) and records a scalar loss contribution
in the ApplyContext; ``d(total_loss)/d(input)`` under autodiff equals the
reference's hand-written gradients exactly:
- softmax  (softmax_layer-inl.hpp:23-32): grad = p - onehot  -> loss = sum CE
- l2_loss  (l2_loss_layer-inl.hpp):       grad = pred - label -> loss = sum 0.5*(pred-label)^2
- multi_logistic (multi_logistic_layer-inl.hpp): out = sigmoid(in),
  grad = out - label -> loss = sum BCE(in, label)

Padded samples (round_batch tail) are masked out of the loss and therefore
out of the gradient — the static-shape answer to the reference's dynamic
last-batch resizing (neural_net-inl.hpp:266-277).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..utils.config import ConfigError
from .base import ApplyContext, Layer, Params, Shape3, register_layer


class LossLayer(Layer):
    is_loss = True

    def __init__(self, spec, cfg):
        self.grad_scale = 1.0
        self.target = "label"
        super().__init__(spec, cfg)

    def set_param(self, name, val):
        if name == "grad_scale":
            self.grad_scale = float(val)
        elif name == "target":
            self.target = val

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        shape = self.check_one_to_one(in_shapes)
        if self.spec.inputs != self.spec.outputs:
            raise ConfigError("%s is a self-loop layer (layer[+0])"
                              % self.type_name)
        return [shape]

    def scale(self, ctx: ApplyContext):
        if ctx.batch_size <= 0:
            raise ConfigError("loss layer requires batch_size to be configured")
        return self.grad_scale / (ctx.batch_size * ctx.update_period)

    def get_label(self, ctx: ApplyContext) -> jnp.ndarray:
        if self.target not in ctx.labels:
            raise ConfigError("loss target label field %r not found (have %r)"
                              % (self.target, sorted(ctx.labels)))
        return ctx.labels[self.target]

    def mask1(self, ctx: ApplyContext, b: int) -> jnp.ndarray:
        if ctx.sample_mask is None:
            return jnp.ones((b,), jnp.float32)
        return ctx.sample_mask.astype(jnp.float32)


@register_layer
class SoftmaxLayer(LossLayer):
    """Forward: softmax over the flattened feature dim; loss: cross-entropy
    against an integer class label (first column of the target field)."""
    type_name = "softmax"

    def apply(self, params: Params, inputs, ctx: ApplyContext):
        x = inputs[0]
        logits = x.reshape(x.shape[0], -1)
        probs = jax.nn.softmax(logits, axis=-1)
        if ctx.train:
            label = self.get_label(ctx)[:, 0].astype(jnp.int32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ce = -jnp.take_along_axis(logp, label[:, None], axis=-1)[:, 0]
            mask = self.mask1(ctx, x.shape[0])
            ctx.losses.append(jnp.sum(ce * mask) * self.scale(ctx))
        return [probs.reshape(x.shape)]


@register_layer
class LMSoftmaxLayer(LossLayer):
    """Causal language-model loss on sequence nodes: next-token
    cross-entropy over every position (position i predicts token i+1; the
    last position predicts nothing — models/gpt.py:gpt_loss semantics,
    exposed through the config DSL so the GPT flagship trains from a
    netconfig file).

    Input node: (b, N, 1, V) per-position logits. Target: a label field of
    width N holding the token ids themselves (for an LM the label IS the
    input sequence — the data pipeline feeds ids as both data and label).
    Loss per sample = mean NLL over the N-1 predicting positions, then the
    reference loss scaling (grad_scale / (batch * update_period)) over the
    batch sum — equal to gpt_loss's flat mean at grad_scale 1. Forward
    emits per-position probabilities (prediction/extraction see them, like
    every loss layer)."""
    type_name = "lm_softmax"

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        shape = super().infer_shapes(in_shapes)
        if shape[0][2] != 1 or shape[0][1] < 2:
            raise ConfigError(
                "lm_softmax: expects (vocab, seq>=2, 1) sequence nodes, "
                "got %r" % (shape[0],))
        return shape

    def apply(self, params: Params, inputs, ctx: ApplyContext):
        x = inputs[0]                            # (b, N, 1, V)
        b, n, _, v = x.shape
        logits = x.reshape(b, n, v)
        if ctx.train:
            ids = self.get_label(ctx)
            if ids.shape[1] != n:
                raise ConfigError(
                    "lm_softmax: label field %r has width %d, need the %d "
                    "token ids (label = the input sequence)"
                    % (self.target, ids.shape[1], n))
            tgt = ids[:, 1:].astype(jnp.int32)
            logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32),
                                      axis=-1)
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
            mask = self.mask1(ctx, b)
            ctx.losses.append(
                jnp.sum(jnp.mean(nll, axis=-1) * mask) * self.scale(ctx))
        return [jax.nn.softmax(logits, axis=-1).reshape(x.shape)]


@register_layer
class L2LossLayer(LossLayer):
    """Identity forward; loss 0.5*||pred - label||^2 per sample."""
    type_name = "l2_loss"

    def apply(self, params: Params, inputs, ctx: ApplyContext):
        x = inputs[0]
        if ctx.train:
            pred = x.reshape(x.shape[0], -1)
            label = self.get_label(ctx).astype(pred.dtype)
            if label.shape[1] != pred.shape[1]:
                raise ConfigError(
                    "l2_loss: label width %d != prediction width %d"
                    % (label.shape[1], pred.shape[1]))
            diff = pred - label
            mask = self.mask1(ctx, x.shape[0])
            ctx.losses.append(
                0.5 * jnp.sum(jnp.sum(diff * diff, axis=-1) * mask)
                * self.scale(ctx))
        return [x]


@register_layer
class MultiLogisticLayer(LossLayer):
    """Forward: elementwise sigmoid; loss: multi-label binary cross-entropy."""
    type_name = "multi_logistic"

    def apply(self, params: Params, inputs, ctx: ApplyContext):
        x = inputs[0]
        logits = x.reshape(x.shape[0], -1)
        out = jax.nn.sigmoid(logits)
        if ctx.train:
            label = self.get_label(ctx).astype(logits.dtype)
            if label.shape[1] != logits.shape[1]:
                raise ConfigError(
                    "multi_logistic: label width %d != prediction width %d"
                    % (label.shape[1], logits.shape[1]))
            # stable BCE on logits: max(z,0) - z*y + log(1+exp(-|z|))
            bce = (jnp.maximum(logits, 0.0) - logits * label
                   + jnp.log1p(jnp.exp(-jnp.abs(logits))))
            mask = self.mask1(ctx, x.shape[0])
            ctx.losses.append(
                jnp.sum(jnp.sum(bce, axis=-1) * mask) * self.scale(ctx))
        return [out.reshape(x.shape)]
