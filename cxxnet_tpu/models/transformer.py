"""Transformer encoder expressed in the config DSL — the long-context
flagship.

The reference has no attention at all (SURVEY §5.7); this model family shows
the framework's first-class long-context path: pre-LN encoder blocks built
from the attention / layer_norm / add / split layers, sequence-parallel via
``seq_parallel = k`` (ring attention over the mesh's ``seq`` axis) and
tensor-parallel via ``model_parallel``.

Graph per block (pre-LN):
    x -> split -> [ln1 -> attention] -> add(x) -> split -> [ln2 -> fullc
    -> relu -> fullc] -> add -> out

The default net is a sequence *classifier* (mean-pool head + softmax) so it
trains against the standard label pipeline; ``causal=1`` turns the attention
masks autoregressive.
"""

from __future__ import annotations


def transformer_block(L, src: str, out: str, i: int, feat: int, nhead: int,
                      causal: int, mlp_ratio: int = 4,
                      moe_experts: int = 0,
                      seq_parallel_mode: str = "ring") -> None:
    # position-wise MLP = 1x1 conv on the (b, N, 1, F) node; with
    # moe_experts > 0 the MLP becomes a switch-MoE (expert parallelism)
    a, b = "b%da" % i, "b%db" % i
    L.append("layer[%s->%s,%s_r] = split" % (src, a, a))
    L.append("layer[%s->%s] = layer_norm:ln%da" % (a, a, i))
    L.append("layer[%s->%s] = attention:att%d" % (a, a, i))
    L.append("  nhead = %d" % nhead)
    if seq_parallel_mode != "ring":
        L.append("  seq_parallel_mode = %s" % seq_parallel_mode)
    if causal:
        L.append("  causal = 1")
    L.append("layer[%s,%s_r->%s] = add" % (a, a, b))
    L.append("layer[%s->%s,%s_r] = split" % (b, b, b))
    L.append("layer[%s->%s] = layer_norm:ln%db" % (b, b, i))
    if moe_experts > 0:
        L.append("layer[%s->%s] = moe:moe%d" % (b, b, i))
        L.append("  nexpert = %d" % moe_experts)
        L.append("  nhidden = %d" % (feat * mlp_ratio))
    else:
        L.append("layer[%s->%s] = conv:mlp%da" % (b, b, i))
        L.append("  kernel_size = 1")
        L.append("  nchannel = %d" % (feat * mlp_ratio))
        L.append("layer[%s->%s] = relu" % (b, b))
        L.append("layer[%s->%s] = conv:mlp%db" % (b, b, i))
        L.append("  kernel_size = 1")
        L.append("  nchannel = %d" % feat)
    L.append("layer[%s,%s_r->%s] = add" % (b, b, out))


def gpt_lm_config(seq_len: int = 128, vocab_size: int = 256,
                  feat: int = 64, nhead: int = 4, nblock: int = 4,
                  mlp_ratio: int = 4, batch_size: int = 16, dev: str = "",
                  seq_parallel: int = 1, model_parallel: int = 1,
                  pipeline_parallel: int = 1, pipeline_microbatch: int = 0,
                  precision: str = "float32", eta: float = 0.1,
                  remat: int = 0, remat_mode: str = "block",
                  attn_layout: str = "auto", zero: int = 0,
                  updater: str = "sgd", momentum: float = 0.9,
                  moe_experts: int = 0,
                  seq_parallel_mode: str = "ring") -> str:
    """Causal GPT language model in the config DSL — the netconfig twin of
    the models/gpt.py flagship, with the SAME performance levers exposed
    as config keys: ``remat`` / ``remat_mode`` (block | attn_saved),
    ``attn_layout`` (auto | bnhd | bhnd), ``zero`` (= shard_optimizer
    levels 1/2/3), and the four parallel axes. The data pipeline feeds
    token ids as BOTH the data node (b, 1, 1, N) and the label field
    (width N); the ``lm_softmax`` loss trains next-token prediction
    (gpt.py:gpt_loss semantics).

    Per-position MLP halves are 1x1 convs and the LM head is a 1x1 conv
    to vocab — XLA lowers both to the same matmuls as gpt.py's einsums.
    """
    L = ["netconfig=start"]
    L.append("layer[0->emb] = embedding:emb")
    L.append("  vocab_size = %d" % vocab_size)
    L.append("  nhidden = %d" % feat)
    src = "emb"
    for i in range(nblock):
        out = "blk%d" % i
        transformer_block(L, src, out, i, feat, nhead, causal=1,
                          mlp_ratio=mlp_ratio, moe_experts=moe_experts,
                          seq_parallel_mode=seq_parallel_mode)
        src = out
    L.append("layer[%s->%s] = layer_norm:lnf" % (src, src))
    L.append("layer[%s->logits] = conv:head" % src)
    L.append("  kernel_size = 1")
    L.append("  nchannel = %d" % vocab_size)
    L.append("  init_sigma = 0.02")
    L.append("  no_bias = 1")
    L.append("layer[logits->logits] = lm_softmax")
    L.append("  target = ids")
    L.append("netconfig=end")
    dev_line = ("dev = %s" % dev) if dev else ""
    L.append("""
input_shape = 1,1,%d
label_vec[0,%d) = ids
batch_size = %d
%s
seq_parallel = %d
model_parallel = %d
pipeline_parallel = %d
pipeline_microbatch = %d
precision = %s
remat = %d
remat_mode = %s
attn_layout = %s
zero = %d
updater = %s
random_type = gaussian
init_sigma = 0.02
eta = %g
momentum = %g
metric[ids] = lm_nll
""" % (seq_len, seq_len, batch_size, dev_line, seq_parallel, model_parallel,
       pipeline_parallel, pipeline_microbatch, precision, remat, remat_mode,
       attn_layout, zero, updater, eta, momentum))
    return "\n".join(L)


def transformer_config(seq_len: int = 128, vocab_size: int = 256,
                       feat: int = 64, nhead: int = 4, nblock: int = 2,
                       num_classes: int = 10, causal: int = 0,
                       batch_size: int = 16, dev: str = "",
                       seq_parallel: int = 1, model_parallel: int = 1,
                       moe_experts: int = 0, precision: str = "float32",
                       eta: float = 0.05,
                       seq_parallel_mode: str = "ring",
                       pipeline_parallel: int = 1,
                       pipeline_microbatch: int = 0) -> str:
    L = ["netconfig=start"]
    L.append("layer[0->emb] = embedding:emb")
    L.append("  vocab_size = %d" % vocab_size)
    L.append("  nhidden = %d" % feat)
    src = "emb"
    for i in range(nblock):
        out = "blk%d" % i
        transformer_block(L, src, out, i, feat, nhead, causal,
                          moe_experts=moe_experts,
                          seq_parallel_mode=seq_parallel_mode)
        src = out
    L.append("layer[%s->%s] = layer_norm:lnf" % (src, src))
    # mean-pool over the sequence -> (b, 1, 1, feat) -> classifier head
    L.append("layer[%s->pool] = avg_pooling" % src)
    L.append("  kernel_height = %d" % seq_len)
    L.append("  kernel_width = 1")
    L.append("  stride = %d" % seq_len)
    L.append("layer[pool->flat] = flatten")
    L.append("layer[flat->out] = fullc:head")
    L.append("  nhidden = %d" % num_classes)
    L.append("  init_sigma = 0.02")
    L.append("layer[out->out] = softmax")
    L.append("netconfig=end")
    dev_line = ("dev = %s" % dev) if dev else ""
    L.append("""
input_shape = 1,1,%d
batch_size = %d
%s
seq_parallel = %d
model_parallel = %d
pipeline_parallel = %d
pipeline_microbatch = %d
precision = %s
random_type = gaussian
init_sigma = 0.02
eta = %g
momentum = 0.9
metric = error
""" % (seq_len, batch_size, dev_line, seq_parallel, model_parallel,
       pipeline_parallel, pipeline_microbatch, precision, eta))
    return "\n".join(L)
