"""ResNet (He et al. 2015) netconfig generator — bottleneck residual nets.

Beyond the reference's model era (cxxnet predates ResNet; its layer zoo has
concat joins but no residual nets), but entirely expressible in the same
config DSL: the ``add`` N->1 elementwise-sum layer (layers/attention.py)
plays the shortcut join, ``batch_norm`` with ``moving_average = 1`` provides
modern eval-time statistics, and strided 1x1 projection convs downsample the
identity path. Depths: 50 = [3,4,6,3], 101 = [3,4,23,3] bottlenecks.
"""

from __future__ import annotations

_PLANS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3)}


def _bn(L, src, name):
    L.append("layer[%s->%s] = batch_norm:%s" % (src, src, name))
    L.append("  moving_average = 1")


def resnet_config(depth: int = 50, batch_size: int = 256,
                  num_classes: int = 1000, dev: str = "tpu",
                  precision: str = "bfloat16") -> str:
    if depth not in _PLANS:
        raise ValueError("supported depths: %s" % sorted(_PLANS))
    plan = _PLANS[depth]
    L = ["netconfig=start"]

    # stem: 7x7/2 conv + BN + relu + 3x3/2 max pool
    L.append("layer[0->stem] = conv:conv1")
    L.append("  kernel_size = 7")
    L.append("  stride = 2")
    L.append("  pad = 3")
    L.append("  nchannel = 64")
    L.append("  random_type = kaiming")
    L.append("  no_bias = 1")
    _bn(L, "stem", "bn1")
    L.append("layer[stem->stem] = relu")
    # ceil-mode pooling (the reference's formula): k3/s2 unpadded on 112
    # lands on 56, dimensionally equal to torch's pad-1 floor-mode stem
    L.append("layer[stem->p1] = max_pooling")
    L.append("  kernel_size = 3")
    L.append("  stride = 2")

    src = "p1"
    for stage, reps in enumerate(plan, start=2):
        width = 64 * 2 ** (stage - 2)          # bottleneck inner width
        for r in range(1, reps + 1):
            stride = 2 if (r == 1 and stage > 2) else 1
            base = "s%dr%d" % (stage, r)
            # main path: 1x1 (stride) -> 3x3 -> 1x1 (4x width), BN each
            specs = [(1, stride, width), (3, 1, width), (1, 1, 4 * width)]
            inner = src
            for i, (k, st, ch) in enumerate(specs, start=1):
                dst = "%s_c%d" % (base, i)
                L.append("layer[%s->%s] = conv:%s" % (inner, dst, dst))
                L.append("  kernel_size = %d" % k)
                if k == 3:
                    L.append("  pad = 1")
                if st != 1:
                    L.append("  stride = %d" % st)
                L.append("  nchannel = %d" % ch)
                L.append("  random_type = kaiming")
                L.append("  no_bias = 1")
                _bn(L, dst, dst + "_bn")
                if i < 3:
                    L.append("layer[%s->%s] = relu" % (dst, dst))
                inner = dst
            # shortcut: identity, or strided 1x1 projection on stage entry
            if r == 1:
                sc = base + "_sc"
                L.append("layer[%s->%s] = conv:%s" % (src, sc, sc))
                L.append("  kernel_size = 1")
                if stride != 1:
                    L.append("  stride = %d" % stride)
                L.append("  nchannel = %d" % (4 * width))
                L.append("  random_type = kaiming")
                L.append("  no_bias = 1")
                _bn(L, sc, sc + "_bn")
            else:
                sc = src
            out = base
            L.append("layer[%s,%s->%s] = add" % (inner, sc, out))
            L.append("layer[%s->%s] = relu" % (out, out))
            src = out

    L.append("layer[%s->gap] = avg_pooling" % src)
    L.append("  kernel_size = 7")
    L.append("  stride = 7")
    L.append("layer[gap->flat] = flatten")
    L.append("layer[flat->fc] = fullc:fc%d" % num_classes)
    L.append("  nhidden = %d" % num_classes)
    L.append("  init_sigma = 0.01")
    L.append("layer[fc->fc] = softmax")
    L.append("netconfig=end")
    L.append("input_shape = 3,224,224")
    L.append("batch_size = %d" % batch_size)
    if dev:
        L.append("dev = %s" % dev)
    L.append("precision = %s" % precision)
    L.append("eta = 0.1")
    L.append("momentum = 0.9")
    L.append("wd = 0.0001")
    L.append("metric = error")
    L.append("metric = rec@5")
    return "\n".join(L) + "\n"
