"""Inception-BN (Ioffe & Szegedy 2015, GoogLeNet-v2 style) netconfig generator.

Exercises the full structural layer set: split / ch_concat / batch_norm /
grouped pooling branches — the workload BASELINE.md lists as
"Inception-BN-style nets (split/concat/batch-norm layers exist for this)".
The factorized 5x5->double-3x3 towers follow the BN-Inception paper.
"""

from __future__ import annotations


def _conv_bn_relu(lines, src, dst, name, nch, k, pad=0, stride=1):
    lines.append("layer[%s->%s] = conv:%s" % (src, dst, name))
    lines.append("  kernel_size = %d" % k)
    if pad:
        lines.append("  pad = %d" % pad)
    if stride != 1:
        lines.append("  stride = %d" % stride)
    lines.append("  nchannel = %d" % nch)
    lines.append("  random_type = xavier")
    lines.append("  no_bias = 1")
    lines.append("layer[%s->%s] = batch_norm:%s_bn" % (dst, dst, name))
    lines.append("layer[%s->%s] = relu" % (dst, dst))
    return dst


def _inception(lines, src, prefix, n1, n3r, n3, nd3r, nd3, pool, npool,
               stride=1):
    """One inception module; returns the output node name."""
    branches = []
    # branch tags
    b1 = "%s_b1" % prefix
    b3a, b3b = "%s_b3r" % prefix, "%s_b3" % prefix
    bd1, bd2, bd3 = "%s_bd3r" % prefix, "%s_bd3a" % prefix, "%s_bd3b" % prefix
    bp, bpc = "%s_pool" % prefix, "%s_proj" % prefix
    fan = []
    if n1 > 0:
        fan.append(b1)
    fan.extend([b3a, bd1, bp])
    lines.append("layer[%s->%s] = split" % (src, ",".join(fan)))
    if n1 > 0:
        _conv_bn_relu(lines, b1, b1, "%s_1x1" % prefix, n1, 1)
        branches.append(b1)
    _conv_bn_relu(lines, b3a, b3a, "%s_3x3r" % prefix, n3r, 1)
    _conv_bn_relu(lines, b3a, b3b, "%s_3x3" % prefix, n3, 3, pad=1,
                  stride=stride)
    branches.append(b3b)
    _conv_bn_relu(lines, bd1, bd1, "%s_d3r" % prefix, nd3r, 1)
    _conv_bn_relu(lines, bd1, bd2, "%s_d3a" % prefix, nd3, 3, pad=1)
    _conv_bn_relu(lines, bd2, bd3, "%s_d3b" % prefix, nd3, 3, pad=1,
                  stride=stride)
    branches.append(bd3)
    lines.append("layer[%s->%s] = %s_pooling" % (bp, bp, pool))
    if stride == 1:
        # 'same'-size pooling branch: k3 s1 with symmetric pad 1
        lines.append("  kernel_size = 3")
        lines.append("  pad = 1")
    else:
        # reduction: k2 s2 matches the stride-2 pad-1 3x3 conv branches'
        # floor((H-1)/2)+1 output under our ceil-mode formula
        lines.append("  kernel_size = 2")
    lines.append("  stride = %d" % stride)
    if npool > 0:
        _conv_bn_relu(lines, bp, bpc, "%s_proj" % prefix, npool, 1)
        branches.append(bpc)
    else:
        branches.append(bp)
    out = "%s_out" % prefix
    lines.append("layer[%s->%s] = ch_concat" % (",".join(branches), out))
    return out


def inception_bn_config(batch_size: int = 128, num_classes: int = 1000,
                        dev: str = "tpu", precision: str = "bfloat16") -> str:
    """Full-size BN-Inception stem + 3a/3b towers + reduction + 4a + head.

    NOTE on fidelity: pooling-branch padding differs from the paper (our
    pooling layer is pad-free ceil-mode, as in the reference framework), so
    modules use stride-2 reductions where spatial dims must align.
    """
    L = []
    L.append("netconfig=start")
    # stem: 7x7/2 conv, pool, 3x3 conv, pool
    _conv_bn_relu(L, "0", "stem1", "conv1", 64, 7, pad=3, stride=2)
    L.append("layer[stem1->stem1p] = max_pooling")
    L.append("  kernel_size = 3")
    L.append("  stride = 2")
    _conv_bn_relu(L, "stem1p", "stem2r", "conv2r", 64, 1)
    _conv_bn_relu(L, "stem2r", "stem2", "conv2", 192, 3, pad=1)
    L.append("layer[stem2->stem2p] = max_pooling")
    L.append("  kernel_size = 3")
    L.append("  stride = 2")
    n = _inception(L, "stem2p", "i3a", 64, 64, 64, 64, 96, "avg", 32)
    n = _inception(L, n, "i3b", 64, 64, 96, 64, 96, "avg", 64)
    n = _inception(L, n, "i3c", 0, 128, 160, 64, 96, "max", 0, stride=2)
    n = _inception(L, n, "i4a", 224, 64, 96, 96, 128, "avg", 128)
    n = _inception(L, n, "i4b", 192, 96, 128, 96, 128, "avg", 128)
    n = _inception(L, n, "i4c", 160, 128, 160, 128, 160, "avg", 128)
    n = _inception(L, n, "i4d", 96, 128, 192, 160, 192, "avg", 128)
    n = _inception(L, n, "i4e", 0, 128, 192, 192, 256, "max", 0, stride=2)
    n = _inception(L, n, "i5a", 352, 192, 320, 160, 224, "avg", 128)
    n = _inception(L, n, "i5b", 352, 192, 320, 192, 224, "max", 128)
    # global average pool + classifier
    L.append("layer[%s->gap] = avg_pooling" % n)
    L.append("  kernel_size = 7")
    L.append("  stride = 1")
    L.append("layer[gap->flat] = flatten")
    L.append("layer[flat->fc] = fullc:fc1")
    L.append("  nhidden = %d" % num_classes)
    L.append("  init_sigma = 0.01")
    L.append("layer[fc->fc] = softmax")
    L.append("netconfig=end")
    L.append("input_shape = 3,224,224")
    L.append("batch_size = %d" % batch_size)
    if dev:
        L.append("dev = %s" % dev)
    L.append("precision = %s" % precision)
    L.append("eta = 0.05")
    L.append("momentum = 0.9")
    L.append("wd = 0.0001")
    L.append("metric = error")
    L.append("metric = rec@5")
    return "\n".join(L) + "\n"
