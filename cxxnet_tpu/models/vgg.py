"""VGG-16 (Simonyan & Zisserman 2014) netconfig generator — the data-parallel
parity workload from BASELINE.md ("VGG-16 data-parallel across the TPU mesh")."""

from __future__ import annotations

_VGG16_PLAN = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]


def vgg16_config(batch_size: int = 64, num_classes: int = 1000,
                 dev: str = "tpu", precision: str = "bfloat16") -> str:
    L = ["netconfig=start"]
    src = "0"
    node = 0
    for block, (nch, reps) in enumerate(_VGG16_PLAN, start=1):
        for r in range(1, reps + 1):
            dst = "c%d_%d" % (block, r)
            L.append("layer[%s->%s] = conv:conv%d_%d" % (src, dst, block, r))
            L.append("  kernel_size = 3")
            L.append("  pad = 1")
            L.append("  nchannel = %d" % nch)
            L.append("  random_type = xavier")
            L.append("layer[%s->%s] = relu" % (dst, dst))
            src = dst
        dst = "p%d" % block
        L.append("layer[%s->%s] = max_pooling" % (src, dst))
        L.append("  kernel_size = 2")
        L.append("  stride = 2")
        src = dst
    L.append("layer[%s->flat] = flatten" % src)
    for i, nh in ((6, 4096), (7, 4096)):
        L.append("layer[%s->fc%d] = fullc:fc%d" % ("flat" if i == 6
                                                   else "fc6", i, i))
        L.append("  nhidden = %d" % nh)
        L.append("  random_type = xavier")
        L.append("layer[fc%d->fc%d] = relu" % (i, i))
        L.append("layer[fc%d->fc%d] = dropout" % (i, i))
        L.append("  threshold = 0.5")
    L.append("layer[fc7->fc8] = fullc:fc8")
    L.append("  nhidden = %d" % num_classes)
    L.append("  init_sigma = 0.01")
    L.append("layer[fc8->fc8] = softmax")
    L.append("netconfig=end")
    L.append("input_shape = 3,224,224")
    L.append("batch_size = %d" % batch_size)
    if dev:
        L.append("dev = %s" % dev)
    L.append("precision = %s" % precision)
    L.append("eta = 0.01")
    L.append("momentum = 0.9")
    L.append("wd = 0.0005")
    L.append("metric = error")
    L.append("metric = rec@5")
    return "\n".join(L) + "\n"
