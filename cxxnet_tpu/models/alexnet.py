"""AlexNet (Krizhevsky et al. 2012) in the netconfig DSL — the flagship/bench
model, matching the reference's ImageNet example workload (grouped convs, LRN,
dropout; cf. /root/reference/example/ImageNet/ImageNet.conf structure)."""

ALEXNET_NETCONFIG = """
netconfig=start
layer[0->1] = conv:conv1
  kernel_size = 11
  stride = 4
  nchannel = 96
  random_type = gaussian
  init_sigma = 0.01
layer[1->2] = relu
layer[2->3] = lrn
  local_size = 5
  alpha = 0.0001
  beta = 0.75
layer[3->4] = max_pooling
  kernel_size = 3
  stride = 2
layer[4->5] = conv:conv2
  kernel_size = 5
  pad = 2
  ngroup = 2
  nchannel = 256
  init_sigma = 0.01
  init_bias = 1.0
layer[5->6] = relu
layer[6->7] = lrn
  local_size = 5
  alpha = 0.0001
  beta = 0.75
layer[7->8] = max_pooling
  kernel_size = 3
  stride = 2
layer[8->9] = conv:conv3
  kernel_size = 3
  pad = 1
  nchannel = 384
  init_sigma = 0.01
layer[9->10] = relu
layer[10->11] = conv:conv4
  kernel_size = 3
  pad = 1
  ngroup = 2
  nchannel = 384
  init_sigma = 0.01
  init_bias = 1.0
layer[11->12] = relu
layer[12->13] = conv:conv5
  kernel_size = 3
  pad = 1
  ngroup = 2
  nchannel = 256
  init_sigma = 0.01
  init_bias = 1.0
layer[13->14] = relu
layer[14->15] = max_pooling
  kernel_size = 3
  stride = 2
layer[15->16] = flatten
layer[16->16] = dropout
  threshold = 0.5
layer[16->17] = fullc:fc6
  nhidden = 4096
  init_sigma = 0.005
  init_bias = 1.0
layer[17->18] = relu
layer[18->18] = dropout
  threshold = 0.5
layer[18->19] = fullc:fc7
  nhidden = 4096
  init_sigma = 0.005
  init_bias = 1.0
layer[19->20] = relu
layer[20->21] = fullc:fc8
  nhidden = 1000
  init_sigma = 0.01
layer[21->21] = softmax
netconfig=end
input_shape = 3,227,227
"""


def alexnet_config(batch_size: int = 128, dev: str = "tpu",
                   precision: str = "bfloat16", num_classes: int = 1000,
                   eta: float = 0.01) -> str:
    cfg = ALEXNET_NETCONFIG
    if num_classes != 1000:
        cfg = cfg.replace("nhidden = 1000", "nhidden = %d" % num_classes)
    dev_line = ("dev = %s\n" % dev) if dev else ""
    return cfg + """
batch_size = %d
%sprecision = %s
eta = %g
momentum = 0.9
wd = 0.0005
metric = error
metric = rec@5
""" % (batch_size, dev_line, precision, eta)
