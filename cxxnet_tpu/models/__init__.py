"""Built-in model config texts (the framework's example zoo).

These are authored in the framework's netconfig DSL; they correspond to the
workloads that define parity with the reference (BASELINE.md): MNIST MLP /
LeNet-style conv, kaggle-bowl CNN, ImageNet AlexNet, Inception-BN, VGG-16.
"""

from .alexnet import ALEXNET_NETCONFIG, alexnet_config
from .inception_bn import inception_bn_config
from .resnet import resnet_config
from .transformer import gpt_lm_config, transformer_config
from .vgg import vgg16_config

__all__ = ["ALEXNET_NETCONFIG", "alexnet_config", "gpt_lm_config",
           "inception_bn_config", "resnet_config", "transformer_config",
           "vgg16_config"]
