"""GPT-style causal LM — the 4D-parallel flagship (dp x pp x sp x tp).

The reference tops out at data parallelism over a parameter server
(SURVEY §2.7); this model demonstrates the framework's full modern scaling
stack in ONE jitted train step:

- **dp**   batch sharded over ``data`` (gradient psum by GSPMD)
- **pp**   transformer blocks pipelined over ``pipe`` (gpipe microbatches)
- **sp**   sequence sharded over ``seq`` (ring attention K/V rotation)
- **tp**   megatron-style tensor parallelism over ``model``: QKV/MLP-in
           column-sharded, proj/MLP-out row-sharded with an explicit psum —
           written with manual collectives because the block body executes
           inside the gpipe shard_map where GSPMD does not reach.

Everything outside the pipelined blocks (embedding, final norm, LM head,
loss) is plain jnp under jit, partitioned automatically from the argument
shardings.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import (local_attention, local_attention_bhnd,
                             ring_attention_inner,
                             ring_attention_inner_bhnd,
                             ulysses_attention_inner,
                             ulysses_attention_inner_bhnd)
from ..parallel.mesh import (DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
                             batch_sharding)
from ..parallel.pipeline import gpipe


@dataclass
class GPTConfig:
    vocab_size: int = 256
    seq_len: int = 128
    n_layer: int = 4
    n_head: int = 4
    feat: int = 64
    mlp_ratio: int = 4
    n_microbatch: int = 2
    dtype: str = "float32"      # activation dtype ("bfloat16" on real chips)
    remat: bool = False         # rematerialize blocks in backward: trades
    #                             ~1/3 more FLOPs for O(layers) less HBM —
    #                             the long-context/deep-model memory lever
    #                             (jax.checkpoint per transformer block)
    remat_save_attn: bool = False  # under remat_mode="block", also save
    #                             each block's attention output
    #                             (checkpoint_name policy). Measured SLOWER
    #                             both at 85M (330 vs 312 ms/step, 32x1024)
    #                             and 303M (439 vs 423, 16x1024): the flash
    #                             custom-vjp re-runs its forward for its
    #                             internal residuals regardless, so the
    #                             saved output is pure extra HBM traffic.
    #                             Kept for the measurement; prefer
    #                             remat_mode="attn_saved".
    seq_parallel_mode: str = "ring"  # sequence-parallel attention variant
    #                             when the mesh's seq axis is > 1:
    #                             "ring" rotates K/V chunks (works for any
    #                             head count, O((n/P)^2) score memory);
    #                             "ulysses" all-to-alls to head sharding
    #                             and runs full-sequence flash locally
    #                             (needs heads % (sp*tp) == 0). See
    #                             doc/multi-device.md for the crossover.
    attn_layout: str = "auto"   # "bnhd": token-major activations with
    #                             (b,n,h,d)<->(b,h,n,d) transposes at the
    #                             flash-kernel boundary; "bhnd": project
    #                             straight into the kernels' head-major
    #                             layout (einsum bnf,fhd->bhnd) and consume
    #                             head-major output, so XLA has no layout
    #                             copy to insert. At head_dim 64 the
    #                             per-head 64-wide projection matmuls make
    #                             bhnd a net LOSS (448 vs 422 ms @ 303M,
    #                             round 2); at head_dim 128 they are
    #                             lane-native. "auto" picks by measurement:
    #                             bhnd iff head_dim >= 128 — layout-only,
    #                             composes with BOTH sp modes (ring and
    #                             ulysses cores are head-major; pinned by
    #                             test_gpt.py layout-equivalence tests).
    pipeline_schedule: str = "gpipe"  # "gpipe": every-stage-every-tick
    #                             schedule, differentiated by autodiff —
    #                             composes with sp/ep and stays the
    #                             default; "1f1b": one-forward-one-
    #                             backward schedule with the loss
    #                             computed in the last stage
    #                             (parallel/pipeline_1f1b.py): no garbage
    #                             bubble compute, no whole-output psum,
    #                             O(P) in-flight activations instead of
    #                             O(M) — the pp >= 4 memory/schedule
    #                             lever. Composes dp x pp x tp (sp/ep
    #                             need gpipe); remat is implicit (stage-
    #                             granularity recompute).
    remat_mode: str = "block"   # "block": whole-block remat (max memory
    #                             savings — the long-context mode) — the
    #                             DEFAULT, and measured fastest or tied at
    #                             every scale tried. "attn_saved": remat
    #                             only the MLP half; the attention half's
    #                             residuals (packed head-major qo/kv +
    #                             lse) stay saved, so the flash forward
    #                             never re-runs in the backward. Measured
    #                             on one v5e chip: 85M @ 32x1024 within
    #                             noise (283 vs 286 ms/step); 303M @
    #                             16x1024 SLOWER (481 vs 423) — the saved
    #                             attention activations push HBM pressure
    #                             into XLA's own rematerialization/
    #                             compression passes, which cost more than
    #                             the avoided recompute. Kept as the
    #                             measured option switch.


def _layernorm(x, g, b, eps=1e-5):
    # plain jnp: XLA's LN fusions fold the stats and scale/shift into the
    # neighboring residual/projection fusions. The Pallas layernorm_fused
    # kernel (one pass per direction, f32 row stats saved) measured
    # NEUTRAL-to-slightly-slower swapped in here (427 vs 422 ms/step on
    # the 303M flagship) — what the op-level trace attributes to "LN
    # fusions" is shared with neighbors, so a standalone kernel just
    # un-fuses those. Kept in ops/pallas_kernels.py as the measured
    # alternative.
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    return ((xf - mean) * lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _attn_core(p: Dict[str, jnp.ndarray], h: jnp.ndarray, n_head: int,
               attn, reduce, pre=lambda x: x):
    """Attention half of the pre-LN block (LN1 -> QKV -> attn -> proj ->
    residual). ``attn(q4, k4, v4) -> (att4, aux)`` supplies the attention
    variant (full-causal, ring, or KV-cached); ``reduce`` combines
    row-sharded matmul partials (lax.psum inside shard_map, identity under
    GSPMD jit); ``pre`` marks the tensor-parallel region's entry on the
    manually-VJP'd 1F1B path (megatron's f operator — identity otherwise).
    Separate Q/K/V projections so the model-axis shard of each
    is a whole set of heads (a fused (F,3F) weight sharded on its last dim
    would hand rank 0 all of Q and half of K instead)."""
    b, n, _ = h.shape
    x = pre(_layernorm(h, p["ln1_g"], p["ln1_b"]))
    # separate Q/K/V matmuls: a trace-time concat into one fused (F, 3F)
    # product measured 7% SLOWER end-to-end (451 vs 422 ms @ 303M) — the
    # per-layer weight concat re-runs inside the scan (and again in the
    # remat recompute), costing more than the larger matmul saves
    q = x @ p["w_q"].astype(x.dtype) + p["b_q"].astype(x.dtype)
    k = x @ p["w_k"].astype(x.dtype) + p["b_k"].astype(x.dtype)
    v = x @ p["w_v"].astype(x.dtype) + p["b_v"].astype(x.dtype)
    d = q.shape[-1] // n_head
    att, aux = attn(q.reshape(b, n, n_head, d), k.reshape(b, n, n_head, d),
                    v.reshape(b, n, n_head, d))
    o = reduce(att.reshape(b, n, -1) @ p["w_proj"].astype(x.dtype))
    return h + o + p["b_proj"].astype(x.dtype), aux


def _attn_core_bhnd(p: Dict[str, jnp.ndarray], h: jnp.ndarray, n_head: int,
                    attn_bhnd, reduce, pre=lambda x: x):
    """Head-major attention half: projections go straight into the flash
    kernels' native (b, heads, n, head_dim) layout (einsum bnf,fhd->bhnd)
    and the output projection consumes it (bhnd,hdf->bnf), so XLA never
    materializes a (b,n,h,d)<->(b,h,n,d) transpose at the kernel boundary.
    Only profitable when head_dim is lane-native (>= 128): the projection
    becomes h batched (b*n, f) x (f, d) matmuls instead of one
    (b*n, f) x (f, h*d) — at d=64 that narrowness costs more than the
    copies it saves (measured round 2), at d=128 it wins (measured round
    3, doc/performance.md)."""
    b, n, f = h.shape
    x = pre(_layernorm(h, p["ln1_g"], p["ln1_b"]))

    def proj(w, bias):
        w = w.astype(x.dtype).reshape(f, n_head, -1)       # (f, h, d)
        bias = bias.astype(x.dtype).reshape(n_head, -1)    # (h, d)
        return (jnp.einsum("bnf,fhd->bhnd", x, w)
                + bias[None, :, None, :])

    att = attn_bhnd(proj(p["w_q"], p["b_q"]), proj(p["w_k"], p["b_k"]),
                    proj(p["w_v"], p["b_v"]))
    wp = p["w_proj"].astype(x.dtype)                       # (h*d, f)
    o = reduce(jnp.einsum("bhnd,hdf->bnf", att,
                          wp.reshape(n_head, -1, f)))
    return h + o + p["b_proj"].astype(x.dtype)


def _qmat(x, p: Dict[str, jnp.ndarray], wk: str, sk: str,
          shards: int = 1):
    """``x @ p[wk]`` with the int8 weight-streaming dequant applied when
    ``p`` carries the matching per-out-column scale ``sk`` (the
    _quantize_decode_blocks scheme: dequant commutes with the
    contraction, so ONE row-scale lands after the matmul). Without the
    scale key this is exactly the pre-existing cast-and-matmul — the
    scale check is a static (trace-time) dict lookup, so unquantized
    programs are byte-for-byte unchanged. The int8 weight converts to
    the COMPUTE dtype (never silently to f32 — the CXN209 audit
    contract; int8 values are exactly representable in bf16's 8
    mantissa bits).

    A uint8 weight means PACKED int4 nibbles (_quantize_decode_blocks
    _int4): group-wise scales on the CONTRACTION dim do not commute
    with the matmul, so the whole product routes to _qmat4 (per-group
    partials scaled before the cross-group sum). The dtype check is
    static too — bf16/f32 and int8 programs keep their exact jaxpr.
    ``shards``: how many independent out-dim segments the packed plane
    holds (the shard-aware TP packing — see _pack_int4)."""
    w = p[wk]
    if w.dtype == jnp.uint8:
        return _qmat4(x, w, p[sk], shards=shards)
    y = x @ w.astype(x.dtype)
    if sk in p:
        y = y * p[sk].astype(x.dtype)
    return y


def _mlp_core(p: Dict[str, jnp.ndarray], h: jnp.ndarray, reduce,
              pre=lambda x: x, lora=None, int4_shards: int = 1):
    """MLP half of the pre-LN block (LN2 -> up -> relu -> down ->
    residual). ``lora``, when set, is the serve-time per-row low-rank
    delta hook ``lora(site, x, y) -> y'`` (serve/lora.py) — a static
    (trace-time) check, so lora-less programs keep their exact jaxpr."""
    x = pre(_layernorm(h, p["ln2_g"], p["ln2_b"]))
    m = _qmat(x, p, "w_mlp1", "s_mlp1", int4_shards)
    if lora is not None:
        m = lora("mlp1", x, m)
    m = jax.nn.relu(m + p["b_mlp1"].astype(x.dtype))
    m2 = _qmat(m, p, "w_mlp2", "s_mlp2", int4_shards)
    if lora is not None:
        m2 = lora("mlp2", m, m2)
    m = reduce(m2)
    return h + m + p["b_mlp2"].astype(x.dtype)


def _block_core(p: Dict[str, jnp.ndarray], h: jnp.ndarray, n_head: int,
                attn, reduce):
    """Pre-LN transformer block body — the ONE copy of the block math
    (attention half + MLP half; split so the train path can draw the
    remat boundary between them)."""
    h, aux = _attn_core(p, h, n_head, attn, reduce)
    return _mlp_core(p, h, reduce), aux


def _train_attn(q, k, v, use_ring: bool, sp_mode: str = "ring"):
    """Training-time attention variant: ring or ulysses over the seq
    axis, else the head-major flash path (residuals saved (b,h,n,d), so
    under remat_mode="attn_saved" the backward re-reads them with zero
    layout copies)."""
    if use_ring:
        if sp_mode == "ulysses":
            att = ulysses_attention_inner(q, k, v, SEQ_AXIS, causal=True)
        else:
            att = ring_attention_inner(q, k, v, SEQ_AXIS, causal=True)
    else:
        tr = lambda t: jnp.transpose(t, (0, 2, 1, 3))
        att = tr(local_attention_bhnd(tr(q), tr(k), tr(v), causal=True))
    # tagged for the remat policy: save the attention output instead of
    # re-running the kernel in the backward (gpt_logits, remat_save_attn)
    return checkpoint_name(att, "attn_out"), None


def _train_attn_bhnd(q, k, v, use_ring: bool = False,
                     sp_mode: str = "ring"):
    """Head-major training attention; with sequence parallelism the
    head-major ring rotates K/V chunks along dim 2, or head-major
    ulysses all-to-alls the head dim — zero layout copies either way
    (round 3)."""
    if use_ring:
        if sp_mode == "ulysses":
            att = ulysses_attention_inner_bhnd(q, k, v, SEQ_AXIS,
                                               causal=True)
        else:
            att = ring_attention_inner_bhnd(q, k, v, SEQ_AXIS, causal=True)
    else:
        att = local_attention_bhnd(q, k, v, causal=True)
    return checkpoint_name(att, "attn_out")


def _block(p: Dict[str, jnp.ndarray], h: jnp.ndarray, *, n_head_local: int,
           use_ring: bool, layout: str = "bnhd",
           sp_mode: str = "ring") -> jnp.ndarray:
    """Training block on local shards (b, n_local, F), inside gpipe's
    shard_map: explicit psum combines row-sharded partials (on a size-1
    model axis it is the identity, and demotes the vma type)."""
    reduce = lambda t: lax.psum(t, MODEL_AXIS)
    if layout == "bhnd":
        h = _attn_core_bhnd(p, h, n_head_local,
                            lambda q, k, v: _train_attn_bhnd(q, k, v,
                                                             use_ring,
                                                             sp_mode),
                            reduce)
        return _mlp_core(p, h, reduce)
    out, _ = _block_core(p, h, n_head_local,
                         lambda q, k, v: _train_attn(q, k, v, use_ring,
                                                     sp_mode),
                         reduce)
    return out


def _block_mlp_remat(p: Dict[str, jnp.ndarray], h: jnp.ndarray, *,
                     n_head_local: int, use_ring: bool,
                     layout: str = "bnhd",
                     sp_mode: str = "ring") -> jnp.ndarray:
    """Training block with the remat boundary between the halves: the
    attention half runs un-rematted (the flash custom-vjp's residuals —
    q/k/v/out head-major + log-sum-exp — stay saved, so its backward does
    NOT re-run the forward kernel), while the MLP half is rematerialized.

    Motivation: whole-block jax.checkpoint re-runs the flash forward in
    the backward (~28 ms/step at 303M) plus the LN1/QKV projections and
    the (b,n,h,d)<->(b,h,n,d) layout copies around the kernels (~36
    ms/step of pure copies). Saving only the attention *output*
    (remat_save_attn) cannot avoid that: the custom-vjp still needs its
    internal residuals, so the forward re-runs anyway and the saved copy
    is pure extra HBM traffic (measured SLOWER, 439 vs 423 ms/step).

    Measured outcome (one v5e chip): the avoided recompute does NOT beat
    whole-block remat in practice — 85M @ 32x1024 within noise (283 vs
    286 ms/step), 303M @ 16x1024 slower (481 vs 423) because the
    O(layers) saved attention activations (even lane-packed, see
    _flash_pack_res) push HBM occupancy into XLA's own remat/compression
    passes. XLA overlaps the block-remat recompute well enough that the
    boundary move buys nothing; kept as a config switch because the
    trade-off is scale-dependent."""
    reduce = lambda t: lax.psum(t, MODEL_AXIS)
    if layout == "bhnd":
        h = _attn_core_bhnd(p, h, n_head_local,
                            lambda q, k, v: _train_attn_bhnd(q, k, v,
                                                             use_ring,
                                                             sp_mode),
                            reduce)
    else:
        h, _ = _attn_core(p, h, n_head_local,
                          lambda q, k, v: _train_attn(q, k, v, use_ring,
                                                      sp_mode),
                          reduce)
    return jax.checkpoint(lambda pp, hh: _mlp_core(pp, hh, reduce))(p, h)


def _block_1f1b(p: Dict[str, jnp.ndarray], h: jnp.ndarray, *,
                n_head_local: int, layout: str = "bnhd") -> jnp.ndarray:
    """Training block for the manually-VJP'd 1F1B schedule: the same
    math as `_block`, with megatron's conjugate f/g operators bracketing
    each tensor-parallel region (tp_region_in: identity fwd / psum bwd at
    the LN output; tp_region_out: psum fwd / identity bwd at the
    row-sharded projection) so `jax.vjp` of the per-device body computes
    the correct cross-shard cotangents without shard_map's automatic
    replication-aware transposes (parallel/pipeline_1f1b.py)."""
    from ..parallel.pipeline_1f1b import tp_region_in, tp_region_out
    pre = lambda t: tp_region_in(t, MODEL_AXIS)
    reduce = lambda t: tp_region_out(t, MODEL_AXIS)
    if layout == "bhnd":
        h = _attn_core_bhnd(p, h, n_head_local,
                            lambda q, k, v: _train_attn_bhnd(q, k, v,
                                                             False),
                            reduce, pre)
        return _mlp_core(p, h, reduce, pre)
    out, _ = _block_core_pre(p, h, n_head_local,
                             lambda q, k, v: _train_attn(q, k, v, False),
                             reduce, pre)
    return out


def _block_core_pre(p, h, n_head, attn, reduce, pre):
    h, aux = _attn_core(p, h, n_head, attn, reduce, pre)
    return _mlp_core(p, h, reduce, pre), aux


def _gpt_1f1b_loss_and_grads(params: Dict, ids: jnp.ndarray,
                             cfg: GPTConfig, mesh: Mesh):
    """(loss, grads) via the 1F1B pipeline schedule
    (parallel/pipeline_1f1b.py): embedding forward + its VJP run under
    GSPMD outside the schedule; the block stack runs the manual
    one-forward-one-backward schedule with the head/loss computed in the
    last stage; the entry cotangent closes the embedding backward.
    Composes dp x pp x tp; sequence/expert parallelism stay on the gpipe
    schedule (gpt_loss)."""
    from ..parallel.pipeline_1f1b import pipeline_1f1b
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    n_tp = mesh.shape.get(MODEL_AXIS, 1)
    if mesh.shape.get(SEQ_AXIS, 1) > 1:
        raise ValueError(
            "pipeline_schedule='1f1b' composes dp x pp x tp; "
            "seq_parallel needs pipeline_schedule='gpipe'")
    if cfg.n_head % max(n_tp, 1):
        raise ValueError("n_head %d must divide over model axis %d"
                         % (cfg.n_head, n_tp))
    layout = cfg.attn_layout
    if layout == "auto":
        layout = "bhnd" if cfg.feat // cfg.n_head >= 128 else "bnhd"

    def emb_fn(ep):
        return (ep["emb"][ids]
                + ep["pos"][None, :ids.shape[1]]).astype(dtype)

    h, emb_vjp = jax.vjp(emb_fn, {"emb": params["emb"],
                                  "pos": params["pos"]})

    def head_loss(lp, hh, tgt):
        hl = _layernorm(hh, lp["lnf_g"], lp["lnf_b"])
        logits = (hl @ lp["head"].astype(hl.dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1])
        tgt2 = tgt[:, 1:].astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, tgt2[..., None], axis=-1)[..., 0]
        return nll.mean()

    block = functools.partial(_block_1f1b,
                              n_head_local=cfg.n_head // max(n_tp, 1),
                              layout=layout)
    lp = {"lnf_g": params["lnf_g"], "lnf_b": params["lnf_b"],
          "head": params["head"]}
    loss, gblocks, glp, dxs = pipeline_1f1b(
        block, params["blocks"], head_loss, lp, h, ids, mesh,
        cfg.n_microbatch, param_specs=_block_param_specs())
    (demb,) = emb_vjp(dxs.astype(h.dtype))
    grads = {"emb": demb["emb"], "pos": demb["pos"],
             "lnf_g": glp["lnf_g"], "lnf_b": glp["lnf_b"],
             "head": glp["head"], "blocks": gblocks}
    return loss, grads


def gpt_init(key: jax.Array, cfg: GPTConfig) -> Dict:
    """Random init; blocks stacked along a leading n_layer dim."""
    f, l = cfg.feat, cfg.n_layer
    mf = cfg.mlp_ratio * f
    k = iter(jax.random.split(key, 16))

    def norm(kk, shape, scale):
        return scale * jax.random.normal(kk, shape, jnp.float32)

    blocks = {
        "ln1_g": jnp.ones((l, f)), "ln1_b": jnp.zeros((l, f)),
        "ln2_g": jnp.ones((l, f)), "ln2_b": jnp.zeros((l, f)),
        "w_q": norm(next(k), (l, f, f), 0.02),
        "w_k": norm(next(k), (l, f, f), 0.02),
        "w_v": norm(next(k), (l, f, f), 0.02),
        "b_q": jnp.zeros((l, f)),
        "b_k": jnp.zeros((l, f)),
        "b_v": jnp.zeros((l, f)),
        "w_proj": norm(next(k), (l, f, f), 0.02 / max(1, l) ** 0.5),
        "b_proj": jnp.zeros((l, f)),
        "w_mlp1": norm(next(k), (l, f, mf), 0.02),
        "b_mlp1": jnp.zeros((l, mf)),
        "w_mlp2": norm(next(k), (l, mf, f), 0.02 / max(1, l) ** 0.5),
        "b_mlp2": jnp.zeros((l, f)),
    }
    return {
        "emb": norm(next(k), (cfg.vocab_size, f), 0.02),
        "pos": norm(next(k), (cfg.seq_len, f), 0.01),
        "lnf_g": jnp.ones((f,)), "lnf_b": jnp.zeros((f,)),
        "head": norm(next(k), (f, cfg.vocab_size), 0.02),
        "blocks": blocks,
    }


def gpt_num_params(params: Dict) -> int:
    """Total parameter count of a param tree (any pytree of arrays:
    the functional GPT tree or a config-DSL ``Net.params``) — the N of
    every 6*N-per-token FLOP estimate. bench.py's analytic MFU counts
    through this one definition, so the analytic and cost-model MFU
    lines are computed over the same model."""
    total = 0
    for w in jax.tree_util.tree_leaves(params):
        n = 1
        for d in w.shape:
            n *= int(d)
        total += n
    return total


def _with_data_axis(spec: P, shape, mesh: Mesh) -> P:
    """ZeRO placement: additionally shard the first free (unsharded,
    divisible) dim over ``data``. XLA all-gathers the tensor at its use
    sites and reduce-scatters its gradient — FSDP semantics from a
    sharding annotation alone. Delegates to the Net path's rule
    (parallel/sharding.py:_data_shard_spec) so the two ZeRO placements
    cannot drift; idempotent (a spec that already carries ``data`` is
    returned unchanged)."""
    from ..parallel.sharding import _data_shard_spec
    out = list(tuple(spec)) + [None] * (len(shape) - len(tuple(spec)))
    if DATA_AXIS in out:
        return P(*out)
    return P(*_data_shard_spec(out, shape, mesh))


def gpt_param_shardings(mesh: Mesh, params: Optional[Dict] = None,
                        zero: int = 0) -> Dict:
    """Placement: blocks pipe-sharded on dim0 + tp-sharded on the megatron
    dims (derived from the same spec table gpipe uses, so placement and
    shard_map in_specs cannot diverge); embeddings/head replicated (small at
    these scales).

    ``zero >= 3`` additionally shards every parameter over the ``data``
    axis (ZeRO-3/FSDP); requires ``params`` (or example shapes) to check
    divisibility. GSPMD gathers each weight at its use sites — for the
    pipelined blocks that is the resharding into gpipe's shard_map
    in_specs."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))
    blocks = {k: NamedSharding(mesh, s)
              for k, s in _block_param_specs().items()}
    sh = {"emb": ns(), "pos": ns(), "lnf_g": ns(), "lnf_b": ns(),
          "head": ns(), "blocks": blocks}
    if zero >= 3:
        if params is None:
            raise ValueError("zero>=3 needs the params tree for shapes")
        sh = jax.tree.map(
            lambda s, p: NamedSharding(mesh, _with_data_axis(s.spec,
                                                             p.shape, mesh)),
            sh, params,
            is_leaf=lambda t: isinstance(t, NamedSharding))
    return sh


def gpt_opt_shardings(params: Dict, mesh: Mesh, zero: int = 0) -> Dict:
    """Shardings for the momentum/variance trees: the param placements,
    plus a ``data``-axis dim when ``zero >= 1`` (ZeRO-1: each DP rank owns
    a slice of the optimizer state)."""
    sh = gpt_param_shardings(mesh, params, zero if zero >= 3 else 0)
    if zero >= 1:
        sh = jax.tree.map(
            lambda s, p: NamedSharding(mesh, _with_data_axis(s.spec,
                                                             p.shape, mesh)),
            sh, params,
            is_leaf=lambda t: isinstance(t, NamedSharding))
    return sh


def _block_param_specs() -> Dict:
    return {
        "ln1_g": P(PIPE_AXIS), "ln1_b": P(PIPE_AXIS),
        "ln2_g": P(PIPE_AXIS), "ln2_b": P(PIPE_AXIS),
        "w_q": P(PIPE_AXIS, None, MODEL_AXIS),
        "w_k": P(PIPE_AXIS, None, MODEL_AXIS),
        "w_v": P(PIPE_AXIS, None, MODEL_AXIS),
        "b_q": P(PIPE_AXIS, MODEL_AXIS),
        "b_k": P(PIPE_AXIS, MODEL_AXIS),
        "b_v": P(PIPE_AXIS, MODEL_AXIS),
        "w_proj": P(PIPE_AXIS, MODEL_AXIS, None),
        "b_proj": P(PIPE_AXIS),
        "w_mlp1": P(PIPE_AXIS, None, MODEL_AXIS),
        "b_mlp1": P(PIPE_AXIS, MODEL_AXIS),
        "w_mlp2": P(PIPE_AXIS, MODEL_AXIS, None),
        "b_mlp2": P(PIPE_AXIS),
    }


def gpt_logits(params: Dict, ids: jnp.ndarray, cfg: GPTConfig,
               mesh: Mesh) -> jnp.ndarray:
    """ids (batch, seq_len) int32 -> logits (batch, seq_len, vocab)."""
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    n_tp = mesh.shape.get(MODEL_AXIS, 1)
    n_sp = mesh.shape.get(SEQ_AXIS, 1)
    if cfg.n_head % max(n_tp, 1):
        raise ValueError("n_head %d must divide over model axis %d"
                         % (cfg.n_head, n_tp))
    if cfg.seq_len % max(n_sp, 1):
        raise ValueError("seq_len %d must be divisible by the seq axis "
                         "(seq_parallel=%d)" % (cfg.seq_len, n_sp))
    if cfg.remat_mode not in ("block", "attn_saved"):
        raise ValueError("remat_mode must be 'block' or 'attn_saved', got %r"
                         % (cfg.remat_mode,))
    if cfg.attn_layout not in ("auto", "bnhd", "bhnd"):
        raise ValueError("attn_layout must be 'auto', 'bnhd' or 'bhnd', "
                         "got %r" % (cfg.attn_layout,))
    use_ring = n_sp > 1
    if cfg.seq_parallel_mode not in ("ring", "ulysses"):
        raise ValueError("seq_parallel_mode must be 'ring' or 'ulysses', "
                         "got %r" % (cfg.seq_parallel_mode,))
    if (cfg.seq_parallel_mode == "ulysses" and use_ring
            and (cfg.n_head // max(n_tp, 1)) % n_sp):
        raise ValueError(
            "seq_parallel_mode='ulysses' needs local heads %d (n_head/tp) "
            "divisible by the seq axis %d; use 'ring'"
            % (cfg.n_head // max(n_tp, 1), n_sp))
    layout = cfg.attn_layout
    if layout == "auto":
        # measured rule (doc/performance.md round 3): head-major wins when
        # the per-head projection width is lane-native (d >= 128); both
        # sequence-parallel variants have head-major cores, so the rule
        # is layout-only
        layout = "bhnd" if cfg.feat // cfg.n_head >= 128 else "bnhd"

    h = (params["emb"][ids] + params["pos"][None, :ids.shape[1]]).astype(dtype)
    kw = dict(n_head_local=cfg.n_head // max(n_tp, 1), use_ring=use_ring,
              layout=layout, sp_mode=cfg.seq_parallel_mode)
    if cfg.remat and cfg.remat_mode == "attn_saved":
        # remat boundary between the block halves — see _block_mlp_remat
        block = functools.partial(_block_mlp_remat, **kw)
    else:
        block = functools.partial(_block, **kw)
        if cfg.remat:
            policy = (
                jax.checkpoint_policies.save_only_these_names("attn_out")
                if cfg.remat_save_attn else None)
            block = jax.checkpoint(block, policy=policy)
    h = gpipe(block, params["blocks"], h, mesh, cfg.n_microbatch,
              extra_spec_axes=(SEQ_AXIS,), param_specs=_block_param_specs())
    h = _layernorm(h, params["lnf_g"], params["lnf_b"])
    return (h @ params["head"].astype(h.dtype)).astype(jnp.float32)


def gpt_loss(params: Dict, ids: jnp.ndarray, cfg: GPTConfig,
             mesh: Mesh) -> jnp.ndarray:
    """Next-token cross-entropy (last position predicts nothing)."""
    logits = gpt_logits(params, ids, cfg, mesh)
    logp = jax.nn.log_softmax(logits[:, :-1])
    tgt = ids[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean()


def gpt_opt_init(params: Dict, mesh: Mesh, optimizer: str = "sgd",
                 zero: int = 0) -> Dict:
    """Optimizer state placed like the params: sgd -> momentum tree;
    adam -> {m, v, t} (same math as updaters.AdamUpdater, one-minus
    decay convention not used here — betas are the usual 0.9/0.999).
    ``zero >= 1`` shards the state over the ``data`` axis (ZeRO)."""
    opt_sh = gpt_opt_shardings(params, mesh, zero)
    zeros = jax.device_put(jax.tree.map(jnp.zeros_like, params), opt_sh)
    if optimizer == "sgd":
        return zeros
    if optimizer == "adam":
        # t is mesh-replicated (not an uncommitted host scalar) so a
        # checkpoint restore places it compatibly with the mesh-resident
        # params instead of committing it to one device
        from jax.sharding import NamedSharding, PartitionSpec
        t = jax.device_put(jnp.zeros((), jnp.int32),
                           NamedSharding(mesh, PartitionSpec()))
        return {"m": zeros,
                "v": jax.device_put(jax.tree.map(jnp.zeros_like, params),
                                    opt_sh),
                "t": t}
    raise ValueError("unknown optimizer %r" % optimizer)


def make_train_step(cfg: GPTConfig, mesh: Mesh, eta: float = 0.1,
                    momentum: float = 0.9, optimizer: str = "sgd",
                    beta2: float = 0.999, eps: float = 1e-8,
                    zero: int = 0):
    """Jitted train step; donates params/opt state. ``optimizer``: "sgd"
    (momentum; opt state = momentum tree, the original signature) or
    "adam" (opt state from gpt_opt_init(..., "adam")). ``zero``: ZeRO
    level — 1 shards optimizer state over ``data``, 3 also shards the
    params (pass the same level to gpt_place/gpt_opt_init)."""
    if optimizer not in ("sgd", "adam"):
        raise ValueError("unknown optimizer %r" % optimizer)
    if zero:
        shapes = jax.eval_shape(lambda k: gpt_init(k, cfg),
                                jax.random.PRNGKey(0))
        shardings = gpt_param_shardings(mesh, shapes,
                                        zero if zero >= 3 else 0)
        opt_shardings = gpt_opt_shardings(shapes, mesh, zero)
    else:
        shardings = gpt_param_shardings(mesh)
        opt_shardings = shardings

    def constrain(tree):
        return jax.lax.with_sharding_constraint(tree, shardings)

    def constrain_opt(tree):
        return jax.lax.with_sharding_constraint(tree, opt_shardings)

    if cfg.pipeline_schedule not in ("gpipe", "1f1b"):
        raise ValueError("pipeline_schedule must be 'gpipe' or '1f1b', "
                         "got %r" % (cfg.pipeline_schedule,))

    def loss_and_grads(params, ids):
        if cfg.pipeline_schedule == "1f1b" \
                and mesh.shape.get(PIPE_AXIS, 1) > 1:
            return _gpt_1f1b_loss_and_grads(params, ids, cfg, mesh)
        return jax.value_and_grad(gpt_loss)(params, ids, cfg, mesh)

    def step(params, opt, ids):
        loss, grads = loss_and_grads(params, ids)
        if optimizer == "sgd":
            new_opt = jax.tree.map(lambda m, g: momentum * m - eta * g,
                                   opt, grads)
            new_params = jax.tree.map(jnp.add, params, new_opt)
            new_opt = constrain_opt(new_opt)
        else:
            t = opt["t"] + 1
            m = jax.tree.map(lambda m, g: momentum * m + (1 - momentum) * g,
                             opt["m"], grads)
            v = jax.tree.map(lambda v, g: beta2 * v + (1 - beta2) * g * g,
                             opt["v"], grads)
            # bias-corrected step size, computed once from the traced count
            a = eta * jnp.sqrt(1 - beta2 ** t.astype(jnp.float32)) \
                / (1 - momentum ** t.astype(jnp.float32))
            new_params = jax.tree.map(
                lambda p, mm, vv: p - a * mm / (jnp.sqrt(vv) + eps),
                params, m, v)
            new_opt = {"m": constrain_opt(m), "v": constrain_opt(v), "t": t}
        # keep placements stable step-over-step
        new_params = constrain(new_params)
        return new_params, new_opt, loss

    return jax.jit(step, donate_argnums=(0, 1))


def gpt_place(params: Dict, mesh: Mesh, zero: int = 0) -> Dict:
    return jax.device_put(params, gpt_param_shardings(
        mesh, params if zero >= 3 else None, zero))


# ---------------------------------------------------------------------------
# autoregressive decode with a KV cache
# ---------------------------------------------------------------------------
# Inference analogue of the reference's `pred` task for the flagship: one
# forward per generated token instead of a full-sequence forward per token.
# Runs under plain jit (GSPMD partitions dp over the batch and tp over the
# head/feature dims automatically — the explicit psum in `_block` exists only
# because gpipe's shard_map needs it; here XLA inserts the collectives).
# Pipeline-sharded (pipe>1) block params are scanned layer-by-layer, which
# GSPMD resolves with per-layer collective-permutes; decode is latency-bound,
# so microbatched pipelining would not help anyway.


def _block_core_fusedqkv(p: Dict[str, jnp.ndarray], h: jnp.ndarray,
                         n_head: int, attn, reduce, lora=None,
                         int4_shards: int = 1):
    """Decode-path block body on pre-fused QKV weights ("w_qkv" (f, 3f),
    "b_qkv" (3f)): batch-1 decode is bound by per-layer op count, not
    bandwidth (doc/performance.md round 3), so one projection matmul
    instead of three measured +12% tok/s with bit-identical outputs. The
    training path keeps separate projections — there the fused weight
    concat re-runs inside scan/remat and measured 7% SLOWER (round 2).

    ``lora`` (serve/lora.py): per-row low-rank delta hook
    ``lora(site, x, y) -> y'`` applied to all four matmul sites; a
    static trace-time check, so lora-less programs keep their exact
    jaxpr. ``int4_shards``: shard count of a shard-aware int4 packing
    (serve_tp x serve_int4_weights — see _pack_int4)."""
    b, n, _ = h.shape
    x = _layernorm(h, p["ln1_g"], p["ln1_b"])
    qkv = _qmat(x, p, "w_qkv", "s_qkv", int4_shards)
    if lora is not None:
        qkv = lora("qkv", x, qkv)
    qkv = qkv + p["b_qkv"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    d = q.shape[-1] // n_head
    att, aux = attn(q.reshape(b, n, n_head, d), k.reshape(b, n, n_head, d),
                    v.reshape(b, n, n_head, d))
    af = att.reshape(b, n, -1)
    o = _qmat(af, p, "w_proj", "s_proj", int4_shards)
    if lora is not None:
        o = lora("proj", af, o)
    o = reduce(o)
    return _mlp_core(p, h + o + p["b_proj"].astype(x.dtype), reduce,
                     lora=lora, int4_shards=int4_shards), aux


def _fuse_qkv_blocks(blocks: Dict[str, jnp.ndarray]) -> Dict:
    """(w_q,w_k,w_v,b_*) -> (w_qkv, b_qkv); runs once per decode call
    (outside the token scan), trading one weight concat for two fewer
    matmul dispatches per layer per token."""
    bl = dict(blocks)
    bl["w_qkv"] = jnp.concatenate([bl.pop("w_q"), bl.pop("w_k"),
                                   bl.pop("w_v")], axis=-1)
    bl["b_qkv"] = jnp.concatenate([bl.pop("b_q"), bl.pop("b_k"),
                                   bl.pop("b_v")], axis=-1)
    return bl


def _attn_cached(q, ck, cv, pos):
    """q (b,1,H,d) against HEAD-MAJOR cache (b,H,S,d); positions > pos
    are masked. On TPU with aligned shapes the whole scores->mask->
    softmax->PV chain runs as ONE Pallas kernel per (batch, head) —
    batch-1 decode is op-count-bound (doc/performance.md round 3), so
    collapsing the ~6 XLA kernels per layer is the lever; the jnp
    formulation is the fallback and the differential oracle. (The
    (b,1,h,d)<->(b,h,1,d) swaps are free: the swapped dims include a
    singleton, so the memory layout is unchanged.)"""
    from ..ops.pallas_kernels import (cached_attention,
                                      cached_attention_supported)
    qh = jnp.swapaxes(q, 1, 2)                         # (b, h, 1, d)
    if cached_attention_supported(ck.shape):
        out = cached_attention(qh, ck, cv, pos)
    else:
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                       ck.astype(jnp.float32)) / (d ** 0.5)
        mask = jnp.arange(ck.shape[2])[None, None, None, :] <= pos
        w = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", w,
                         cv.astype(jnp.float32)).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)                     # (b, 1, h, d)


# decode signatures whose fused compile hit a scoped-VMEM OOM (see
# gpt_decode's fallback) — they use the XLA scan from then on
_FUSED_DECODE_BLOCKLIST: set = set()


# (weight, scale) tag pairs of the int8 weight-streaming decode — the
# single source for the quantizer, its inverse, and the kernel wiring
QUANT_DECODE_PAIRS = (("w_qkv", "s_qkv"), ("w_proj", "s_proj"),
                      ("w_mlp1", "s_mlp1"), ("w_mlp2", "s_mlp2"))


def _quantize_decode_blocks(blocks: Dict) -> Dict:
    """Per-out-column symmetric int8 quantization of the four matmul
    weights in the fused-QKV block dict (the int8 weight-streaming
    decode, round 5): scale[l, j] = max_i |w[l, i, j]| / 127, so the
    dequant multiply commutes with the contraction and the kernel
    applies ONE row-scale after each matmul. Biases/LN stay exact."""
    bl = dict(blocks)
    for wk, sk in QUANT_DECODE_PAIRS:
        w = bl[wk].astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(w), axis=-2) / 127.0, 1e-8)
        bl[wk] = jnp.round(w / s[:, None, :]).astype(jnp.int8)
        bl[sk] = s
    return bl


def _dequantize_decode_blocks(qblocks: Dict, dtype=jnp.float32) -> Dict:
    """Inverse of :func:`_quantize_decode_blocks` (tests/smokes compare
    the kernel on int8 inputs against the kernel on these)."""
    bl = dict(qblocks)
    for wk, sk in QUANT_DECODE_PAIRS:
        bl[wk] = (bl[wk].astype(jnp.float32)
                  * bl.pop(sk)[:, None, :]).astype(dtype)
    return bl


# ---------------------------------------------------------------------------
# int4 weight streaming (round 19): two nibbles per byte along the
# out-column dim, group-wise symmetric scales over in-rows. The group
# scales sit on the CONTRACTION dim, so (unlike int8's per-out-column
# scheme) dequant does NOT commute with the matmul — _qmat4 scales each
# group's partial product before the cross-group sum, and the Pallas
# kernel (ops/pallas_kernels.int4_matmul) does the same accumulation
# with the unpack in VMEM so the unpacked weight never touches HBM.

INT4_GROUP_DEFAULT = 64


def _int4_groups(k: int, group: int) -> int:
    """Number of scale groups for k in-rows: ceil(k / group), or ONE
    group (= per-out-column scaling) when group <= 0."""
    return 1 if group <= 0 else -(-k // group)


def _pack_int4(q: jnp.ndarray, shards: int = 1) -> jnp.ndarray:
    """int8 codes in [-7, 7] (..., k, n) -> packed uint8 (..., k, n/2).
    Halves layout: byte column j holds out-column j in the LOW nibble
    and out-column j + n/2 in the HIGH nibble (offset-8 codes), so the
    unpack is one lane-dim concatenate — no interleave reshape, which
    Mosaic would materialize. n must be even (the quantizer pads).

    ``shards`` > 1 (serve_tp x serve_int4_weights): each of the
    ``shards`` equal out-dim segments packs INDEPENDENTLY — nibble
    pairs never straddle a shard boundary, so sharding the packed
    plane's byte dim over the model axis hands every device exactly
    its own shard's self-contained bytes. The codes themselves are
    packing-independent, which is what keeps TP-int4 bit-identical to
    the single-device packing."""
    if shards > 1:
        w = q.shape[-1] // shards
        return jnp.concatenate(
            [_pack_int4(q[..., s * w:(s + 1) * w])
             for s in range(shards)], axis=-1)
    half = q.shape[-1] // 2
    u = (q + jnp.int8(8)).astype(jnp.uint8)
    return u[..., :half] | (u[..., half:] << jnp.uint8(4))


def _unpack_int4(packed: jnp.ndarray, shards: int = 1) -> jnp.ndarray:
    """packed uint8 (..., k, n/2) -> int8 codes (..., k, n); exact
    inverse of :func:`_pack_int4` (``shards`` must match the packing).
    The uint8 -> int8 hop happens BEFORE any float convert (the
    CXN209/CXN211 audit contract: nibble codes are exact in bf16's 8
    mantissa bits, so no silent f32 promotion)."""
    if shards > 1:
        w = packed.shape[-1] // shards
        return jnp.concatenate(
            [_unpack_int4(packed[..., s * w:(s + 1) * w])
             for s in range(shards)], axis=-1)
    lo = (packed & jnp.uint8(0xF)).astype(jnp.int8) - jnp.int8(8)
    hi = (packed >> jnp.uint8(4)).astype(jnp.int8) - jnp.int8(8)
    return jnp.concatenate([lo, hi], axis=-1)


def _quantize_decode_blocks_int4(blocks: Dict,
                                 group: int = INT4_GROUP_DEFAULT,
                                 shards: int = 1) -> Dict:
    """Group-wise symmetric int4 quantization of the four matmul weights
    in the fused-QKV block dict: scale[l, g, j] = max over the g-th
    in-row group of |w[l, :, j]| / 7, codes clipped to [-7, 7] and
    packed two-per-byte (_pack_int4). Groups are BALANCED — G =
    ceil(k / group) groups of g0 = ceil(k / G) rows, last group ragged
    — so G and g0 re-derive from the scale plane's shape alone and the
    fast kernel's equal-block grid applies whenever G divides k.
    Biases/LN stay exact; odd out-widths pad one zero column (packed
    only — the scale plane keeps the true n). ``shards`` > 1 selects
    the shard-aware TP packing (see _pack_int4); codes and scales are
    packing-independent, only the byte layout changes."""
    bl = dict(blocks)
    for wk, sk in QUANT_DECODE_PAIRS:
        w = bl[wk].astype(jnp.float32)                 # (L, k, n)
        L, k, n = w.shape
        G = _int4_groups(k, group)
        g0 = -(-k // G)
        rows = jnp.minimum(jnp.arange(k) // g0, G - 1)
        wg = jnp.pad(w, ((0, 0), (0, G * g0 - k), (0, 0)))
        wg = wg.reshape(L, G, g0, n)
        s = jnp.maximum(jnp.max(jnp.abs(wg), axis=2) / 7.0, 1e-8)
        q = jnp.clip(jnp.round(w / s[:, rows, :]), -7, 7).astype(jnp.int8)
        if shards > 1:
            if n % (2 * shards):
                raise ValueError(
                    "int4 TP packing needs the out dim to split into "
                    "%d even shards, got n=%d (%s)" % (shards, n, wk))
        elif n % 2:
            q = jnp.pad(q, ((0, 0), (0, 0), (0, 1)))
        bl[wk] = _pack_int4(q, shards)                 # (L, k, ~n/2) u8
        bl[sk] = s                                     # (L, G, n) f32
    return bl


def _dequantize_decode_blocks_int4(qblocks: Dict, dtype=jnp.float32,
                                   shards: int = 1) -> Dict:
    """Inverse of :func:`_quantize_decode_blocks_int4` up to the int4
    rounding (tests compare programs on packed inputs against programs
    on these)."""
    bl = dict(qblocks)
    for wk, sk in QUANT_DECODE_PAIRS:
        s = bl.pop(sk)                                 # (L, G, n)
        q = _unpack_int4(bl[wk], shards)               # (L, k, n_pad)
        k = q.shape[1]
        G, n = int(s.shape[1]), int(s.shape[2])
        g0 = -(-k // G)
        rows = jnp.minimum(jnp.arange(k) // g0, G - 1)
        bl[wk] = (q[..., :n].astype(jnp.float32)
                  * s[:, rows, :]).astype(dtype)
    return bl


def _qmat4_ref(x, packed, scales, shards: int = 1):
    """XLA reference for the packed-int4 matmul — mirrors the Pallas
    kernel OP FOR OP (zeros-init f32 accumulator; per group: unpack,
    cast to the compute dtype, dot_general with f32 accumulation, scale
    the partial, add) so interpret-mode bit-identity is a structural
    property, not a tolerance. Handles the ragged last group, the odd-n
    pad column the kernel's geometry gate excludes, and the shard-aware
    TP packing (``shards`` > 1): the unpack keeps each shard's columns
    device-local, and every out column is still one full-k contraction,
    so the result is bit-identical to the single-device packing's."""
    G, n = int(scales.shape[0]), int(scales.shape[1])
    k = int(x.shape[-1])
    g0 = -(-k // G)
    qq = _unpack_int4(packed, shards)[:, :n]
    acc = jnp.zeros((x.shape[0], n), jnp.float32)
    for g in range(G):
        lo, hi = g * g0, min((g + 1) * g0, k)
        wq = qq[lo:hi].astype(x.dtype)
        part = jax.lax.dot_general(x[:, lo:hi], wq,
                                   (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        acc = acc + part * scales[g][None]
    return acc.astype(x.dtype)


def _qmat4(x, packed, scales, shards: int = 1):
    """``x @ dequant(packed, scales)`` without materializing the
    dequantized weight: the Pallas kernel when the geometry qualifies
    (ops/pallas_kernels.int4_matmul — unpack + dequant inside the
    matmul tile in VMEM), else :func:`_qmat4_ref`. The route is a
    trace-time decision, so each compiled program contains exactly one
    formulation. A shard-aware packing (``shards`` > 1, the TP path)
    always keeps the XLA reference: the kernel's in-tile unpack
    assumes the single-segment halves layout, and GSPMD cannot
    partition the pallas_call anyway — the reference's per-shard
    unpack is what partitions cleanly."""
    lead, k = x.shape[:-1], int(x.shape[-1])
    G, n = int(scales.shape[0]), int(scales.shape[1])
    m = 1
    for d in lead:
        m *= int(d)
    x2 = x.reshape(m, k)
    from ..ops import pallas_kernels as _pk
    if (shards == 1 and k % G == 0 and 2 * int(packed.shape[-1]) == n
            and _pk.int4_matmul_supported(m, k, n, G,
                                          itemsize=x.dtype.itemsize)):
        y = _pk.int4_matmul(x2, packed, scales)
    else:
        y = _qmat4_ref(x2, packed, scales, shards)
    return y.reshape(lead + (n,))


@functools.lru_cache(maxsize=64)
def _decode_fn(cfg_key: tuple, n_prompt: int, max_new: int,
               temperature: float, fused: bool = False,
               int8: bool = False, fold_head: bool = False,
               top_k: int = 0, top_p: float = 1.0,
               int4: bool = False,
               int4_group: int = INT4_GROUP_DEFAULT):
    """Build (and cache) the jitted prefill+decode program for one
    (config, prompt length, generation length, sampling) signature —
    repeated gpt_decode calls hit jit's cache instead of retracing.
    ``fused``: run the whole decode step's layer stack as ONE Pallas
    kernel per batch row (ops/pallas_kernels.fused_decode_step) with
    bf16 weights double-buffered through VMEM. ``int8``: additionally
    stream the matmul weights int8-quantized (half the bytes of the
    weight-bandwidth-bound step; fused path only). ``int4``: stream
    them PACKED int4 with ``int4_group``-row scale groups through the
    XLA scan's _qmat dispatch instead (the fused whole-step kernel
    stays int8/bf16 — the caller forces ``fused=False``); prefill keeps
    the full-precision blocks either way. ``top_k``/``top_p``
    restrict the sampling candidate set (ops/sampling.py — the SAME
    filter the serving tick applies per slot row, so serve-vs-generate
    token identity holds under any sampling params); both are inert on
    the greedy (temperature 0) path, which keeps the head-fold fast
    path."""
    cfg = GPTConfig(*cfg_key)
    total = n_prompt + max_new
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    n_head = cfg.n_head
    hd = cfg.feat // n_head
    identity = lambda t: t          # GSPMD inserts the tp collectives

    def pick(logits, key):
        if temperature > 0:
            scaled = logits / temperature
            # top_k/top_p are STATIC here: skip the filter (and its two
            # full-vocab sorts per token) entirely when both are
            # disabled, keeping the pre-existing temperature-only path's
            # op count. When a filter is on, the masked values equal the
            # input wherever kept, so enabling k=V/p=1 is value-level
            # identical to this bypass — sampled streams stay pinned
            # either way.
            if top_k > 0 or top_p < 1.0:
                from ..ops.sampling import filter_logits
                scaled = filter_logits(scaled, top_k, top_p)
            return jax.random.categorical(key, scaled, -1)
        return jnp.argmax(logits, -1)

    def run(params, prompt, rng):
        b = prompt.shape[0]
        # fused QKV weights for the whole decode (see _block_core_fusedqkv)
        blocks = _fuse_qkv_blocks(params["blocks"])
        dec_blocks = blocks
        if fused:
            # the fused kernel streams weights HBM->VMEM per layer per
            # token; converting once here halves that traffic (the XLA
            # path measured bf16 weights SLOWER — an M=1 tiling artifact
            # the kernel does not share, doc/performance.md round 4)
            blocks = jax.tree.map(lambda a: a.astype(dtype), blocks)
            dec_blocks = blocks
            if int8:
                # quantize ONCE per decode call (outside the token
                # scan); halves the weight stream again. DECODE steps
                # only: the prefill keeps the bf16 blocks (it is one
                # batched full-sequence pass — compute-shaped, not
                # weight-bandwidth-bound — and its math must match the
                # training forward that produced the caches)
                dec_blocks = _quantize_decode_blocks(blocks)
        elif int4:
            # packed nibbles + group scales for the DECODE scan only
            # (same prefill reasoning as int8 above); quantized once per
            # decode call, outside the token scan. dec_blocks is the
            # SAME object as blocks when int4 is off, so the unquantized
            # scan's jaxpr is byte-for-byte unchanged.
            dec_blocks = _quantize_decode_blocks_int4(blocks, int4_group)

        # ---- prefill: full forward over the prompt, emitting k/v caches
        h = (params["emb"][prompt]
             + params["pos"][None, :n_prompt]).astype(dtype)

        def prefill_layer(carry, p):
            def attn(q, k, v):
                return local_attention(q, k, v, causal=True), (k, v)
            out, (k, v) = _block_core_fusedqkv(p, carry, n_head, attn,
                                               identity)
            # head-major (b, h, S, d) caches: the decode step's update at
            # [:, :, pos] is then a free-layout dus and the cached-
            # attention kernel reads its native layout
            kh = jnp.transpose(k, (0, 2, 1, 3))
            vh = jnp.transpose(v, (0, 2, 1, 3))
            pad = ((0, 0), (0, 0), (0, total - n_prompt), (0, 0))
            return out, (jnp.pad(kh, pad), jnp.pad(vh, pad))

        h, (cache_k, cache_v) = lax.scan(prefill_layer, h, blocks)
        hl = _layernorm(h[:, -1:], params["lnf_g"], params["lnf_b"])
        logits = hl[:, 0] @ params["head"].astype(hl.dtype)

        ids = jnp.zeros((b, total), jnp.int32)
        ids = lax.dynamic_update_slice(ids, prompt, (0, 0))
        ids = ids.at[:, n_prompt].set(
            pick(logits, jax.random.fold_in(rng, 0)).astype(jnp.int32))

        # hoisted once per decode call for the head-folded greedy path
        head_cast = params["head"].astype(dtype)

        # ---- decode: one token per step against the caches
        def step(carry, i):
            ids, cache_k, cache_v = carry
            pos = n_prompt + i                     # position being processed
            tok = lax.dynamic_slice_in_dim(ids, pos, 1, axis=1)   # (b, 1)
            h = (params["emb"][tok]
                 + lax.dynamic_slice_in_dim(params["pos"], pos, 1,
                                            axis=0)[None]).astype(dtype)

            if fused and fold_head:
                # batch-1 greedy decode with the final LN + LM-head
                # matmul + argmax folded INTO the kernel (round 5) —
                # removes ~6 glue ops per token (measured +5% on the
                # int8 85M cell same-run; folding the embedding lookup
                # too measured a WASH and is not used). The caller gates
                # fold_head on batch 1 (the latency-bound case it exists
                # for — batched decode shares the glue dispatch across
                # rows, and the b>1 head-folded grid trips a JAX
                # lowering-cache crash), greedy sampling, AND the head
                # matrix fitting the scoped-VMEM budget
                # (doc/performance.md round 5)
                from ..ops.pallas_kernels import fused_decode_step
                tok_next, cache_k, cache_v = fused_decode_step(
                    dec_blocks, h, cache_k, cache_v, pos, n_head,
                    head=(params["lnf_g"], params["lnf_b"], head_cast))
                ids = lax.dynamic_update_slice(ids, tok_next, (0, pos + 1))
                return (ids, cache_k, cache_v), None
            if fused:
                # ONE kernel per token per batch row: grid over layers,
                # weights double-buffered by the pallas pipeline, h in
                # VMEM scratch, caches updated by a single dus per cache
                # (in place — they are token-loop carries). The lax.scan
                # form instead streams every cache through the scan's
                # xs->ys, which XLA materializes as a full cache copy per
                # layer per token — measured 87% of the fused decode step
                # (doc/performance.md round 4).
                from ..ops.pallas_kernels import fused_decode_step
                h, cache_k, cache_v = fused_decode_step(
                    dec_blocks, h, cache_k, cache_v, pos, n_head)
            else:
                def layer(carry_h, xs):
                    p, ck, cv = xs

                    def attn(q, k, v):
                        kh = jnp.swapaxes(k, 1, 2)     # (b, h, 1, d) free
                        vh = jnp.swapaxes(v, 1, 2)
                        ck2 = lax.dynamic_update_slice(ck, kh,
                                                       (0, 0, pos, 0))
                        cv2 = lax.dynamic_update_slice(cv, vh,
                                                       (0, 0, pos, 0))
                        return _attn_cached(q, ck2, cv2, pos), (ck2, cv2)

                    out, (ck, cv) = _block_core_fusedqkv(
                        p, carry_h, n_head, attn, identity)
                    return out, (ck, cv)

                h, (cache_k, cache_v) = lax.scan(
                    layer, h, (dec_blocks, cache_k, cache_v))
            hl = _layernorm(h, params["lnf_g"], params["lnf_b"])
            logits = hl[:, 0] @ params["head"].astype(hl.dtype)
            nxt = pick(logits, jax.random.fold_in(rng, i + 1))
            ids = lax.dynamic_update_slice(
                ids, nxt[:, None].astype(jnp.int32), (0, pos + 1))
            return (ids, cache_k, cache_v), None

        if max_new > 1:
            (ids, _, _), _ = lax.scan(step, (ids, cache_k, cache_v),
                                      jnp.arange(max_new - 1))
        return ids

    # AOT executable cache (analysis/aot_cache.py): when a cache is
    # active (aot_cache config key / CXN_AOT_CACHE env), the first call
    # of each decode signature loads its persisted executable instead of
    # compiling — the per-signature compile storm CompileWatch measures
    # under fn="gpt_decode" disappears on a warm start. Inactive (the
    # default), the wrapper is one ``active() is None`` check per call.
    # Every lru-key constant selects a different program, so all of them
    # ride in the cache key's `extra` component.
    from ..analysis.aot_cache import CachedProgram, config_hash
    return CachedProgram(
        jax.jit(run), "gpt_decode", config=config_hash(cfg_key),
        extra=repr((n_prompt, max_new, temperature, fused, int8,
                    fold_head, top_k, top_p, int4, int4_group)))


def gpt_decode(params: Dict, prompt: jnp.ndarray, max_new: int,
               cfg: GPTConfig, mesh: Optional[Mesh] = None,
               temperature: float = 0.0,
               rng: Optional[jax.Array] = None,
               int8_weights: bool = False,
               top_k: int = 0, top_p: float = 1.0,
               speculative=None,
               int4_weights: bool = False,
               int4_group: int = INT4_GROUP_DEFAULT) -> jnp.ndarray:
    """Generate ``max_new`` (>= 1) tokens after ``prompt`` (b, n_prompt)
    int32. temperature 0 = greedy; else categorical sampling with ``rng``,
    optionally restricted by ``top_k`` (keep the k most likely tokens;
    0 disables) and ``top_p`` (nucleus sampling, keep the smallest set
    reaching cumulative probability p; 1.0 disables) — both compose with
    temperature (scale first, then filter; ops/sampling.py).
    Returns (b, n_prompt + max_new). n_prompt + max_new <= cfg.seq_len.

    ``mesh`` is accepted for API symmetry with gpt_logits but unused:
    decode partitioning follows the placements of ``params`` via GSPMD.

    ``int8_weights`` (opt-in, round 5): stream the block matmul weights
    int8-quantized through the fused kernel — decode is weight-bandwidth
    -bound (the kernel measured 98.5% of the bf16 streaming floor), so
    halving the bytes is the remaining lever; accuracy is pinned by the
    interpret-mode differential + the on-chip token-agreement smoke.
    Requires the fused path (single shard); ignored with a notice
    otherwise.

    ``speculative`` (opt-in, round 10): draft-and-verify multi-token
    decoding (serve/speculative.py) — an int is a ``spec_len`` for the
    zero-cost n-gram/prompt-lookup drafter, a dict takes ``{"mode":
    "ngram" | "model", "spec_len": K, "model": (draft_cfg,
    draft_params), "stats": {}}`` (``stats`` is filled with
    accept_rate / forwards / drafted on return). Greedy output is
    bit-identical to the non-speculative scan; sampled output is
    identical in distribution. ``int8_weights`` COMPOSES with it since
    the quantized-serving round: the verify/tick programs stream the
    per-out-column int8 weights through the XLA formulation
    (serve/engine.py), so greedy speculative-int8 output is
    bit-identical to the engine's own non-speculative int8 stream —
    int8 is a weight-fidelity choice, speculation a scheduling choice,
    and the two no longer exclude each other.

    ``int4_weights`` (opt-in, round 19): stream the block matmul
    weights PACKED int4 — two nibbles per byte, group-wise symmetric
    scales over ``int4_group`` in-rows (0 = one group = per-out-column)
    — through the XLA decode scan's _qmat4 route (Pallas dequant-matmul
    where the geometry qualifies, the op-for-op XLA reference
    elsewhere). Quarter the weight bytes of bf16, ~half of int8, on the
    weight-bandwidth-bound decode step. Mutually exclusive with
    ``int8_weights``; the fused whole-step kernel is bypassed (it
    streams int8/bf16 only). Accuracy rides the serve engine's
    ``w_int4_tolerance()`` contract; composes with ``speculative`` the
    same way int8 does."""
    n_prompt = int(prompt.shape[1])
    if max_new < 1:
        raise ValueError("max_new must be >= 1, got %d" % max_new)
    if n_prompt + max_new > cfg.seq_len:
        raise ValueError("prompt+max_new %d exceeds seq_len %d"
                         % (n_prompt + max_new, cfg.seq_len))
    if temperature > 0 and rng is None:
        raise ValueError("sampling needs an rng key")
    if top_k < 0:
        raise ValueError("top_k must be >= 0 (0 disables), got %d" % top_k)
    if not 0.0 < top_p <= 1.0:
        raise ValueError("top_p must be in (0, 1], got %g" % top_p)
    if int4_weights and int8_weights:
        raise ValueError("int4_weights and int8_weights are mutually "
                         "exclusive — pick one weight stream")
    if int4_group < 0:
        raise ValueError("int4_group must be >= 0 (0 = per-out-column),"
                         " got %d" % int4_group)
    if speculative:
        # lazy import: serve imports models.gpt at module load, so the
        # reverse edge must stay inside this branch
        import numpy as np

        from ..serve.speculative import speculative_decode
        spec = ({"spec_len": int(speculative)}
                if isinstance(speculative, int) else dict(speculative))
        return jnp.asarray(speculative_decode(
            params, np.asarray(prompt, np.int32), max_new, cfg,
            temperature=float(temperature), rng=rng, top_k=int(top_k),
            top_p=float(top_p), spec=spec,
            int8_weights=bool(int8_weights),
            int4_weights=bool(int4_weights),
            int4_group=int(int4_group)))
    if temperature <= 0:
        # the filters are inert on the greedy path; normalizing them out
        # of the _decode_fn cache key avoids compiling duplicate
        # identical greedy programs per sampling-param combination
        top_k, top_p = 0, 1.0
    if rng is None:
        rng = jax.random.PRNGKey(0)
    import dataclasses
    from ..ops.pallas_kernels import fused_decode_supported
    hd = cfg.feat // cfg.n_head

    _unknown_mesh = {"suppressed": False}

    def _unsharded(leaf):
        # decode partitioning follows the PARAMS' placements (docstring
        # above), so the fusion gate inspects them, not the advisory
        # mesh. A spec axis whose mesh size is 1 is replication in
        # disguise (gpt_place emits P('pipe', ...) even on one chip) —
        # without this, placed single-chip params silently lost the
        # fused kernel (round-5 fix)
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is None:
            return True
        msh = getattr(sh, "mesh", None)
        hit_unknown = [False]

        def size(a):
            try:
                return dict(msh.shape).get(a, 1)
            except Exception:           # unknown mesh type: be safe
                hit_unknown[0] = True
                return 2

        ok = all(ax is None or all(size(a) == 1 for a in
                                   (ax if isinstance(ax, tuple)
                                    else (ax,)))
                 for ax in spec)
        if not ok and hit_unknown[0]:
            # this leaf's verdict came from the conservative unknown-mesh
            # branch, not a real >1 axis — remember so the fallback is
            # announced instead of silent
            _unknown_mesh["suppressed"] = True
        return ok

    # the Pallas kernel is a Mosaic custom call GSPMD cannot partition:
    # any multi-device axis (including data) keeps the XLA scan path
    single_shard = (mesh is None or mesh.devices.size == 1) \
        and all(_unsharded(x) for x in jax.tree.leaves(params["blocks"]))
    if _unknown_mesh["suppressed"] and not single_shard:
        from ..utils import profiler
        profiler.warn(
            "gpt_decode: param sharding uses a mesh type this gate "
            "cannot inspect — conservatively treating it as sharded, "
            "so the fused whole-step decode kernel is disabled "
            "(falling back to the XLA scan); re-place the params with "
            "a jax.sharding.Mesh to re-enable fusion")
    itemsize = 2 if cfg.dtype == "bfloat16" else 4
    # the fused whole-step kernel streams bf16/int8 weights only — int4
    # decode runs the XLA scan, whose _qmat dispatch routes the hot
    # matmuls to the int4 dequant-matmul kernel per-op instead
    fused = bool(single_shard and not int4_weights and fused_decode_supported(
        (int(prompt.shape[0]), cfg.n_head, n_prompt + max_new, hd),
        cfg.n_head, cfg.feat, itemsize=itemsize,
        weight_itemsize=1 if int8_weights else None))
    cfg_key = dataclasses.astuple(cfg)
    # blocklist keyed WITH the int8 flag: an OOM of the bf16-fused
    # program must not lock out the int8 variant (half the weight VMEM
    # — the large shapes that OOM are exactly where int8 fits)
    if (cfg_key, n_prompt, max_new,
            bool(int8_weights)) in _FUSED_DECODE_BLOCKLIST:
        fused = False
    if int8_weights and not fused:
        from ..utils import profiler
        profiler.warn(
            "gpt_decode: int8_weights needs the fused single-shard "
            "path; falling back to the bf16/f32 decode")
    # the head fold has its OWN vmem gate (the resident (feat, vocab)
    # head matrix): an over-budget head only drops the fold, never the
    # fused kernel (review r5)
    fold_head = bool(
        fused and temperature == 0 and int(prompt.shape[0]) == 1
        and fused_decode_supported(
            (int(prompt.shape[0]), cfg.n_head, n_prompt + max_new, hd),
            cfg.n_head, cfg.feat, itemsize=itemsize,
            weight_itemsize=1 if int8_weights else None,
            head_bytes=cfg.feat * cfg.vocab_size * itemsize
            + 8 * cfg.feat))
    fn = _decode_fn(cfg_key, n_prompt, max_new, float(temperature), fused,
                    int8=bool(int8_weights and fused),
                    fold_head=fold_head, top_k=int(top_k),
                    top_p=float(top_p), int4=bool(int4_weights),
                    int4_group=int(int4_group))

    # compile-time accounting (obs/devprof.py): a first-call compile of
    # any decode signature lands in cxn_compile_seconds{fn="gpt_decode"}
    # — the per-signature compile storm the AOT-cache roadmap item
    # wants measured is exactly this label's growth
    from ..obs.devprof import compile_attribution

    def _run(f):
        with compile_attribution("gpt_decode"):
            return f(params, prompt, rng)

    try:
        return _run(fn)
    except Exception as e:                              # noqa: BLE001
        # the supported() VMEM estimate is approximate; a Mosaic scoped-
        # vmem compile OOM on a large shape degrades to the XLA scan
        # (sticky per signature) instead of failing the decode. Matched
        # on 'vmem' or 'scoped'+'memory' (the two Mosaic scoped-memory
        # phrasings) but NOT bare 'memory': an unrelated HBM OOM must
        # not trigger a pointless second trace of the unfused path
        # before failing (ADVICE r4)
        msg = str(e).lower()
        scoped = "vmem" in msg or ("scoped" in msg and "memory" in msg)
        if not fused or not scoped:
            raise
        from ..utils import profiler
        if fold_head:
            # an over-budget HEAD must only drop the fold, never the
            # fused kernel (the fold's vmem gate is approximate too):
            # retry fused-without-fold before considering the blocklist
            profiler.warn(
                "gpt_decode: head-folded kernel exceeded the scoped-"
                "VMEM budget; retrying the fused kernel without the "
                "fold")
            fn = _decode_fn(cfg_key, n_prompt, max_new,
                            float(temperature), fused,
                            int8=bool(int8_weights and fused),
                            fold_head=False, top_k=int(top_k),
                            top_p=float(top_p), int4=bool(int4_weights),
                            int4_group=int(int4_group))
            try:
                return _run(fn)
            except Exception as e2:                     # noqa: BLE001
                msg2 = str(e2).lower()
                if "vmem" not in msg2 and not ("scoped" in msg2
                                               and "memory" in msg2):
                    raise
        profiler.warn(
            "gpt_decode: fused kernel exceeded the scoped-VMEM budget "
            "for this shape; falling back to the XLA scan (raise "
            "--xla_tpu_scoped_vmem_limit_kib to re-enable)")
        _FUSED_DECODE_BLOCKLIST.add((cfg_key, n_prompt, max_new,
                                     bool(int8_weights)))
        # kwargs spelled the same way as the primary call so lru_cache
        # reuses one entry for the unfused program (a kwarg/positional
        # mismatch would trace+compile it twice)
        fn = _decode_fn(cfg_key, n_prompt, max_new, float(temperature),
                        False, int8=False, fold_head=False,
                        top_k=int(top_k), top_p=float(top_p),
                        int4=bool(int4_weights),
                        int4_group=int(int4_group))
        return _run(fn)


def gpt_data_sharding(mesh: Mesh) -> NamedSharding:
    return batch_sharding(mesh)


__all__ = ["GPTConfig", "gpt_init", "gpt_num_params", "gpt_logits",
           "gpt_loss", "gpt_decode", "gpt_opt_init", "make_train_step",
           "gpt_place", "gpt_param_shardings", "gpt_opt_shardings"]
