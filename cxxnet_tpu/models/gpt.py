"""GPT-style causal LM — the 4D-parallel flagship (dp x pp x sp x tp).

The reference tops out at data parallelism over a parameter server
(SURVEY §2.7); this model demonstrates the framework's full modern scaling
stack in ONE jitted train step:

- **dp**   batch sharded over ``data`` (gradient psum by GSPMD)
- **pp**   transformer blocks pipelined over ``pipe`` (gpipe microbatches)
- **sp**   sequence sharded over ``seq`` (ring attention K/V rotation)
- **tp**   megatron-style tensor parallelism over ``model``: QKV/MLP-in
           column-sharded, proj/MLP-out row-sharded with an explicit psum —
           written with manual collectives because the block body executes
           inside the gpipe shard_map where GSPMD does not reach.

Everything outside the pipelined blocks (embedding, final norm, LM head,
loss) is plain jnp under jit, partitioned automatically from the argument
shardings.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import local_attention, ring_attention_inner
from ..parallel.mesh import (DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
                             batch_sharding)
from ..parallel.pipeline import gpipe


@dataclass
class GPTConfig:
    vocab_size: int = 256
    seq_len: int = 128
    n_layer: int = 4
    n_head: int = 4
    feat: int = 64
    mlp_ratio: int = 4
    n_microbatch: int = 2
    dtype: str = "float32"      # activation dtype ("bfloat16" on real chips)


def _layernorm(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    return ((xf - mean) * lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _block(p: Dict[str, jnp.ndarray], h: jnp.ndarray, *, n_head_local: int,
           use_ring: bool) -> jnp.ndarray:
    """Pre-LN transformer block on local shards (b, n_local, F)."""
    b, n, f = h.shape
    x = _layernorm(h, p["ln1_g"], p["ln1_b"])
    # separate Q/K/V projections so the model-axis shard of each is a whole
    # set of heads (a fused (F,3F) weight sharded on its last dim would hand
    # rank 0 all of Q and half of K instead)
    q = x @ p["w_q"].astype(x.dtype) + p["b_q"].astype(x.dtype)
    k = x @ p["w_k"].astype(x.dtype) + p["b_k"].astype(x.dtype)
    v = x @ p["w_v"].astype(x.dtype) + p["b_v"].astype(x.dtype)
    d = q.shape[-1] // n_head_local
    q = q.reshape(b, n, n_head_local, d)
    k = k.reshape(b, n, n_head_local, d)
    v = v.reshape(b, n, n_head_local, d)
    if use_ring:
        att = ring_attention_inner(q, k, v, SEQ_AXIS, causal=True)
    else:
        att = local_attention(q, k, v, causal=True)
    o = att.reshape(b, n, -1) @ p["w_proj"].astype(x.dtype)
    # row-sharded matmul: psum combines the per-rank partial sums; on a
    # size-1 model axis this is the identity (and demotes the vma type)
    o = lax.psum(o, MODEL_AXIS)
    h = h + o + p["b_proj"].astype(x.dtype)
    x = _layernorm(h, p["ln2_g"], p["ln2_b"])
    m = jax.nn.relu(x @ p["w_mlp1"].astype(x.dtype) + p["b_mlp1"].astype(x.dtype))
    m = m @ p["w_mlp2"].astype(x.dtype)
    m = lax.psum(m, MODEL_AXIS)
    return h + m + p["b_mlp2"].astype(x.dtype)


def gpt_init(key: jax.Array, cfg: GPTConfig) -> Dict:
    """Random init; blocks stacked along a leading n_layer dim."""
    f, l = cfg.feat, cfg.n_layer
    mf = cfg.mlp_ratio * f
    k = iter(jax.random.split(key, 16))

    def norm(kk, shape, scale):
        return scale * jax.random.normal(kk, shape, jnp.float32)

    blocks = {
        "ln1_g": jnp.ones((l, f)), "ln1_b": jnp.zeros((l, f)),
        "ln2_g": jnp.ones((l, f)), "ln2_b": jnp.zeros((l, f)),
        "w_q": norm(next(k), (l, f, f), 0.02),
        "w_k": norm(next(k), (l, f, f), 0.02),
        "w_v": norm(next(k), (l, f, f), 0.02),
        "b_q": jnp.zeros((l, f)),
        "b_k": jnp.zeros((l, f)),
        "b_v": jnp.zeros((l, f)),
        "w_proj": norm(next(k), (l, f, f), 0.02 / max(1, l) ** 0.5),
        "b_proj": jnp.zeros((l, f)),
        "w_mlp1": norm(next(k), (l, f, mf), 0.02),
        "b_mlp1": jnp.zeros((l, mf)),
        "w_mlp2": norm(next(k), (l, mf, f), 0.02 / max(1, l) ** 0.5),
        "b_mlp2": jnp.zeros((l, f)),
    }
    return {
        "emb": norm(next(k), (cfg.vocab_size, f), 0.02),
        "pos": norm(next(k), (cfg.seq_len, f), 0.01),
        "lnf_g": jnp.ones((f,)), "lnf_b": jnp.zeros((f,)),
        "head": norm(next(k), (f, cfg.vocab_size), 0.02),
        "blocks": blocks,
    }


def gpt_param_shardings(mesh: Mesh) -> Dict:
    """Placement: blocks pipe-sharded on dim0 + tp-sharded on the megatron
    dims (derived from the same spec table gpipe uses, so placement and
    shard_map in_specs cannot diverge); embeddings/head replicated (small at
    these scales)."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))
    blocks = {k: NamedSharding(mesh, s)
              for k, s in _block_param_specs().items()}
    return {"emb": ns(), "pos": ns(), "lnf_g": ns(), "lnf_b": ns(),
            "head": ns(), "blocks": blocks}


def _block_param_specs() -> Dict:
    return {
        "ln1_g": P(PIPE_AXIS), "ln1_b": P(PIPE_AXIS),
        "ln2_g": P(PIPE_AXIS), "ln2_b": P(PIPE_AXIS),
        "w_q": P(PIPE_AXIS, None, MODEL_AXIS),
        "w_k": P(PIPE_AXIS, None, MODEL_AXIS),
        "w_v": P(PIPE_AXIS, None, MODEL_AXIS),
        "b_q": P(PIPE_AXIS, MODEL_AXIS),
        "b_k": P(PIPE_AXIS, MODEL_AXIS),
        "b_v": P(PIPE_AXIS, MODEL_AXIS),
        "w_proj": P(PIPE_AXIS, MODEL_AXIS, None),
        "b_proj": P(PIPE_AXIS),
        "w_mlp1": P(PIPE_AXIS, None, MODEL_AXIS),
        "b_mlp1": P(PIPE_AXIS, MODEL_AXIS),
        "w_mlp2": P(PIPE_AXIS, MODEL_AXIS, None),
        "b_mlp2": P(PIPE_AXIS),
    }


def gpt_logits(params: Dict, ids: jnp.ndarray, cfg: GPTConfig,
               mesh: Mesh) -> jnp.ndarray:
    """ids (batch, seq_len) int32 -> logits (batch, seq_len, vocab)."""
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    n_tp = mesh.shape.get(MODEL_AXIS, 1)
    n_sp = mesh.shape.get(SEQ_AXIS, 1)
    if cfg.n_head % max(n_tp, 1):
        raise ValueError("n_head %d must divide over model axis %d"
                         % (cfg.n_head, n_tp))
    if cfg.seq_len % max(n_sp, 1):
        raise ValueError("seq_len %d must be divisible by the seq axis "
                         "(seq_parallel=%d)" % (cfg.seq_len, n_sp))
    h = (params["emb"][ids] + params["pos"][None, :ids.shape[1]]).astype(dtype)
    block = functools.partial(
        _block, n_head_local=cfg.n_head // max(n_tp, 1),
        use_ring=n_sp > 1)
    h = gpipe(block, params["blocks"], h, mesh, cfg.n_microbatch,
              extra_spec_axes=(SEQ_AXIS,), param_specs=_block_param_specs())
    h = _layernorm(h, params["lnf_g"], params["lnf_b"])
    return (h @ params["head"].astype(h.dtype)).astype(jnp.float32)


def gpt_loss(params: Dict, ids: jnp.ndarray, cfg: GPTConfig,
             mesh: Mesh) -> jnp.ndarray:
    """Next-token cross-entropy (last position predicts nothing)."""
    logits = gpt_logits(params, ids, cfg, mesh)
    logp = jax.nn.log_softmax(logits[:, :-1])
    tgt = ids[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(cfg: GPTConfig, mesh: Mesh, eta: float = 0.1,
                    momentum: float = 0.9):
    """Jitted SGD-momentum train step; donates params/opt state."""
    shardings = gpt_param_shardings(mesh)

    def step(params, mom, ids):
        loss, grads = jax.value_and_grad(gpt_loss)(params, ids, cfg, mesh)
        new_mom = jax.tree.map(lambda m, g: momentum * m - eta * g, mom, grads)
        new_params = jax.tree.map(jnp.add, params, new_mom)
        # keep placements stable step-over-step
        new_params = jax.lax.with_sharding_constraint(new_params, shardings)
        new_mom = jax.lax.with_sharding_constraint(new_mom, shardings)
        return new_params, new_mom, loss

    return jax.jit(step, donate_argnums=(0, 1))


def gpt_place(params: Dict, mesh: Mesh) -> Dict:
    return jax.device_put(params, gpt_param_shardings(mesh))


def gpt_data_sharding(mesh: Mesh) -> NamedSharding:
    return batch_sharding(mesh)


__all__ = ["GPTConfig", "gpt_init", "gpt_logits", "gpt_loss",
           "make_train_step", "gpt_place", "gpt_param_shardings"]
