"""Evaluation metrics with reference semantics (/root/reference/src/utils/metric.h).

- error   (metric.h:92-110): top-1 argmax mismatch; scalar preds threshold at 0
- rmse    (metric.h:73-89):  mean per-instance sum of squared differences
- logloss (metric.h:113-132): -log p[target], probs clipped to [1e-15, 1-1e-15];
  scalar preds use binary logloss
- rec@n   (metric.h:135-172): fraction of true labels in the top-n predictions
  (ties broken by a random shuffle before the stable sort, as in the reference)

Accumulators are numpy-side: predictions arrive as host arrays copied out of
the jitted step (the eval_req path, nnet_impl-inl.hpp:152-180). Batched
vectorized math replaces the reference's per-instance loops.

Device path (round 6): metrics that define :meth:`Metric.device_calc`
(``device_capable = True``) can ALSO run inside the jitted train step —
the trainer sums their per-instance values into an on-device (sum, count)
accumulator and fetches it only at round/log boundaries, so ``eval_train``
costs zero device->host syncs per step (nnet/net.py). ``rec@n`` stays
host-only: its tie-break draws from a stateful host RNG
(reference metric.h:165) that a pure traced function cannot reproduce.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

import numpy as np


class Metric:
    name = ""
    # True when device_calc mirrors calc under jit (jnp, f32) — the
    # trainer then accumulates this metric on device between log
    # boundaries instead of fetching predictions every step
    device_capable = False

    def __init__(self) -> None:
        self.clear()

    def clear(self) -> None:
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def add_eval(self, pred: np.ndarray, label: np.ndarray) -> None:
        """pred: (n, d); label: (n, label_width)."""
        vals = self.calc(np.asarray(pred), np.asarray(label))
        self.sum_metric += float(np.sum(vals))
        self.cnt_inst += pred.shape[0]

    def get(self) -> float:
        return self.finish(self.sum_metric, float(self.cnt_inst))

    def finish(self, sum_metric: float, cnt_inst: float) -> float:
        """Turn globally-summable accumulators into the statistic. The
        cross-process reduce path (MetricSet.print with a reducer) sums
        (sum_metric, cnt_inst) over ranks and applies finish() to the
        totals — so a subclass with a nonlinear finish (e.g. a true RMSE
        sqrt) must express it HERE, not in get(), to be multi-host
        correct. All reference metrics (utils/metric.h) are plain
        sum/cnt means."""
        return sum_metric / max(cnt_inst, 1.0)

    def calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def device_calc(self, pred, label):
        """Traced twin of :meth:`calc`: jnp in, per-instance jnp f32 out.
        Only meaningful when ``device_capable``; values must equal calc's
        (so the (sum, count) accumulators agree with the host path —
        bit-for-bit for counting metrics like ``error``, to f32 rounding
        for continuous ones)."""
        raise NotImplementedError


class MetricError(Metric):
    name = "error"
    device_capable = True

    def calc(self, pred, label):
        if pred.shape[1] != 1:
            maxidx = np.argmax(pred, axis=1)
        else:
            maxidx = (pred[:, 0] > 0.0).astype(np.int64)
        return (maxidx != label[:, 0].astype(np.int64)).astype(np.float64)

    def device_calc(self, pred, label):
        import jax.numpy as jnp
        if pred.shape[1] != 1:
            maxidx = jnp.argmax(pred, axis=1)
        else:
            maxidx = (pred[:, 0] > 0.0).astype(jnp.int32)
        return (maxidx != label[:, 0].astype(jnp.int32)).astype(jnp.float32)


class MetricRMSE(Metric):
    name = "rmse"
    device_capable = True

    def calc(self, pred, label):
        if pred.shape[1] != label.shape[1]:
            raise ValueError("rmse: prediction and label size must match")
        return np.sum((pred - label) ** 2, axis=1)

    def device_calc(self, pred, label):
        import jax.numpy as jnp
        if pred.shape[1] != label.shape[1]:
            raise ValueError("rmse: prediction and label size must match")
        return jnp.sum((pred - label) ** 2, axis=1)


class MetricLogloss(Metric):
    name = "logloss"
    device_capable = True

    def calc(self, pred, label):
        eps = 1e-15
        if pred.shape[1] != 1:
            target = label[:, 0].astype(np.int64)
            p = np.clip(pred[np.arange(pred.shape[0]), target], eps, 1 - eps)
            return -np.log(p)
        p = np.clip(pred[:, 0], eps, 1 - eps)
        y = label[:, 0]
        res = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        if np.any(np.isnan(res)):
            raise FloatingPointError("logloss: NaN detected")
        return res

    def device_calc(self, pred, label):
        # eps clips to f32 denormal scale on device; the NaN raise of the
        # host path becomes a NaN accumulator the nan_check watchdog sees
        import jax.numpy as jnp
        eps = 1e-15
        if pred.shape[1] != 1:
            target = label[:, 0].astype(jnp.int32)
            p = jnp.take_along_axis(pred, target[:, None], axis=1)[:, 0]
            return -jnp.log(jnp.clip(p, eps, 1 - eps))
        p = jnp.clip(pred[:, 0], eps, 1 - eps)
        y = label[:, 0]
        return -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))


class MetricLMNLL(Metric):
    """Per-token negative log-likelihood of a causal LM (no reference
    counterpart — the reference has no sequence models, SURVEY §5.7).
    pred: the flattened (n, seq*vocab) probabilities of an ``lm_softmax``
    node; label: the (n, seq) token ids (position i's prediction is
    scored against token i+1; the last position predicts nothing).
    Perplexity = exp(lm_nll)."""
    name = "lm_nll"
    device_capable = True

    def calc(self, pred, label):
        b, nv = pred.shape
        n = label.shape[1]
        if n < 2 or nv % n:
            raise ValueError(
                "lm_nll: prediction width %d is not seq*vocab for label "
                "width %d" % (nv, n))
        probs = pred.reshape(b, n, nv // n)
        tgt = label[:, 1:].astype(np.int64)
        p = np.take_along_axis(probs[:, :-1], tgt[..., None], axis=-1)[..., 0]
        return -np.log(np.clip(p, 1e-15, None)).mean(axis=1)

    def device_calc(self, pred, label):
        import jax.numpy as jnp
        b, nv = pred.shape
        n = label.shape[1]
        if n < 2 or nv % n:
            raise ValueError(
                "lm_nll: prediction width %d is not seq*vocab for label "
                "width %d" % (nv, n))
        probs = pred.reshape(b, n, nv // n)
        tgt = label[:, 1:].astype(jnp.int32)
        p = jnp.take_along_axis(probs[:, :-1], tgt[..., None],
                                axis=-1)[..., 0]
        return -jnp.log(jnp.clip(p, 1e-15, None)).mean(axis=1)


class MetricRecall(Metric):
    def __init__(self, name: str) -> None:
        m = re.match(r"^rec@(\d+)$", name)
        if not m:
            raise ValueError("must specify n for rec@n")
        self.topn = int(m.group(1))
        self.name = name
        # private seeded RNG for tie-breaks: deterministic evals, and no
        # perturbation of global np.random state (reference uses a private
        # seeded RandomSampler, metric.h:165)
        self._rng = np.random.RandomState(131)
        super().__init__()

    def calc(self, pred, label):
        n, d = pred.shape
        if d < self.topn:
            raise ValueError("rec@%d on prediction list of length %d"
                             % (self.topn, d))
        # random tie-break then stable sort by descending score (metric.h:148-151)
        perm = self._rng.permutation(d)
        order = perm[np.argsort(-pred[:, perm], axis=1, kind="stable")]
        top = order[:, :self.topn]                       # (n, topn) class indices
        hits = (top[:, :, None] == label[:, None, :].astype(np.int64)).any(axis=1)
        return hits.sum(axis=1).astype(np.float64) / label.shape[1]


def create_metric(name: str) -> Metric:
    if name == "error":
        return MetricError()
    if name == "rmse":
        return MetricRMSE()
    if name == "logloss":
        return MetricLogloss()
    if name == "lm_nll":
        return MetricLMNLL()
    if name.startswith("rec@"):
        return MetricRecall(name)
    raise ValueError("unknown metric name %r" % name)


class MetricSet:
    """Set of metrics, each bound to a label field (and optionally a node).

    Config forms (nnet_impl-inl.hpp:57-67):
      ``metric = error``                 — label field "label", default out node
      ``metric[label] = error``          — explicit label field
      ``metric[label,node] = error``     — bind to a named node's output
    """

    def __init__(self) -> None:
        self.metrics: List[Metric] = []
        self.label_fields: List[str] = []
        self.node_names: List[str] = []    # "" = default output node

    def add_metric(self, name: str, field: str = "label",
                   node: str = "") -> None:
        self.metrics.append(create_metric(name))
        self.label_fields.append(field)
        self.node_names.append(node)

    def configure(self, key: str, val: str) -> bool:
        """Handle a ``metric...`` config pair; returns True if consumed."""
        if key == "metric":
            self.add_metric(val)
            return True
        m = re.match(r"^metric\[([^\],]+)(?:,([^\]]+))?\]$", key)
        if m:
            self.add_metric(val, m.group(1), m.group(2) or "")
            return True
        return False

    def clear(self) -> None:
        for m in self.metrics:
            m.clear()

    def add_eval(self, predscores: List[np.ndarray],
                 labels: Dict[str, np.ndarray]) -> None:
        if len(predscores) != len(self.metrics):
            raise ValueError("MetricSet: #predictions != #metrics")
        for metric, field, pred in zip(self.metrics, self.label_fields,
                                       predscores):
            if field not in labels:
                raise KeyError("Metric: unknown target %r" % field)
            metric.add_eval(pred, labels[field])

    def print(self, evname: str, reduce=None) -> str:
        """Format the eval line. ``reduce`` (optional) is applied to the
        (n_metrics, 2) array of [sum_metric, cnt_inst] accumulator pairs
        before the division — pass a cross-process summing reducer
        (parallel.distributed.host_psum) so every rank prints the GLOBAL
        statistic instead of its own shard's (the reference printed
        per-worker numbers, utils/metric.h:175-236)."""
        if reduce is not None:
            # cross-process path: sum the raw (sum, cnt) accumulators over
            # ranks, then apply each metric's finish() to the totals —
            # nonlinear finishes are honored as long as they are expressed
            # as Metric.finish (see its docstring); overriding get()
            # directly would only affect the local path below
            pairs = np.asarray([[m.sum_metric, float(m.cnt_inst)]
                                for m in self.metrics], np.float64)
            if len(pairs):
                pairs = np.asarray(reduce(pairs), np.float64)
            values = [m.finish(s, c) for m, (s, c) in zip(self.metrics,
                                                          pairs)]
        else:
            values = [m.get() for m in self.metrics]
        out = []
        for v, metric, field in zip(values, self.metrics,
                                    self.label_fields):
            tag = metric.name if field == "label" else "%s[%s]" % (metric.name,
                                                                   field)
            out.append("\t%s-%s:%g" % (evname, tag, v))
        return "".join(out)
