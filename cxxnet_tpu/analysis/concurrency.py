"""cxn-lint pass 3: host-side concurrency discipline (CXN3xx).

Passes 1-2 (graph_lint.py, step_audit.py) audit what the *compiler*
sees — configs and HLO. This pass audits what the compiler cannot see:
the Python host runtime that PRs 16-17 turned into a multi-threaded,
multi-process serving fleet (router threads, RPC reader/writer threads,
scheduler queues, merged metrics registries). Two halves share this
module:

**Static half** — an AST pass over the package driven by a lightweight
annotation convention::

    self._tries = {}        # guarded_by: self._lock

marks ``_tries`` as shared mutable state protected by ``self._lock``.
The analyzer then reports:

- **CXN301** write to a guarded attribute outside any ``with <guard>:``
  block in a thread-reachable method. Exempt: ``__init__``/``__new__``/
  ``__del__`` (happens-before publication), methods whose name ends in
  ``_locked``, and methods whose docstring says "caller holds" — both
  existing repo conventions for lock-is-already-held helpers.
- **CXN302** lock-acquisition-order cycle in the static acquisition
  graph (deadlock potential across router <-> fleet <-> metrics). Edges
  come from lexically nested ``with`` blocks plus one level of
  same-class / same-module call resolution.
- **CXN303** blocking call while holding a lock: socket ``recv``/
  ``accept``, ``queue.get()`` with no timeout, ``subprocess`` ``wait``,
  ``time.sleep``, ``jax.block_until_ready``, thread ``join``. Waiting
  on a *held* ``Condition`` is NOT flagged — ``Condition.wait``
  releases its lock while parked (that is CXN305's business).
- **CXN304** ``threading.Thread`` created without ``daemon=`` and
  without a visible join/daemon-flag path — the pattern the test
  suite's leaked-thread fixture exists to catch after the fact.
- **CXN305** untimed ``Condition.wait()`` outside a predicate ``while``
  loop (lost-wakeup / spurious-wakeup hazard). Timed waits are polls by
  construction and stay quiet.

Per-line suppression: ``# cxn-lint: disable=CXN301`` on (or directly
above) the offending line, for annotated-intentional patterns; config
``lint_ignore`` works through LintReport exactly as for passes 1-2.

**Runtime half** — a debug lock-order watchdog, armed by
``CXN_LOCK_WATCH=1``. :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition` return plain ``threading`` primitives normally;
armed, they return wrapped primitives that maintain per-thread held
stacks and a global acquisition-order graph keyed by creation-site
name. Acquiring B while holding A records the edge A->B; an acquire
that would close an observed inversion (B->A exists) raises
:class:`LockOrderError` at the acquire site — the dynamic oracle that
validates CXN302's static graph during the fleet/router suites
(tests/fleet_harness.py arms it in every worker). An optional hold-time
budget (``CXN_LOCK_HOLD_MS``, float, 0/unset = off) records — but does
not raise on — sections that held a lock past the budget; tests drain
them via :func:`violations` / :func:`check`.

This module is stdlib-only on purpose: the swept modules (serve/, obs/,
io/) import it at module scope, and it must never drag jax into a
process that only wanted a metrics counter.
"""

from __future__ import annotations

import ast
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding, LintReport

__all__ = [
    "analyze_package", "analyze_source", "lint_threads",
    "make_lock", "make_rlock", "make_condition",
    "watch_enabled", "violations", "reset_watch", "check",
    "LockOrderError",
]

_LAYER = "threads"

_GUARD_RE = re.compile(r"#\s*guarded_by:\s*([^#\r\n]+?)\s*$")
_DISABLE_RE = re.compile(r"#\s*cxn-lint:\s*disable=([A-Za-z0-9,\s]+)")

# container mutations that count as writes for CXN301 (reads stay quiet
# by design: the convention is deliberately lightweight, and benign
# racy stat reads are annotated-intentional)
_MUTATORS = frozenset((
    "append", "appendleft", "add", "extend", "extendleft", "insert",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "rotate", "move_to_end",
))

# attribute calls that block on the network while a lock is held
_BLOCKING_SOCK = frozenset(("recv", "recv_into", "recvfrom", "accept"))


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:               # pragma: no cover - malformed node
        return ""


def _norm_expr(text: str) -> str:
    """Canonicalize a guard expression ('self. _lock' -> 'self._lock')
    so annotation text and ``with`` context expressions compare equal."""
    try:
        return ast.unparse(ast.parse(text.strip(), mode="eval"))
    except SyntaxError:
        return text.strip()


def _self_attr_root(node: ast.AST) -> Optional[str]:
    """The attribute name a write ultimately lands on, for targets
    rooted at ``self``: ``self.x``, ``self.x[k]``, ``self.x[k].y`` all
    resolve to ``x``. None for anything not rooted at ``self``."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        node = node.value
    return None


def _name_root(node: ast.AST) -> Optional[str]:
    """Like :func:`_self_attr_root` for module-level names: ``x``,
    ``x[k]`` resolve to ``x``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_condition_ctor(call: ast.Call) -> bool:
    return isinstance(call, ast.Call) and (
        _unparse(call.func).endswith("Condition")
        or _unparse(call.func).endswith("make_condition"))


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = _unparse(call.func)
    return fn == "Thread" or fn.endswith("threading.Thread")


class _Edges:
    """The static lock-acquisition graph (CXN302). Nodes are
    class-qualified guard names (``ServeRouter._lock``); edges carry one
    witness site each for the report."""

    def __init__(self) -> None:
        self.out: Dict[str, Dict[str, Tuple[str, int]]] = {}

    def add(self, a: str, b: str, path: str, line: int) -> None:
        if a == b:      # reentrant same-guard nesting (RLock) is fine
            return
        self.out.setdefault(a, {}).setdefault(b, (path, line))

    def cycles(self) -> List[Tuple[List[str], Tuple[str, int]]]:
        """Every distinct acquisition-order cycle, each with the witness
        site of its first edge. Deduped on the node set, so A->B->A and
        B->A->B report once."""
        found: List[Tuple[List[str], Tuple[str, int]]] = []
        seen: Set[frozenset] = set()
        for start in sorted(self.out):
            stack = [(start, [start])]
            while stack:
                node, trail = stack.pop()
                for nxt in sorted(self.out.get(node, ())):
                    if nxt == start:
                        key = frozenset(trail)
                        if key not in seen:
                            seen.add(key)
                            found.append((trail + [start],
                                          self.out[start][trail[1]]
                                          if len(trail) > 1
                                          else self.out[node][nxt]))
                    elif nxt not in trail:
                        stack.append((nxt, trail + [nxt]))
        return found


class _ModuleLint(ast.NodeVisitor):
    """One file's static pass. Collects findings for CXN301/303/304/305
    directly and acquisition edges (CXN302) into a shared graph."""

    def __init__(self, tree: ast.Module, src: str, path: str,
                 modname: str, edges: _Edges) -> None:
        self.tree = tree
        self.path = path
        self.modname = modname
        self.edges = edges
        self.findings: List[Finding] = []
        lines = src.splitlines()
        self.guards_at: Dict[int, str] = {}     # line -> guard expr
        self.comment_only: Set[int] = set()     # whole-line comments
        self.disables: Dict[int, Set[str]] = {}  # line -> {"CXN301",...}
        for i, ln in enumerate(lines, 1):
            if ln.lstrip().startswith("#"):
                self.comment_only.add(i)
            m = _GUARD_RE.search(ln)
            if m:
                self.guards_at[i] = _norm_expr(m.group(1))
            m = _DISABLE_RE.search(ln)
            if m:
                self.disables[i] = {r.strip().upper()
                                    for r in m.group(1).split(",")
                                    if r.strip()}
        # join/daemon escape hatch for CXN304: any name that is ever
        # .join()ed or has .daemon assigned counts as tracked
        self.joined: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and isinstance(node.func.value,
                                   (ast.Name, ast.Attribute)):
                leaf = (node.func.value.attr
                        if isinstance(node.func.value, ast.Attribute)
                        else node.func.value.id)
                self.joined.add(leaf)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon":
                        base = t.value
                        leaf = (base.attr if isinstance(base, ast.Attribute)
                                else base.id if isinstance(base, ast.Name)
                                else None)
                        if leaf:
                            self.joined.add(leaf)
        # module-scope guarded names and conditions
        self.mod_guarded: Dict[str, str] = {}
        self.mod_conds: Set[str] = set()
        self._scan_scope(tree.body, None)
        # class name -> {attr: guard} / {condition attr exprs}
        self.cls_guarded: Dict[str, Dict[str, str]] = {}
        self.cls_conds: Dict[str, Set[str]] = {}
        self.cls_method_guards: Dict[str, Dict[str, List[str]]] = {}
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            self._scan_class(cls)
        self.mod_fn_guards: Dict[str, List[str]] = {
            fn.name: self._guards_in(fn) for fn in tree.body
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))}

    # ---------------------------------------------------- collection
    def _guard_for_line(self, line: int) -> Optional[str]:
        """The guarded_by annotation covering ``line``: same line, or a
        comment-ONLY line directly above (a trailing annotation on the
        previous statement must not bleed onto this one)."""
        g = self.guards_at.get(line)
        if g is None and line - 1 in self.comment_only:
            g = self.guards_at.get(line - 1)
        return g

    def _scan_scope(self, body: Sequence[ast.stmt], cls: None) -> None:
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                g = self._guard_for_line(stmt.lineno)
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        if g:
                            self.mod_guarded[t.id] = g
                        if isinstance(stmt.value, ast.Call) \
                                and _is_condition_ctor(stmt.value):
                            self.mod_conds.add(t.id)

    def _scan_class(self, cls: ast.ClassDef) -> None:
        guarded: Dict[str, str] = {}
        conds: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                g = self._guard_for_line(node.lineno)
                for t in node.targets:
                    attr = t.attr if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" else None
                    if attr and g:
                        guarded[attr] = g
                    if attr and isinstance(node.value, ast.Call) \
                            and _is_condition_ctor(node.value):
                        conds.add("self." + attr)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Attribute) \
                    and isinstance(node.target.value, ast.Name) \
                    and node.target.value.id == "self":
                g = self._guard_for_line(node.lineno)
                if g:
                    guarded[node.target.attr] = g
                if isinstance(node.value, ast.Call) \
                        and _is_condition_ctor(node.value):
                    conds.add("self." + node.target.attr)
        self.cls_guarded[cls.name] = guarded
        self.cls_conds[cls.name] = conds
        self.cls_method_guards[cls.name] = {
            fn.name: self._guards_in(fn) for fn in cls.body
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _guards_in(self, fn: ast.AST) -> List[str]:
        out = []
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    out.append(_norm_expr(_unparse(item.context_expr)))
        return out

    # ------------------------------------------------------ reporting
    def _suppressed(self, rule: str, line: int) -> bool:
        dis = self.disables.get(line)
        if (dis is None or not dis) and line - 1 in self.comment_only:
            dis = self.disables.get(line - 1)
        return bool(dis) and (rule in dis or "CXN3XX" in dis)

    def _emit(self, rule: str, line: int, msg: str) -> None:
        if not self._suppressed(rule, line):
            self.findings.append(Finding(rule, msg, path=self.path,
                                         line=line, layer=_LAYER))

    def _node_name(self, guard: str, cls: Optional[str]) -> str:
        """Class-qualify a guard for the acquisition graph:
        ``self._lock`` inside ServeRouter -> ``ServeRouter._lock``;
        module-level guards get the module name."""
        if cls and guard.startswith("self."):
            return "%s.%s" % (cls, guard[5:])
        if guard.startswith("self."):
            return "%s.%s" % (self.modname, guard[5:])
        return "%s:%s" % (self.modname, guard)

    # ------------------------------------------------------- the walk
    def run(self) -> List[Finding]:
        for stmt in self.tree.body:
            if isinstance(stmt, ast.ClassDef):
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._walk_fn(item, stmt.name)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_fn(stmt, None)
        return self.findings

    @staticmethod
    def _caller_holds(fn: ast.AST) -> bool:
        if fn.name.endswith("_locked"):
            return True
        doc = ast.get_docstring(fn) or ""
        return "caller holds" in doc.lower()

    def _walk_fn(self, fn: ast.AST, cls: Optional[str]) -> None:
        exempt301 = (fn.name in ("__init__", "__new__", "__del__")
                     or self._caller_holds(fn))
        guarded = dict(self.mod_guarded)
        conds = set(self.mod_conds)
        attr_guards = self.cls_guarded.get(cls, {}) if cls else {}
        if cls:
            conds |= self.cls_conds.get(cls, set())
        # local conditions (cli.py's `feed = threading.Condition()`)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _is_condition_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        conds.add(t.id)
        self._visit(fn.body, cls, fn, exempt301, attr_guards, guarded,
                    conds, held=[], in_while=False)

    def _visit(self, body: Sequence[ast.stmt], cls, fn, exempt301,
               attr_guards, name_guards, conds,
               held: List[str], in_while: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_fn(stmt, cls)    # fresh held stack: runs later
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered = []
                for item in stmt.items:
                    g = _norm_expr(_unparse(item.context_expr))
                    for h in held + entered:
                        self.edges.add(self._node_name(h, cls),
                                       self._node_name(g, cls),
                                       self.path, stmt.lineno)
                    entered.append(g)
                self._visit(stmt.body, cls, fn, exempt301, attr_guards,
                            name_guards, conds, held + entered, in_while)
                continue
            if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                self._check_stmt(stmt, cls, fn, exempt301, attr_guards,
                                 name_guards, conds, held, in_while,
                                 header_only=True)
                self._visit(stmt.body, cls, fn, exempt301, attr_guards,
                            name_guards, conds, held,
                            in_while or isinstance(stmt, ast.While))
                self._visit(stmt.orelse, cls, fn, exempt301, attr_guards,
                            name_guards, conds, held, in_while)
                continue
            if isinstance(stmt, ast.If):
                self._check_expr(stmt.test, cls, fn, exempt301,
                                 attr_guards, name_guards, conds, held,
                                 in_while)
                self._visit(stmt.body, cls, fn, exempt301, attr_guards,
                            name_guards, conds, held, in_while)
                self._visit(stmt.orelse, cls, fn, exempt301, attr_guards,
                            name_guards, conds, held, in_while)
                continue
            if isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._visit(blk, cls, fn, exempt301, attr_guards,
                                name_guards, conds, held, in_while)
                for h in stmt.handlers:
                    self._visit(h.body, cls, fn, exempt301, attr_guards,
                                name_guards, conds, held, in_while)
                continue
            self._check_stmt(stmt, cls, fn, exempt301, attr_guards,
                             name_guards, conds, held, in_while)

    # ------------------------------------------------- per-node rules
    def _check_stmt(self, stmt, cls, fn, exempt301, attr_guards,
                    name_guards, conds, held, in_while,
                    header_only=False) -> None:
        # CXN301: writes to guarded state
        if not exempt301 and not header_only:
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            elif isinstance(stmt, ast.Delete):
                targets = list(stmt.targets)
            for t in targets:
                self._check_write(t, cls, attr_guards, name_guards, held)
        nodes = ast.walk(stmt.test if header_only and
                         hasattr(stmt, "test") else stmt) \
            if not header_only or hasattr(stmt, "test") else ()
        for node in nodes:
            if isinstance(node, ast.Call):
                self._check_call(node, cls, fn, exempt301, attr_guards,
                                 name_guards, conds, held, in_while)

    def _check_expr(self, expr, cls, fn, exempt301, attr_guards,
                    name_guards, conds, held, in_while) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._check_call(node, cls, fn, exempt301, attr_guards,
                                 name_guards, conds, held, in_while)

    def _check_write(self, target, cls, attr_guards, name_guards,
                     held) -> None:
        attr = _self_attr_root(target)
        guard = attr_guards.get(attr) if attr else None
        label = "self.%s" % attr if attr else None
        if guard is None:
            name = _name_root(target)
            guard = name_guards.get(name) if name else None
            label = name
        if guard and guard not in held:
            self._emit("CXN301", target.lineno,
                       "write to %s outside its guard `with %s:`"
                       % (label, guard))

    def _check_call(self, call: ast.Call, cls, fn, exempt301,
                    attr_guards, name_guards, conds, held,
                    in_while) -> None:
        fn_text = _unparse(call.func)
        recv = None
        attr = None
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            recv = _norm_expr(_unparse(call.func.value))
        # CXN301: mutating container calls on guarded state
        if attr in _MUTATORS and not exempt301:
            owner = _self_attr_root(call.func.value)
            guard = attr_guards.get(owner) if owner else None
            label = "self.%s" % owner if owner else None
            if guard is None:
                name = _name_root(call.func.value)
                guard = name_guards.get(name) if name else None
                label = name
            if guard and guard not in held:
                self._emit("CXN301", call.lineno,
                           "%s.%s() mutates guarded state outside "
                           "`with %s:`" % (label, attr, guard))
        # CXN302: one-level call resolution into the acquisition graph
        if held and recv == "self" and cls:
            for g in self.cls_method_guards.get(cls, {}).get(attr, ()):
                for h in held:
                    self.edges.add(self._node_name(h, cls),
                                   self._node_name(g, cls),
                                   self.path, call.lineno)
        elif held and recv is None and isinstance(call.func, ast.Name):
            for g in self.mod_fn_guards.get(call.func.id, ()):
                for h in held:
                    self.edges.add(self._node_name(h, cls),
                                   self._node_name(g, None),
                                   self.path, call.lineno)
        # CXN303: blocking while holding a lock
        if held:
            blocked = None
            if fn_text.endswith("time.sleep") or fn_text == "sleep":
                blocked = "time.sleep()"
            elif attr == "block_until_ready" \
                    or fn_text.endswith("block_until_ready"):
                blocked = "jax.block_until_ready()"
            elif attr in _BLOCKING_SOCK:
                blocked = "socket .%s()" % attr
            elif attr == "get" and not call.args and not call.keywords:
                blocked = "queue .get() with no timeout"
            elif attr == "get" and not any(
                    kw.arg == "timeout" for kw in call.keywords) \
                    and call.args \
                    and isinstance(call.args[0], ast.Constant) \
                    and call.args[0].value is True \
                    and len(call.args) < 2:
                blocked = "queue .get(block=True) with no timeout"
            elif attr == "wait" and recv not in held \
                    and recv is not None \
                    and re.search(r"proc|popen", recv, re.I):
                blocked = "subprocess .wait()"
            elif attr == "join" and recv is not None \
                    and re.search(r"thread|_t\b", recv, re.I):
                blocked = "thread .join()"
            if blocked:
                self._emit("CXN303", call.lineno,
                           "blocking %s while holding %s"
                           % (blocked, ", ".join(sorted(set(held)))))
        # CXN304: untracked threads
        if _is_thread_ctor(call):
            if not any(kw.arg == "daemon" for kw in call.keywords):
                if not (self.joined & self._target_leaves(call)):
                    self._emit("CXN304", call.lineno,
                               "threading.Thread without daemon= and no "
                               "tracked join/daemon path")
        # CXN305: condition wait outside a predicate while loop
        if attr == "wait" and recv in conds and not call.args \
                and not call.keywords and not in_while:
            self._emit("CXN305", call.lineno,
                       "untimed %s.wait() outside a predicate `while` "
                       "loop (lost/spurious wakeup hazard)" % recv)

    def _target_leaves(self, call: ast.Call) -> Set[str]:
        """Names the Thread object could be reachable by, to match
        against the module's joined/daemon-assigned set. Walks the whole
        tree for `x = threading.Thread(...)` statements owning this
        exact call node."""
        leaves: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        leaves.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        leaves.add(t.attr)
        return leaves


# ------------------------------------------------------------- drivers
def _analyze_module(src: str, path: str, modname: str,
                    edges: _Edges) -> List[Finding]:
    tree = ast.parse(src, filename=path)
    return _ModuleLint(tree, src, path, modname, edges).run()


def _emit_cycles(edges: _Edges, report: LintReport) -> None:
    for trail, (path, line) in edges.cycles():
        report.add(Finding(
            "CXN302",
            "lock-acquisition-order cycle: %s" % " -> ".join(trail),
            path=path, line=line, layer=_LAYER))


def analyze_source(src: str, path: str = "<source>",
                   report: Optional[LintReport] = None,
                   modname: Optional[str] = None) -> LintReport:
    """Static pass over one module's source (the test-fixture entry
    point). Runs all five rules including a module-local CXN302 cycle
    check."""
    report = report if report is not None else LintReport()
    edges = _Edges()
    for f in _analyze_module(src, path, modname or
                             os.path.splitext(os.path.basename(path))[0],
                             edges):
        report.add(f)
    _emit_cycles(edges, report)
    return report


def analyze_package(root: Optional[str] = None,
                    report: Optional[LintReport] = None) -> LintReport:
    """Static pass over every ``*.py`` under ``root`` (default: the
    installed ``cxxnet_tpu`` package), with the acquisition graph —
    and so CXN302 — built package-wide."""
    report = report if report is not None else LintReport()
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    edges = _Edges()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__",))
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            modname = rel[:-3].replace(os.sep, ".")
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            try:
                for f in _analyze_module(src, rel, modname, edges):
                    report.add(f)
            except SyntaxError as e:
                report.add(Finding("CXN302", "unparsable module: %s" % e,
                                   path=rel, line=e.lineno or 0,
                                   layer=_LAYER))
    _emit_cycles(edges, report)
    return report


def lint_threads(root: Optional[str] = None,
                 report: Optional[LintReport] = None) -> LintReport:
    """The ``task=lint`` / ``tools/cxn_lint.py --threads`` entry point:
    :func:`analyze_package` under the standard report plumbing."""
    return analyze_package(root=root, report=report)


# =====================================================================
# Runtime half: the lock-order watchdog
# =====================================================================
class LockOrderError(RuntimeError):
    """An acquire that closes an observed lock-order inversion, raised
    in the acquiring thread the moment the cycle becomes possible —
    BEFORE it can deadlock, not after."""


def watch_enabled() -> bool:
    return os.environ.get("CXN_LOCK_WATCH", "") not in ("", "0")


def _hold_budget_ms() -> float:
    try:
        return float(os.environ.get("CXN_LOCK_HOLD_MS", "0") or 0)
    except ValueError:
        return 0.0


class _Held:
    __slots__ = ("name", "depth", "t0")

    def __init__(self, name: str) -> None:
        self.name = name
        self.depth = 1
        self.t0 = time.monotonic()


class _WatchState:
    """Global watchdog state: the observed acquisition graph (keyed by
    creation-site lock NAME, so the check survives respawned instances)
    plus per-thread held stacks and the violation journal."""

    def __init__(self) -> None:
        self.mu = threading.Lock()      # raw on purpose: never watched
        self.edges: Dict[str, Set[str]] = {}
        self.violations: List[str] = []
        self.tls = threading.local()

    def held(self) -> List[_Held]:
        try:
            return self.tls.held
        except AttributeError:
            self.tls.held = []
            return self.tls.held

    def before_acquire(self, name: str) -> None:
        held = self.held()
        for h in held:
            if h.name == name:          # reentrant (RLock) — no edge
                return
        if not held:
            return
        with self.mu:
            back = self.edges.get(name, ())
            for h in held:
                if h.name in back:
                    msg = ("lock-order inversion: acquiring %r while "
                           "holding %r, but %r -> %r was already "
                           "observed" % (name, h.name, name, h.name))
                    self.violations.append(msg)
                    raise LockOrderError(msg)

    def after_acquire(self, name: str) -> None:
        held = self.held()
        for h in held:
            if h.name == name:
                h.depth += 1
                return
        with self.mu:
            for h in held:
                if h.name != name:
                    self.edges.setdefault(h.name, set()).add(name)
        held.append(_Held(name))

    def before_release(self, name: str, budget_ms: float) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].name == name:
                held[i].depth -= 1
                if held[i].depth == 0:
                    if budget_ms > 0:
                        ms = (time.monotonic() - held[i].t0) * 1e3
                        if ms > budget_ms:
                            with self.mu:
                                self.violations.append(
                                    "hold-time budget breach: %r held "
                                    "%.1f ms (budget %.1f ms)"
                                    % (name, ms, budget_ms))
                    del held[i]
                return

    def suspend(self, name: str) -> Optional[_Held]:
        """Condition.wait releases its lock while parked: pop the held
        record so waiting threads do not pin stale edges/hold-times."""
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].name == name:
                h = held[i]
                del held[i]
                return h
        return None

    def resume(self, h: Optional[_Held]) -> None:
        if h is not None:
            h.t0 = time.monotonic()
            self.held().append(h)


_STATE = _WatchState()


class _WatchedLock:
    """threading.Lock/RLock with lockdep-style order tracking."""

    __slots__ = ("name", "_lk", "_budget")

    def __init__(self, name: str, lk) -> None:
        self.name = name
        self._lk = lk
        self._budget = _hold_budget_ms()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _STATE.before_acquire(self.name)
        got = self._lk.acquire(blocking, timeout)
        if got:
            _STATE.after_acquire(self.name)
        return got

    def release(self) -> None:
        _STATE.before_release(self.name, self._budget)
        self._lk.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _WatchedCondition:
    """threading.Condition over a watched lock. ``wait`` suspends the
    held record (the underlying lock really is released while parked)
    and resumes it — with a fresh hold-clock — on wakeup."""

    __slots__ = ("name", "_cv", "_budget")

    def __init__(self, name: str) -> None:
        self.name = name
        self._cv = threading.Condition()
        self._budget = _hold_budget_ms()

    def acquire(self, *a):
        _STATE.before_acquire(self.name)
        got = self._cv.acquire(*a)
        if got:
            _STATE.after_acquire(self.name)
        return got

    def release(self) -> None:
        _STATE.before_release(self.name, self._budget)
        self._cv.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        h = _STATE.suspend(self.name)
        try:
            return self._cv.wait(timeout)
        finally:
            _STATE.resume(h)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        h = _STATE.suspend(self.name)
        try:
            return self._cv.wait_for(predicate, timeout)
        finally:
            _STATE.resume(h)

    def notify(self, n: int = 1) -> None:
        self._cv.notify(n)

    def notify_all(self) -> None:
        self._cv.notify_all()


def make_lock(name: str):
    """A ``threading.Lock`` — watched when ``CXN_LOCK_WATCH`` is armed.
    ``name`` is the creation-site identity the acquisition graph keys
    on (convention: ``ClassName._attr``)."""
    if watch_enabled():
        return _WatchedLock(name, threading.Lock())
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — watched when armed; reentrant acquires
    are depth-counted, never self-edges."""
    if watch_enabled():
        return _WatchedLock(name, threading.RLock())
    return threading.RLock()


def make_condition(name: str):
    """A ``threading.Condition`` — watched when armed. The wait() hole
    in the held stack is handled (Condition.wait releases its lock)."""
    if watch_enabled():
        return _WatchedCondition(name)
    return threading.Condition()


def violations() -> List[str]:
    """The watchdog's journal: inversions (also raised) and hold-time
    budget breaches (recorded only — CI jitter must not flake)."""
    with _STATE.mu:
        return list(_STATE.violations)


def reset_watch() -> None:
    """Clear the acquisition graph and journal (test isolation)."""
    with _STATE.mu:
        _STATE.edges.clear()
        _STATE.violations.clear()


def check() -> None:
    """Raise :class:`LockOrderError` if the journal is non-empty — the
    end-of-test gate for suites that run with the watchdog armed."""
    v = violations()
    if v:
        raise LockOrderError("; ".join(v))
