"""Recompilation guard: catch silent per-epoch re-specialization.

A jitted step recompiles whenever the *abstract signature* of its inputs
changes — a drifting batch shape, a weak-typed scalar, a new static arg.
On a real run that is minutes of XLA time burned silently every epoch.
The guard hashes the abstract signature of every call and errors (or
warns) when a hot function has seen more than ``limit`` distinct
signatures — one trace per signature is exactly what jit's cache does, so
counting signatures counts compilations without touching jax internals.

Wired into :class:`~cxxnet_tpu.nnet.net.Net` via the
``lint_recompile_limit`` config key (0 = off) and enabled by default by
the ``CXN_LINT`` runtime hook (doc/lint.md). The serve engine arms one
guard per compiled program family — prefill/chunk, verify, and (paged
engines) the batched tick, whose counted signature carries the
block-table shape, so a drifting table would surface as a CXN205 trip
naming the drift rather than a silent second compilation (the
one-signature discipline doc/serving.md's paged section leans on).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax

from .findings import LintError


def abstract_signature(args: tuple, kwargs: Dict[str, Any] = None) -> tuple:
    """Hashable abstract signature of a call: (shape, dtype) per array
    leaf, repr for static/python leaves, with the pytree structure."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype),
                        bool(getattr(leaf, "weak_type", False))))
        else:
            sig.append(repr(leaf))
    return (str(treedef), tuple(sig))


def trip_counter(registry):
    """The guard-trip metric family — the ONE spelling of its
    name/help/labels for every subsystem that wires ``on_trip`` into an
    obs registry (``nnet.Net``, the serve engine, the server's catalog
    pre-touch). Returns the labeled ``cxn_recompile_trips_total{fn=}``
    family; trip with ``.labels(guard_name).inc()``."""
    return registry.counter("cxn_recompile_trips_total",
                            "RecompileGuard trips (CXN205)",
                            labelnames=("fn",))


class RecompileGuard:
    """Transparent wrapper around a jitted callable that tracks distinct
    abstract input signatures. Attribute access (``.lower``, ...)
    delegates to the wrapped function, so guarded steps stay drop-in for
    AOT inspection and the step audit."""

    def __init__(self, fn: Callable, name: str, limit: int,
                 strict: bool = True, log: Callable[[str], None] = None,
                 on_trip: Callable[[str], None] = None):
        """``on_trip``: optional ``(guard_name)`` callable invoked on
        EVERY trip, strict or not, before any raise — the obs hook that
        turns trips into a registry counter
        (``cxn_recompile_trips_total{fn=...}``) so a scraper sees them
        even when the run survives in non-strict mode."""
        self._fn = fn
        self._name = name
        self._limit = max(1, int(limit))
        self._strict = strict
        self._log = log
        self._on_trip = on_trip
        self.trips = 0
        self._seen: Dict[tuple, int] = {}       # signature -> first call no
        self._calls = 0

    @property
    def signatures(self) -> Tuple[tuple, ...]:
        return tuple(self._seen)

    def __call__(self, *args, **kwargs):
        self._calls += 1
        sig = abstract_signature(args, kwargs)
        if sig not in self._seen:
            self._seen[sig] = self._calls
            if len(self._seen) > self._limit:
                msg = ("CXN205: hot function %r traced %d times (limit %d) "
                       "— its abstract input signature keeps changing "
                       "(call %d introduced %s); pad/bucket the offending "
                       "input or raise lint_recompile_limit"
                       % (self._name, len(self._seen), self._limit,
                          self._calls, _diff_hint(self._seen)))
                self.trips += 1
                if self._on_trip is not None:
                    self._on_trip(self._name)
                if self._strict:
                    raise LintError(msg)
                if self._log is not None:
                    self._log(msg)
        return self._fn(*args, **kwargs)

    def __getattr__(self, attr):
        return getattr(self._fn, attr)


def _diff_hint(seen: Dict[tuple, int]) -> str:
    """Name the leaf positions whose (shape, dtype) differ across the two
    most recent signatures — usually the one drifting input."""
    sigs = list(seen)
    if len(sigs) < 2:
        return "a new signature"
    (_, a), (_, b) = sigs[-2], sigs[-1]
    if len(a) != len(b):
        return "a different input structure"
    diffs = [i for i, (x, y) in enumerate(zip(a, b)) if x != y]
    parts = ["leaf %d: %s -> %s" % (i, a[i], b[i]) for i in diffs[:3]]
    return "; ".join(parts) if parts else "a different input structure"
