"""Known-config-key registry: which component reads which ``key = value``.

The tokenizer keeps the config as ordered pairs, so "this key is never read
by any component" is decidable — IF we know every consumer's key set. Rather
than a hand-maintained list that drifts, the registry *introspects* the
consumers: every ``set_param``-style function in the codebase is an
if/elif chain comparing the key against string literals, so a small AST walk
over each consumer's source recovers its exact keys (``name == "lr"``,
``name in ("a", "b")``, ``name.startswith("metric")``). Hand-curated entries
cover only what AST cannot see (regex-matched structural keys in graph.py,
the ``lr:``/``wmat:`` scoped-key grammars).

Scopes (mirroring how the CLI routes pairs):
- ``global``   — outside iterator sections / netconfig layer blocks; the
  reference broadcasts these to every component, so the known set is the
  union of everything.
- ``iterator`` — inside a ``data``/``eval``/``pred`` section: the union of
  keys of the iterator types the section's ``iter =`` lines name.
- ``layer:<type>`` — after a ``layer[...]`` declaration: that layer type's
  keys (common LayerParam + class-specific) plus updater keys (layer-scoped
  optimizer overrides are legal: ``Net._init_updaters`` feeds ``spec.cfg``
  to ``create_updater``).
"""

from __future__ import annotations

import ast
import functools
import inspect
import re
import textwrap
from typing import Iterable, Set, Tuple

# variable names that hold the config key in consumer code
_KEY_VARS = frozenset(("name", "k", "key"))

# keys consumed by regex/structural matching the AST walk cannot see
_GRAPH_EXACT = frozenset(("netconfig", "input_shape", "extra_data_num",
                          "updater"))
_GRAPH_PATTERNS = (
    re.compile(r"^extra_data_shape\[\d+\]$"),
    re.compile(r"^label_vec\[\d+,\d+\)$"),
    re.compile(r"^layer\[[^\]]+\]$"),
    re.compile(r"^metric(\[[^\]]+\])?$"),
)

# ``lr:<sub>`` / ``eta:<sub>`` schedule sub-keys (updaters/__init__.py
# validates <sub> against these on a different variable, out of AST reach)
_LR_SUBKEYS = frozenset(("schedule", "gamma", "alpha", "step", "factor",
                         "minimum_lr", "start_epoch"))
# per-tensor scope prefixes: ``wmat:lr = ...`` applies a valid updater key
# to one weight tag (UpdaterParam.set_param strips the prefix)
_TAG_PREFIXES = ("wmat:", "bias:")

# keys introduced by the analysis subsystem itself
_LINT_KEYS = frozenset(("lint_ignore", "lint_threads"))


def _keys_of_callable(fn) -> Tuple[Set[str], Set[str]]:
    """(exact keys, prefix keys) a consumer function reads, via AST."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return set(), set()
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare) \
                and isinstance(node.left, ast.Name) \
                and node.left.id in _KEY_VARS:
            for comp in node.comparators:
                if isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, str):
                    exact.add(comp.value)
                elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    exact.update(e.value for e in comp.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, str))
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "startswith" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in _KEY_VARS \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            prefixes.add(node.args[0].value)
    return exact, prefixes


def _keys_of_class(cls) -> Tuple[Set[str], Set[str]]:
    """Union over every ``set_param`` in the MRO (subclasses delegate up)."""
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for klass in cls.__mro__:
        fn = klass.__dict__.get("set_param")
        if fn is not None:
            e, p = _keys_of_callable(fn)
            exact |= e
            prefixes |= p
    return exact, prefixes


@functools.lru_cache(maxsize=None)
def cli_keys() -> frozenset:
    from ..cli import LearnTask
    return frozenset(_keys_of_callable(LearnTask.set_param)[0])


@functools.lru_cache(maxsize=None)
def trainer_keys() -> Tuple[frozenset, frozenset]:
    from ..nnet.net import Net
    exact, prefixes = _keys_of_callable(Net._parse_trainer_cfg)
    return frozenset(exact), frozenset(prefixes)


@functools.lru_cache(maxsize=None)
def updater_keys() -> Tuple[frozenset, frozenset]:
    from ..updaters import UPDATER_REGISTRY, Updater, UpdaterParam
    exact, prefixes = _keys_of_callable(UpdaterParam.set_param)
    for cls in set(UPDATER_REGISTRY.values()) | {Updater}:
        e, p = _keys_of_class(cls)
        exact |= e
        prefixes |= p
    return frozenset(exact), frozenset(prefixes)


@functools.lru_cache(maxsize=None)
def layer_keys(layer_type: str) -> frozenset:
    """Keys a layer-scoped block may set for one layer type: the layer
    class's own keys (incl. LayerParam via the base Layer __init__ feeding
    both) plus updater keys (per-layer optimizer overrides)."""
    from ..layers import LAYER_REGISTRY
    from ..layers.base import LayerParam
    exact = set(_keys_of_callable(LayerParam.set_param)[0])
    cls = LAYER_REGISTRY.get(layer_type)
    if cls is not None:
        exact |= _keys_of_class(cls)[0]
    u_exact, _ = updater_keys()
    return frozenset(exact | u_exact)


@functools.lru_cache(maxsize=None)
def all_layer_keys() -> frozenset:
    from ..layers import LAYER_REGISTRY
    keys: Set[str] = set()
    for t in LAYER_REGISTRY:
        keys |= layer_keys(t)
    return frozenset(keys)


def _iterator_chain_classes(iter_type: str) -> list:
    """Instantiate one registered iterator factory (init() NOT called — no
    I/O) and collect the classes of everything ``set_param`` reaches
    through its attributes: proc iterators hold their base, and helpers
    like the augmenter (AugmentIterator.aug) receive the same broadcast."""
    from ..io.data import (_BASE_FACTORIES, _PROC_FACTORIES,  # noqa
                           IIterator)
    if iter_type in _BASE_FACTORIES:
        obj = _BASE_FACTORIES[iter_type]()
    elif iter_type in _PROC_FACTORIES:
        obj = _PROC_FACTORIES[iter_type](IIterator())
    else:
        return []
    seen, todo, out = set(), [obj], []
    while todo:
        cur = todo.pop()
        if id(cur) in seen:
            continue
        seen.add(id(cur))
        out.append(type(cur))
        for v in vars(cur).values():
            if callable(getattr(v, "set_param", None)):
                todo.append(v)
    return out


@functools.lru_cache(maxsize=None)
def iterator_type_names() -> frozenset:
    from ..io.data import _BASE_FACTORIES, _PROC_FACTORIES  # noqa
    return frozenset(set(_BASE_FACTORIES) | set(_PROC_FACTORIES))


@functools.lru_cache(maxsize=None)
def iterator_keys(iter_types: Tuple[str, ...]) -> frozenset:
    keys: Set[str] = set()
    for t in iter_types:
        for cls in _iterator_chain_classes(t):
            keys |= _keys_of_class(cls)[0]
    return frozenset(keys | {"iter"})


@functools.lru_cache(maxsize=None)
def all_iterator_keys() -> frozenset:
    return iterator_keys(tuple(sorted(iterator_type_names())))


@functools.lru_cache(maxsize=None)
def global_keys() -> frozenset:
    """Everything a global pair can legally reach: the CLI task, the
    trainer, graph structure, every layer type (layer params broadcast),
    every updater, every iterator (the CLI appends globals to each
    section's chain), and the lint's own keys."""
    t_exact, _ = trainer_keys()
    u_exact, _ = updater_keys()
    return frozenset(cli_keys() | t_exact | _GRAPH_EXACT | all_layer_keys()
                     | u_exact | all_iterator_keys() | _LINT_KEYS)


def _match_patterns(key: str) -> bool:
    return any(p.match(key) for p in _GRAPH_PATTERNS)


def _strip_tag_prefix(key: str) -> str:
    for pref in _TAG_PREFIXES:
        if key.startswith(pref):
            return key[len(pref):]
    return key


def _updater_scoped_ok(key: str) -> bool:
    """lr:/eta: schedule sub-keys and wmat:/bias: tag-scoped keys."""
    key = _strip_tag_prefix(key)
    for pref in ("lr:", "eta:"):
        if key.startswith(pref):
            return key[len(pref):] in _LR_SUBKEYS
    u_exact, _ = updater_keys()
    return key in u_exact


def known_in_scope(key: str, scope: str) -> bool:
    """Is ``key`` read by any component reachable from ``scope``
    ("global", "iterator:<t1+t2>", "layer:<type>")?"""
    if _match_patterns(key):
        return True
    if _updater_scoped_ok(key):
        return True
    if scope == "global":
        return key in global_keys()
    if scope.startswith("iterator:"):
        types = tuple(t for t in scope[len("iterator:"):].split("+") if t)
        return key in iterator_keys(types)
    if scope.startswith("layer:"):
        return key in layer_keys(scope[len("layer:"):])
    return key in global_keys()


def candidates_in_scope(scope: str) -> Iterable[str]:
    """Key universe for did-you-mean suggestions in a scope."""
    if scope.startswith("iterator:"):
        types = tuple(t for t in scope[len("iterator:"):].split("+") if t)
        return iterator_keys(types)
    if scope.startswith("layer:"):
        return layer_keys(scope[len("layer:"):])
    return global_keys()
