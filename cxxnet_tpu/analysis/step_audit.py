"""Pass 2: compiled-step audit — inspect the lowered/compiled XLA steps.

Works entirely through the AOT API (``fn.lower(...)`` on abstract
ShapeDtypeStructs, then ``.compile()``): nothing executes, no batch is
needed, and the audit sees exactly the programs the run will use.

Checks per jitted step:

- **donation** (CXN201): every ``donate_argnums`` buffer must survive to
  an ``input_output_alias`` entry in the compiled executable. Drops are
  attributed to the stage that lost them — jax's lowering (no unaliased
  output of matching shape/dtype existed: the donated arg's
  ``tf.aliasing_output`` attribute is missing from the StableHLO) or XLA
  itself (the attribute was there but the executable kept no alias).
- **dtype promotion** (CXN202): any ``f64`` tensor inside the step — the
  classic silent 2x-slowdown when a python float sneaks in under
  ``jax_enable_x64``.
- **host transfers** (CXN203): callback/infeed/outfeed custom-calls
  inside the step (a ``pure_callback`` in a layer turns every step into
  a device->host round-trip).
- **weak-typed inputs** (CXN206): python scalars passed as traced args —
  each distinct strong/weak pairing re-specializes the step.
- **collectives** (CXN204): all-gather/all-reduce/reduce-scatter/
  all-to-all/collective-permute count in the optimized HLO, compared
  against a pinned budget (``lint_collective_budget``); an unbudgeted
  audit still reports the counts so a new collective shows up in logs.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .findings import Finding, LintReport

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute")
_ALIAS_RE = re.compile(r"\{\s*\d+\s*\}\s*:\s*\((\d+),")


def _alias_body(hlo: str) -> str:
    """The ``input_output_alias={...}`` body from an HLO module header
    (brace-matched — the map nests braces), or "" when absent. Shared by
    :func:`audit_jit` and :func:`audit_executable` so the two CXN201
    checks can never drift apart on header parsing."""
    header = hlo.splitlines()[0] if hlo else ""
    if "input_output_alias={" not in header:
        return ""
    start = header.index("input_output_alias={") + len(
        "input_output_alias={")
    depth, end = 1, start
    while end < len(header) and depth:
        depth += {"{": 1, "}": -1}.get(header[end], 0)
        end += 1
    return header[start:end]
_HOST_MARKERS = ("callback", "infeed", "outfeed", "SendToHost",
                 "RecvFromHost")
# donation markers on @main arguments: jax emits tf.aliasing_output when
# it resolves the alias itself at lowering, jax.buffer_donor when it
# defers the pairing to XLA — either means "this donation survived jax"
_DONOR_MARKS = ("jax.buffer_donor", "tf.aliasing_output")


def _requested_donations(args: Sequence, donate_argnums: Sequence[int],
                         static_argnums: Sequence[int]) -> int:
    """How many array leaves the caller asked to donate."""
    import jax
    n = 0
    for i in donate_argnums:
        if i not in static_argnums and i < len(args):
            n += len(jax.tree_util.tree_leaves(args[i]))
    return n


def _main_signature_donors(stable: str) -> Tuple[set, Dict[int, str]]:
    """(donor param numbers, param -> tensor type) of the entry function.

    Parsed from the ``@main`` signature only — inner stablehlo functions
    have their own %argN numbering. XLA parameter numbering matches the
    entry signature (jax prunes unused args BEFORE lowering, so the
    signature already reflects the executable's parameter list)."""
    sig = ""
    for line in stable.splitlines():
        if "@main(" in line:
            sig = line
            break
    sig = sig.split(") -> ", 1)[0]
    donors, types = set(), {}
    parts = re.split(r"%arg(\d+)", sig)
    for j in range(1, len(parts) - 1, 2):
        pnum = int(parts[j])
        seg = parts[j + 1]
        m = re.match(r": tensor<([^>]*)>", seg)
        types[pnum] = m.group(1) if m else "?"
        if any(mark in seg for mark in _DONOR_MARKS):
            donors.add(pnum)
    return donors, types


def _arg_sharding_specs(args: Sequence) -> List[str]:
    """Sorted distinct non-trivial PartitionSpec strings carried by the
    abstract args (ShapeDtypeStructs with ``sharding=`` — how
    net_step_specs and a TP serve engine's lint_specs pass real mesh
    placements into the AOT lower). Replicated/unspecified leaves are
    skipped: the interesting fact is WHAT is sharded, not that scalars
    are not."""
    import jax
    specs = set()
    for a in args:
        for leaf in jax.tree_util.tree_leaves(a):
            sh = getattr(leaf, "sharding", None)
            spec = getattr(sh, "spec", None)
            if spec is None:
                continue
            if any(ax is not None for ax in tuple(spec)):
                specs.add(str(spec))
    return sorted(specs)


def collective_counts(hlo_text: str) -> Dict[str, int]:
    return {op: len(re.findall(r"\b%s(?:-start)?\(" % op, hlo_text))
            for op in _COLLECTIVE_OPS}


def entry_clamp_count(hlo_text: str) -> int:
    """Standalone ``clamp`` instructions in the optimized HLO's ENTRY
    computation. The paged serve programs clip their position/block
    indices explicitly (engine.py documents the clip as free); this is
    the check that keeps that claim honest: a clamp that XLA fused into
    a gather/scatter fusion lives in a fusion sub-computation and
    counts 0 here, while a clamp materialized as its own entry-level
    instruction (an extra HLO pass over the index tensor) counts — and
    trips CXN208 in the serve audit."""
    in_entry = False
    depth = 0
    n = 0
    seen = 0
    for ln in hlo_text.splitlines():
        if not in_entry and ln.startswith("ENTRY "):
            in_entry = True
        if in_entry:
            seen += 1
            n += ln.count(" clamp(")
            depth += ln.count("{") - ln.count("}")
            if depth <= 0 and seen > 1:
                break
    return n


_INT8_PROMOTE_RE = re.compile(
    r"convert\s+[^:\n]*:\s*\(tensor<[^>]*x(?:u?i8|u?i4)>\)"
    r"\s*->\s*tensor<[^>]*xf32>")


def int8_promotions(stable: str) -> int:
    """StableHLO converts of a narrow-integer tensor STRAIGHT to f32.
    Inside a bf16 quantized serve program (serve_int8_weights /
    serve_int4_weights / serve_kv_dtype=int8) every quantized operand
    must dequantize to the COMPUTE dtype — int8 values and int4 nibble
    codes are exact in bf16's 8 mantissa bits, so an i8/ui8/i4/ui4 ->
    f32 convert means some op silently widened the quantized stream
    (doubling or quadrupling the very bytes quantization shrank)
    instead of computing in bf16; CXN209 names it. f32-compute configs
    are exempt: there f32 IS the dequant target."""
    return len(_INT8_PROMOTE_RE.findall(stable))


# a convert out of the packed-int4 unpack chain (i8 codes, or a ui8
# byte that skipped the signed hop) into EITHER float dtype — CXN211
# flags these only when the tensor's trailing dims equal an unpacked
# quantized-weight image (k, n), i.e. the full-width dequant buffer the
# fused dequant-matmul exists to keep out of HBM
_INT4_DEQUANT_RE = re.compile(
    r"convert\s+[^:\n]*:\s*\(tensor<([0-9x]*)x(?:u?i8|u?i4)>\)"
    r"\s*->\s*tensor<[0-9x]*x(?:f32|bf16)>")
_HLO_INT4_DEQUANT_RE = re.compile(
    r"=\s*(?:f32|bf16)\[([\d,]*)\]\S*\s+convert\(\s*[su]8\[")


def _trailing2(dims_txt: str, sep: str):
    parts = [p for p in dims_txt.split(sep) if p]
    if len(parts) < 2:
        return None
    return int(parts[-2]), int(parts[-1])


def int4_dequant_buffers(stable: str, weight_shapes) -> int:
    """Count StableHLO converts that materialize a FULL-WIDTH unpacked
    int4 weight: an i8/ui8 (or i4/ui4) tensor whose trailing two dims
    equal one of ``weight_shapes`` — the set of unpacked (k, n) images
    of the engine's quantized matmul weights — converting to f32/bf16.
    When the fused dequant-matmul should be active, the unpack lives in
    VMEM inside the kernel tile; a match here means the program built
    the dequantized weight in HBM anyway (the exact traffic int4
    packing exists to remove). CXN211 names it."""
    shapes = {tuple(s) for s in weight_shapes}
    n = 0
    for m in _INT4_DEQUANT_RE.finditer(stable):
        if _trailing2(m.group(1), "x") in shapes:
            n += 1
    return n


def int4_dequant_buffers_hlo(hlo_text: str, weight_shapes) -> int:
    """Optimized-HLO twin of :func:`int4_dequant_buffers` for the
    artifact validator (cache-loaded executables render no
    StableHLO)."""
    shapes = {tuple(s) for s in weight_shapes}
    n = 0
    for m in _HLO_INT4_DEQUANT_RE.finditer(hlo_text):
        if _trailing2(m.group(1), ",") in shapes:
            n += 1
    return n


def format_step_info(info: Dict) -> str:
    """One human line per audited step's info dict (the single renderer —
    task=lint, the CXN_LINT hook, and tools/cxn_lint.py all print this)."""
    cc = ", ".join("%s=%d" % (k, v)
                   for k, v in info["collectives"].items() if v)
    line = "%s: donated %d aliased %d collectives {%s} compile %.2fs" % (
        info["label"], info["donated"], info["aliased"], cc or "none",
        info.get("compile_s", 0.0))
    if "entry_clamps" in info:
        # the serve audit's clip-fold assertion (CXN208): "folded" means
        # every explicit index clip fused into its gather/scatter
        line += " clip=%s" % ("folded" if info["entry_clamps"] == 0
                              else "%d materialized"
                              % info["entry_clamps"])
    if "int8_promotions" in info:
        # the quantized-serve audit's dequant-dtype assertion (CXN209):
        # "clean" means no int8 operand widened to f32 in a bf16 step
        line += " int8=%s" % ("clean" if info["int8_promotions"] == 0
                              else "%d promoted"
                              % info["int8_promotions"])
    if "int4_dequants" in info:
        # the int4-streaming audit's in-VMEM-unpack assertion (CXN211):
        # "clean" means no full-width dequantized weight image was
        # materialized where the fused dequant-matmul should be active
        line += " int4=%s" % ("clean" if info["int4_dequants"] == 0
                              else "%d materialized"
                              % info["int4_dequants"])
    if info.get("shardings"):
        # a sharded audit names its input placements, so the step table
        # shows the executable was partitioned (not a 1-device lookalike)
        line += " sharded[%s]" % "; ".join(info["shardings"])
    return line


def audit_jit(fn, args: tuple, label: str,
              donate_argnums: Sequence[int] = (),
              static_argnums: Sequence[int] = (),
              collective_budget: Optional[int] = None,
              compile_budget_s: Optional[float] = None,
              check_clip: bool = False,
              check_int8: bool = False,
              check_int4=None) -> Tuple[List[Finding], Dict]:
    """Audit one jitted function AOT. Returns (findings, info) where info
    carries the raw counts ({"collectives", "donated", "aliased"}) plus
    the step's measured AOT lower+compile seconds ("compile_s") — the
    compile-time baseline the AOT-executable-cache roadmap item needs,
    gated in CI by ``compile_budget_s`` (CXN207) the same way
    collective counts are by ``lint_collective_budget``.
    ``check_int8`` (bf16 quantized serve programs) additionally asserts
    no int8 operand is silently promoted to f32 (CXN209,
    :func:`int8_promotions`). ``check_int4`` (a set of unpacked (k, n)
    weight shapes, or None) asserts no full-width dequantized int4
    weight is materialized where the fused dequant-matmul should be
    active (CXN211, :func:`int4_dequant_buffers`)."""
    import time
    import warnings
    findings: List[Finding] = []
    t0 = time.perf_counter()
    with warnings.catch_warnings(record=True) as wrec:
        warnings.simplefilter("always")
        lowered = fn.lower(*args)
    lower_s = time.perf_counter() - t0
    stable = lowered.as_text()      # text render excluded from the budget
    t1 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = lower_s + time.perf_counter() - t1
    hlo = compiled.as_text()
    if compile_budget_s is not None and compile_budget_s > 0 \
            and compile_s > compile_budget_s:
        findings.append(Finding(
            "CXN207", "%s: AOT lower+compile took %.2fs, over the "
            "pinned budget %gs (lint_compile_budget_s) — a compile-"
            "time regression slows every cold start and CI run"
            % (label, compile_s, compile_budget_s)))

    # ---- donation ---------------------------------------------------
    requested = _requested_donations(args, donate_argnums, static_argnums)
    donors, arg_types = _main_signature_donors(stable)
    # jax-level drops announce themselves at lowering ("Some donated
    # buffers were not usable: ShapedArray(...)"): no unaliased output of
    # matching shape/dtype existed for that buffer
    for w in wrec:
        msg = str(w.message)
        if "donated buffers were not usable" in msg:
            findings.append(Finding(
                "CXN201", "%s: donation dropped at lowering — %s (no "
                "unaliased output of matching shape/dtype; the buffer "
                "cannot be reused in place)" % (label, msg.split("\n")[0])))
    compiled_aliased = {int(m) for m in _ALIAS_RE.findall(_alias_body(hlo))}
    for p in sorted(donors - compiled_aliased):
        findings.append(Finding(
            "CXN201", "%s: donated buffer (entry param %d, tensor<%s>) "
            "survived lowering but the compiled executable keeps no "
            "input_output_alias for it — XLA dropped the aliasing "
            "(backend limitation or layout mismatch)"
            % (label, p, arg_types.get(p, "?"))))

    # ---- dtype promotion / host transfers / weak inputs -------------
    if re.search(r"tensor<(?:\d+x)*f64>", stable):
        findings.append(Finding(
            "CXN202", "%s: f64 tensors inside the step — a python float "
            "or numpy f64 promoted the computation (check jax_enable_x64 "
            "and input dtypes)" % label))
    host_hits = sorted({mk for mk in _HOST_MARKERS
                        if mk in stable or mk in hlo})
    if host_hits:
        findings.append(Finding(
            "CXN203", "%s: host transfer inside the step (%s) — every "
            "step round-trips to the host" % (label, ", ".join(host_hits))))
    import jax
    weak = []
    for i, a in enumerate(args):
        if i in static_argnums:
            continue
        for leaf in jax.tree_util.tree_leaves(a):
            if isinstance(leaf, (bool, int, float)) \
                    or getattr(leaf, "weak_type", False):
                weak.append(i)
                break
    for i in weak:
        findings.append(Finding(
            "CXN206", "%s: arg %d is weak-typed (python scalar) — pass "
            "jnp.asarray(x, dtype) so strong/weak pairings don't "
            "re-specialize the step" % (label, i)))

    # ---- collectives ------------------------------------------------
    counts = collective_counts(hlo)
    total = sum(counts.values())
    if collective_budget is not None and collective_budget >= 0 \
            and total > collective_budget:
        findings.append(Finding(
            "CXN204", "%s: %d collectives per step (%s) exceeds the "
            "pinned budget %d (lint_collective_budget)"
            % (label, total,
               ", ".join("%s=%d" % (k, v) for k, v in counts.items() if v),
               collective_budget)))
    info = {"label": label, "collectives": counts,
            "donated": requested,
            "aliased": len(donors & compiled_aliased),
            "compile_s": compile_s,
            # the distinct non-trivial PartitionSpecs of the abstract
            # inputs — how a sharded audit PROVES the executable was
            # lowered against real mesh shardings (the TP serve audit
            # asserts the KV pool's head-axis spec shows up here;
            # tests/test_serve_tp.py)
            "shardings": _arg_sharding_specs(args)}
    if check_clip:
        info["entry_clamps"] = entry_clamp_count(hlo)
        if info["entry_clamps"] > 0:
            findings.append(Finding(
                "CXN208", "%s: %d standalone clamp instruction(s) in "
                "the entry computation — the explicit index clip did "
                "NOT fold into its gather/scatter fusion, so every "
                "step pays an extra HLO pass the engine documents as "
                "free" % (label, info["entry_clamps"])))
    if check_int8:
        info["int8_promotions"] = int8_promotions(stable)
        if info["int8_promotions"] > 0:
            findings.append(Finding(
                "CXN209", "%s: %d int8 operand(s) converted straight "
                "to f32 inside a bf16 quantized step — the dequant "
                "must target the compute dtype (int8 is exact in "
                "bf16), or the step silently re-widens the very "
                "stream quantization halved"
                % (label, info["int8_promotions"])))
    if check_int4:
        info["int4_dequants"] = int4_dequant_buffers(stable, check_int4)
        if info["int4_dequants"] > 0:
            findings.append(Finding(
                "CXN211", "%s: %d full-width unpacked int4 weight "
                "tensor(s) materialized in HBM — the fused dequant-"
                "matmul is active for this geometry, so the nibble "
                "unpack must stay inside the kernel tile's VMEM; a "
                "materialized dequant buffer re-streams the very bytes "
                "packing removed" % (label, info["int4_dequants"])))
    return findings, info


_HLO_INT8_PROMOTE_RE = re.compile(
    r"=\s*f32\[[^\]]*\]\S*\s+convert\(\s*[su][48]\[")


def int8_promotions_hlo(hlo_text: str) -> int:
    """The optimized-HLO twin of :func:`int8_promotions` — ``s8/u8/s4/
    u4 -> f32`` converts in the compiled executable's text. The artifact validator
    only holds the deserialized executable (no StableHLO render
    exists for a loaded program), so CXN209 checks the same contract
    at the HLO level there."""
    return len(_HLO_INT8_PROMOTE_RE.findall(hlo_text))


def audit_executable(compiled, label: str, requested_donations: int = 0,
                     collective_budget: Optional[int] = None,
                     check_clip: bool = False,
                     check_int8: bool = False,
                     check_int4=None) -> Tuple[List[Finding],
                                               Dict]:
    """Audit one ALREADY-COMPILED (typically cache-loaded) executable —
    the artifact-validator half of :func:`audit_jit`, for programs with
    no lowering to inspect: donation aliasing (CXN201, via the
    executable's ``input_output_alias`` header against the requested
    donation count), collective counts (CXN204), paged clip-folding
    (CXN208), and quantized-dequant hygiene (CXN209, HLO-level)."""
    findings: List[Finding] = []
    hlo = compiled.as_text()
    aliased = len(set(_ALIAS_RE.findall(_alias_body(hlo))))
    if requested_donations and aliased < requested_donations:
        findings.append(Finding(
            "CXN201", "%s: cached executable aliases %d of %d donated "
            "buffer(s) — the persisted program lost donation aliasing "
            "the engine relies on for in-place cache updates"
            % (label, aliased, requested_donations)))
    counts = collective_counts(hlo)
    total = sum(counts.values())
    if collective_budget is not None and collective_budget >= 0 \
            and total > collective_budget:
        findings.append(Finding(
            "CXN204", "%s: cached executable runs %d collectives per "
            "step (%s), over the pinned budget %d"
            % (label, total,
               ", ".join("%s=%d" % (k, v) for k, v in counts.items()
                         if v), collective_budget)))
    info = {"label": label, "collectives": counts,
            "donated": requested_donations, "aliased": aliased,
            "compile_s": 0.0, "shardings": []}
    if check_clip:
        info["entry_clamps"] = entry_clamp_count(hlo)
        if info["entry_clamps"] > 0:
            findings.append(Finding(
                "CXN208", "%s: cached executable materializes %d "
                "standalone entry-computation clamp(s) — the explicit "
                "index clip did not fold into its gather/scatter "
                "fusion" % (label, info["entry_clamps"])))
    if check_int8:
        info["int8_promotions"] = int8_promotions_hlo(hlo)
        if info["int8_promotions"] > 0:
            findings.append(Finding(
                "CXN209", "%s: cached executable converts %d int8 "
                "operand(s) straight to f32 inside a bf16 quantized "
                "step" % (label, info["int8_promotions"])))
    if check_int4:
        info["int4_dequants"] = int4_dequant_buffers_hlo(hlo, check_int4)
        if info["int4_dequants"] > 0:
            findings.append(Finding(
                "CXN211", "%s: cached executable materializes %d "
                "full-width unpacked int4 weight tensor(s) — the "
                "nibble unpack must stay inside the fused dequant-"
                "matmul's VMEM tile for this geometry"
                % (label, info["int4_dequants"])))
    return findings, info


def _int4_check_shapes(engine, label: str):
    """The CXN211 arming decision for ONE serve program: the set of
    unpacked (k, n) weight images to scan for, or None when the check
    does not apply. Armed only when the engine streams int4 AND every
    one of the program's four hot matmuls passes the fused dequant-
    matmul's geometry gate at the program's own row count — programs
    the gate routes to the XLA reference unpack full-width BY DESIGN
    (that IS the reference formulation), so flagging them would make
    the lint cry wolf on every CPU rig."""
    if not getattr(engine, "int4_weights", False) \
            or getattr(engine, "int4_formulation", "") != "fused":
        return None
    if "verify" in label:
        m = engine.slots * (engine.spec_len + 1)
    elif "tick" in label:
        m = engine.slots
    elif "chunk" in label:
        m = engine.chunk
    else:
        return None
    from ..models.gpt import QUANT_DECODE_PAIRS
    from ..ops.pallas_kernels import int4_matmul_supported
    citem = 2 if engine.cfg.dtype == "bfloat16" else 4
    shapes = set()
    for wk, sk in QUANT_DECODE_PAIRS:
        w = engine._blocks.get(wk)
        s = engine._blocks.get(sk)
        if w is None or s is None:
            return None
        k, n = int(w.shape[-2]), int(s.shape[-1])
        g = int(s.shape[-2])
        if 2 * int(w.shape[-1]) != n or k % g \
                or not int4_matmul_supported(m, k, n, g, itemsize=citem):
            return None
        shapes.add((k, n))
    return shapes


def audit_aot_artifacts(engine, cache,
                        collective_budget: Optional[int] = None,
                        donate: Optional[bool] = None
                        ) -> Tuple[LintReport, List[Dict]]:
    """Artifact-validator mode of the compiled-step audit
    (``tools/cxn_lint.py --compile`` with ``aot_cache=DIR``): for each
    serve program of ``engine`` (abstract engines audit free — nothing
    is allocated), compute the CURRENT cache key, then

    * an exact-key artifact is deserialized and audited in place
      (:func:`audit_executable` — the CI gate sees the program a warm
      production startup would actually LOAD, not a fresh lookalike);
    * every same-program entry under a DIFFERENT key is a CXN210
      "stale AOT artifact" naming the drifting key component(s) —
      a config edit, mesh change, or jax upgrade that was not followed
      by re-warming the cache fails CI instead of silently compiling
      at the next cold start;
    * a program with no entry at all is reported in the info rows
      (``aot=absent``) without a finding — an empty cache is cold, not
      wrong."""
    from .aot_cache import config_hash, get_cache
    report = LintReport()
    infos: List[Dict] = []
    if isinstance(cache, str):
        cache = get_cache(cache)
    paged = bool(getattr(engine, "paged", False))
    quant = bool(getattr(engine, "int8_weights", False)
                 or getattr(engine, "kv_int8", False)
                 or getattr(engine, "int4_weights", False))
    check_int8 = quant and getattr(engine, "cfg", None) is not None \
        and engine.cfg.dtype == "bfloat16"
    cfg_hash = config_hash(engine._cfg_key)
    for label, fn, args, donate_nums in engine.lint_specs(donate=donate):
        if label == "serve_prefill":    # per-length legacy admit: uncached
            continue
        comp = cache.components(label, args, donate_argnums=donate_nums,
                                extra=engine.aot_extra(label),
                                config=cfg_hash, mesh=engine.mesh)
        for digest, drift in cache.stale_entries(comp):
            if set(drift) <= {"devices"}:
                # a sibling artifact for the SAME program on a
                # different device block — the router's per-replica
                # placement story, not staleness (each replica warms
                # its own devices; the validator engine keys to the
                # default block)
                continue
            elide = lambda s: s if len(s) <= 60 else \
                "%s…%s" % (s[:40], s[-16:])
            report.add(Finding(
                "CXN210", "%s: stale AOT artifact %s… — key drifted on "
                "%s (re-warm the cache, or prune the entry)"
                % (label, digest[:12],
                   "; ".join("%s: %r -> %r" % (k, elide(old), elide(new))
                             for k, (old, new) in sorted(drift.items())))))
        if not cache.has(comp):
            infos.append({"label": label, "collectives": {},
                          "donated": 0, "aliased": 0, "compile_s": 0.0,
                          "shardings": [], "aot": "absent"})
            continue
        compiled = cache.load(comp)
        if compiled is None:            # corrupt on disk: load() warned
            infos.append({"label": label, "collectives": {},
                          "donated": 0, "aliased": 0, "compile_s": 0.0,
                          "shardings": [], "aot": "corrupt"})
            continue
        findings, info = audit_executable(
            compiled, label,
            requested_donations=_requested_donations(args, donate_nums,
                                                     ()),
            collective_budget=collective_budget,
            check_clip=paged, check_int8=check_int8,
            check_int4=_int4_check_shapes(engine, label))
        info["aot"] = "ok"
        report.extend(findings)
        infos.append(info)
    return report, infos


def net_step_specs(net) -> List[Tuple[str, object, tuple, tuple, tuple]]:
    """(label, fn, abstract args, donate_argnums, static_argnums) for the
    four hot jitted steps of an initialized :class:`Net` — built from
    ShapeDtypeStructs carrying the REAL mesh shardings (batch sharded on
    the data axis, scalars replicated, gsum on its placement sharding),
    so the audited executable is the partitioned program the run uses —
    with its collectives — not an unpartitioned lookalike. No batch and
    no execution is needed."""
    import jax
    from ..parallel.mesh import batch_sharding, replicated_sharding
    g = net.graph
    b = net.batch_size
    bsh = batch_sharding(net.mesh)
    rsh = replicated_sharding(net.mesh)

    def SDS(shape, dtype, sharding=None):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    data = SDS((b,) + tuple(g.input_shape), np.float32, bsh)
    extras = [SDS((b,) + tuple(s), np.float32, bsh) for s in g.extra_shapes]
    label_w = max(hi for _, hi in g.label_range)
    label = SDS((b, label_w), np.float32, bsh)
    rng = SDS((2,), np.uint32, rsh)
    epoch = SDS((), np.int32, rsh)
    maccum = SDS(tuple(net._train_accum.shape), np.float32, rsh)
    gsum_sh = net._opt_shardings if net.shard_optimizer >= 2 \
        else net._param_shardings
    gsum = {lk: {tag: SDS(tuple(w.shape), w.dtype, gsum_sh[lk][tag])
                 for tag, w in tags.items()}
            for lk, tags in net.params.items()}
    out_node = (g.num_nodes - 1,)
    return [
        ("net_update", net._jit_update,
         (net.params, net.opt_state, net.states, maccum, data, extras,
          label, None, rng, epoch), (0, 1, 2, 3), ()),
        ("net_accum", net._jit_accum,
         (gsum, net.params, net.states, maccum, data, extras, label, None,
          rng, epoch), (0, 3), ()),
        ("net_apply", net._jit_apply,
         (net.params, net.opt_state, gsum, epoch), (0, 1, 2), ()),
        ("net_forward", net._jit_forward,
         (net.params, net.states, data, extras, out_node), (), (4,)),
    ]


def audit_net(net, collective_budget: Optional[int] = None,
              compile_budget_s: Optional[float] = None
              ) -> Tuple[LintReport, List[Dict]]:
    """Audit all four Net jit steps; returns (report, per-step info).
    Budgets default to the net's ``lint_collective_budget`` /
    ``lint_compile_budget_s`` config keys (-1 / 0 = unbudgeted)."""
    report = LintReport()
    infos = []
    budget = collective_budget
    if budget is None:
        budget = getattr(net, "lint_collective_budget", -1)
        budget = budget if budget >= 0 else None
    cbudget = compile_budget_s
    if cbudget is None:
        cbudget = getattr(net, "lint_compile_budget_s", 0.0)
        cbudget = cbudget if cbudget > 0 else None
    for label, fn, args, donate, static in net_step_specs(net):
        findings, info = audit_jit(fn, args, label, donate_argnums=donate,
                                   static_argnums=static,
                                   collective_budget=budget,
                                   compile_budget_s=cbudget)
        report.extend(findings)
        infos.append(info)
    return report, infos


def audit_serve_engine(engine, n_prompt: int = 8,
                       collective_budget: Optional[int] = None,
                       donate: Optional[bool] = None,
                       compile_budget_s: Optional[float] = None
                       ) -> Tuple[LintReport, List[Dict]]:
    """Audit the serve engine's compiled programs. Dense engine: the
    prefill (one representative prompt length), the chunk-prefill step
    (when the engine runs chunked — its donation aliasing matters
    double: the chunk program runs ceil(n/chunk) times per admit), the
    speculative ``serve_verify_chunk`` step (when the engine was built
    with a ``spec_len`` — a verify forward runs once per speculative
    window, so an unaliased cache there would copy the whole slot pool
    every few tokens), and the shared decode tick. PAGED engine: the
    paged chunk-prefill / verify / tick programs with abstract
    block-table inputs (engine.lint_specs supplies the table
    ShapeDtypeStructs), so the audit pins the BLOCK POOL's donation
    aliasing — an unaliased pool would copy every block per token —
    and sees exactly the one compiled signature each program holds
    (a drifting table shape at runtime trips the engine's
    RecompileGuard as CXN205 instead). The audited tick/verify are the
    engine's RESOLVED variants — the fused Pallas block-table-walk
    programs when ``engine.fused_attn`` is on, the XLA gather programs
    otherwise — and the paged rows additionally assert the explicit
    index clips folded into their fusions (CXN208,
    :func:`entry_clamp_count`; the ``clip=folded`` column of the step
    table). ``donate`` overrides the engine's backend-gated donation
    choice — tests pass True to pin the aliasing contract even on the
    CPU mesh."""
    report = LintReport()
    infos = []
    paged = bool(getattr(engine, "paged", False))
    # quantized engines (serve_int8_weights / serve_int4_weights /
    # serve_kv_dtype=int8) with bf16 compute additionally assert no
    # quantized operand is silently promoted to f32 (CXN209, the
    # `int8=clean` column) — the audited rows ARE the quantized
    # variants: lint_specs hands over the engine's own quantized blocks
    # and (values, scales) pool structs. Int4 engines whose fused
    # dequant-matmul resolved ON additionally assert no full-width
    # unpacked weight is materialized (CXN211, the `int4=clean` column;
    # armed per program by _int4_check_shapes).
    quant = bool(getattr(engine, "int8_weights", False)
                 or getattr(engine, "kv_int8", False)
                 or getattr(engine, "int4_weights", False))
    check_int8 = quant and getattr(engine, "cfg", None) is not None \
        and engine.cfg.dtype == "bfloat16"
    for label, fn, args, donate_nums in engine.lint_specs(
            n_prompt=n_prompt, donate=donate):
        findings, info = audit_jit(fn, args, label,
                                   donate_argnums=donate_nums,
                                   collective_budget=collective_budget,
                                   compile_budget_s=compile_budget_s,
                                   check_clip=paged,
                                   check_int8=check_int8,
                                   check_int4=_int4_check_shapes(
                                       engine, label))
        report.extend(findings)
        infos.append(info)
    return report, infos
