"""Lint findings: the shared result type of both analysis passes.

Every rule has a stable id (``CXN1xx`` = graph/config lint, ``CXN2xx`` =
compiled-step audit, ``CXN3xx`` = host-concurrency lint) so findings can be suppressed per-config with
``lint_ignore = <rule_id>`` (comma-separated ids accepted, repeatable) and
golden-tested by exact formatted output. The catalog below is the single
source of truth doc/lint.md renders from.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Optional

# rule_id -> (default severity, one-line description)
RULES = {
    # ---- pass 1: graph/config lint (no devices) ----
    "CXN100": ("error", "config parse / graph structure error"),
    "CXN101": ("error", "unknown config key (never read by any component)"),
    "CXN102": ("error", "layer wiring / shape-inference error"),
    "CXN103": ("error", "dead node or unreachable layer"),
    "CXN104": ("error", "share-layer inconsistency (input shapes differ "
                        "from the primary layer's)"),
    "CXN105": ("error", "metric bound to an unknown label field or node"),
    "CXN106": ("warning", "embedding input is a computed node, not an id "
                          "entry (values will be cast, ids may corrupt)"),
    "CXN107": ("error", "invalid trainer config value"),
    # ---- pass 2: compiled-step audit (lower/compile, no execution) ----
    "CXN201": ("error", "donated buffer not aliased in the compiled "
                        "executable"),
    "CXN202": ("error", "f32->f64 dtype promotion inside a jitted step"),
    "CXN203": ("error", "host transfer / callback inside a jitted step"),
    "CXN204": ("error", "collective count exceeds the pinned budget"),
    "CXN205": ("error", "hot function re-traced more than the allowed "
                        "number of times"),
    "CXN206": ("warning", "weak-typed step input (re-specializes against "
                          "strong-typed callers)"),
    "CXN207": ("error", "AOT lower+compile time exceeds the pinned "
                        "lint_compile_budget_s budget"),
    "CXN208": ("error", "explicit index clip materialized as a "
                        "standalone entry-computation clamp instead of "
                        "folding into its gather/scatter fusion"),
    "CXN209": ("error", "int8 operand silently promoted to f32 inside a "
                        "bf16 quantized step (dequant must target the "
                        "compute dtype)"),
    "CXN210": ("error", "stale AOT executable-cache artifact: a cached "
                        "program's key no longer matches the current "
                        "config/mesh/backend/jax version (the drifting "
                        "component is named)"),
    "CXN211": ("error", "unpacked int4 weight tensor materialized in "
                        "HBM where the fused dequant-matmul should be "
                        "active (the nibble unpack belongs inside the "
                        "kernel tile's VMEM)"),
    # ---- pass 3: host-concurrency lint (AST, no devices) ----
    "CXN301": ("error", "write to a `# guarded_by:` attribute outside "
                        "any `with <guard>:` block in a thread-reachable "
                        "method"),
    "CXN302": ("error", "lock-acquisition-order cycle in the static "
                        "acquisition graph (deadlock potential)"),
    "CXN303": ("error", "blocking call (socket recv/accept, untimed "
                        "queue.get, subprocess wait, time.sleep, "
                        "jax.block_until_ready, thread join) while "
                        "holding a lock"),
    "CXN304": ("error", "threading.Thread created without daemon= and "
                        "without a tracked join/daemon path"),
    "CXN305": ("error", "untimed Condition.wait() outside a predicate "
                        "`while` loop (lost/spurious wakeup hazard)"),
}


class LintError(RuntimeError):
    """Raised by strict surfaces (CXN_LINT=2, the recompilation guard)."""


@dataclass
class Finding:
    rule: str
    message: str
    path: str = ""            # config file ("" = not file-attributed)
    line: int = 0             # 1-based; 0 = unknown
    layer: str = ""           # layer name/key when the finding is per-layer
    severity: str = ""        # default from RULES when empty

    def __post_init__(self) -> None:
        if not self.severity:
            self.severity = RULES.get(self.rule, ("error",))[0]

    def format(self) -> str:
        loc = "%s:%d: " % (self.path or "<config>", self.line) if self.line \
            else ("%s: " % self.path if self.path else "")
        layer = " [layer %s]" % self.layer if self.layer else ""
        return "%s%s %s:%s %s" % (loc, self.severity, self.rule, layer,
                                  self.message)


@dataclass
class LintReport:
    """Findings of one lint run. ``suppressed`` rule ids (from
    ``lint_ignore``) are dropped at add() time but counted."""

    findings: List[Finding] = field(default_factory=list)
    suppress: frozenset = frozenset()
    n_suppressed: int = 0

    def add(self, finding: Finding) -> None:
        if finding.rule in self.suppress:
            self.n_suppressed += 1
            return
        self.findings.append(finding)

    def extend(self, findings: Iterable[Finding]) -> None:
        for f in findings:
            self.add(f)

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warning"]

    def ok(self) -> bool:
        return not self.errors()

    def exit_code(self) -> int:
        return 0 if self.ok() else 1

    def format(self) -> str:
        out = [f.format() for f in self.findings]
        tail = "%d error(s), %d warning(s)" % (len(self.errors()),
                                               len(self.warnings()))
        if self.n_suppressed:
            tail += ", %d suppressed" % self.n_suppressed
        out.append(tail)
        return "\n".join(out)


def parse_suppressions(pairs) -> frozenset:
    """Collect ``lint_ignore = CXN103[,CXN106...]`` values from config
    pairs (2- or 3-tuples)."""
    ids = set()
    for p in pairs:
        if p[0] == "lint_ignore":
            for rid in str(p[1]).replace(",", " ").split():
                ids.add(rid.strip())
    return frozenset(ids)
