"""Pass 1: graph/config lint — runs on the parsed IR with NO devices.

Everything here is pre-flight: tokenize the config (keeping line numbers),
replay the CLI's section routing and the netconfig scoping to know which
component each ``key = value`` pair feeds, then

- audit every key against the introspected consumer registry
  (:mod:`.registry`) with did-you-mean suggestions          -> CXN101
- build the :class:`~cxxnet_tpu.graph.NetGraph` and run full shape
  inference over the layer zoo, attributing any wiring/shape error to the
  exact layer declaration line                               -> CXN100/102
- share-layer consistency (input shapes match the primary)   -> CXN104
- dead-node / unreachable-layer detection (liveness walk
  back from losses, metric bindings, and the output node)    -> CXN103
- metric label-field / node bindings                         -> CXN105
- embedding inputs that are computed nodes, not id entries   -> CXN106
- trainer scalar validation (batch_size, remat_mode, ...)    -> CXN107
"""

from __future__ import annotations

import difflib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..graph import NetGraph
from ..utils.config import ConfigError, tokenize
from . import registry
from .findings import Finding, LintReport, parse_suppressions

_SECTION_MARKERS = ("data", "eval", "pred")
_LOSS_TYPES = frozenset(("softmax", "l2_loss", "multi_logistic",
                         "lm_softmax", "pairtest"))


@dataclass
class _Scoped:
    """One config pair with its resolved routing scope."""
    name: str
    val: str
    line: int
    scope: str          # "global" | "iterator:<t1+t2>" | "layer:<type>"
    marker: bool = False  # structural marker (data/eval/pred/iter/netconfig)


@dataclass
class GraphLintResult:
    report: LintReport
    graph: Optional[NetGraph] = None
    node_shapes: List[Optional[Tuple[int, int, int]]] = field(
        default_factory=list)

    def ok(self) -> bool:
        return self.report.ok()


def _layer_type_of_decl(val: str) -> str:
    ltype = val.split(":", 1)[0]
    if ltype.startswith("share"):
        return "share"
    if ltype.startswith("pairtest-"):
        return "pairtest"
    return ltype


def _route_scopes(triples: Sequence[Tuple[str, str, int]], path: str,
                  report: LintReport) -> List[_Scoped]:
    """Replay CLI section routing + netconfig layer scoping over the
    ordered pairs. Emits CXN100 for structural misuse it can see."""
    # prescan: iterator types per section (keys may precede iter lines)
    section_types: List[List[str]] = []
    cur: Optional[List[str]] = None
    for name, val, _ in triples:
        if name in _SECTION_MARKERS:
            cur = []
            section_types.append(cur)
        elif name == "iter" and val == "end":
            cur = None
        elif name == "iter" and cur is not None:
            cur.append(val)
    out: List[_Scoped] = []
    sec_i = -1
    in_section = False
    layer_scope = ""          # layer type of the open layer block
    for name, val, line in triples:
        if name in _SECTION_MARKERS:
            sec_i += 1
            in_section = True
            out.append(_Scoped(name, val, line, "global", marker=True))
            continue
        if name == "iter":
            if val == "end":
                if not in_section:
                    report.add(Finding(
                        "CXN100", "'iter = end' outside a data/eval/pred "
                        "section", path=path, line=line))
                in_section = False
            elif not in_section:
                report.add(Finding(
                    "CXN100", "'iter = %s' outside a data/eval/pred "
                    "section" % val, path=path, line=line))
            elif val not in registry.iterator_type_names():
                hint = difflib.get_close_matches(
                    val, registry.iterator_type_names(), n=1, cutoff=0.6)
                report.add(Finding(
                    "CXN101", "unknown iterator type %r%s" % (
                        val, " (did you mean %r?)" % hint[0] if hint else ""),
                    path=path, line=line))
            out.append(_Scoped(name, val, line, "global", marker=True))
            continue
        if in_section:
            types = [t for t in section_types[sec_i]
                     if t in registry.iterator_type_names()]
            out.append(_Scoped(name, val, line,
                               "iterator:%s" % "+".join(types)))
            continue
        if name == "netconfig":
            layer_scope = ""
            out.append(_Scoped(name, val, line, "global", marker=True))
            continue
        if name.startswith("layer["):
            layer_scope = _layer_type_of_decl(val)
            out.append(_Scoped(name, val, line, "global", marker=True))
            continue
        if layer_scope:
            out.append(_Scoped(name, val, line, "layer:%s" % layer_scope))
        else:
            out.append(_Scoped(name, val, line, "global"))
    return out


def _audit_keys(scoped: List[_Scoped], path: str,
                report: LintReport) -> None:
    for s in scoped:
        if s.marker or registry.known_in_scope(s.name, s.scope):
            continue
        hint = difflib.get_close_matches(
            s.name, registry.candidates_in_scope(s.scope), n=1, cutoff=0.6)
        where = ""
        if s.scope.startswith("iterator:"):
            where = " in a data section (iterators: %s)" \
                % (s.scope[len("iterator:"):] or "none")
        elif s.scope.startswith("layer:"):
            where = " on a %r layer" % s.scope[len("layer:"):]
        report.add(Finding(
            "CXN101", "unknown config key %r%s — never read by any "
            "component%s" % (
                s.name, where,
                "; did you mean %r?" % hint[0] if hint else ""),
            path=path, line=s.line))


def _trainer_triples(scoped: List[_Scoped]) -> List[Tuple[str, str, int]]:
    """The pairs the CLI would hand the trainer (cli._trainer_cfg)."""
    return [(s.name, s.val, s.line) for s in scoped
            if not s.scope.startswith("iterator:")
            and s.name not in _SECTION_MARKERS and s.name != "iter"]


def _resolve_extract_node(g: NetGraph, node: str) -> Optional[int]:
    if node.startswith("top[-") and node.endswith("]"):
        try:
            return g.num_nodes - int(node[len("top[-"):-1])
        except ValueError:
            return None
    return g.node_map.get(node)


def _lint_structure(g: NetGraph, decl_lines: List[int], scoped: List[_Scoped],
                    path: str, report: LintReport) -> GraphLintResult:
    """Layer construction + shape inference + share/dead/metric checks."""
    from ..layers import create_layer

    result = GraphLintResult(report, graph=g)
    if not g.layers:
        return result

    def decl_line(i: int) -> int:
        return decl_lines[i] if i < len(decl_lines) else 0

    layers: List[Optional[object]] = []
    for i, spec in enumerate(g.layers):
        if spec.type == "share":
            layers.append(layers[spec.primary])
            continue
        try:
            layers.append(create_layer(spec, g.defcfg))
        except Exception as e:          # pre-flight: never crash the lint
            report.add(Finding("CXN102", "layer cannot be constructed: %s"
                               % e, path=path, line=decl_line(i),
                               layer=spec.key()))
            layers.append(None)

    # ---- shape inference (the trainer's walk, with line attribution) ----
    if g.input_shape is None:
        report.add(Finding("CXN100", "input_shape must be set", path=path))
        return result
    node_shapes: List[Optional[Tuple[int, int, int]]] = [None] * g.num_nodes
    node_shapes[0] = g.input_shape
    for i in range(g.extra_data_num):
        if i < len(g.extra_shapes):
            node_shapes[1 + i] = g.extra_shapes[i]
    layer_in_shapes: List[Optional[list]] = [None] * len(g.layers)
    for i, (spec, layer) in enumerate(zip(g.layers, layers)):
        in_shapes = []
        for ni in spec.inputs:
            if node_shapes[ni] is None:
                report.add(Finding(
                    "CXN102", "node %r used before it is produced"
                    % g.node_names[ni], path=path, line=decl_line(i),
                    layer=spec.key()))
                in_shapes = None
                break
            in_shapes.append(node_shapes[ni])
        if in_shapes is None or layer is None:
            continue
        layer_in_shapes[i] = in_shapes
        if spec.type == "share":
            prim_in = layer_in_shapes[spec.primary]
            if prim_in is not None and prim_in != in_shapes:
                report.add(Finding(
                    "CXN104", "share layer input shapes %s do not match "
                    "the primary layer %r's input shapes %s — the shared "
                    "weights cannot apply" % (
                        in_shapes, g.layers[spec.primary].key(), prim_in),
                    path=path, line=decl_line(i), layer=spec.key()))
                continue
        try:
            out_shapes = layer.infer_shapes(in_shapes)
        except Exception as e:
            report.add(Finding(
                "CXN102", "shape inference failed for input shapes %s: %s"
                % (in_shapes, e), path=path, line=decl_line(i),
                layer=spec.key()))
            continue
        for ni, s in zip(spec.outputs, out_shapes):
            node_shapes[ni] = s
        if spec.type == "embedding" and any(
                ni > g.extra_data_num for ni in spec.inputs):
            report.add(Finding(
                "CXN106", "embedding input %s is a computed node, not a "
                "data-entry node — token ids will pass through float "
                "compute and may be corrupted" % (
                    [g.node_names[ni] for ni in spec.inputs
                     if ni > g.extra_data_num]),
                path=path, line=decl_line(i), layer=spec.key()))
    result.node_shapes = node_shapes

    # ---- metric / extract bindings ----------------------------------
    metric_nodes = set()
    for s in scoped:
        m = re.match(r"^metric(?:\[([^\],]+)(?:,([^\]]+))?\])?$", s.name)
        if not m or s.scope.startswith("iterator:"):
            continue
        fld, node = m.group(1) or "label", m.group(2)
        if fld not in g.label_name_map:
            report.add(Finding(
                "CXN105", "metric label field %r is not declared "
                "(label_vec[...] registers fields; known: %s)"
                % (fld, sorted(g.label_name_map)), path=path, line=s.line))
        if node is not None:
            if node not in g.node_map:
                report.add(Finding(
                    "CXN105", "metric bound to unknown node %r" % node,
                    path=path, line=s.line))
            else:
                metric_nodes.add(g.node_map[node])
    for s in scoped:
        if s.name == "extract_node_name":
            nid = _resolve_extract_node(g, s.val)
            if nid is None or not (0 <= nid < g.num_nodes):
                report.add(Finding(
                    "CXN105", "extract_node_name %r names no node" % s.val,
                    path=path, line=s.line))
            else:
                metric_nodes.add(nid)

    # ---- dead nodes / unreachable layers ----------------------------
    live_nodes = set(metric_nodes)
    live_nodes.add(g.num_nodes - 1)        # default output/metric node
    live_layers = set()
    for i in range(len(g.layers) - 1, -1, -1):
        spec, layer = g.layers[i], layers[i]
        is_loss = (getattr(layer, "is_loss", False)
                   or spec.type in _LOSS_TYPES)
        if is_loss or any(o in live_nodes for o in spec.outputs):
            live_layers.add(i)
            live_nodes.update(spec.inputs)
    consumed = set()
    for spec in g.layers:
        consumed.update(spec.inputs)
    for i, spec in enumerate(g.layers):
        if i not in live_layers:
            report.add(Finding(
                "CXN103", "unreachable layer: its outputs %s reach no "
                "loss, metric, or output node — remove it or wire it in"
                % ([g.node_names[o] for o in spec.outputs]),
                path=path, line=decl_line(i), layer=spec.key()))
            continue
        for o in spec.outputs:
            if o not in consumed and o not in live_nodes \
                    and o != g.num_nodes - 1 and o not in spec.inputs:
                report.add(Finding(
                    "CXN103", "dead node %r: produced but never consumed "
                    "by any layer, metric, or output"
                    % g.node_names[o], path=path, line=decl_line(i),
                    layer=spec.key()))
    return result


def _lint_trainer_values(g: NetGraph,
                         triples: List[Tuple[str, str, int]], path: str,
                         report: LintReport) -> None:
    """Run the trainer's own scalar validation (batch_size, remat_mode,
    dist_feed, metric names, ...) pre-flight — CXN107."""
    from ..nnet.net import Net
    net = Net([(n, v) for n, v, _ in triples])
    net.graph = g
    try:
        net._parse_trainer_cfg()
    except (ConfigError, ValueError) as e:
        msg = str(e)
        line = 0
        for n, v, ln in triples:       # best-effort: the key or value the
            if n in msg or (v and v in msg):   # message names
                line = ln
                break
        report.add(Finding("CXN107", msg, path=path, line=line))


def lint_pairs(triples: Sequence[Tuple[str, str, int]],
               path: str = "<config>") -> GraphLintResult:
    """Lint ordered (name, value, line) triples (pass 1, no devices)."""
    report = LintReport(suppress=parse_suppressions(triples))
    scoped = _route_scopes(list(triples), path, report)
    _audit_keys(scoped, path, report)
    trainer = _trainer_triples(scoped)
    g = NetGraph()
    try:
        g.configure([(n, v) for n, v, _ in trainer],
                    lines=[ln for _, _, ln in trainer])
    except ConfigError as e:
        report.add(Finding("CXN100", re.sub(r"^line \d+: ", "", str(e)),
                           path=path, line=getattr(e, "line", 0) or 0))
        return GraphLintResult(report, graph=None)
    decl_lines = [s.line for s in scoped if s.name.startswith("layer[")
                  and not s.scope.startswith("iterator:")]
    result = _lint_structure(g, decl_lines, scoped, path, report)
    _lint_trainer_values(g, trainer, path, report)
    return result


def lint_config_text(text: str, path: str = "<config>",
                     extra_pairs: Optional[Sequence[Tuple[str, str]]] = None
                     ) -> GraphLintResult:
    try:
        triples = tokenize(text, with_lines=True)
    except ConfigError as e:
        report = LintReport()
        report.add(Finding("CXN100", re.sub(r"^line \d+: ", "", str(e)),
                           path=path, line=getattr(e, "line", 0) or 0))
        return GraphLintResult(report)
    triples = list(triples) + [(n, v, 0) for n, v in (extra_pairs or [])]
    return lint_pairs(triples, path=path)


def lint_config_file(path: str,
                     extra_pairs: Optional[Sequence[Tuple[str, str]]] = None
                     ) -> GraphLintResult:
    """Lint a config file; findings carry ``path:line`` locations."""
    with open(path, "r") as f:
        return lint_config_text(f.read(), path=path, extra_pairs=extra_pairs)
