"""cxn-lint: static analysis over both halves of the stack.

**Pass 1** (:mod:`.graph_lint`) runs on the parsed netconfig IR with no
devices: unknown/unconsumed config keys with did-you-mean, full
shape/dtype inference with ``file:line`` attribution, dead-node and
unreachable-layer detection, share-layer consistency, metric bindings,
trainer scalar validation.

**Pass 2** (:mod:`.step_audit`) inspects the lowered/compiled XLA
programs of the trainer's four jitted steps and the serve engine's
prefill/tick through the AOT API: donation aliasing, f64 promotion,
host transfers, weak-typed inputs, collective counts vs a pinned
budget. :mod:`.recompile` adds the runtime recompilation guard.

**Pass 3** (:mod:`.concurrency`) audits the Python host runtime itself:
an AST pass over the package enforcing the ``# guarded_by:`` lock
annotation convention (CXN301-CXN305 — unguarded writes, acquisition-
order cycles, blocking-under-lock, untracked threads, waits without a
predicate loop) plus the ``CXN_LOCK_WATCH=1`` runtime lock-order
watchdog that validates the static graph during the fleet suites.

Surfaces: ``task=lint`` (CLI), the ``CXN_LINT`` runtime hook (both at
startup, findings through the profiler log), and ``tools/cxn_lint.py``
for CI. Rule catalog and exit codes: doc/lint.md.
"""

from .aot_cache import AotCache, CachedProgram, get_cache
from .concurrency import (LockOrderError, analyze_package, analyze_source,
                          lint_threads, make_condition, make_lock,
                          make_rlock, watch_enabled)
from .findings import (Finding, LintError, LintReport, RULES,
                       parse_suppressions)
from .graph_lint import (GraphLintResult, lint_config_file,
                         lint_config_text, lint_pairs)
from .recompile import RecompileGuard, abstract_signature
from .step_audit import (audit_aot_artifacts, audit_executable, audit_jit,
                         audit_net, audit_serve_engine, collective_counts,
                         format_step_info, net_step_specs)

__all__ = [
    "AotCache", "CachedProgram", "get_cache",
    "LockOrderError", "analyze_package", "analyze_source", "lint_threads",
    "make_condition", "make_lock", "make_rlock", "watch_enabled",
    "Finding", "LintError", "LintReport", "RULES", "parse_suppressions",
    "GraphLintResult", "lint_config_file", "lint_config_text", "lint_pairs",
    "RecompileGuard", "abstract_signature",
    "audit_aot_artifacts", "audit_executable", "audit_jit", "audit_net",
    "audit_serve_engine", "collective_counts", "format_step_info",
    "net_step_specs",
]
