"""cxn-lint: static analysis over both halves of the stack.

**Pass 1** (:mod:`.graph_lint`) runs on the parsed netconfig IR with no
devices: unknown/unconsumed config keys with did-you-mean, full
shape/dtype inference with ``file:line`` attribution, dead-node and
unreachable-layer detection, share-layer consistency, metric bindings,
trainer scalar validation.

**Pass 2** (:mod:`.step_audit`) inspects the lowered/compiled XLA
programs of the trainer's four jitted steps and the serve engine's
prefill/tick through the AOT API: donation aliasing, f64 promotion,
host transfers, weak-typed inputs, collective counts vs a pinned
budget. :mod:`.recompile` adds the runtime recompilation guard.

Surfaces: ``task=lint`` (CLI), the ``CXN_LINT`` runtime hook (both at
startup, findings through the profiler log), and ``tools/cxn_lint.py``
for CI. Rule catalog and exit codes: doc/lint.md.
"""

from .aot_cache import AotCache, CachedProgram, get_cache
from .findings import (Finding, LintError, LintReport, RULES,
                       parse_suppressions)
from .graph_lint import (GraphLintResult, lint_config_file,
                         lint_config_text, lint_pairs)
from .recompile import RecompileGuard, abstract_signature
from .step_audit import (audit_aot_artifacts, audit_executable, audit_jit,
                         audit_net, audit_serve_engine, collective_counts,
                         format_step_info, net_step_specs)

__all__ = [
    "AotCache", "CachedProgram", "get_cache",
    "Finding", "LintError", "LintReport", "RULES", "parse_suppressions",
    "GraphLintResult", "lint_config_file", "lint_config_text", "lint_pairs",
    "RecompileGuard", "abstract_signature",
    "audit_aot_artifacts", "audit_executable", "audit_jit", "audit_net",
    "audit_serve_engine", "collective_counts", "format_step_info",
    "net_step_specs",
]
