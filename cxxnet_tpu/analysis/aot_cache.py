"""AOT executable cache: persist compiled serve/train programs on disk.

Every engine build, trainer startup, and watchdog restart pays full XLA
compilation for the same small set of shape-specialized programs — the
exact cost :class:`~cxxnet_tpu.obs.devprof.CompileWatch` measures
(``cxn_compile_seconds{fn=}``) and CXN207 budgets. This repo's
one-signature-per-program discipline (RecompileGuard) means the artifact
set is tiny and stable, so the compiled executables are serialized once
(``jax.experimental.serialize_executable``) and reloaded on every later
startup: a warm cold start performs ZERO ``/jax/core/compile/*`` work
for the cached programs, and PR 9's ``_build_stack()`` recovery path and
the router's replica spin-up stop paying compile at all.

**Key anatomy** — one artifact per full key; any component drifting is a
different key (the stale entry stays until pruned; CXN210 names the
drifted component):

``program``
    program name (``serve_tick``, ``net_update``, ``gpt_decode``, ...).
``signature``
    abstract call signature: pytree structure + per-leaf
    ``dtype[shape]`` (weak types marked, non-trivial NamedSharding
    specs included) + the donated/static argnums.
``extra``
    builder constants that select a different program WITHOUT changing
    the abstract signature (prefill chunk, spec_len, block geometry,
    fused/gather resolution, the ``/mesh=``/``/w=int8``/``/kv=int8``
    guard suffixes, Pallas interpret mode).
``config``
    hash of the owning config (``GPTConfig`` tuple / the Net's raw
    config pairs) — python-level constants baked into the trace
    (learning rates, layer wiring) never alias across configs.
``mesh`` / ``devices``
    mesh axis names x sizes, and the device ids + device kind the
    executable was compiled against (a serialized executable embeds its
    device assignment — replica i's artifact must not load onto
    replica j's device block).
``backend`` / ``jax`` / ``jaxlib``
    ``jax.default_backend()`` and the jax/jaxlib versions — an XLA
    upgrade invalidates every artifact it might lower differently.

**Layout** (content-addressed, ``aot_cache=DIR`` config key or the
``CXN_AOT_CACHE`` env var)::

    DIR/<program>/<sha256-of-key>.bin    # pickle: key + payload + trees
    DIR/<program>/<sha256-of-key>.json   # key components (the validator
                                         # scans these without unpickling)
    DIR/serve_tuned_geometry/<key>.json  # geometry-autotune winner
                                         # (task=autotune, loaded by
                                         # serve_block_size=auto — no
                                         # .bin: the winner's programs
                                         # persist under their own keys)

Writes are atomic (tempfile + ``os.replace`` in the target dir), loads
are corruption-safe: a torn/corrupt/stale/unreadable entry logs one
``profiler.warn`` and falls through to a normal compile — the cache can
NEVER fail a startup, only speed one up. An unwritable cache directory
degrades the same way: one warn, every lookup a miss, the engine builds
by compiling.

**Observability**: ``cxn_aot_cache_{hits,misses,stale,bytes}_total{fn=}``
counters on every attached sink registry (:meth:`AotCache.add_sink`,
the CompileWatch idiom), and each hit emits an ``aot_load`` span on the
sink tracer's engine track — where the ``compile`` span would have been.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["AotCache", "CachedProgram", "ResolvedProgram", "get_cache",
           "active", "configure", "config_hash", "signature_string",
           "devices_string", "mesh_tag", "tuned_components",
           "configure_relabel", "relabel_active", "METRIC_NAMES"]

METRIC_NAMES = (
    ("cxn_aot_cache_hits_total",
     "AOT executable cache hits (program loaded instead of compiled)"),
    ("cxn_aot_cache_misses_total",
     "AOT executable cache misses (program compiled, then persisted)"),
    ("cxn_aot_cache_stale_total",
     "corrupt or key-mismatched cache entries skipped (fell through "
     "to compile)"),
    ("cxn_aot_cache_bytes_total",
     "artifact bytes moved through the cache (read on hit, written "
     "on store)"),
)

_KIND_TO_NAME = {"hit": "cxn_aot_cache_hits_total",
                 "miss": "cxn_aot_cache_misses_total",
                 "stale": "cxn_aot_cache_stale_total",
                 "bytes": "cxn_aot_cache_bytes_total"}


def _versions() -> Tuple[str, str]:
    """(jax, jaxlib) versions — a module-level seam so tests can fake a
    jax upgrade and pin the key invalidation it must cause."""
    import jax
    import jaxlib
    return jax.__version__, jaxlib.__version__


def _interpret_flag() -> bool:
    """Pallas interpret mode changes every kernel-bearing executable
    (tools/cxn_lint.py arms it off-TPU); it must live in the key."""
    try:
        from ..ops import pallas_kernels
        return bool(pallas_kernels._INTERPRET)
    except Exception:
        return False


def config_hash(obj) -> str:
    """Short stable hash of a config object (``repr``-based: GPTConfig
    tuples and Net's (key, value) pair lists are both repr-stable)."""
    return hashlib.sha256(repr(obj).encode()).hexdigest()[:16]


def mesh_tag(mesh) -> str:
    if mesh is None:
        return "none"
    return ",".join("%s=%d" % (n, s)
                    for n, s in zip(mesh.axis_names, mesh.devices.shape))


def _leaf_sig(leaf) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return repr(leaf)
    s = "%s[%s]" % (dtype, ",".join(str(d) for d in shape))
    if getattr(leaf, "weak_type", False):
        s += "~w"
    sh = getattr(leaf, "sharding", None)
    if sh is not None and type(sh).__name__ == "NamedSharding":
        s += "{%s}" % (sh.spec,)
    return s


def signature_string(args: tuple, donate_argnums: Sequence[int] = (),
                     static_argnums: Sequence[int] = ()) -> str:
    """Abstract-signature component of the key: pytree structure +
    per-leaf dtype/shape/weak-type/sharding, plus the donation/static
    contract. Computed WITHOUT tracing — a cache hit must not emit a
    single ``/jax/core/compile/*`` event."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return "%s|%s|donate=%s|static=%s" % (
        treedef, ";".join(_leaf_sig(x) for x in leaves),
        tuple(sorted(donate_argnums)), tuple(sorted(static_argnums)))


def devices_string(args: tuple = (), mesh=None) -> str:
    """Device ids + device kind the program binds to: the mesh's devices
    when given, else the union of the args' committed placements, else
    the default device. Serialized executables embed their device
    assignment, so two placements are two artifacts — UNLESS device
    relabeling is armed (:func:`configure_relabel` / CXN_AOT_RELABEL):
    then the ids are rewritten positionally (0..n-1, count and kind
    preserved), so every identically-shaped replica device block of a
    fleet tier shares ONE persisted artifact instead of compiling and
    storing per block. Only safe when the blocks really are
    interchangeable — the serving fleet's replica workers, each seeing
    its own local devices — which is why it is opt-in, never the
    default."""
    import jax
    ids, kind = set(), ""
    devs = []
    if mesh is not None:
        devs = list(mesh.devices.flat)
    else:
        for leaf in jax.tree_util.tree_leaves(args):
            ds = getattr(getattr(leaf, "sharding", None), "device_set",
                         None)
            if ds:
                devs.extend(ds)
    if not devs:
        devs = [jax.devices()[0]]
    for d in devs:
        ids.add(int(d.id))
        kind = getattr(d, "device_kind", kind) or kind
    if relabel_active():
        ids = range(len(ids))
    return "%s:%s" % (",".join(str(i) for i in sorted(ids)), kind)


# device-relabeling module flag: None = follow the CXN_AOT_RELABEL env
# (how fleet worker processes arm it); configure_relabel() overrides
# in-process (tests, embedders). Off by default — the pinned no-op.
_relabel: Optional[bool] = None


def configure_relabel(on: Optional[bool]) -> None:
    """Force device relabeling on/off for this process; ``None``
    returns control to the ``CXN_AOT_RELABEL`` environment switch."""
    global _relabel
    _relabel = None if on is None else bool(on)


def relabel_active() -> bool:
    if _relabel is not None:
        return _relabel
    return os.environ.get("CXN_AOT_RELABEL", "") not in ("", "0")


def tuned_components(config: str, chunk: int, kv_dtype: str = "",
                     tp: int = 1, weights: str = "") -> Dict[str, str]:
    """The key of one persisted geometry-autotune winner
    (``task=autotune`` → ``serve_block_size=auto``): device kind +
    backend + model geometry (the config hash) + prefill chunk +
    KV dtype + TP degree + weight stream (``weights``: the
    ``serve.engine.weight_stream_tag`` spelling — "int8" / "int4:gN" /
    "" for full precision; int4 swaps the hot matmul formulation, so
    its winner must never leak to a bf16 engine) — everything that
    changes which ``serve_block_size`` wins. Deliberately NOT keyed on
    jax/jaxlib versions (a timing winner survives an upgrade; the
    executables it points at re-warm under their own versioned keys)
    but keyed on the interpret flag: interpret-mode timings say nothing
    about a real backend."""
    import jax
    dev = jax.devices()[0]
    return {
        "program": "serve_tuned_geometry",
        "config": str(config),
        "chunk": str(int(chunk)),
        "kv": str(kv_dtype or "").lower() or "none",
        "tp": str(int(tp)),
        "w": str(weights or "") or "none",
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "")),
        "interpret": str(int(_interpret_flag())),
    }


class AotCache:
    """One on-disk executable cache rooted at ``path`` (use
    :func:`get_cache` — instances are shared per real path so the
    hit/miss counters aggregate per process)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self._sinks: List[tuple] = []       # (registry, tracer or None)
        self._warned: set = set()           # warn-once keys (per category)
        # in-memory executables by digest, populated on LOAD success: an
        # in-process rebuild (PR 9's watchdog recovery) re-resolves
        # WITHOUT re-reading and re-deserializing the artifact — same
        # lifetime semantics as the engine's module-level lru'd jit
        # programs. Deliberately NOT populated on a SUCCESSFUL store, so
        # the first warm start of a populating process still proves the
        # disk round trip — but a FAILED store memoizes (see store):
        # recovery must not recompile just because the disk half is
        # degraded. clear_memory_caches() restores fresh-process
        # semantics for tests/bench.
        self._mem: Dict[str, object] = {}
        self.hits = 0
        self.misses = 0
        self.stale = 0
        self.bytes = 0

    # ------------------------------------------------------------ key
    def components(self, program: str, args: tuple,
                   donate_argnums: Sequence[int] = (),
                   static_argnums: Sequence[int] = (),
                   extra: str = "", config: str = "",
                   mesh=None) -> Dict[str, str]:
        jx, jlib = _versions()
        import jax
        return {
            "program": str(program),
            "signature": signature_string(args, donate_argnums,
                                          static_argnums),
            "extra": "%s|interpret=%d" % (extra, _interpret_flag()),
            "config": str(config),
            "mesh": mesh_tag(mesh),
            "devices": devices_string(args, mesh),
            "backend": jax.default_backend(),
            "jax": jx,
            "jaxlib": jlib,
        }

    @staticmethod
    def digest(components: Dict[str, str]) -> str:
        return hashlib.sha256(
            json.dumps(components, sort_keys=True).encode()).hexdigest()

    def _paths(self, components: Dict[str, str]) -> Tuple[str, str, str]:
        d = self.digest(components)
        base = os.path.join(self.path, components["program"])
        return d, os.path.join(base, d + ".bin"), \
            os.path.join(base, d + ".json")

    # ---------------------------------------------------------- load
    def load(self, components: Dict[str, str], tracer=None):
        """Deserialize-and-load the artifact for this exact key, or
        ``None`` (miss / stale / corrupt — never raises). A hit emits an
        ``aot_load`` span where the compile span would have been."""
        from ..utils import profiler
        label = components["program"]
        digest, bin_path, _ = self._paths(components)
        with self._lock:
            cached = self._mem.get(digest)
        if cached is not None:
            self._emit("hit", label)
            self._span(tracer, label, time.perf_counter(), 0.0, 0)
            return cached
        try:
            with open(bin_path, "rb") as f:
                blob = f.read()
        except OSError:
            self._emit("miss", label)
            return None
        t0 = time.perf_counter()
        try:
            rec = pickle.loads(blob)
            if rec["meta"] != components:
                raise ValueError("stored key != requested key")
            if hashlib.sha256(rec["payload"]).hexdigest() != rec["sha256"]:
                raise ValueError("payload checksum mismatch")
            from jax.experimental import serialize_executable as se
            compiled = se.deserialize_and_load(
                rec["payload"], rec["in_tree"], rec["out_tree"])
        except Exception as e:                          # noqa: BLE001
            # corrupt / truncated / version-skewed pickle: log once per
            # entry, count stale, fall through to a normal compile —
            # a bad cache entry must never fail a startup
            profiler.warn(
                "aot_cache: dropping unusable entry for %r (%s: %s) — "
                "recompiling" % (label, type(e).__name__, e))
            self._emit("stale", label)
            self._emit("miss", label)
            return None
        dur = time.perf_counter() - t0
        with self._lock:
            self._mem[digest] = compiled
        self._emit("hit", label)
        self._emit("bytes", label, float(len(blob)))
        self._span(tracer, label, t0, dur, len(blob))
        return compiled

    def _span(self, tracer, label: str, t0: float, dur: float,
              nbytes: int) -> None:
        with self._lock:
            tracers = [t for _, t in self._sinks if t is not None]
        if tracer is not None and all(t is not tracer for t in tracers):
            tracers.append(tracer)
        for t in tracers:
            try:
                from ..obs.trace import TID_ENGINE
                t.add("aot_load", t0, dur, TID_ENGINE, cat="compile",
                      args={"fn": label, "bytes": nbytes})
            except Exception:       # a dead sink must not break loads
                pass

    # --------------------------------------------------------- store
    def store(self, components: Dict[str, str], compiled) -> bool:
        """Serialize + atomically persist one compiled executable.
        Returns False (after ONE warn per cache) when the backend cannot
        serialize or the directory is unwritable — the caller keeps its
        freshly compiled executable either way, and the executable is
        MEMOIZED in-process so a watchdog/chaos recovery rebuild does
        not pay XLA again for a disk-degraded cache (a cache-off rebuild
        reuses the lru'd jit programs for free; armed-but-unwritable
        must never be slower than off)."""
        from ..utils import profiler
        label = components["program"]
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
        except Exception as e:                          # noqa: BLE001
            self._warn_once(
                "serialize",
                "aot_cache: backend cannot serialize %r (%s: %s) — "
                "cache stays cold" % (label, type(e).__name__, e))
            self._memoize(components, compiled)
            return False
        rec = {"meta": components, "payload": payload,
               "sha256": hashlib.sha256(payload).hexdigest(),
               "in_tree": in_tree, "out_tree": out_tree}
        try:
            blob = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:                          # noqa: BLE001
            self._warn_once(
                "pickle", "aot_cache: cannot pickle artifact for %r "
                "(%s: %s)" % (label, type(e).__name__, e))
            self._memoize(components, compiled)
            return False
        digest, bin_path, meta_path = self._paths(components)
        try:
            os.makedirs(os.path.dirname(bin_path), exist_ok=True)
            self._atomic_write(bin_path, blob)
            self._atomic_write(
                meta_path,
                json.dumps(components, sort_keys=True, indent=1).encode())
        except OSError as e:
            # unwritable/readonly cache dir: ONE warn, metrics keep
            # showing misses, the engine serves from the compiled
            # executable it already holds
            self._warn_once(
                "unwritable",
                "aot_cache: cache dir %r unwritable (%s) — compiled "
                "programs will not persist" % (self.path, e))
            self._memoize(components, compiled)
            return False
        self._emit("bytes", label, float(len(blob)))
        return True

    def _memoize(self, components: Dict[str, str], compiled) -> None:
        """In-process fallback for a failed persist (see store)."""
        with self._lock:
            self._mem[self.digest(components)] = compiled

    # -------------------------------------------- tuned geometry winners
    def store_tuned(self, components: Dict[str, str], record: Dict
                    ) -> bool:
        """Atomically persist one geometry-autotune winner (a small
        JSON sidecar — no executable payload; the winner's programs
        persist under their own keys when the tuning sweep warms them).
        The sidecar carries the full key at the top level, so
        :meth:`stale_entries` names a drifted winner's components the
        same way it names a drifted executable's (CXN210)."""
        _, _, meta_path = self._paths(components)
        doc = dict(components)
        doc["winner"] = dict(record)
        try:
            os.makedirs(os.path.dirname(meta_path), exist_ok=True)
            self._atomic_write(
                meta_path,
                json.dumps(doc, sort_keys=True, indent=1).encode())
        except (OSError, TypeError) as e:
            self._warn_once(
                "unwritable",
                "aot_cache: cache dir %r unwritable (%s) — autotune "
                "winner will not persist" % (self.path, e))
            return False
        return True

    def load_tuned(self, components: Dict[str, str]) -> Optional[Dict]:
        """The persisted winner record for this exact key, or ``None``
        (miss / key drift / corrupt — never raises; drift and
        corruption count as stale, the CXN210 idiom: a winner tuned
        for a different geometry must not silently steer this one)."""
        label = components["program"]
        _, _, meta_path = self._paths(components)
        try:
            with open(meta_path) as f:
                doc = json.load(f)
        except OSError:
            self._emit("miss", label)
            return None
        except Exception:                               # noqa: BLE001
            self._emit("stale", label)
            self._emit("miss", label)
            return None
        rec = doc.get("winner")
        if ({k: doc.get(k) for k in components} != dict(components)
                or not isinstance(rec, dict) or "block_size" not in rec):
            self._emit("stale", label)
            self._emit("miss", label)
            return None
        self._emit("hit", label)
        return rec

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".aot-tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _warn_once(self, category: str, msg: str) -> None:
        """One warning per failure CATEGORY (serialize / pickle /
        unwritable): an early backend-serialize warn must not swallow a
        later unwritable-directory warn."""
        from ..utils import profiler
        with self._lock:
            if category in self._warned:
                return
            self._warned.add(category)
        profiler.warn(msg)

    # ------------------------------------------------- staleness scan
    def stale_entries(self, components: Dict[str, str]
                      ) -> List[Tuple[str, Dict[str, Tuple[str, str]]]]:
        """Same-program entries whose key differs from ``components``:
        ``[(digest, {component: (stored, current), ...}), ...]`` — the
        CXN210 validator names exactly the drifting component(s)."""
        cur_digest, _, _ = self._paths(components)
        base = os.path.join(self.path, components["program"])
        out = []
        try:
            names = sorted(os.listdir(base))
        except OSError:
            return out
        # union of sidecar and payload names: an orphaned .bin (crash /
        # disk-full between the pair of writes) must still surface as
        # CXN210 — a cold start would silently miss it and recompile
        digests = sorted({n[:-5] for n in names if n.endswith(".json")}
                         | {n[:-4] for n in names if n.endswith(".bin")})
        for digest in digests:
            if digest == cur_digest:
                continue
            try:
                with open(os.path.join(base, digest + ".json")) as f:
                    stored = json.load(f)
            except Exception:                           # noqa: BLE001
                out.append((digest, {"entry": ("unreadable meta", "")}))
                continue
            drift = {k: (str(stored.get(k, "<absent>")), str(v))
                     for k, v in components.items()
                     if stored.get(k) != v}
            out.append((digest, drift or
                        {"entry": ("meta/digest mismatch", "")}))
        return out

    def has(self, components: Dict[str, str]) -> bool:
        return os.path.exists(self._paths(components)[1])

    # ------------------------------------------------------- metrics
    def add_sink(self, registry, tracer=None) -> None:
        """Attach a metrics registry (and optional tracer): the four
        ``cxn_aot_cache_*_total{fn=}`` families are pre-created so the
        series exist before the first event. Idempotent per registry."""
        for name, help_ in METRIC_NAMES:
            registry.counter(name, help_, labelnames=("fn",))
        with self._lock:
            if not any(r is registry for r, _ in self._sinks):
                self._sinks.append((registry, tracer))

    def remove_sink(self, registry) -> None:
        with self._lock:
            self._sinks = [(r, t) for r, t in self._sinks
                           if r is not registry]

    def _emit(self, kind: str, label: str, n: float = 1.0) -> None:
        with self._lock:
            if kind == "hit":
                self.hits += 1
            elif kind == "miss":
                self.misses += 1
            elif kind == "stale":
                self.stale += 1
            elif kind == "bytes":
                self.bytes += int(n)
            sinks = list(self._sinks)
        for registry, _ in sinks:
            try:
                registry.counter(_KIND_TO_NAME[kind],
                                 labelnames=("fn",)).labels(label).inc(n)
            except Exception:   # a dead sink must not break the cache
                pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "stale": self.stale, "bytes": self.bytes}


# ---------------------------------------------------- process-wide state
_caches: Dict[str, AotCache] = {}
_caches_lock = threading.Lock()
_UNSET = object()
_override = _UNSET


def get_cache(path: str) -> AotCache:
    """The shared :class:`AotCache` for ``path`` (one instance per real
    path, so every owner's hits land in the same counters)."""
    key = os.path.realpath(str(path))
    with _caches_lock:
        c = _caches.get(key)
        if c is None:
            c = _caches[key] = AotCache(str(path))
        return c


def clear_memory_caches() -> None:
    """Drop every cache's in-memory executable memo (disk artifacts are
    untouched) — the fresh-process stand-in for tests and the
    cold-start bench; ``serve.engine.clear_program_caches`` calls this
    so one helper resets the whole compiled-program surface."""
    with _caches_lock:
        caches = list(_caches.values())
    for c in caches:
        with c._lock:
            c._mem.clear()


def configure(path: Optional[str]) -> None:
    """Set (or, with ``None``, disable) the process-default cache that
    lazily-resolved programs consult — overrides ``CXN_AOT_CACHE``.
    Call :func:`reset_configured` to restore env-driven behavior."""
    global _override
    _override = get_cache(path) if path else None


def reset_configured() -> None:
    global _override
    _override = _UNSET


def active() -> Optional[AotCache]:
    """The process-default cache: an explicit :func:`configure` wins,
    else the ``CXN_AOT_CACHE`` env var, else None (cache off — the
    pinned no-op)."""
    if _override is not _UNSET:
        return _override
    path = os.environ.get("CXN_AOT_CACHE", "")
    return get_cache(path) if path else None


# ------------------------------------------------------- program wrappers
class ResolvedProgram:
    """A loaded/AOT-compiled executable standing in for a jitted
    program fetch. Calls go to the executable; a signature-mismatch
    ``TypeError`` (the one-signature discipline was violated) logs once,
    permanently falls back to the lazy jit builder, and never corrupts
    state (the pytree/aval check fires before any buffer is donated)."""

    __slots__ = ("exec", "label", "source", "_fallback", "_dead")

    def __init__(self, compiled, label: str, source: str, fallback):
        self.exec = compiled
        self.label = label
        self.source = source            # "aot_load" | "compiled"
        self._fallback = fallback       # () -> jitted fn
        self._dead = False

    def __call__(self, *args):
        if not self._dead:
            try:
                return self.exec(*args)
            except TypeError as e:
                from ..utils import profiler
                profiler.warn(
                    "aot_cache: resolved %r rejected a call signature "
                    "(%s) — falling back to the jit path" %
                    (self.label, e))
                self._dead = True
        return self._fallback()(*args)


class CachedProgram:
    """Attribute-transparent wrapper (the RecompileGuard idiom: .lower
    and friends delegate to the wrapped jit) that resolves its ONE
    compiled executable through an :class:`AotCache` on first call —
    load on hit, AOT-compile-then-persist on miss. Calls whose abstract
    signature differs from the resolved one (a second eval batch shape,
    a different static node set) drop to the plain jit path, which
    compiles them lazily exactly as before."""

    def __init__(self, fn, name: str, config: str = "", extra: str = "",
                 donate_argnums: Sequence[int] = (),
                 static_argnums: Sequence[int] = (), cache=None,
                 mesh=None):
        self._fn = fn
        self._name = name
        self._config = config
        self._extra = extra
        self._donate = tuple(donate_argnums)
        self._static = tuple(static_argnums)
        self._static_set = frozenset(self._static)
        self._cache = cache
        self._mesh = mesh
        self._exec = None
        self._static_vals = None
        self._resolve_failed = False
        self.source = ""                # "" | "aot_load" | "compiled"

    def __call__(self, *args, **kwargs):
        if kwargs:                      # call sites are positional-only
            return self._fn(*args, **kwargs)
        if self._exec is not None:
            if not self._static_set:
                # hot path (Net's per-step calls): hand the args straight
                # to the executable — its own pytree/aval validation
                # rejects an off-signature call BEFORE any buffer is
                # donated, so the TypeError fallback is state-safe and
                # the steady state pays zero signature recomputation
                try:
                    return self._exec(*args)
                except TypeError:
                    return self._fn(*args)
            # static args are EXCLUDED from the executable's inputs, so
            # a drifted static (a new forward node set) would not trip
            # the aval check — compare the static VALUES captured at
            # resolve (a cheap tuple ==, not a full abstract-signature
            # recomputation over the args pytree) and leave dynamic-arg
            # drift to the executable's validation, exactly as above
            if tuple(args[i] for i in self._static) == self._static_vals:
                try:
                    return self._exec(*(a for i, a in enumerate(args)
                                        if i not in self._static_set))
                except TypeError:
                    return self._fn(*args)
            return self._fn(*args)
        if self._resolve_failed:
            return self._fn(*args)
        cache = self._cache if self._cache is not None else active()
        if cache is None:
            return self._fn(*args)
        self.resolve(cache, args)
        return self(*args)

    def resolve(self, cache: AotCache, args: tuple, tracer=None) -> str:
        """Load-or-compile the executable for this exact call signature.
        Returns the source ("aot_load" / "compiled" / "" on failure)."""
        comp = cache.components(self._name, args,
                                donate_argnums=self._donate,
                                static_argnums=self._static,
                                extra=self._extra, config=self._config,
                                mesh=self._mesh)
        compiled = cache.load(comp, tracer=tracer)
        if compiled is None:
            from ..obs.devprof import compile_attribution
            with compile_attribution(self._name):
                try:
                    lowered = self._fn.lower(*args)
                except Exception:       # noqa: BLE001
                    # an arg mix .lower cannot abstract (exotic
                    # static): permanently defer to plain jit dispatch
                    self._resolve_failed = True
                    return ""
                # a genuine compile failure propagates — the jit path
                # would only repeat the identical (expensive) compile
                # for the same exception, so no fallback here
                compiled = lowered.compile()
            cache.store(comp, compiled)
            self.source = "compiled"
        else:
            self.source = "aot_load"
        self._exec = compiled
        self._static_vals = tuple(args[i] for i in self._static)
        return self.source

    def __getattr__(self, attr):
        return getattr(self._fn, attr)
