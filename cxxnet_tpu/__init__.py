"""cxxnet_tpu — a TPU-native deep-learning training framework with the
capabilities of the reference cxxnet (see SURVEY.md at the repo root).

Public surface:
- :class:`cxxnet_tpu.nnet.net.Net` — the trainer (INetTrainer equivalent)
- :func:`cxxnet_tpu.io.create_iterator` — config-driven data pipelines
- :mod:`cxxnet_tpu.cli` — the ``cxxnet <config> [k=v ...]`` runner
- :mod:`cxxnet_tpu.wrapper` — the cxxnet.py-compatible Python API
- :mod:`cxxnet_tpu.serve` — the continuous-batching inference server
  (``task = serve`` / ``Net.serve_*``; doc/serving.md)
- :mod:`cxxnet_tpu.analysis` — cxn-lint static analysis: graph/config
  lint + compiled-step audit (``task = lint`` / ``CXN_LINT``; doc/lint.md)
"""

__version__ = "0.1.0"

from .graph import NetGraph
from .nnet.net import Net
from .io import create_iterator

__all__ = ["Net", "NetGraph", "create_iterator", "__version__"]
