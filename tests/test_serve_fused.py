"""Fused paged-attention decode kernel + partial-tail prefix sharing.

The load-bearing invariants of the fused serving path
(ops/pallas_kernels.py:paged_attention, the engine's fused tick/verify
variants, PagedPrefixCache partial tails):

1. **shared tolerance contract** — fused-vs-gather agreement is defined
   ONCE (serve.engine.fused_attn_tolerance): EXACT in interpret mode on
   the CPU mesh (these tests), bounded ULP on a real TPU. Every
   differential here asserts through assert_fused_allclose — no
   per-test ad-hoc allclose settings.
2. **bit identity** — with the kernel armed (interpret mode), served
   tokens AND cache bytes equal the gather path's and the solo
   ``gpt_decode`` oracle under every admission shape: chunked,
   prefix-hit, partial-tail hit, speculative, recycled rows,
   preempt/swap/resume, chaos recovery.
3. **off-switch is a true no-op** — ``fused_attn=False`` /
   ``CXN_FUSED_ATTN=0`` resolve to the gather programs.
4. **compiled-program hygiene** — one signature per fused program
   across mixed traffic; the RecompileGuard signature strings do NOT
   carry the fused/gather flag; the fused programs audit fully
   donation-aliased with every index clip folded (CXN208).
5. **partial tails** — the trie donates/restores the prompt suffix
   beyond the last complete chunk (per-node valid length, masked
   garbage past it), so a hit restores MORE than chunk-granular
   matching could, bit-identically.
"""

import numpy as np
import pytest

import jax

import cxxnet_tpu.ops.pallas_kernels as pk
from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (DecodeEngine, InferenceServer,
                              assert_fused_allclose, fused_attn_tolerance)
from cxxnet_tpu.serve.engine import (_attn_cached_rows, _attn_verify,
                                     _gather_row, _gather_rows)

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


@pytest.fixture(autouse=True)
def interpret(monkeypatch):
    """Arm Pallas interpret mode: the fused kernel runs (and AOT-lowers)
    on the CPU mesh, and the tolerance contract's exact branch
    applies."""
    monkeypatch.setattr(pk, "_INTERPRET", True)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


# --------------------------------------------------------------- kernel
def test_kernel_exact_vs_gather_reference():
    """paged_attention against the gather reference (_gather_rows +
    _attn_cached_rows for the tick shape, _gather_row + _attn_verify
    for the verify shape), both jitted, f32 AND bf16: exact under the
    interpret-mode branch of the shared contract — including garbage
    (id 0) table entries, which the position mask must zero."""
    rs = np.random.RandomState(0)
    L, NB, H, bs, d = 2, 20, CFG.n_head, 4, CFG.feat // CFG.n_head
    b, bpr = 3, 6
    for dtype in (jax.numpy.float32, jax.numpy.bfloat16):
        pool_k = jax.numpy.asarray(rs.randn(L, NB, H, bs, d), dtype)
        pool_v = jax.numpy.asarray(rs.randn(L, NB, H, bs, d), dtype)
        table = np.zeros((b, bpr), np.int32)
        table[0, :3] = [5, 9, 2]            # rest: garbage block 0
        table[1, :5] = [7, 11, 1, 3, 8]
        table[2, :2] = [4, 6]
        table = jax.numpy.asarray(table)
        pos = jax.numpy.asarray([9, 17, 6], jax.numpy.int32)
        q = jax.numpy.asarray(rs.randn(b, 1, H, d), dtype)

        @jax.jit
        def gather_tick(q, pk_, pv_, table, pos):
            ck = _gather_rows(pk_[1], table, H, bs)
            cv = _gather_rows(pv_[1], table, H, bs)
            return _attn_cached_rows(q, ck, cv, pos)

        @jax.jit
        def fused_tick(q, pk_, pv_, table, pos):
            return pk.paged_attention(q, pk_, pv_, table, pos, 1, bs)

        assert_fused_allclose(fused_tick(q, pool_k, pool_v, table, pos),
                              gather_tick(q, pool_k, pool_v, table, pos),
                              "tick %s" % dtype.__name__)

        R = 4
        qv = jax.numpy.asarray(rs.randn(1, R, H, d), dtype)
        vpos = jax.numpy.asarray(9, jax.numpy.int32)

        @jax.jit
        def gather_verify(q, pk_, pv_, table, pos):
            ck = _gather_row(pk_[0], table[0], H, bs)
            cv = _gather_row(pv_[0], table[0], H, bs)
            return _attn_verify(q, ck, cv, pos)

        @jax.jit
        def fused_verify(q, pk_, pv_, table, pos):
            return pk.paged_attention(q, pk_, pv_, table[:1],
                                      jax.numpy.reshape(pos, (1,)), 0, bs)

        assert_fused_allclose(
            fused_verify(qv, pool_k, pool_v, table, vpos),
            gather_verify(qv, pool_k, pool_v, table, vpos),
            "verify %s" % dtype.__name__)


def test_tolerance_contract_exact_here():
    """On the CPU mesh with interpret armed, the shared contract's
    exact branch applies — rtol = atol = 0, not an ad-hoc epsilon."""
    assert fused_attn_tolerance() == {"rtol": 0.0, "atol": 0.0}


# ------------------------------------------------- served-token identity
def test_fused_vs_gather_vs_oracle_mixed_workload():
    """The tentpole differential: a mixed workload — non-multiple
    lengths, sampling, shared prefixes, recycled rows — served with the
    fused kernel armed produces tokens IDENTICAL to the gather path
    and the solo gpt_decode oracle, and the final pools agree under the
    shared contract (exact here)."""
    rs = np.random.RandomState(0)
    shared = _prompt(rs, 12)
    cases = [
        dict(p=_prompt(rs, 3), max_tokens=5),
        dict(p=_prompt(rs, 9), max_tokens=6, temperature=0.8, top_k=5,
             top_p=0.9, seed=7),
        dict(p=np.concatenate([shared, _prompt(rs, 3)]), max_tokens=5,
             temperature=0.7, seed=2),
        dict(p=np.concatenate([shared, _prompt(rs, 5)]), max_tokens=5),
        dict(p=_prompt(rs, 13), max_tokens=5),
    ]
    outs = {}
    for fused in (True, False):
        with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                             prefill_chunk=4, fused_attn=fused) as srv:
            hs = [srv.submit(c["p"], **{k: v for k, v in c.items()
                                        if k != "p"}) for c in cases]
            outs[fused] = [srv.result(h, timeout=300) for h in hs]
            m = srv.metrics()
            assert m["paged"]["fused_attn"] is fused
        assert all(r.status == "ok" for r in outs[fused])
    for c, rf, rg in zip(cases, outs[True], outs[False]):
        kw = {k: v for k, v in c.items() if k not in ("p", "max_tokens")}
        ref = _ref(c["p"], c["max_tokens"], **kw)
        np.testing.assert_array_equal(rf.tokens, ref)
        np.testing.assert_array_equal(rf.tokens, rg.tokens)


def test_fused_speculative_identity():
    """Greedy speculative serving through the FUSED verify program
    stays bit-identical to the solo oracle."""
    rs = np.random.RandomState(3)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base, base])     # n-gram bait
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="ngram", spec_len=3,
                         fused_attn=True) as srv:
        res = srv.result(srv.submit(prompt, max_tokens=8), timeout=300)
        m = srv.metrics()
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(prompt, 8))
    assert m["paged"]["fused_attn"] and m["spec_forwards"] >= 1


def test_fused_swap_resume_identity_under_tiny_pool():
    """Preempt -> swap -> resume with the fused kernel armed: a pool
    ~2x smaller than the working set still serves every request the
    oracle's exact tokens (the kernel reads whatever blocks the resume
    scattered — sharing/swap policy is untouched by the read path)."""
    rs = np.random.RandomState(6)
    prompts = [_prompt(rs, 6) for _ in range(3)]
    srv = InferenceServer(CFG, PARAMS, slots=3, queue=8, prefill_chunk=4,
                          prefix_mb=0.0, num_blocks=15, fused_attn=True)
    hs = [srv.submit(p, max_tokens=20) for p in prompts]
    res = [srv.result(h, timeout=300) for h in hs]
    m = srv.metrics()
    srv.shutdown()
    assert [r.status for r in res] == ["ok"] * 3
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, 20))
    assert m["paged"]["swaps_out"] >= 1 and m["paged"]["swaps_in"] >= 1


def test_chaos_recovery_bit_identical_with_fused_kernel():
    """PR 9's recovery contract survives the fused kernel: an injected
    tick fault tears the engine down, the replayed request regenerates
    through the FUSED programs, and the stream stays bit-identical."""
    rs = np.random.RandomState(11)
    prompt = _prompt(rs, 7)
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         fused_attn=True, chaos="tick_raise@3",
                         max_restarts=3) as srv:
        res = srv.result(srv.submit(prompt, max_tokens=10), timeout=300)
        m = srv.metrics()
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(prompt, 10))
    assert m["resilience"]["restarts"] >= 1
    assert m["resilience"]["replayed"] >= 1


# ---------------------------------------------------------- off-switch
def test_off_switch_param_resolves_gather():
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, fused_attn=False)
    assert eng.fused_attn is False
    eng.close()


def test_off_switch_env_true_noop(monkeypatch):
    """CXN_FUSED_ATTN=0 force-disables resolution even where the
    kernel is supported, and the served stream is the gather path's."""
    monkeypatch.setenv("CXN_FUSED_ATTN", "0")
    rs = np.random.RandomState(4)
    prompt = _prompt(rs, 9)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                         prefill_chunk=4) as srv:
        assert srv.metrics()["paged"]["fused_attn"] is False
        res = srv.result(srv.submit(prompt, max_tokens=6), timeout=300)
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(prompt, 6))


# ------------------------------------------- compiled-program hygiene
def test_one_compiled_signature_fused_across_mixed_traffic():
    """30 mixed-length requests through a strict RecompileGuard with
    the fused kernel armed: chunk, tick, and verify each keep ONE
    compiled signature (the acceptance bound)."""
    rs = np.random.RandomState(9)
    with InferenceServer(CFG, PARAMS, slots=3, queue=64, prefill_chunk=4,
                         recompile_limit=1, recompile_strict=True,
                         spec_mode="ngram", spec_len=2,
                         fused_attn=True) as srv:
        hs = [srv.submit(_prompt(rs, 1 + (i * 7) % 20), max_tokens=3)
              for i in range(30)]
        assert all(srv.result(h, timeout=300).status == "ok"
                   for h in hs)
        eng = srv._engine
        assert eng.fused_attn
        assert len(eng.prefill_signatures) == 1, eng.prefill_signatures
        assert len(eng.tick_signatures) == 1, eng.tick_signatures
        assert len(eng.verify_signatures) <= 1


def test_guard_signatures_do_not_carry_fused_flag():
    """The fused/gather choice is fixed at construction, so it must
    NOT appear in any RecompileGuard signature string — a fused and a
    gather engine over the same traffic count IDENTICAL signatures
    (the flag can never read as a drifting leaf)."""
    rs = np.random.RandomState(2)
    prompt = _prompt(rs, 6)
    sigs = {}
    for fused in (True, False):
        with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                             prefill_chunk=4, recompile_limit=2,
                             spec_mode="ngram", spec_len=2,
                             fused_attn=fused) as srv:
            srv.result(srv.submit(np.concatenate([prompt, prompt]),
                                  max_tokens=4), timeout=300)
            eng = srv._engine
            sigs[fused] = (eng.prefill_signatures, eng.tick_signatures,
                           eng.verify_signatures)
    assert sigs[True] == sigs[False], sigs
    for group in sigs[True]:
        for s in group:
            assert "fused" not in s and "gather" not in s, s


def test_fused_audit_fully_aliased_and_clip_folded():
    """cxn-lint pass 2 on the FUSED engine: chunk/verify/tick audit
    with both pool buffers donation-aliased end to end AND every
    explicit index clip folded into its fusion (CXN208 /
    entry_clamps == 0 — the step table's clip=folded column)."""
    from cxxnet_tpu.analysis import audit_serve_engine
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, spec_len=2, abstract=True,
                       fused_attn=True)
    assert eng.fused_attn
    report, infos = audit_serve_engine(eng, donate=True)
    assert report.ok(), report.format()
    assert [i["label"] for i in infos] == [
        "serve_prefill_chunk", "serve_verify_chunk", "serve_tick"]
    for info in infos:
        assert info["donated"] == 2 and info["aliased"] == 2, info
        assert info["entry_clamps"] == 0, info


def test_block_table_width_gauge_published():
    """The observatory surfaces the compiled block-table width next to
    the per-program cost rows (cxn_program_block_table_width{fn=}), so
    pool-geometry changes are attributable from a scrape."""
    from cxxnet_tpu.obs.devprof import profile_engine
    from cxxnet_tpu.obs.metrics import Registry
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, fused_attn=False)
    reg = Registry()
    profile_engine(eng, registry=reg)
    snap = reg.snapshot()
    key = 'cxn_program_block_table_width{fn="serve_tick"}'
    assert snap.get(key) == eng.bpr, sorted(
        k for k in snap if k.startswith("cxn_program_block_table"))
    eng.close()


# ------------------------------------------------------- partial tails
def test_partial_tail_prefix_hit_restores_sub_chunk_tokens():
    """Two prompts sharing an 11-token prefix at chunk 4: chunk-granular
    matching could restore at most 8 tokens, the partial tail brings
    the hit to 11 — and the hit stream stays bit-identical to the solo
    oracle (the restored tail block's garbage past `valid` is masked,
    the first write into it COW-faults)."""
    rs = np.random.RandomState(12)
    shared = _prompt(rs, 11)
    p_a = shared
    p_b = np.concatenate([shared, _prompt(rs, 5)])
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         prefix_mb=1.0, fused_attn=True) as srv:
        res_a = srv.result(srv.submit(p_a, max_tokens=4), timeout=300)
        res_b = srv.result(srv.submit(p_b, max_tokens=6), timeout=300)
        hit = srv.metrics()["prefix_cache"]["hit_tokens"]
    assert res_a.status == "ok" and res_b.status == "ok"
    np.testing.assert_array_equal(res_a.tokens, _ref(p_a, 4))
    np.testing.assert_array_equal(res_b.tokens, _ref(p_b, 6))
    assert hit >= 11, hit        # > the 8 chunk-granular tokens


def test_partial_tail_trie_unit():
    """Trie-level pin: donation creates ONE terminal tail node with a
    per-node valid length and ceil(valid/bs) block refs; matching a
    longer prompt returns it; eviction hands the blocks back and the
    refcount audit stays clean."""
    from cxxnet_tpu.serve.prefix_cache import PagedPrefixCache
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, fused_attn=False)
    cache = PagedPrefixCache(eng, 1 << 20)
    rs = np.random.RandomState(13)
    prompt = _prompt(rs, 11)            # 2 chunks + 3-token tail
    key = np.asarray(jax.random.PRNGKey(0), np.uint32)
    for start in range(0, 11, 4):
        end = min(start + 4, 11)
        eng.reserve_window(0, start, start + 4)
        buf = np.zeros(4, np.int32)
        buf[:end - start] = prompt[start:end]
        eng.prefill_chunk(0, buf, start, end - start, key, 0.0, 0, 1.0)
    added = cache.donate_from_row(0, prompt)
    assert added == 3                   # 2 chunk nodes + 1 tail node
    tail = [nd for nd in cache._nodes if nd.valid < cache.chunk]
    assert len(tail) == 1 and tail[0].valid == 3
    assert len(tail[0].blocks) == 1     # ceil(3 / bs=4)
    assert cache.match_tokens(np.concatenate(
        [prompt, _prompt(rs, 4)])) == 11
    # the donor's own prompt must not over-match (final token rule):
    # chain capped at 10 -> complete chunks 8 + no 3-token tail room
    assert cache.match_tokens(prompt) == 8
    m = eng.manager
    eng.release_row(0)
    cache.clear()
    m.check_consistency(trie_refs=0)
    assert m.free_count == eng.num_blocks - 1
    eng.close()