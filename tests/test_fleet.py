"""Cross-process serving fleet (serve/fleet.py + serve/rpc.py):
disaggregated prefill/decode tiers behind the out-of-process RPC
router. Pins the ISSUE-17 contracts: prefill->decode KV migration over
the checksummed wire is bit-identical to the single-process engine
oracle (greedy, sampled, prefix-hit, int8 KV); a corrupted wire payload
fails typed and replays only that row; a SIGKILL'd decode worker's
requests replay bit-identically on a survivor; drain loses nothing;
malformed RPC frames get typed rejection, not a hang; and a
second/replacement worker spins up with zero labeled XLA compiles via
the shared relabeled AOT cache.

Worker processes ride the shared spawn plumbing of
tests/fleet_harness.py (free ports; FleetRouter itself carries the
pipe-drain reader discipline the harness pioneered)."""

import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

import jax

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (FleetRouter, FrameError, InferenceServer,
                              RpcError, WorkerLostError, parse_tiers)
from cxxnet_tpu.serve.rpc import (KIND_ERROR, KIND_REQUEST, MAGIC,
                                  RpcClient, RpcServer, read_frame,
                                  write_frame)
from fleet_harness import free_port

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)

# fleet workers are single-device processes: the parent's 8-virtual-CPU
# XLA_FLAGS (conftest) must not leak in (8x the host arena per worker)
WENV = {"JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
KW = dict(slots=2, queue=16, prefill_chunk=4, spawn_timeout=600,
          worker_env=WENV)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, temperature=0.0, seed=0):
    rng = jax.random.PRNGKey(seed) if temperature > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 temperature=temperature, rng=rng))[0]


@pytest.fixture(scope="module")
def aot_dir(tmp_path_factory):
    """ONE AOT executable cache shared by every fleet in this module:
    the first worker compiles and persists the serve programs, every
    later spawn (relabeling armed by default) loads them — which both
    keeps this module's wall clock sane and is itself the spin-up
    contract under test."""
    return str(tmp_path_factory.mktemp("fleet-aot"))


# --------------------------------------------------------------- units
def test_parse_tiers():
    assert parse_tiers("prefill=1,decode=2") == {"prefill": 1,
                                                "decode": 2}
    assert parse_tiers("3") == {"prefill": 0, "decode": 3}
    assert parse_tiers("") == {"prefill": 0, "decode": 0}
    assert parse_tiers("decode=4") == {"prefill": 0, "decode": 4}
    with pytest.raises(ValueError, match="tier"):
        parse_tiers("draft=2")


def test_fleet_validation():
    with pytest.raises(ValueError, match="decode"):
        FleetRouter(CFG, PARAMS, prefill=1, decode=0, **KW)
    with pytest.raises(ValueError, match="prefill"):
        FleetRouter(CFG, PARAMS, prefill=-1, decode=1, **KW)
    with pytest.raises(ValueError):
        parse_tiers("prefill=x")


def test_aot_relabel_rewrites_device_ids():
    """Relabeling rewrites the device-id key component positionally
    (count + kind preserved): an array committed to device 3 keys like
    one on device 0, so identical replica blocks share one artifact.
    Off by default — placements key separately."""
    from cxxnet_tpu.analysis import aot_cache as ac
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-virtual-device conftest topology")
    x0 = jax.device_put(np.ones((4,), np.float32), jax.devices()[0])
    x3 = jax.device_put(np.ones((4,), np.float32), jax.devices()[3])
    try:
        ac.configure_relabel(False)
        assert ac.devices_string((x3,)) != ac.devices_string((x0,))
        ac.configure_relabel(True)
        assert ac.relabel_active()
        assert ac.devices_string((x3,)) == ac.devices_string((x0,))
        # count preserved: a 2-device placement never aliases 1-device
        x03 = (x0, x3)
        assert ac.devices_string(x03) != ac.devices_string((x0,))
    finally:
        ac.configure_relabel(None)
    assert not ac.relabel_active()      # env switch unset -> off


# ----------------------------------------------------------- RPC layer
def _frame_echo_server():
    srv = RpcServer(lambda verb, p: {"verb": verb, **p}, name="fuzz")
    srv.start()
    return srv


def test_rpc_frame_fuzz_typed_rejection():
    """Malformed frames get a typed KIND_ERROR reply (or a clean
    connection close for a torn stream) in bounded time — never a hang,
    never a crashed server: a healthy client keeps working after every
    abuse below."""
    srv = _frame_echo_server()
    try:
        def raw():
            s = socket.create_connection(("127.0.0.1", srv.port),
                                         timeout=10)
            s.settimeout(10)
            return s

        hdr = struct.Struct("!4sBBIQ")
        # bad magic
        s = raw()
        s.sendall(hdr.pack(b"XXXX", 1, KIND_REQUEST, 1, 0))
        _, _, err = read_frame(s)
        assert err["reason"] == "bad-magic", err
        s.close()
        # bad version
        s = raw()
        s.sendall(hdr.pack(MAGIC, 9, KIND_REQUEST, 1, 0))
        _, _, err = read_frame(s)
        assert err["reason"] == "bad-version", err
        s.close()
        # oversized declared length
        s = raw()
        s.sendall(hdr.pack(MAGIC, 1, KIND_REQUEST, 1, 1 << 40))
        _, _, err = read_frame(s)
        assert err["reason"] == "oversized", err
        s.close()
        # undecodable payload
        s = raw()
        s.sendall(hdr.pack(MAGIC, 1, KIND_REQUEST, 1, 4) + b"\x00junk")
        _, _, err = read_frame(s)
        assert err["reason"] == "bad-payload", err
        s.close()
        # non-request kind
        s = raw()
        write_frame(s, threading.Lock(), KIND_ERROR, 7,
                    {"verb": "ping", "payload": {}})
        kind, seq, err = read_frame(s)
        assert kind == KIND_ERROR and err["reason"] == "bad-kind", err
        s.close()
        # truncated mid-frame: torn header, then torn body
        for blob in (hdr.pack(MAGIC, 1, KIND_REQUEST, 1, 64)[:9],
                     hdr.pack(MAGIC, 1, KIND_REQUEST, 1, 64) + b"xy"):
            s = raw()
            s.sendall(blob)
            s.close()
        # the server survived it all: a real client round-trips
        cli = RpcClient("127.0.0.1", srv.port, name="fuzz")
        try:
            out = cli.call("echo", x=3, timeout=30)
            assert out == {"verb": "echo", "x": 3}
        finally:
            cli.close()
    finally:
        srv.close()


def test_rpc_typed_remote_errors_and_loss():
    """A handler exception crosses the wire with its type + attributes;
    a server that dies mid-call releases every waiter with
    WorkerLostError immediately (the SIGKILL contract), not a hang."""
    from cxxnet_tpu.serve.server import QueueFullError

    release = threading.Event()

    def handler(verb, p):
        if verb == "full":
            raise QueueFullError("queue is full", retry_after_ms=125.0)
        if verb == "hang":
            release.wait(60)    # parked until teardown lets it go
        return True

    srv = RpcServer(handler, name="err")
    srv.start()
    cli = RpcClient("127.0.0.1", srv.port, name="err")
    try:
        with pytest.raises(RpcError) as ei:
            cli.call("full", timeout=30)
        assert ei.value.remote_type == "QueueFullError"
        assert ei.value.payload["retry_after_ms"] == 125.0
        done = {}

        def waiter():
            try:
                cli.call("hang", timeout=120)
            except WorkerLostError:
                done["lost"] = time.monotonic()

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.3)
        t0 = time.monotonic()
        srv.close()
        t.join(timeout=20)
        assert not t.is_alive() and done["lost"] - t0 < 15.0
        assert cli.lost
    finally:
        release.set()
        cli.close()
        srv.close()


def test_rpc_client_rejects_bad_port():
    with pytest.raises((ConnectionError, OSError, FrameError)):
        RpcClient("127.0.0.1", free_port(), connect_timeout=5,
                  name="nope")


# ----------------------------------------------- migration bit-identity
def test_fleet_migration_bit_identical(aot_dir):
    """The acceptance fleet — 1 prefill + 2 decode on CPU — serves
    greedy, sampled, and prefix-sharing traffic bit-identically to the
    solo ``gpt_decode`` oracle: chunked prefill on the prefill tier,
    the crc-checksummed KV row over the socket, decode resumed on the
    decode tier. Also pins the merged ``worker=``-labeled scrape and
    zero-lost drain."""
    rs = np.random.RandomState(0)
    shared = _prompt(rs, 8)
    cases = [
        (_prompt(rs, 6), dict(max_tokens=6)),
        (_prompt(rs, 9), dict(max_tokens=5)),
        (np.concatenate([shared, _prompt(rs, 3)]), dict(max_tokens=5)),
        (np.concatenate([shared, _prompt(rs, 4)]), dict(max_tokens=5)),
        (_prompt(rs, 7), dict(max_tokens=6, temperature=0.8, seed=3)),
        (_prompt(rs, 5), dict(max_tokens=6, temperature=1.1, top_k=8,
                              seed=9)),
    ]
    refs = [_ref(p, kw["max_tokens"], kw.get("temperature", 0.0),
                 kw.get("seed", 0))
            for p, kw in cases if "top_k" not in kw]
    with FleetRouter(CFG, PARAMS, prefill=1, decode=2,
                     aot_cache=aot_dir, **KW) as r:
        hs, done = [], {}
        for i, (p, kw) in enumerate(cases):
            hs.append(r.submit(p, **kw))
            if i == 2:
                # let the prefix donor retire so its chunks are in the
                # prefill tier's cache before the sharer prefills
                done[2] = r.result(hs[2], timeout=600)
        outs = [done.get(i) or r.result(h, timeout=600)
                for i, h in enumerate(hs)]
        for res in outs:
            assert res.status == "ok", (res.status, res.error)
        full = [np.asarray(res.tokens) for res in outs]
        for got, ref in zip(full[:5], refs):        # topk has no oracle
            np.testing.assert_array_equal(got, ref)
        m = r.metrics()
        assert m["fleet"]["migrations"] == len(cases)
        assert m["fleet"]["kv_wire_bytes"] > 0
        assert m["requests"]["completed"] == len(cases)
        # prefix reuse happened on the prefill tier
        pw = next(v for k, v in m["workers"].items()
                  if k.startswith("prefill"))
        assert pw["prefix_cache"]["hits"] >= 1
        # ONE merged scrape: router fleet counters + per-worker
        # families under worker= labels
        text = r.metrics_text()
        assert 'cxn_fleet_workers{worker="router"} 3' in text
        assert 'cxn_fleet_migrations_total{worker="router"} %d' \
            % len(cases) in text
        assert 'worker="prefill0"' in text
        assert 'worker="decode0"' in text and 'worker="decode1"' in text
        # sampled determinism across the process hop: resubmitting the
        # same seed reproduces the same stream
        p, kw = cases[4]
        res2 = r.result(r.submit(p, **kw), timeout=600)
        np.testing.assert_array_equal(res2.tokens, full[4])
        # drain = zero lost: in-flight work finishes, results answer
        # from the router cache after the processes are gone
        tail = [(_prompt(rs, 6), _ref_kw) for _ref_kw in
                (dict(max_tokens=4), dict(max_tokens=4))]
        tail_refs = [_ref(p, 4) for p, _ in tail]
        tail_h = [r.submit(p, **kw) for p, kw in tail]
        r.drain(timeout=600)
        for h, ref in zip(tail_h, tail_refs):
            res = r.result(h, timeout=10)
            assert res.status == "ok", (res.status, res.error)
            np.testing.assert_array_equal(res.tokens, ref)


def test_fleet_int8_kv_migrates_bit_exact(aot_dir):
    """int8 KV crosses the wire in stored representation (quantized
    blocks + per-block scales, one crc over both): the fleet's stream
    equals the single-process int8 server's stream exactly."""
    rs = np.random.RandomState(7)
    prompts = [_prompt(rs, 6), _prompt(rs, 9)]
    kw = dict(slots=2, queue=16, prefill_chunk=4, kv_dtype="int8")
    refs = []
    with InferenceServer(CFG, PARAMS, **kw) as solo:
        for p in prompts:
            res = solo.result(solo.submit(p, max_tokens=6), timeout=600)
            assert res.status == "ok"
            refs.append(np.asarray(res.tokens))
    with FleetRouter(CFG, PARAMS, prefill=1, decode=1,
                     kv_dtype="int8", worker_env=WENV,
                     spawn_timeout=600, slots=2, queue=16,
                     prefill_chunk=4) as r:
        hs = [r.submit(p, max_tokens=6) for p in prompts]
        for h, p, ref in zip(hs, prompts, refs):
            res = r.result(h, timeout=600)
            assert res.status == "ok", (res.status, res.error)
            np.testing.assert_array_equal(res.tokens, ref)
        assert r.metrics()["fleet"]["migrations"] == len(prompts)


# -------------------------------------------------------------- chaos
def test_fleet_wire_corruption_single_row_replay(aot_dir):
    """A corrupted KV payload on the wire fails the crc check BEFORE
    touching the decode worker's block pool (SwapCorruptionError), and
    only that row replays — locally, bit-identically (the first token
    crossed as the replay pin); the neighbor request never notices."""
    rs = np.random.RandomState(2)
    p1, p2 = _prompt(rs, 6), _prompt(rs, 8)
    r1, r2 = _ref(p1, 8), _ref(p2, 8)
    with FleetRouter(CFG, PARAMS, prefill=1, decode=1,
                     tier_kw={"decode": {"chaos": "swap_in@1"}},
                     aot_cache=aot_dir, **KW) as r:
        res1 = r.result(r.submit(p1, max_tokens=8), timeout=600)
        res2 = r.result(r.submit(p2, max_tokens=8), timeout=600)
        assert res1.status == "ok", (res1.status, res1.error)
        assert res2.status == "ok", (res2.status, res2.error)
        np.testing.assert_array_equal(res1.tokens, r1)
        np.testing.assert_array_equal(res2.tokens, r2)
        dec = next(v for k, v in r.metrics()["workers"].items()
                   if k.startswith("decode"))
        assert dec["resilience"]["swap_corruptions"] == 1
        assert dec["resilience"]["replayed"] == 1
        assert dec["resilience"]["faults_injected"]["swap_in"] == 1


def test_fleet_sigkill_decode_worker_replays_on_survivor(aot_dir):
    """SIGKILL one decode worker mid-decode: the router's journal
    replays its in-flight requests on the surviving decode worker —
    every stream still bit-identical to the oracle — a replacement is
    spawned, and the survivor's counters stay monotone."""
    rs = np.random.RandomState(4)
    prompts = [_prompt(rs, n) for n in (6, 9, 5, 7)]
    refs = [_ref(p, 16) for p in prompts]
    with FleetRouter(CFG, PARAMS, prefill=1, decode=2,
                     aot_cache=aot_dir, heartbeat_s=0.5, **KW) as r:
        hs = [r.submit(p, max_tokens=16) for p in prompts]
        deadline = time.time() + 300
        while r.migrations < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert r.migrations >= 1, "no migration before the kill"
        victim = next(w for w in r.workers if w.tier == "decode"
                      and any(o is w for o in r._owner.values()))
        survivor = next(w for w in r.workers
                        if w.tier == "decode" and w is not victim)
        before = survivor.call("metrics", timeout=60)["requests"]
        victim.proc.kill()              # SIGKILL, no goodbye
        for h, ref in zip(hs, refs):
            res = r.result(h, timeout=600)
            assert res.status == "ok", (res.status, res.error)
            np.testing.assert_array_equal(res.tokens, ref)
        m = r.metrics()["fleet"]
        assert m["replays"] >= 1, m
        assert m["restarts"] >= 1, m
        after = survivor.call("metrics", timeout=60)["requests"]
        for k, v in before.items():     # monotone across the failover
            assert after[k] >= v, (k, before, after)
        assert after["submitted"] > before["submitted"]
        text = r.metrics_text()
        assert 'cxn_worker_restarts_total{worker="router"}' in text
        deadline = time.time() + 300
        while len(r._live("decode")) < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert len(r._live("decode")) == 2, "replacement never came up"


def test_fleet_replacement_spinup_zero_compile(aot_dir):
    """The last worker to spin up against the warm shared AOT cache
    (device relabeling armed by the router) loads every serve program:
    zero AOT misses, zero labeled compile seconds (CompileWatch) — the
    near-free replacement-worker contract. One request proves the
    loaded executables actually serve."""
    rs = np.random.RandomState(9)
    p = _prompt(rs, 6)
    ref = _ref(p, 5)
    with FleetRouter(CFG, PARAMS, prefill=0, decode=2,
                     aot_cache=aot_dir, **KW) as r:
        info = r.workers[-1].call("spinup", timeout=60)
        aot = info["aot"]
        assert aot is not None and aot["misses"] == 0, aot
        assert aot["hits"] >= 2, aot
        labeled = {k: v for k, v in info["compile_totals"].items()
                   if k != "unattributed"}
        assert not labeled, labeled
        res = r.result(r.submit(p, max_tokens=5), timeout=600)
        assert res.status == "ok", (res.status, res.error)
        np.testing.assert_array_equal(res.tokens, ref)


def test_wrapper_fleet_api():
    """Net.serve_start(fleet=...): the reference-style surface serves
    from worker processes, token-identical to Net.generate; fleet=""
    keeps the in-process server (pinned no-op); registry/tracer and
    replicas conflicts are rejected up front."""
    from cxxnet_tpu import wrapper
    from cxxnet_tpu.models import gpt_lm_config
    from cxxnet_tpu.obs.metrics import Registry

    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=4, dev="cpu:0")
    net = wrapper.Net(cfg=cfg)
    net.init_model()
    prompt = np.arange(4, dtype=np.int32) % 32
    want = net.generate(prompt[None], max_new=5, temperature=0.9, seed=3)
    net.serve_start(slots=2, queue=4, max_tokens=5,
                    fleet="prefill=1,decode=1", worker_env=WENV)
    try:
        res = net.serve_result(
            net.serve_submit(prompt, temperature=0.9, seed=3),
            timeout=600)
        assert res.status == "ok", (res.status, res.error)
        np.testing.assert_array_equal(np.asarray(res.tokens), want[0])
        assert net.serve_metrics()["fleet"]["migrations"] == 1
        assert 'cxn_fleet_workers{worker="router"}' in net.metrics_text()
        assert net.serve_health()["state"] == "SERVING"
    finally:
        net.serve_stop()
    with pytest.raises(ValueError, match="sizes the worker pool"):
        net.serve_start(fleet="prefill=1,decode=1", replicas=2)
    with pytest.raises(ValueError, match="own their registries"):
        net.serve_start(fleet="1", registry=Registry())
