"""Batched multi-LoRA serving end-to-end (doc/serving.md "Batched
multi-LoRA"): the paged adapter pool, per-row ragged grouped dispatch,
and the identity/admission contracts.

The load-bearing invariants:

1. **pinned structural no-op when unset** — an armed server with no
   adapter named streams bit-identically to an unarmed server, and the
   unarmed engine's programs carry no LoRA operand at all (no
   ``/lora=`` signature suffix);
2. **solo-oracle identity** — a request decoding under adapter ``a``
   in a MIXED batch is bit-identical to the same request served alone
   on a server registering only ``a`` — greedy AND sampled, across
   prefix hits, speculative decoding, and preempt/swap/resume;
3. **kernel == reference, bitwise** — ``lora_bgmv`` in interpret mode
   is bit-identical to the ragged XLA reference (both run the same
   f32-accumulated two-dot contraction op for op);
4. **the pool is a real pager** — refcounted acquire/release audited
   by ``check_refs``, LRU eviction of unreferenced slots only,
   checksum-verified swap-in (corruption is a typed fault), and
   admission DEFERS (never faults) when the pool is pinned;
5. **hygiene** — mixed adapter traffic is ONE compiled signature
   (ids are data, not structure), the adapter rides the tenant label,
   the failover/fleet wire records, and the affinity trie keys.
"""

import numpy as np
import pytest

import jax

from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
from cxxnet_tpu.ops import pallas_kernels as pk
from cxxnet_tpu.serve import (AdapterPool, AdmissionError, DecodeEngine,
                              InferenceServer, auto_num_blocks,
                              make_adapter, parse_lora_spec)
from cxxnet_tpu.serve.lora import LORA_SITES, _delta_ragged, lora_delta
from cxxnet_tpu.serve.resilience import SwapCorruptionError

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)
NB = auto_num_blocks(CFG, 2, 4)
RANK = 4
ADS = {"a": make_adapter(CFG, RANK, seed=1),
       "b": make_adapter(CFG, RANK, seed=2),
       "c": make_adapter(CFG, RANK, seed=3)}
REG = "a:a.npz;b:b.npz;c:c.npz"      # paths never touched: in-memory
LKW = dict(lora=REG, lora_rank=RANK, lora_adapters=ADS)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _serve_all(srv, jobs):
    """jobs: [(prompt, max_tokens, overrides)] -> token arrays, order
    preserved; every request must finish ok."""
    hs = [srv.submit(p, max_tokens=m, **ov) for p, m, ov in jobs]
    out = []
    for h in hs:
        r = srv.result(h, timeout=300)
        assert r.status == "ok", (r.status, r.error)
        out.append(r.tokens)
    return out


def _solo(prompt, max_tokens, adapter="", **ov):
    """The oracle: the request served ALONE on a server registering
    only its adapter (or unarmed, for the base model)."""
    kw = dict(slots=2, queue=4, prefill_chunk=4, num_blocks=NB,
              prefix_mb=0.0)
    if adapter:
        kw.update(lora="%s:x.npz" % adapter, lora_rank=RANK,
                  lora_adapters={adapter: ADS[adapter]})
        ov = dict(ov, adapter=adapter)
    with InferenceServer(CFG, PARAMS, **kw) as srv:
        r = srv.result(srv.submit(prompt, max_tokens=max_tokens, **ov),
                       timeout=300)
        assert r.status == "ok", (r.status, r.error)
        return r.tokens


# ------------------------------------------------------ registry / pool
def test_parse_spec_and_pool_geometry():
    assert parse_lora_spec("a:x.npz;b") == {"a": "x.npz", "b": "b.npz"}
    pool = AdapterPool(CFG, parse_lora_spec(REG), rank=RANK, adapters=ADS)
    assert pool.size == 4               # 3 adapters + base slot 0
    hidden = CFG.mlp_ratio * CFG.feat
    want = sum(CFG.n_layer * (i * RANK + RANK * o) * 4
               for i, o in ((CFG.feat, 3 * CFG.feat),
                            (CFG.feat, CFG.feat),
                            (CFG.feat, hidden), (hidden, CFG.feat)))
    assert pool.slot_bytes == want
    assert pool.sig == "/lora=r%d/pool=4" % RANK
    for site in LORA_SITES:             # slot 0 stays all-zeros = base
        assert not np.asarray(pool.pool["b_" + site][0]).any()
    with pytest.raises(ValueError, match="rank"):
        AdapterPool(CFG, {"a": "x"}, rank=8, adapters=ADS)


def test_pool_refcount_eviction_swap_audit():
    # pool_mb sized under 3 slots -> the 2-slot floor: base + ONE page
    pool = AdapterPool(CFG, parse_lora_spec(REG), rank=RANK,
                       pool_mb=1e-9, adapters=ADS)
    assert pool.size == 2
    assert pool.acquire("") == 0        # base: no slot, no ref
    s = pool.acquire("a")
    assert s == 1 and pool.pinned("a") and pool.refs_held() == 1
    assert pool.acquire("a") == s       # resident hit, second ref
    assert pool.hits == 1 and pool.swap_ins == 1
    assert not pool.can_acquire("b") and pool.headroom() == 0
    pool.release("a")
    assert pool.pinned("a")             # one ref still pinned
    pool.release("a")
    pool.check_refs(0)
    assert pool.headroom() == 1 and pool.can_acquire("b")
    assert pool.acquire("b") == 1       # LRU-evicts a's page
    assert pool.evictions == 1 and pool.swap_ins == 2
    pool.release("b")
    with pytest.raises(KeyError):
        pool.acquire("zzz")
    with pytest.raises(AssertionError, match="refcount"):
        pool.check_refs(3)
    # corrupted host pages fail their load-time crc at swap-in
    ADS_local = dict(ADS)
    pool2 = AdapterPool(CFG, {"a": "x", "b": "y"}, rank=RANK,
                        pool_mb=1e-9, adapters=ADS_local)
    pool2.acquire("a")
    pool2.release("a")
    pool2.acquire("b")                  # evict a
    pool2.release("b")
    pool2._host["a"]["a_qkv"] = pool2._host["a"]["a_qkv"] + 1.0
    with pytest.raises(SwapCorruptionError):
        pool2.acquire("a")


# ------------------------------------------------- structural no-op pin
def test_unset_is_pinned_structural_noop():
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB)
    assert "/lora" not in eng._sig_suffix
    rs = np.random.RandomState(0)
    jobs = [(_prompt(rs, n), 6, {}) for n in (5, 9)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         num_blocks=NB, prefix_mb=0.0) as srv:
        base = _serve_all(srv, jobs)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         num_blocks=NB, prefix_mb=0.0, **LKW) as srv:
        armed = _serve_all(srv, jobs)   # armed, nothing named = id 0
        assert "/lora=r%d/pool=4" % RANK in srv._engine._sig_suffix
    for x, y in zip(base, armed):
        np.testing.assert_array_equal(x, y)


def test_validation_and_unknown_adapter():
    with pytest.raises(ValueError, match="paged"):
        InferenceServer(CFG, PARAMS, slots=2, prefill_chunk=0, **LKW)
    with pytest.raises(ValueError, match="serve_lora_rank"):
        InferenceServer(CFG, PARAMS, slots=2, prefill_chunk=4,
                        num_blocks=NB, lora=REG, lora_rank=0,
                        lora_adapters=ADS)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         num_blocks=NB, **LKW) as srv:
        with pytest.raises(AdmissionError, match="unknown LoRA"):
            srv.submit(np.arange(4, dtype=np.int32), max_tokens=2,
                       adapter="zzz")
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         num_blocks=NB) as srv:
        with pytest.raises(AdmissionError, match="not armed"):
            srv.submit(np.arange(4, dtype=np.int32), max_tokens=2,
                       adapter="a")


# --------------------------------------------------- solo-oracle identity
def test_mixed_batch_matches_solo_oracle():
    """One mixed batch over base/a/b/c, greedy AND sampled: every row
    bit-identical to its single-adapter oracle."""
    rs = np.random.RandomState(2)
    names = ["", "a", "b", "c", "a", "b"]
    jobs = []
    for i, name in enumerate(names):
        ov = {"adapter": name} if name else {}
        if i % 2:
            ov.update(temperature=0.8, top_k=8, seed=10 + i)
        jobs.append((_prompt(rs, 5 + 2 * i), 6, ov))
    with InferenceServer(CFG, PARAMS, slots=6, queue=8, prefill_chunk=4,
                         prefix_mb=0.0, **LKW) as srv:
        got = _serve_all(srv, jobs)
        srv.lora_pool.check_refs(0)     # every admission released
    for (p, m, ov), g in zip(jobs, got):
        ref = _solo(p, m, **ov)
        np.testing.assert_array_equal(g, ref)


def test_prefix_hit_identity_and_cross_adapter_no_hit():
    """Prefix KV cached under adapter ``a`` answers a's resubmission
    (tokens unchanged) and NEVER answers ``b`` or the base model — the
    trie keys carry the adapter id; id 0 keys are the pre-LoRA bytes."""
    rs = np.random.RandomState(4)
    p = _prompt(rs, 16)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         num_blocks=NB, prefix_mb=4.0, **LKW) as srv:
        def run(adapter):
            ov = {"adapter": adapter} if adapter else {}
            r = srv.result(srv.submit(p, max_tokens=6, **ov), timeout=300)
            assert r.status == "ok", (r.status, r.error)
            return r.tokens

        first = run("a")
        before = srv.metrics()["prefix_cache"]["hit_tokens"]
        again = run("a")
        hit_a = srv.metrics()["prefix_cache"]["hit_tokens"]
        assert hit_a > before           # a's resubmission hit a's KV
        np.testing.assert_array_equal(first, again)
        run("b")
        run("")
        assert srv.metrics()["prefix_cache"]["hit_tokens"] == hit_a
    np.testing.assert_array_equal(first, _solo(p, 6, adapter="a"))


def test_speculative_composes_bit_identical():
    """ngram speculation with adapters armed: greedy output stays
    bit-identical to the non-speculative solo oracle (the verify
    program reads the same per-row ids), and spec forwards really ran."""
    rs = np.random.RandomState(6)
    # repetitive prompts so the ngram drafter actually drafts
    base = _prompt(rs, 6)
    p1 = np.tile(base, 3)[:16].astype(np.int32)
    p2 = np.tile(_prompt(rs, 5), 3)[:14].astype(np.int32)
    jobs = [(p1, 8, {"adapter": "a"}), (p2, 8, {"adapter": "b"}),
            (p1, 8, {})]
    with InferenceServer(CFG, PARAMS, slots=3, queue=4, prefill_chunk=4,
                         prefix_mb=0.0, spec_mode="ngram", spec_len=2,
                         **LKW) as srv:
        got = _serve_all(srv, jobs)
        assert srv.metrics()["spec_forwards"] > 0
    for (p, m, ov), g in zip(jobs, got):
        np.testing.assert_array_equal(g, _solo(p, m, **ov))


def test_preempt_swap_resume_with_pool_eviction():
    """KV pool small enough to force preemption + a 2-slot adapter pool:
    a preempted row RELEASES its adapter ref (the page may be evicted
    while the row sits in host swap) and resume re-acquires by NAME —
    the resumed stream stays bit-exact through the round trip."""
    rs = np.random.RandomState(3)
    jobs = [(_prompt(rs, 12), 10, {"adapter": "ab"[i % 2]})
            for i in range(4)]
    jobs.append((_prompt(rs, 8), 6, {"adapter": "c"}))
    # 3 pool slots (base + 2 pages): a and b run CONCURRENTLY — their 4
    # rows overflow the 14-block KV pool, forcing preemption — while c
    # must evict whichever page the preempted/retired rows released
    probe = AdapterPool(CFG, parse_lora_spec(REG), rank=RANK,
                        adapters=ADS)
    mb = (3 * probe.slot_bytes + 1) / 2.0 ** 20
    with InferenceServer(CFG, PARAMS, slots=4, queue=8, prefill_chunk=4,
                         num_blocks=14, degrade=False, lora=REG,
                         lora_rank=RANK, lora_adapters=ADS,
                         lora_pool_mb=mb) as srv:
        assert srv.lora_pool.size == 3
        got = _serve_all(srv, jobs)
        m = srv.metrics()
        srv.lora_pool.check_refs(0)
    assert m["paged"]["swaps_out"] > 0 and m["paged"]["swaps_in"] > 0
    lm = m["lora"]
    assert lm["swap_ins"] >= 3 and lm["evictions"] >= 1
    assert lm["acquire_fails"] == 0
    for (p, mt, ov), g in zip(jobs, got):
        np.testing.assert_array_equal(g, _solo(p, mt, **ov))


# ------------------------------------------- kernel == reference, bitwise
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_kernel_bit_identical_to_ragged_reference(dtype):
    """``lora_bgmv`` (interpret mode) vs ``_delta_ragged``: both run
    the identical f32-accumulated two-dot contraction, so equality is
    BITWISE — any difference is structural, not rounding."""
    import jax.numpy as jnp

    rs = np.random.RandomState(7)
    P, L, n = 4, 1, 3
    for rows, d_in, r, d_out in ((6, 16, 8, 32), (5, 32, 8, 16)):
        x = jnp.asarray(rs.randn(rows, n, d_in), dtype)
        y = jnp.asarray(rs.randn(rows, n, d_out), dtype)
        a = jnp.asarray(rs.randn(P, L, d_in, r), jnp.float32)
        b = jnp.asarray(rs.randn(P, L, r, d_out), jnp.float32)
        ids = jnp.asarray(rs.randint(0, P, (rows,)), jnp.int32)
        pool = {"a_qkv": a, "b_qkv": b}
        assert not pk.lora_bgmv_supported(n, d_in, r, d_out)  # CPU: ref
        ref = np.asarray(lora_delta(pool, ids, 0, "qkv", x, y))
        np.testing.assert_array_equal(
            ref, np.asarray(_delta_ragged(a[:, 0], b[:, 0], ids, x, y, P)))
        old = pk._INTERPRET
        pk._INTERPRET = True
        try:
            assert pk.lora_bgmv_supported(n, d_in, r, d_out)
            ker = np.asarray(lora_delta(pool, ids, 0, "qkv", x, y))
        finally:
            pk._INTERPRET = old
        np.testing.assert_array_equal(ker, ref,
                                      err_msg=str((rows, d_in, r, d_out)))
    assert pk.lora_bgmv_fallback_reason(n, 16, 8, 16) == "backend"
    assert pk.lora_bgmv_fallback_reason(n, 16, 8, 16 << 20) != ""


# --------------------------------------------------------------- hygiene
def test_one_signature_mixed_adapters():
    """Any adapter mix is ONE compiled signature per program — the ids
    are traced data; only (rank, pool slots) are static."""
    rs = np.random.RandomState(9)
    jobs = [(_prompt(rs, n), 4, {"adapter": a})
            for n, a in ((5, "a"), (9, "b"), (13, "c"), (7, "a"))]
    jobs.append((_prompt(rs, 6), 4, {}))
    with InferenceServer(CFG, PARAMS, slots=3, queue=8, prefill_chunk=4,
                         prefix_mb=0.0, recompile_limit=1, **LKW) as srv:
        _serve_all(srv, jobs)
        eng = srv._engine
        assert len(eng.prefill_signatures) == 1
        assert "/lora=r%d/pool=4" % RANK in str(eng.prefill_signatures[0])


def test_adapter_rides_tenant_and_admission_defers():
    """An adapter request with no tenant label accounts as tenant
    <adapter>; a pinned 2-slot pool DEFERS the other adapter's
    admission (counted, never an acquire fault) until the slot frees."""
    rs = np.random.RandomState(8)
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         num_blocks=NB, prefix_mb=0.0, lora=REG,
                         lora_rank=RANK, lora_adapters=ADS,
                         lora_pool_mb=1e-9) as srv:
        h1 = srv.submit(_prompt(rs, 5), max_tokens=12, adapter="a")
        assert h1.tenant == "a" and h1.adapter == "a"
        h2 = srv.submit(_prompt(rs, 5), max_tokens=4, adapter="b",
                        tenant="gold")
        assert h2.tenant == "gold"      # explicit label wins
        h3 = srv.submit(_prompt(rs, 7), max_tokens=4, adapter="a")
        for h in (h1, h2, h3):
            assert srv.result(h, timeout=300).status == "ok"
        lm = srv.metrics()["lora"]
        assert lm["defers"] > 0 and lm["acquire_fails"] == 0
        srv.lora_pool.check_refs(0)


def test_wire_records_trie_keys_and_adoption_guard():
    from cxxnet_tpu.serve.fleet import request_from_wire, request_to_wire
    from cxxnet_tpu.serve.router import _AffinityTrie, rewind_request
    from cxxnet_tpu.serve.scheduler import Request, SamplingParams

    req = Request(7, np.arange(6, dtype=np.int32), SamplingParams(
        max_tokens=4), 0.0, tenant="t", adapter="a")
    back = request_from_wire(request_to_wire(req))
    assert back.adapter == "a" and back.tenant == "t"
    assert rewind_request(req).adapter == "a"
    # affinity keys are per-(adapter, prefix): a's history never
    # attracts b's or the base model's traffic; "" keeps pre-LoRA crcs
    trie = _AffinityTrie(chunk=4)
    p = np.arange(12, dtype=np.int32)
    trie.note(p, "a")
    assert trie.match(p, "a") == 12
    assert trie.match(p, "b") == 0 and trie.match(p, "") == 0
    # a replica that doesn't register the adapter refuses adoption
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         num_blocks=NB) as srv:
        with pytest.raises(AdmissionError, match="adapter"):
            srv._check_adoptable(req)


def test_chaos_recovery_with_adapters():
    """The fault-injection soak with adapters armed: every request
    completes and the streams stay bit-identical to an undisturbed
    armed server — replay re-acquires adapters by name through the
    rebuilt engine (the pool survives recovery)."""
    rs = np.random.RandomState(11)
    names = ["", "a", "b"]
    cases = [(_prompt(rs, int(rs.randint(5, 12))),
              int(rs.randint(3, 6)),
              {"adapter": names[i % 3]} if names[i % 3] else {})
             for i in range(6)]
    outs = {}
    for chaos in ("", "all:0.02,seed:3,hang_ms:50"):
        with InferenceServer(CFG, PARAMS, slots=2, queue=8,
                             prefill_chunk=4, num_blocks=NB,
                             prefix_mb=0.0, chaos=chaos,
                             max_restarts=50, **LKW) as srv:
            outs[chaos] = _serve_all(srv, cases)
            srv.lora_pool.check_refs(0)
    for clean, chaotic in zip(outs[""], outs["all:0.02,seed:3,hang_ms:50"]):
        np.testing.assert_array_equal(clean, chaotic)
