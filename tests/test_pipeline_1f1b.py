"""1F1B pipeline schedule (parallel/pipeline_1f1b.py): gradient
equivalence against plain autodiff, megatron-tp composition via the
f/g conjugate operators, and the GPT integration
(GPTConfig.pipeline_schedule='1f1b') matching dp to 1e-5.

Round-5 answer to VERDICT r4 weak #1 (gpipe burned bubble ticks on
garbage and psum'd the whole output buffer)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from cxxnet_tpu.models.gpt import (GPTConfig, gpt_init, gpt_opt_init,
                                   gpt_place, make_train_step)
from cxxnet_tpu.parallel.mesh import make_mesh
from cxxnet_tpu.parallel.pipeline_1f1b import (pipeline_1f1b,
                                               tp_region_in,
                                               tp_region_out)

L, B, F = 4, 8, 16


def _data():
    rs = np.random.RandomState(0)
    return (jnp.asarray(rs.randn(L, F, F).astype(np.float32) * 0.3),
            jnp.asarray(rs.randn(F).astype(np.float32)),
            jnp.asarray(rs.randn(B, F).astype(np.float32)),
            jnp.asarray(rs.randn(B).astype(np.float32)))


def _loss_fn(lp, h, t):
    return jnp.mean((h @ lp["head"] - t) ** 2)


def test_1f1b_matches_autodiff():
    """loss, block grads, loss-param grads and the entry cotangent all
    match a direct jax.value_and_grad over pp x dp x M variations."""
    W, head, x, tgt = _data()

    def block(w, h):
        return jnp.tanh(h @ w)

    def full_loss(params, lp, xx, t):
        h = xx
        for i in range(L):
            h = block(params[i], h)
        return _loss_fn(lp, h, t)

    ref_loss, (ref_gw, ref_glp, ref_gx) = jax.value_and_grad(
        full_loss, argnums=(0, 1, 2))(W, {"head": head}, x, tgt)

    for pp, dp, m in [(2, 1, 4), (2, 4, 2), (4, 2, 4), (4, 2, 1),
                      (2, 1, 8)]:
        mesh = make_mesh("cpu:0-%d" % (pp * dp - 1), pipeline_parallel=pp)

        @jax.jit
        def run(W, head, x, tgt, _m=m, _mesh=mesh):
            return pipeline_1f1b(block, W, _loss_fn, {"head": head}, x,
                                 tgt, _mesh, _m, param_specs=P("pipe"))

        loss, gw, glp, gx = run(W, head, x, tgt)
        tag = "pp%d dp%d M%d" % (pp, dp, m)
        assert abs(float(loss) - float(ref_loss)) < 1e-5, tag
        np.testing.assert_allclose(gw, ref_gw, atol=1e-5, err_msg=tag)
        np.testing.assert_allclose(glp["head"], ref_glp["head"],
                                   atol=1e-5, err_msg=tag)
        np.testing.assert_allclose(gx, ref_gx, atol=1e-5, err_msg=tag)


def test_1f1b_tp_composition():
    """Megatron column/row-sharded block bracketed by tp_region_in/out:
    the manual per-stage VJP computes correct cross-shard cotangents."""
    rs = np.random.RandomState(1)
    W1 = jnp.asarray(rs.randn(L, F, 2 * F).astype(np.float32) * 0.2)
    W2 = jnp.asarray(rs.randn(L, 2 * F, F).astype(np.float32) * 0.2)
    head = jnp.asarray(rs.randn(F).astype(np.float32))
    x = jnp.asarray(rs.randn(B, F).astype(np.float32))
    tgt = jnp.asarray(rs.randn(B).astype(np.float32))
    params = {"w1": W1, "w2": W2}

    def block_tp(w, h):
        hin = tp_region_in(h, "model")
        return h + tp_region_out(jnp.tanh(hin @ w["w1"]) @ w["w2"],
                                 "model")

    def full_loss(p, lp, xx, t):
        h = xx
        for i in range(L):
            h = h + jnp.tanh(h @ p["w1"][i]) @ p["w2"][i]
        return _loss_fn(lp, h, t)

    ref_loss, (ref_g, ref_glp, ref_gx) = jax.value_and_grad(
        full_loss, argnums=(0, 1, 2))(params, {"head": head}, x, tgt)

    specs = {"w1": P("pipe", None, "model"),
             "w2": P("pipe", "model", None)}
    for pp, dp, tp, m in [(2, 2, 2, 2), (2, 1, 4, 4), (4, 1, 2, 4)]:
        mesh = make_mesh("cpu:0-%d" % (pp * dp * tp - 1),
                         model_parallel=tp, pipeline_parallel=pp)

        @jax.jit
        def run(params, head, x, tgt, _m=m, _mesh=mesh):
            return pipeline_1f1b(block_tp, params, _loss_fn,
                                 {"head": head}, x, tgt, _mesh, _m,
                                 param_specs=specs)

        loss, gw, glp, gx = run(params, head, x, tgt)
        tag = "pp%d dp%d tp%d M%d" % (pp, dp, tp, m)
        assert abs(float(loss) - float(ref_loss)) < 2e-5, tag
        for k in gw:
            np.testing.assert_allclose(gw[k], ref_g[k], atol=2e-5,
                                       err_msg="%s %s" % (tag, k))
        np.testing.assert_allclose(gx, ref_gx, atol=2e-5, err_msg=tag)


def test_gpt_1f1b_matches_dp():
    """The integration bar (VERDICT r4 #2): GPT trained 3 steps under the
    1f1b schedule — pp2 and pp4 x tp2, both layouts — matches dp8 losses
    and parameters to 1e-5."""
    rs = np.random.RandomState(0)
    cfg = GPTConfig(vocab_size=32, seq_len=16, n_layer=4, n_head=4,
                    feat=32, n_microbatch=4)
    batch = 32
    ids = jnp.asarray(rs.randint(0, 32, (batch, 16)).astype(np.int32))

    def run(axes, c):
        mesh = make_mesh("cpu:0-7", **axes)
        params = gpt_place(gpt_init(jax.random.PRNGKey(0), c), mesh)
        mom = gpt_opt_init(params, mesh, "sgd")
        step = make_train_step(c, mesh, eta=0.1)
        for _ in range(3):
            params, mom, loss = step(params, mom, ids)
        return float(loss), jax.tree.map(np.asarray, params)

    base_loss, base = run({}, cfg)
    for label, axes, c in [
            ("pp2", dict(pipeline_parallel=2),
             dataclasses.replace(cfg, pipeline_schedule="1f1b")),
            ("pp4xtp2", dict(pipeline_parallel=4, model_parallel=2),
             dataclasses.replace(cfg, pipeline_schedule="1f1b")),
            ("pp2 bhnd", dict(pipeline_parallel=2),
             dataclasses.replace(cfg, pipeline_schedule="1f1b",
                                 attn_layout="bhnd"))]:
        loss, tree = run(axes, c)
        assert abs(loss - base_loss) < 1e-5, (label, loss, base_loss)
        d = max(float(np.max(np.abs(a - b)))
                for a, b in zip(jax.tree.leaves(tree),
                                jax.tree.leaves(base)))
        assert d < 1e-5, (label, d)


def test_gpt_1f1b_rejects_seq_parallel():
    cfg = GPTConfig(vocab_size=32, seq_len=16, n_layer=4, n_head=4,
                    feat=32, n_microbatch=2, pipeline_schedule="1f1b")
    mesh = make_mesh("cpu:0-7", pipeline_parallel=2, seq_parallel=2)
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
    mom = gpt_opt_init(params, mesh, "sgd")
    step = make_train_step(cfg, mesh, eta=0.1)
    ids = jnp.zeros((8, 16), jnp.int32)
    with pytest.raises(ValueError, match="1f1b"):
        step(params, mom, ids)


def test_dsl_rejects_1f1b_schedule():
    """The config DSL must reject (not silently ignore) a 1f1b
    pipeline_schedule request — the schedule lives on the gpt.py path."""
    from cxxnet_tpu import Net
    from cxxnet_tpu.models import gpt_lm_config
    from cxxnet_tpu.utils.config import ConfigError, tokenize

    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=8, dev="cpu:0-7",
                        pipeline_parallel=2)
    cfg += "\npipeline_schedule = 1f1b\n"
    with pytest.raises(ConfigError, match="gpt.py"):
        Net(tokenize(cfg)).init_model()
    # the gpipe spelling is accepted (it is what the DSL runs)
    net = Net(tokenize(cfg.replace("pipeline_schedule = 1f1b",
                                   "pipeline_schedule = gpipe")))
    net.init_model()
