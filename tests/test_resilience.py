"""Serving resilience layer (serve/resilience.py + wiring): fault
injection, deterministic request replay, watchdog restart, degradation
ladder, swap checksums, and the trainer's nan_recover + async-prefetch
interaction.

The acceptance matrix: for every chaos point (pool exhaustion, swap
failure, swap corruption, drafter fault, prefix-restore failure, tick
exception, tick hang) the engine either completes every in-flight
request with tokens BIT-IDENTICAL to the fault-free run (greedy exact;
sampled on the pinned fold_in schedule) or fails it with a typed error
— no hangs, no leaked blocks or threads (conftest fixture), restart
count bounded by serve_max_restarts.
"""

import time

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (AdmissionError, DecodeEngine,
                              EngineFailedError, FaultInjector,
                              InferenceServer, QueueFullError, Request,
                              SamplingParams, SlotScheduler)
from cxxnet_tpu.serve.resilience import (DegradationLadder, ReplayJournal,
                                         reset_for_replay)

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


@pytest.fixture(scope="module", autouse=True)
def _warm_programs():
    """Compile every serve program for CFG once (the jitted fns are
    module-level lru caches keyed by config), so watchdog thresholds in
    the tests below measure PASSES, not first-call compiles."""
    rs = np.random.RandomState(99)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         spec_mode="ngram", spec_len=2) as srv:
        h = srv.submit(_prompt(rs, 6), max_tokens=4)
        assert srv.result(h, timeout=300).status == "ok"


# ----------------------------------------------------------- unit: chaos
def test_chaos_spec_grammar_and_determinism():
    inj = FaultInjector.from_spec(
        "tick_raise:0.5,swap_in@3,seed:7,hang_ms:123")
    assert inj.seed == 7 and inj.hang_ms == 123.0
    # @N one-shot: fires exactly on the Nth call, never again
    assert [inj.fire("swap_in") for _ in range(5)] == \
        [False, False, True, False, False]
    assert inj.counts["swap_in"] == 1
    # probability rolls are deterministic per (seed, point)
    a = FaultInjector.from_spec("tick_raise:0.3,seed:11")
    b = FaultInjector.from_spec("tick_raise:0.3,seed:11")
    seq_a = [a.fire("tick_raise") for _ in range(200)]
    assert seq_a == [b.fire("tick_raise") for _ in range(200)]
    assert 20 < sum(seq_a) < 110          # ~0.3 of 200
    # all:p arms every point; disarm gates everything
    c = FaultInjector.from_spec("all:1.0")
    assert all(c.fire(p) for p in FaultInjector.POINTS)
    c.armed = False
    assert not any(c.fire(p) for p in FaultInjector.POINTS)


def test_chaos_spec_off_and_errors():
    assert FaultInjector.from_spec("") is None
    assert FaultInjector.from_spec("  ") is None
    with pytest.raises(ValueError, match="unknown injection point"):
        FaultInjector.from_spec("tick_rase:0.1")
    with pytest.raises(ValueError, match="unknown injection point"):
        FaultInjector.from_spec("nope@3")
    with pytest.raises(ValueError, match="malformed"):
        FaultInjector.from_spec("tick_raise")


# ---------------------------------------------------------- unit: ladder
def test_ladder_hysteresis_and_effects():
    lad = DegradationLadder(up_hold=2, down_hold=3)
    assert lad.rung == 0 and lad.spec_enabled and lad.prefix_admission
    # one hot eval is not enough (hysteresis)
    lad.evaluate(1.0, None)
    assert lad.rung == 0
    lad.evaluate(1.0, None)
    assert lad.rung == 1 and not lad.spec_enabled and lad.prefix_admission
    # the middle band resets the streak: no climb, no descent
    lad.evaluate(0.5, None)
    lad.evaluate(1.0, None)
    assert lad.rung == 1
    for _ in range(4):
        lad.evaluate(1.0, None)
    assert lad.rung == 3 and lad.shedding and not lad.prefix_admission
    assert lad.evaluate(1.0, None) == 3     # capped at MAX_RUNG
    # cool-down needs down_hold consecutive calm evals per rung
    for _ in range(3):
        lad.evaluate(0.0, None)
    assert lad.rung == 2
    for _ in range(6):
        lad.evaluate(0.0, None)
    assert lad.rung == 0


def test_ladder_stall_and_headroom_signals():
    lad = DegradationLadder(up_hold=1)
    lad.note_stall()
    lad.evaluate(0.0, None)                 # stall alone is hot
    assert lad.rung == 1
    lad.evaluate(0.0, 0.01)                 # headroom <= lo is hot
    assert lad.rung == 2
    # a disabled ladder never moves
    off = DegradationLadder(enabled=False, up_hold=1)
    off.note_stall()
    assert off.evaluate(1.0, 0.0) == 0


def test_ladder_tick_budget_signal():
    lad = DegradationLadder(up_hold=1, tick_budget_ms=5.0)
    lad.evaluate(0.0, None, tick_p95_ms=50.0)
    assert lad.rung == 1
    # without a budget the tick signal is inert
    lad2 = DegradationLadder(up_hold=1)
    lad2.evaluate(0.0, None, tick_p95_ms=50.0)
    assert lad2.rung == 0


# --------------------------------------------------- unit: replay pieces
def test_reset_for_replay_and_journal():
    j = ReplayJournal()
    req = Request(1, np.arange(4, dtype=np.int32), SamplingParams(),
                  time.perf_counter())
    req.params = SamplingParams(timeout_ms=5.0)
    req.deadline = time.perf_counter() + 0.005
    req.tokens = [3, 1, 4]
    j.add(req)
    assert len(j) == 1 and j.requests() == [req]
    reset_for_replay(req)
    assert req.replay_expect == [3, 1, 4]
    assert req.tokens == [] and req.status == "queued"
    assert req.deadline is None             # admitted once: never expires
    # a second crash mid-replay keeps the ORIGINAL (longer) pin
    req.tokens = [3, 1]
    reset_for_replay(req)
    assert req.replay_expect == [3, 1, 4]
    # ...unless the replay got further than the pin
    req.tokens = [3, 1, 4, 1, 5]
    reset_for_replay(req)
    assert req.replay_expect == [3, 1, 4, 1, 5]
    j.remove(req)
    assert len(j) == 0


def test_replay_mismatch_fails_typed():
    """A replayed request whose regenerated token diverges from the
    journaled prefix is failed typed, not silently forked."""
    eng = DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=4,
                       num_blocks=30)
    sched = SlotScheduler(eng)
    req = Request(7, np.arange(4, dtype=np.int32), SamplingParams(),
                  time.perf_counter())
    req.replay_expect = [5, 9]
    assert sched._emit(0, req, 5) is None           # matches the pin
    err = sched._emit(0, req, 8)                    # diverges
    assert err is not None and "replay diverged at token 1" in err
    assert sched.replay_mismatches == 1
    eng.close()


def test_request_finish_is_first_wins():
    req = Request(2, np.arange(3, dtype=np.int32), SamplingParams(),
                  time.perf_counter())
    req.finish("error", "engine failed")
    req.finish("cancelled", "server shutdown")
    assert req.status == "error" and req.error == "engine failed"


# ------------------------------------------------------- the chaos matrix
def test_tick_exception_recovers_and_replays_bit_identical():
    """An injected tick exception mid-stream: the supervisor rebuilds
    the engine cold and replays every in-flight request — final tokens
    bit-identical to the fault-free oracle, restart counted, recovery
    spans on the engine track."""
    from cxxnet_tpu.obs.trace import TID_ENGINE, Tracer
    rs = np.random.RandomState(0)
    tracer = Tracer(enabled=True)
    cases = [
        dict(p=_prompt(rs, 3), max_tokens=8),
        dict(p=_prompt(rs, 9), max_tokens=6, temperature=0.8, top_k=5,
             top_p=0.9, seed=7),
        dict(p=_prompt(rs, 13), max_tokens=5, temperature=1.2, seed=3),
        dict(p=_prompt(rs, 6), max_tokens=7),
    ]
    with InferenceServer(CFG, PARAMS, slots=2, queue=16, prefill_chunk=4,
                         chaos="tick_raise@3", tracer=tracer) as srv:
        hs = [srv.submit(c["p"], **{k: v for k, v in c.items()
                                    if k != "p"}) for c in cases]
        res = [srv.result(h, timeout=300) for h in hs]
        m = srv.metrics()
        text = srv.metrics_text()
        assert srv.health()["state"] == "SERVING"
    assert [r.status for r in res] == ["ok"] * 4
    for c, r in zip(cases, res):
        kw = {k: v for k, v in c.items() if k not in ("p", "max_tokens")}
        np.testing.assert_array_equal(r.tokens,
                                      _ref(c["p"], c["max_tokens"], **kw))
    assert m["resilience"]["restarts"] == 1
    assert m["resilience"]["replayed"] >= 1
    assert m["resilience"]["replay_mismatches"] == 0
    assert m["resilience"]["faults_injected"]["tick_raise"] == 1
    assert "cxn_engine_restarts_total 1" in text
    assert 'cxn_faults_injected_total{point="tick_raise"} 1' in text
    # the recovery span tree landed on the engine track
    names = [s.name for s in tracer.spans(TID_ENGINE)]
    for want in ("teardown", "rebuild", "replay", "recovery"):
        assert want in names, names


def test_tick_hang_without_watchdog_is_a_transient_stall():
    """hang_ms expires with no watchdog armed: the tick resumes
    normally — a stall, not a fault; zero restarts."""
    rs = np.random.RandomState(1)
    p = _prompt(rs, 5)
    with InferenceServer(CFG, PARAMS, slots=1, queue=4, prefill_chunk=4,
                         chaos="tick_hang@2,hang_ms:300") as srv:
        t0 = time.perf_counter()
        res = srv.result(srv.submit(p, max_tokens=6), timeout=300)
        dt = time.perf_counter() - t0
        m = srv.metrics()
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(p, 6))
    assert dt >= 0.3                        # the stall really happened
    assert m["resilience"]["restarts"] == 0
    assert m["resilience"]["faults_injected"]["tick_hang"] == 1


def test_tick_hang_watchdog_converts_to_restart():
    """A hang far longer than serve_watchdog_ms: the watchdog abandons
    the stuck thread, rebuilds, and replays — tokens identical, restart
    counted, and the total wall time is far below the hang length."""
    rs = np.random.RandomState(2)
    cases = [(_prompt(rs, 5), 8), (_prompt(rs, 9), 6)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         chaos="tick_hang@3,hang_ms:60000",
                         watchdog_ms=800.0) as srv:
        t0 = time.perf_counter()
        hs = [srv.submit(p, max_tokens=n) for p, n in cases]
        res = [srv.result(h, timeout=300) for h in hs]
        dt = time.perf_counter() - t0
        m = srv.metrics()
    assert [r.status for r in res] == ["ok"] * 2
    for (p, n), r in zip(cases, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, n))
    assert m["resilience"]["restarts"] == 1
    assert m["resilience"]["replayed"] >= 1
    assert dt < 30.0, dt                    # nowhere near the 60 s hang


def test_reserve_exhaustion_injection_is_absorbed():
    """Injected BlockPoolExhausted mid-reserve drives the real make-room
    escapes (trie evict, preempt, swap) — or at worst a replay — and
    every request still matches the oracle."""
    rs = np.random.RandomState(3)
    prompts = [_prompt(rs, 6) for _ in range(4)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         prefix_mb=1.0, max_restarts=10,
                         chaos="reserve:0.2,seed:5") as srv:
        hs = [srv.submit(p, max_tokens=8) for p in prompts]
        res = [srv.result(h, timeout=300) for h in hs]
        m = srv.metrics()
    assert [r.status for r in res] == ["ok"] * 4
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, 8))
    assert m["resilience"]["faults_injected"]["reserve"] >= 1
    assert m["resilience"]["restarts"] <= 10


def test_swap_out_failure_recovers_bit_identical():
    """A tiny pool forces preemption; the first swap-out raises an
    injected I/O failure — engine-fatal, so the supervisor replays the
    whole working set. Every request still equals the oracle."""
    rs = np.random.RandomState(6)
    prompts = [_prompt(rs, 6) for _ in range(3)]
    srv = InferenceServer(CFG, PARAMS, slots=3, queue=8, prefill_chunk=4,
                          prefix_mb=0.0, num_blocks=15,
                          chaos="swap_out@1")
    hs = [srv.submit(p, max_tokens=20) for p in prompts]
    res = [srv.result(h, timeout=300) for h in hs]
    m = srv.metrics()
    srv.shutdown()
    assert [r.status for r in res] == ["ok"] * 3
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, 20))
    assert m["resilience"]["faults_injected"]["swap_out"] == 1
    assert m["resilience"]["restarts"] == 1
    assert m["resilience"]["replayed"] >= 1


def test_swap_in_corruption_checksum_catches_and_replays_row():
    """A corrupted swap-in host buffer fails its checksum: the row is
    NOT resumed from garbage — the one request replays through the
    journal (no engine restart) and still matches the oracle."""
    rs = np.random.RandomState(7)
    prompts = [_prompt(rs, 6) for _ in range(3)]
    srv = InferenceServer(CFG, PARAMS, slots=3, queue=8, prefill_chunk=4,
                          prefix_mb=0.0, num_blocks=15,
                          chaos="swap_in@1")
    hs = [srv.submit(p, max_tokens=20) for p in prompts]
    res = [srv.result(h, timeout=300) for h in hs]
    m = srv.metrics()
    srv.shutdown()
    assert [r.status for r in res] == ["ok"] * 3
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, 20))
    assert m["resilience"]["faults_injected"]["swap_in"] == 1
    assert m["resilience"]["swap_corruptions"] == 1
    assert m["resilience"]["restarts"] == 0     # contained, no rebuild
    assert m["resilience"]["replayed"] >= 1
    assert m["resilience"]["replay_mismatches"] == 0


def test_drafter_fault_contained_and_identity_kept():
    """Drafter exceptions are contained (rows tick plain that pass) and
    a persistently-failing drafter is disabled — greedy output stays
    bit-identical throughout."""
    rs = np.random.RandomState(8)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base, base])     # n-gram bait
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="ngram", spec_len=2,
                         chaos="drafter:1.0") as srv:
        res = srv.result(srv.submit(prompt, max_tokens=10), timeout=300)
        m = srv.metrics()
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(prompt, 10))
    assert m["resilience"]["drafter_faults"] >= 1
    assert m["resilience"]["restarts"] == 0
    assert m["spec_forwards"] == 0          # every draft pass faulted


def test_prefix_restore_fault_degrades_to_miss():
    """An injected prefix-restore failure is treated as a cache miss:
    the prompt prefills from scratch and the tokens are unchanged."""
    rs = np.random.RandomState(9)
    shared = _prompt(rs, 8)
    prompts = [np.concatenate([shared, _prompt(rs, k)]) for k in (3, 5)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         prefix_mb=1.0,
                         chaos="prefix_restore:1.0") as srv:
        hs = [srv.submit(p, max_tokens=5) for p in prompts]
        res = [srv.result(h, timeout=300) for h in hs]
        m = srv.metrics()
    assert [r.status for r in res] == ["ok"] * 2
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, 5))
    assert m["resilience"]["prefix_restore_faults"] >= 1
    assert m["resilience"]["restarts"] == 0


def test_max_restarts_exhausted_fails_typed_no_hang():
    """Every tick raises: the restart budget burns down and the server
    FAILS typed — in-flight requests get EngineFailedError-status
    results (no hang), later submits raise it, shutdown stays clean."""
    rs = np.random.RandomState(10)
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                          chaos="tick_raise:1.0", max_restarts=2)
    hs = [srv.submit(_prompt(rs, 4), max_tokens=6) for _ in range(3)]
    res = [srv.result(h, timeout=120) for h in hs]
    assert [r.status for r in res] == ["error"] * 3
    assert all("serve_max_restarts" in r.error for r in res)
    assert srv.health()["state"] == "FAILED"
    with pytest.raises(EngineFailedError, match="serve_max_restarts"):
        srv.submit(_prompt(rs, 4))
    m = srv.metrics()
    srv.shutdown()
    assert m["resilience"]["restarts"] == 3     # 2 allowed + the fatal one
    assert m["requests"]["error"] == 3
    assert srv.health()["state"] == "FAILED"    # sticky after shutdown


def test_chaos_env_var_overrides_config(monkeypatch):
    monkeypatch.setenv("CXN_CHAOS", "tick_raise@1")
    rs = np.random.RandomState(11)
    p = _prompt(rs, 5)
    with InferenceServer(CFG, PARAMS, slots=1, queue=4,
                         prefill_chunk=4, chaos="") as srv:
        assert srv.fault_injector is not None
        assert srv.fault_injector.spec == "tick_raise@1"
        res = srv.result(srv.submit(p, max_tokens=4), timeout=300)
        m = srv.metrics()
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(p, 4))
    assert m["resilience"]["restarts"] == 1


# ------------------------------------------------------ degradation ladder
def test_overload_degrades_sheds_and_hints_retry():
    """The acceptance overload trace: sustained queue pressure climbs
    the ladder to shedding; rejections and shed results carry
    retry_after_ms; every ADMITTED request still completes; health and
    the state gauge read DEGRADED."""
    rs = np.random.RandomState(12)
    srv = InferenceServer(CFG, PARAMS, slots=1, queue=6, prefill_chunk=4,
                          prefix_mb=0.0)
    srv.ladder.up_hold = 1              # climb one rung per hot pass
    try:
        # seed the service-time EMA with one clean request
        assert srv.result(srv.submit(_prompt(rs, 4), max_tokens=8),
                          timeout=300).status == "ok"
        holder = srv.submit(_prompt(rs, 4), max_tokens=36)
        deadline = time.time() + 60     # wait for admission so the six
        while holder.status == "queued" and time.time() < deadline:
            time.sleep(0.002)           # fills below own the whole queue
        fill = [srv.submit(_prompt(rs, 4), max_tokens=24)
                for _ in range(6)]
        # plain queue-full rejection carries the back-off hint
        with pytest.raises(QueueFullError) as e1:
            srv.submit(_prompt(rs, 4), max_tokens=2)
        assert e1.value.retry_after_ms > 0
        # the ladder reaches shedding while the holder decodes with a
        # full queue (3 hot passes at up_hold=1)
        deadline = time.time() + 60
        while srv.ladder.rung < 3 and time.time() < deadline:
            time.sleep(0.002)
        assert srv.ladder.rung == 3
        h = srv.health()
        assert h["state"] == "DEGRADED" and h["retry_after_ms"] > 0
        # a deadline the backlog cannot meet is shed AT THE DOOR
        with pytest.raises(QueueFullError) as e2:
            srv.submit(_prompt(rs, 4), max_tokens=24, timeout_ms=1.0)
        assert "overload shed" in str(e2.value)
        assert e2.value.retry_after_ms > 0
        # gauges read while the overload holds (the ladder cools on its
        # own hysteresis once the queue drains)
        text = srv.metrics_text()
        assert 'cxn_shed_requests_total{rung="3"}' in text
        assert "cxn_serve_degrade_rung 3" in text
        assert "cxn_serve_state 1" in text      # DEGRADED
        # queue-resident shedding: slip one past the door estimate, then
        # make the backlog estimate hopeless — it is shed with a hint
        # instead of rotting to expiry
        srv._ema_req_s = 0.0
        doomed = srv.submit(_prompt(rs, 4), max_tokens=24,
                            timeout_ms=2000.0, block=True)
        srv._ema_req_s = 100.0
        res_doomed = srv.result(doomed, timeout=300)
        assert res_doomed.status == "shed", res_doomed
        assert res_doomed.retry_after_ms > 0
        assert "retry after" in res_doomed.error
        srv._ema_req_s = 0.05
        # every admitted request completes despite the overload
        assert srv.result(holder, timeout=300).status == "ok"
        assert all(srv.result(h2, timeout=300).status == "ok"
                   for h2 in fill)
        m = srv.metrics()
        assert m["requests"]["shed"] >= 2       # door + queue shed
        assert m["resilience"]["shed"] >= 2
    finally:
        srv.shutdown()
    assert srv.health()["state"] == "DRAINING"


def test_degrade_off_never_moves():
    rs = np.random.RandomState(13)
    with InferenceServer(CFG, PARAMS, slots=1, queue=2, prefill_chunk=4,
                         degrade=False) as srv:
        hs = [srv.submit(_prompt(rs, 4), max_tokens=10, block=True)
              for _ in range(5)]
        assert all(srv.result(h, timeout=300).status == "ok"
                   for h in hs)
        assert srv.ladder.rung == 0
        assert srv.health()["state"] == "SERVING"


def test_reserve_stall_counter_and_degraded_trigger():
    """The make-room loop's terminal stall (queue head unplaceable with
    every slot free) is COUNTED and drives the ladder hot — no more
    silent 50 ms parking. The organic trigger needs an estimate bug
    (num_blocks >= bpr + 1 guarantees one row always fits), so the
    admission gate is held shut for a few passes to pin the path."""
    rs = np.random.RandomState(14)
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4)
    srv.ladder.up_hold = 1
    try:
        sched = srv._sched
        orig = sched.admissible
        deny = {"n": 0}

        def gate(req, claimed=0):
            if deny["n"] < 3:
                deny["n"] += 1
                return False
            return orig(req, claimed)

        sched.admissible = gate
        res = srv.result(srv.submit(_prompt(rs, 5), max_tokens=4),
                         timeout=300)
        assert res.status == "ok"
        m = srv.metrics()
        assert m["resilience"]["reserve_stalls"] >= 3
        assert srv.ladder.transitions >= 1      # the stall ran it hot
        assert "cxn_reserve_stalls_total" in srv.metrics_text()
    finally:
        srv.shutdown()


# ------------------------------------------------------------- the soak
@pytest.mark.slow
def test_chaos_soak_mixed_traffic_bit_identical():
    """Every injection point armed at low probability over mixed
    chunked / prefix-hit / speculative / paged traffic: zero hangs,
    zero leaked blocks (refcount audit) or threads (conftest), final
    outputs bit-identical to the fault-free oracle, restarts within
    budget."""
    rs = np.random.RandomState(15)
    shared = _prompt(rs, 8)
    cases = []
    for i in range(24):
        kind = i % 4
        if kind == 0:
            p = np.concatenate([shared, _prompt(rs, 1 + i % 7)])
        elif kind == 1:
            base = _prompt(rs, 4 + i % 3)
            p = np.concatenate([base, base, base])      # n-gram bait
        else:
            p = _prompt(rs, 3 + (i * 5) % 17)
        kw = {}
        if kind == 3:
            # sampled cases pin against the solo oracle, so they opt
            # out of speculation per-request: sampled + spec is
            # distribution-preserving, not bit-exact (greedy cases keep
            # speculating — their argmax chain IS exact)
            kw = dict(temperature=0.8, top_k=5, top_p=0.9, seed=i,
                      spec_mode="off")
        cases.append((p, 4 + i % 9, kw))
    srv = InferenceServer(
        CFG, PARAMS, slots=3, queue=32, prefill_chunk=4, prefix_mb=0.5,
        num_blocks=24, spec_mode="ngram", spec_len=2, max_restarts=50,
        watchdog_ms=2000.0,
        chaos="all:0.01,seed:21,hang_ms:400")
    try:
        hs = []
        for p, n, kw in cases:
            while True:
                try:
                    hs.append(srv.submit(p, max_tokens=n, **kw))
                    break
                except AdmissionError as e:
                    # the 'admit' chaos point fails ONE submit typed
                    # (containment is the point); retrying is what a
                    # real client does
                    assert "admit" in str(e)
        res = [srv.result(h, timeout=600) for h in hs]
        m = srv.metrics()
        assert [r.status for r in res] == ["ok"] * len(cases)
        for (p, n, kw), r in zip(cases, res):
            okw = {k: v for k, v in kw.items() if k != "spec_mode"}
            np.testing.assert_array_equal(r.tokens, _ref(p, n, **okw))
        assert m["resilience"]["restarts"] <= 50
        assert m["resilience"]["replay_mismatches"] == 0
        assert sum(m["resilience"]["faults_injected"].values()) >= 1
        # refcount/leak audit on the FINAL engine after all rows retired
        eng, pc = srv._engine, srv._prefix
        eng.manager.check_consistency(trie_refs=pc.trie_refs())
    finally:
        srv.shutdown()
    # post-drain: every block back on the free list
    eng = srv._engine
    assert eng.manager.free_count == eng.num_blocks - 1
    eng.manager.check_consistency(trie_refs=0)


# -------------------------------------------- trainer: nan_recover + feed
def test_nan_recover_rebuilds_async_feed(tmp_path, capfd, monkeypatch):
    """cli.py:_task_train_rounds recovery path under the async device
    feed: when nan_recover reloads the snapshot (replacing self.net),
    the OLD DevicePrefetcher — bound to the dead trainer, holding
    in-flight placed batches — must be closed and a NEW one built over
    the reloaded net; the old feed's batches are discarded, not fed."""
    from test_train_e2e import CONF, write_idx_images, write_idx_labels

    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.io.device_prefetch import DevicePrefetcher
    from cxxnet_tpu.nnet.net import Net as CoreNet

    d = tmp_path / "mnist"
    d.mkdir()
    rs = np.random.RandomState(42)
    protos = rs.rand(10, 8, 8) * 255
    y = rs.randint(0, 10, 96)
    x = np.clip(protos[y] + rs.randn(96, 8, 8) * 20, 0,
                255).astype(np.uint8)
    write_idx_images(str(d / "train-img.gz"), x)
    write_idx_labels(str(d / "train-lab.gz"), y)
    write_idx_images(str(d / "test-img.gz"), x[:32])
    write_idx_labels(str(d / "test-lab.gz"), y[:32])
    md = tmp_path / "models"
    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=d, md=md))
    # a snapshot to recover from
    assert LearnTask().run([str(conf), "num_round=1", "max_round=1",
                            "save_model=1", "silent=1"]) == 0
    capfd.readouterr()

    events = []
    orig_init = DevicePrefetcher.__init__
    orig_close = DevicePrefetcher.close

    def rec_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        events.append(("new", self))

    def rec_close(self):
        events.append(("close", self))
        orig_close(self)

    monkeypatch.setattr(DevicePrefetcher, "__init__", rec_init)
    monkeypatch.setattr(DevicePrefetcher, "close", rec_close)

    orig_ll = CoreNet.last_loss
    calls = {"n": 0}

    def nan_once(self):
        calls["n"] += 1
        if calls["n"] == 2:
            return float("nan")
        return orig_ll(self)

    monkeypatch.setattr(CoreNet, "last_loss", nan_once)

    task = LearnTask()
    assert task.run([str(conf), "num_round=2", "max_round=4",
                     "nan_check=1", "nan_recover=1", "save_model=0",
                     "prefetch_to_device=2", "silent=1"]) == 0
    err = capfd.readouterr().err
    assert "divergent loss detected" in err
    assert "recovered from snapshot" in err
    # exactly two feeds: the diverged round's, then the restarted
    # round's — and the old one was CLOSED before the new one existed
    kinds = [k for k, _ in events]
    assert kinds[:3] == ["new", "close", "new"], kinds
    feeds = [obj for k, obj in events if k == "new"]
    assert len(feeds) == 2
    # the old feed placed batches that were then discarded, and the new
    # feed is bound to the RELOADED net — the old net's in-flight
    # batches can never reach the restarted trainer
    assert feeds[0].placed >= 1
    assert feeds[1].place_fn.__self__ is task.net
    assert feeds[0].place_fn.__self__ is not task.net
