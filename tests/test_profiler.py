"""Tracing/profiling (SURVEY §5.1 upgrade) and failure detection (§5.3):
StepStats timers, replica-consistency check, NaN watchdog recovery."""

import time

import numpy as np
import pytest

from cxxnet_tpu.cli import LearnTask
from cxxnet_tpu.utils import profiler
from cxxnet_tpu.utils.config import load_config

from test_train_e2e import CONF, synth_mnist  # noqa: F401 (fixture)


def test_step_stats_phases_and_summary():
    stats = profiler.StepStats(batch_size=32)
    for _ in range(5):
        with stats.phase("data"):
            time.sleep(0.001)
        with stats.phase("step"):
            time.sleep(0.002)
        stats.end_step()
    assert stats.num_steps == 5
    totals = stats.phase_totals()
    assert totals["data"] >= 0.005
    assert totals["step"] >= 0.010
    s = stats.summary()
    assert "5 steps" in s and "data" in s and "step" in s
    assert "data-wait" in s
    stats.clear()
    assert stats.num_steps == 0
    assert stats.summary() == "no steps recorded"


def test_step_stats_empty_phase_is_cheap():
    stats = profiler.StepStats()
    stats.end_step()
    assert "1 steps" in stats.summary()


def test_percentiles_empty_window_is_zero():
    """The empty-window contract: summarizing a phase that never ran
    (zero ticks, zero requests) yields consistent finite zeros — never
    a raise, never NaN in a stats line."""
    out = profiler.percentiles([])
    assert out == {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    assert profiler.percentiles([], qs=(0.25, 0.5)) == {"p25": 0.0,
                                                        "p50": 0.0}
    stats = profiler.StepStats()
    assert stats.percentiles("never_ran") == {"p50": 0.0, "p95": 0.0,
                                              "p99": 0.0}
    # non-finite samples are dropped instead of propagating into the
    # summary (a poisoned entry must not surface NaN percentiles)
    out = profiler.percentiles([float("nan"), 1.0, float("inf"), 2.0])
    assert all(np.isfinite(v) for v in out.values())
    assert out["p99"] == 2.0
    assert all(np.isfinite(v)
               for v in profiler.percentiles([float("nan")]).values())


def test_server_gauges_zero_ticks_consistent():
    """A server that served nothing (zero ticks, zero admits) reports
    0.0 occupancy/batch-efficiency and all-finite metrics — the gauges
    the CLI stats line formats must never see NaN."""
    import math

    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.serve import InferenceServer

    cfg = GPTConfig(vocab_size=16, seq_len=16, n_layer=1, n_head=2,
                    feat=8, n_microbatch=1)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    with InferenceServer(cfg, params, slots=2, queue=4) as srv:
        m = srv.metrics()
    assert m["slot_occupancy"] == 0.0
    assert m["batch_efficiency"] == 0.0
    assert m["ticks"] == 0

    def flat(v):
        if isinstance(v, dict):
            for x in v.values():
                yield from flat(x)
        elif isinstance(v, (int, float)):
            yield v

    assert all(math.isfinite(v) for v in flat(m)), m
    # the CLI stats line's formatting of the empty window cannot raise
    line = ("serve: ttft p50 %.1f / p95 %.1f; batch efficiency %.2f "
            "over %d ticks" % (m["ttft_ms"]["p50"], m["ttft_ms"]["p95"],
                               m["batch_efficiency"], m["ticks"]))
    assert "nan" not in line


def test_trace_noop_without_logdir():
    with profiler.trace(None):
        pass
    with profiler.trace(""):
        pass


def test_cli_step_stats_and_consistency(synth_mnist, tmp_path, capfd):  # noqa: F811
    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=tmp_path / "models"))
    task = LearnTask()
    assert task.run([str(conf), "num_round=1", "max_round=1",
                     "step_stats=1", "check_consistency=1"]) == 0
    out = capfd.readouterr()
    assert "round 0:" in out.out and "steps/s" in out.out
    # replicated weights must be identical on all 8 virtual devices
    line = [l for l in out.err.splitlines() if "replica-consistency" in l]
    assert line, out.err
    diff = float(line[0].split("max|Δ|=")[1].split()[0].split(" at")[0])
    assert diff == 0.0


def test_last_loss_and_consistency_api(synth_mnist, tmp_path):  # noqa: F811
    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=tmp_path / "models"))
    task = LearnTask()
    task.run([str(conf), "num_round=1", "max_round=1", "save_model=0"])
    assert np.isfinite(task.net.last_loss())
    diff, worst = task.net.check_replica_consistency()
    assert diff == 0.0


def test_nan_recovery_from_snapshot(synth_mnist, tmp_path, capfd):  # noqa: F811
    md = tmp_path / "models"
    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=md))
    # produce a snapshot to recover from
    LearnTask().run([str(conf), "num_round=1", "max_round=1", "save_model=1"])
    capfd.readouterr()

    task = LearnTask()
    for name, val in load_config(str(conf)):
        task.set_param(name, val)
    task.set_param("nan_recover", "1")
    assert task._recover_from_divergence(7) is True
    assert task.start_counter == 2          # resumes after snapshot 0001
    assert task.net is not None
    err = capfd.readouterr().err
    assert "divergent loss" in err and "recovered from snapshot" in err


def test_live_divergence_recovery(synth_mnist, tmp_path, capfd):  # noqa: F811
    """eta=1e10 explodes the loss (finite, saturating net) -> loss_bound
    triggers recovery from the snapshot; max_round bounds the retries."""
    md = tmp_path / "models"
    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=md))
    LearnTask().run([str(conf), "num_round=1", "max_round=1", "save_model=1"])
    capfd.readouterr()

    task = LearnTask()
    assert task.run([str(conf), "eta=1e10", "nan_check=2", "nan_recover=1",
                     "loss_bound=100", "max_round=2", "num_round=20",
                     "save_model=0", "silent=1"]) == 0
    err = capfd.readouterr().err
    assert err.count("divergent loss") == 2
    assert err.count("recovered from snapshot") == 2


def test_nan_halt_without_snapshot(tmp_path, capfd):
    task = LearnTask()
    task.set_param("model_dir", str(tmp_path / "empty"))
    with pytest.raises(RuntimeError, match="diverged"):
        task._recover_from_divergence(3)
    task2 = LearnTask()
    task2.set_param("nan_recover", "1")
    task2.set_param("model_dir", str(tmp_path / "empty"))
    with pytest.raises(RuntimeError, match="diverged"):
        task2._recover_from_divergence(3)
