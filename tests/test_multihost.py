"""True multi-process (multi-"host") data parallelism over gloo CPU
collectives: two JAX processes with 2 virtual devices each join one
4-device mesh via ``init_distributed``; each feeds only its half of the
global batch (``global_batch`` / make_array_from_process_local_data).
After 3 SGD steps both replicas must hold identical params, equal to a
single-process run on the full batch — the replica-consistency check the
reference ran with ``test_on_server=1`` (async_updater-inl.hpp:144-154),
here for the dist-PS-replacement runtime (SURVEY §2.7.2, §5.8)."""

import os

import numpy as np

from fleet_harness import (INFRA_SIGNS as _INFRA_SIGNS,
                           PEER_GRACE_S as _PEER_GRACE_S,
                           free_port as _free_port,
                           genuine_failure as _genuine_failure,
                           run_workers)

CONF = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
dist_feed = sharded
eta = 0.1
momentum = 0.9
seed = 5
"""


def make_batches():
    rs = np.random.RandomState(7)
    for _ in range(3):
        x = rs.rand(16, 1, 1, 8).astype(np.float32)
        y = rs.randint(0, 4, (16, 1)).astype(np.float32)
        yield x, y


# round-4 cross-process topologies: one tiny transformer shape shared by
# the workers (sp4/ep4/pp4 segments) and the single-process references
SEQ_KW = dict(seq_len=16, vocab_size=32, feat=16, nhead=4, nblock=2,
              num_classes=4, batch_size=8, dev="", precision="float32")


def make_seq_batches():
    rs = np.random.RandomState(3)
    for _ in range(2):
        x = rs.randint(0, 32, (8, 1, 1, 16)).astype(np.float32)
        y = rs.randint(0, 4, (8, 1)).astype(np.float32)
        yield x, y


def flat_params(net):
    out = {}
    for lkey, tags in net.params.items():
        for tag, w in tags.items():
            out["%s/%s" % (lkey, tag)] = np.asarray(w)
    return out


def _run_workers(ranks, tmp_path, extra=None, timeout=240, attempts=3):
    """The shared flake-hardened spawn loop (tests/fleet_harness.py —
    pipe-drain readers, peer-kill grace, infrastructure-signature
    retries), pointed at this suite's multihost_worker.py."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "multihost_worker.py")
    return run_workers(worker, ranks, tmp_path,
                       extra=([extra] if extra else None),
                       timeout=timeout, attempts=attempts)


def test_two_process_data_parallel_matches_single(tmp_path):
    # reference: single-process run on the full batch (this pytest process)
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize

    net = Net(tokenize(CONF))
    net.init_model()
    for xb, yb in make_batches():

        class B:
            data, label, extra_data = xb, yb, []
            num_batch_padd = 0

        net.update(B)
    ref = flat_params(net)

    # two worker processes, clean env (no ambient TPU plugin)
    outs = _run_workers((0, 1), tmp_path, timeout=240)

    got = [dict(np.load(str(tmp_path / ("params_rank%d.npz" % r))))
           for r in (0, 1)]
    # replica consistency: both processes hold identical params...
    for name in ref:
        np.testing.assert_array_equal(got[0][name], got[1][name],
                                      err_msg=name)
        # ...equal (mod reduction order) to the single-process full batch
        np.testing.assert_allclose(got[0][name], ref[name],
                                   rtol=2e-5, atol=2e-6, err_msg=name)

    # global eval metrics: each rank fed different local rows, but the
    # cross-process (sum, count) reduction makes both print the SAME line
    eval_lines = [next(l for l in o.splitlines()
                       if l.startswith("EVALLINE rank%d" % r))
                  .split(" ", 2)[2] for r, o in zip((0, 1), outs)]
    assert eval_lines[0] == eval_lines[1], eval_lines
    assert "test-error:" in eval_lines[0]

    # cross-host replica check: clean pass reports ~0 on both ranks, and
    # after rank 1 perturbs its local shard of fc1 by +0.125 BOTH ranks
    # flag the divergence (the reference's test_on_server capability,
    # async_updater-inl.hpp:144-154)
    for r, o in zip((0, 1), outs):
        clean = next(l for l in o.splitlines()
                     if l.startswith("CONSISTENCY_CLEAN rank%d" % r))
        assert float(clean.split()[2]) == 0.0, clean
        desync = next(l for l in o.splitlines()
                      if l.startswith("CONSISTENCY_DESYNC rank%d" % r))
        val = float(desync.split()[2])
        assert 0.1 < val < 0.15, desync      # |mean diff| proxy == 0.125
        assert "fc1" in desync, desync
        # row-reversal on rank 1 preserves sum and sumsq exactly; only the
        # order-sensitive CRC channel flags it (tiny positive diff)
        permline = next(l for l in o.splitlines()
                        if l.startswith("CONSISTENCY_PERM rank%d" % r))
        pval = float(permline.split()[2])
        assert 0.0 < pval < 1e-9, permline
        assert "fc1" in permline, permline
        assert any(l.startswith("ZERO3_SAVED rank%d" % r)
                   for l in o.splitlines()), o[-1500:]

    # ZeRO-3 checkpoints gathered from cross-host shards must be
    # byte-identical on both ranks (same global params, full gather)
    b0 = (tmp_path / "zero3_rank0.model").read_bytes()
    b1 = (tmp_path / "zero3_rank1.model").read_bytes()
    assert b0 == b1 and len(b0) > 1000

    # hybrid dp-across-processes x tp-within: both ranks converge to the
    # same params as the single-process full-batch reference
    for r in (0, 1):
        assert any(l.startswith("HYBRID_OK rank%d" % r)
                   for l in outs[r].splitlines()), outs[r][-1500:]
        hyb = dict(np.load(tmp_path / ("hybrid_rank%d.npz" % r)))
        for name in ref:
            np.testing.assert_allclose(hyb[name], ref[name], rtol=2e-5,
                                       atol=2e-6, err_msg="hybrid " + name)


def _seq_reference(tmp_path, **kw):
    """Single-process trajectory of the same tiny transformer (all axes 1)."""
    from cxxnet_tpu import Net
    from cxxnet_tpu.models import transformer_config
    from cxxnet_tpu.utils.config import tokenize

    merged = dict(SEQ_KW, **kw)
    merged["dev"] = "cpu:0"
    net = Net(tokenize(transformer_config(**merged)))
    net.set_param("seed", "11")
    net.init_model()
    for xb, yb in make_seq_batches():

        class B:
            data, label, extra_data = xb, yb, []
            num_batch_padd = 0

        net.update(B)
    return flat_params(net)


def test_cross_process_sp_ep_pp(tmp_path):
    """sp4 / ep4 / pp4 each span the 2-process boundary: ring ppermute,
    MoE all-to-all, and gpipe activation ppermute all execute over gloo;
    both ranks' params must match a single-process run exactly
    (mod reduction order). The heaviest gloo pair in the suite — this
    is the test the round-15 notes flagged as load-flaky (passes in
    isolation, dies of coordination-service heartbeat timeouts under
    full-suite load); _run_workers absorbs exactly that failure mode
    with its infrastructure-gated retries."""
    outs = _run_workers((0, 1), tmp_path, extra="xproc", timeout=480)
    refs = {
        "sp4": _seq_reference(tmp_path),
        "ep4": _seq_reference(tmp_path, moe_experts=4),
        "pp4": _seq_reference(tmp_path, nblock=4),
    }
    for tag, ref in refs.items():
        for r, o in zip((0, 1), outs):
            assert any(l.startswith("%s_OK rank%d" % (tag.upper(), r))
                       for l in o.splitlines()), o[-2000:]
        got = [dict(np.load(str(tmp_path / ("%s_rank%d.npz" % (tag, r)))))
               for r in (0, 1)]
        for name in ref:
            np.testing.assert_array_equal(got[0][name], got[1][name],
                                          err_msg="%s %s" % (tag, name))
            # vs the single-process trajectory: the 4-way axes reassociate
            # reductions (ring online softmax, 4-shard all-to-all sums), and
            # two momentum-SGD steps amplify the f32 reassociation noise —
            # measured max |d| 4.8e-4 here vs 2e-4 for the 2-way
            # single-process case (test_transformer.py:64). The exact
            # inter-rank equality above is the consistency claim; this
            # bound pins the trajectory to the reference
            np.testing.assert_allclose(got[0][name], ref[name], rtol=1e-3,
                                       atol=1e-3,
                                       err_msg="%s %s" % (tag, name))


def test_four_process_data_parallel(tmp_path):
    """4 gloo processes x 1 device each: dp4 with rank-sharded feed; all
    four replicas identical and equal to the single-process run."""
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize

    net = Net(tokenize(CONF))
    net.init_model()
    for xb, yb in make_batches():

        class B:
            data, label, extra_data = xb, yb, []
            num_batch_padd = 0

        net.update(B)
    ref = flat_params(net)

    _run_workers(range(4), tmp_path, extra="dp4", timeout=240)
    got = [dict(np.load(str(tmp_path / ("dp4_rank%d.npz" % r))))
           for r in range(4)]
    for name in ref:
        for r in (1, 2, 3):
            np.testing.assert_array_equal(got[0][name], got[r][name],
                                          err_msg="rank%d %s" % (r, name))
        np.testing.assert_allclose(got[0][name], ref[name], rtol=2e-5,
                                   atol=2e-6, err_msg=name)
