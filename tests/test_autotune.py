"""Geometry autotuner (``task=autotune`` + ``serve_block_size=auto``).

The load-bearing invariants:

1. **winner persistence** — the sweep times real AOT executables and
   persists one winner record per (device kind, model geometry, chunk,
   kv dtype, tp) key under the AOT cache's standard program-dir layout
   (``serve_tuned_geometry/<key>.json``), so tuning runs ONCE per
   fleet and every replica loads the result;
2. **auto resolution** — ``serve_block_size=auto`` (-1) consults the
   tuned winner at engine build, BEFORE the pool is sized; a miss
   falls back to the chunk default (0) with a log line, never an
   error;
3. **zero compile on the tuned path** — the sweep warms the AOT cache
   with every candidate's executables, so a fresh
   ``serve_block_size=auto`` build loads the winner AND its compiled
   programs with no new ``/jax/core/compile/*`` work (CompileWatch is
   the witness);
4. **stale-winner invalidation** — geometry drift (a different config
   hash / chunk / kv dtype / tp) is a miss, and the CXN210
   ``stale_entries`` scan names the drifting component, exactly like
   executable entries.
"""

import dataclasses
import glob
import json
import os

import jax
import numpy as np
import pytest

from cxxnet_tpu.analysis import aot_cache as aot_mod
from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
from cxxnet_tpu.obs import devprof
from cxxnet_tpu.serve import InferenceServer
from cxxnet_tpu.serve import engine as engine_mod
from cxxnet_tpu.serve.engine import resolve_block_size

CFG = GPTConfig(vocab_size=32, seq_len=16, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)

SERVE_LABELS = ("serve_prefill_chunk", "serve_verify_chunk", "serve_tick")


def _cfg_hash(cfg=CFG):
    return aot_mod.config_hash(dataclasses.astuple(cfg))


def _serve_compile_seconds():
    totals = devprof.compile_watch().totals
    return {k: totals.get(k, 0.0) for k in SERVE_LABELS}


# ---------------------------------------------------- unit: persistence
def test_tuned_roundtrip_unit(tmp_path):
    cache = aot_mod.get_cache(str(tmp_path))
    comp = aot_mod.tuned_components(_cfg_hash(), 4, "", 1)
    rec = {"block_size": 2, "formulation": "gather", "tick_ms": 0.5}
    assert cache.store_tuned(comp, rec)
    got = cache.load_tuned(dict(comp))
    assert got is not None and got["block_size"] == 2
    assert got["formulation"] == "gather"
    # the sidecar lives in the standard program-dir layout, components
    # at top level, so the CXN210 machinery reads it like any entry
    files = glob.glob(str(tmp_path / "serve_tuned_geometry" / "*.json"))
    assert len(files) == 1
    doc = json.load(open(files[0]))
    assert doc["program"] == "serve_tuned_geometry"
    assert doc["winner"]["block_size"] == 2


def test_tuned_key_excludes_jax_versions():
    """A jax upgrade must NOT invalidate a tuned geometry — the winner
    depends on device kind and model shape, not the compiler build
    (the executables it points at carry their own version keys)."""
    comp = aot_mod.tuned_components(_cfg_hash(), 4, "", 1)
    assert "jax" not in comp and "jaxlib" not in comp
    for k in ("program", "config", "chunk", "kv", "tp", "backend",
              "device_kind", "interpret"):
        assert k in comp, comp


def test_stale_winner_invalidated_on_geometry_drift(tmp_path):
    """Geometry drift = miss + CXN210: a winner tuned for one config
    hash / chunk / kv dtype / tp never serves another, and the stale
    scan names the drifting component."""
    cache = aot_mod.get_cache(str(tmp_path))
    comp = aot_mod.tuned_components(_cfg_hash(), 4, "", 1)
    cache.store_tuned(comp, {"block_size": 2})
    other = dataclasses.replace(CFG, n_head=4, feat=32)
    for drifted in (
            aot_mod.tuned_components(_cfg_hash(other), 4, "", 1),
            aot_mod.tuned_components(_cfg_hash(), 8, "", 1),
            aot_mod.tuned_components(_cfg_hash(), 4, "int8", 1),
            aot_mod.tuned_components(_cfg_hash(), 4, "", 2)):
        assert cache.load_tuned(drifted) is None
        stale = cache.stale_entries(drifted)
        assert stale, drifted
        drift_keys = set().union(*[set(d) for _, d in stale])
        assert drift_keys & {"config", "chunk", "kv", "tp"}, stale
    # a winner record missing its payload counts stale, not a crash
    bad = aot_mod.tuned_components(_cfg_hash(), 2, "", 1)
    _, _, meta = cache._paths(bad)
    os.makedirs(os.path.dirname(meta), exist_ok=True)
    with open(meta, "w") as f:
        json.dump(dict(bad), f)                     # no "winner" dict
    s0 = cache.stats()["stale"]
    assert cache.load_tuned(bad) is None
    assert cache.stats()["stale"] == s0 + 1


# ------------------------------------------------------ auto resolution
def test_resolve_block_size_paths(tmp_path, capfd):
    cache = aot_mod.get_cache(str(tmp_path))
    # explicit sizes pass through untouched, no cache consulted
    assert resolve_block_size(CFG, 4, 8) == 8
    assert resolve_block_size(CFG, 4, 0) == 0
    # auto + miss: chunk default, logged, never an error
    assert resolve_block_size(CFG, 4, -1, aot=cache) == 0
    # auto + winner: the tuned size
    comp = aot_mod.tuned_components(_cfg_hash(), 4, "", 1)
    cache.store_tuned(comp, {"block_size": 2, "formulation": "gather",
                             "tick_ms": 0.4})
    assert resolve_block_size(CFG, 4, -1, aot=cache) == 2
    # the string path (CXN_AOT_CACHE-style) resolves the same cache
    assert resolve_block_size(CFG, 4, -1, aot=str(tmp_path)) == 2
    # kv-dtype drift within the same cache is a miss
    assert resolve_block_size(CFG, 4, -1, kv_dtype="int8",
                              aot=cache) == 0


def test_cli_parses_auto(monkeypatch):
    from cxxnet_tpu.cli import LearnTask
    task = LearnTask()
    task.set_param("serve_block_size", "auto")
    assert task.serve_block_size == -1
    task.set_param("serve_block_size", "4")
    assert task.serve_block_size == 4


# ----------------------------------------- e2e: sweep -> persist -> load
def test_task_autotune_persists_and_auto_build_loads(tmp_path, capfd):
    """The acceptance pin: ``task=autotune`` sweeps the chunk's
    divisors, persists a winner, and a fresh ``serve_block_size=auto``
    server build loads the winner's geometry AND its executables with
    zero new compile events for the serve programs."""
    from cxxnet_tpu.cli import main as cli_main
    from cxxnet_tpu.models import gpt_lm_config
    from cxxnet_tpu.nnet.lm import net_gpt_export
    from cxxnet_tpu.nnet.net import Net
    from cxxnet_tpu.utils.config import tokenize
    conf_txt = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                             nblock=2, batch_size=8, precision="float32",
                             updater="sgd", eta=0.1)
    conf = tmp_path / "tune.conf"
    conf.write_text(conf_txt)
    cache_dir = tmp_path / "aot"
    rc = cli_main([str(conf), "task=autotune", "prof_reps=1",
                   "serve_prefill_chunk=2", "silent=1",
                   "aot_cache=%s" % cache_dir])
    out = capfd.readouterr().out
    assert rc == 0
    assert "winner serve_block_size=" in out and "persisted" in out
    files = glob.glob(str(cache_dir / "serve_tuned_geometry" / "*.json"))
    assert len(files) == 1
    doc = json.load(open(files[0]))
    winner_bs = doc["winner"]["block_size"]
    assert 2 % winner_bs == 0 and len(doc["winner"]["candidates"]) == 2
    # losing candidates' executables are pruned after the pick, so a
    # CXN210 scan of the tuned cache stays clean: one entry per serve
    # program dir (the winner's), nothing stale
    for prog in ("serve_prefill_chunk", "serve_tick"):
        metas = glob.glob(str(cache_dir / prog / "*.json"))
        assert len(metas) == 1, (prog, metas)
    # a fresh build (fresh-process stand-in: in-process program caches
    # dropped) resolves auto -> winner and loads every serve program
    net = Net(tokenize(conf_txt))
    net.init_model()
    gcfg, gparams = net_gpt_export(net)
    engine_mod.clear_program_caches()
    before = _serve_compile_seconds()
    with InferenceServer(gcfg, gparams, prefill_chunk=2, block_size=-1,
                         aot_cache=str(cache_dir)) as srv:
        m = srv.metrics()
        status = srv._engine.aot_status()
        assert m["paged"]["block_size"] == winner_bs
    assert all(v == "aot_load" for v in status.values()), status
    assert _serve_compile_seconds() == before, \
        "the tuned build must not compile any serve program"
    assert m["aot_cache"]["hits"] >= 2


def test_auto_without_winner_serves_on_chunk_default(tmp_path):
    """auto + an empty cache is the safe path: chunk-default geometry,
    a served request, no error."""
    rs = np.random.RandomState(0)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         block_size=-1,
                         aot_cache=str(tmp_path)) as srv:
        assert srv.metrics()["paged"]["block_size"] == 4
        res = srv.result(srv.submit(
            rs.randint(0, 32, (5,)).astype(np.int32), max_tokens=4),
            timeout=300)
    assert res.status == "ok"
