"""Metric tests (reference: src/utils/metric.h)."""

import numpy as np
import pytest

from cxxnet_tpu.metrics import MetricSet, create_metric


def test_error_metric():
    m = create_metric("error")
    pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = np.array([[1.0], [1.0], [1.0]])
    m.add_eval(pred, label)
    np.testing.assert_allclose(m.get(), 1.0 / 3.0)


def test_error_metric_scalar_threshold():
    m = create_metric("error")
    pred = np.array([[0.5], [-0.5]])
    label = np.array([[1.0], [0.0]])
    m.add_eval(pred, label)
    assert m.get() == 0.0


def test_rmse():
    m = create_metric("rmse")
    pred = np.array([[1.0, 2.0]])
    label = np.array([[0.0, 0.0]])
    m.add_eval(pred, label)
    np.testing.assert_allclose(m.get(), 5.0)


def test_logloss():
    m = create_metric("logloss")
    pred = np.array([[0.25, 0.75]])
    label = np.array([[1.0]])
    m.add_eval(pred, label)
    np.testing.assert_allclose(m.get(), -np.log(0.75), rtol=1e-6)


def test_logloss_clips():
    m = create_metric("logloss")
    m.add_eval(np.array([[1.0, 0.0]]), np.array([[1.0]]))
    assert np.isfinite(m.get())


def test_rec_at_n():
    m = create_metric("rec@2")
    pred = np.array([[0.1, 0.5, 0.4], [0.9, 0.06, 0.04]])
    label = np.array([[2.0], [2.0]])
    m.add_eval(pred, label)
    np.testing.assert_allclose(m.get(), 0.5)


def test_metric_set_print_format():
    ms = MetricSet()
    assert ms.configure("metric", "error")
    assert ms.configure("metric[label]", "logloss")
    assert not ms.configure("batch_size", "10")
    pred = np.array([[0.2, 0.8]])
    ms.add_eval([pred, pred], {"label": np.array([[1.0]])})
    out = ms.print("test")
    assert out.startswith("\ttest-error:")
    assert "test-logloss:" in out


def test_metric_set_multi_field():
    ms = MetricSet()
    ms.configure("metric[aux]", "rmse")
    ms.add_eval([np.array([[1.0]])], {"aux": np.array([[3.0]]),
                                      "label": np.array([[0.0]])})
    np.testing.assert_allclose(ms.metrics[0].get(), 4.0)
    assert "[aux]" in ms.print("e")
