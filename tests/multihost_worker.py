"""Worker process for test_multihost.py — NOT a test module.

Rank ``argv[1]`` of 2 joins the jax.distributed runtime (gloo CPU
collectives, 2 local virtual devices => 4 global), trains a small MLP
data-parallel for 3 steps feeding only its half of each global batch
(the per-process shard contract of the reference's dist workers,
iter_thread_imbin_x-inl.hpp:119-130), and dumps the resulting params.
"""

import os
import sys


def main() -> None:
    rank, port, outdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from cxxnet_tpu.parallel.distributed import (init_distributed,
                                                 is_multi_host,
                                                 process_count)
    init_distributed("127.0.0.1:" + port, 2, rank)
    assert is_multi_host() and process_count() == 2

    import numpy as np
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize
    from tests.test_multihost import CONF, make_batches, flat_params

    net = Net(tokenize(CONF))
    net.init_model()
    for xb, yb in make_batches():
        lo, hi = rank * 8, (rank + 1) * 8

        class B:
            data, label, extra_data = xb[lo:hi], yb[lo:hi], []
            num_batch_padd = 0

        net.update(B)
    np.savez(os.path.join(outdir, "params_rank%d.npz" % rank),
             **flat_params(net))
    print("rank", rank, "done")


if __name__ == "__main__":
    main()
