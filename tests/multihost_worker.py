"""Worker process for test_multihost.py — NOT a test module.

Rank ``argv[1]`` of 2 joins the jax.distributed runtime (gloo CPU
collectives, 2 local virtual devices => 4 global), trains a small MLP
data-parallel for 3 steps feeding only its half of each global batch
(the per-process shard contract of the reference's dist workers,
iter_thread_imbin_x-inl.hpp:119-130), and dumps the resulting params.
"""

import os
import sys


def main() -> None:
    rank, port, outdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "full"
    nproc = 4 if mode == "dp4" else 2
    local_dev = 1 if mode == "dp4" else 2
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = \
        "--xla_force_host_platform_device_count=%d" % local_dev
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from cxxnet_tpu.parallel.distributed import (init_distributed,
                                                 is_multi_host,
                                                 process_count)
    init_distributed("127.0.0.1:" + port, nproc, rank)
    assert is_multi_host() and process_count() == nproc

    if mode == "dp4":
        _dp4_segment(rank, outdir)
        print("rank", rank, "done")
        return
    if mode == "xproc":
        _xproc_segments(rank, outdir)
        print("rank", rank, "done")
        return

    import numpy as np
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize
    from tests.test_multihost import CONF, make_batches, flat_params

    def rank_shard(xb, yb):
        """This rank's half of the global batch (the per-process feed
        contract, iter_thread_imbin_x-inl.hpp:119-130)."""
        lo, hi = rank * 8, (rank + 1) * 8

        class B:
            data, label, extra_data = xb[lo:hi], yb[lo:hi], []
            num_batch_padd = 0
        return B

    net = Net(tokenize(CONF))
    net.init_model()
    batches = list(make_batches())
    for xb, yb in batches:
        net.update(rank_shard(xb, yb))
    np.savez(os.path.join(outdir, "params_rank%d.npz" % rank),
             **flat_params(net))

    # global eval line: each rank feeds only ITS half of the eval set
    # (different local rows -> different per-rank statistics), yet both
    # must print the identical cross-process-reduced metric
    class EvalIter:
        def before_first(self):
            self._i = 0

        def next(self):
            if self._i >= len(batches):
                return False
            xb, yb = batches[self._i]
            self._value = rank_shard(xb, yb)
            self._i += 1
            return True

        def value(self):
            return self._value

    line = net.evaluate(EvalIter(), "test")
    print("EVALLINE rank%d %s" % (rank, line.strip()))

    # cross-host replica consistency: clean pass, then rank 1 desyncs one
    # of its local weight shards and BOTH ranks must detect it
    diff, _ = net.check_replica_consistency()
    print("CONSISTENCY_CLEAN rank%d %.3g" % (rank, diff))
    import jax
    w = net.params["fc1"]["wmat"]
    local = [np.asarray(s.data) for s in w.addressable_shards]
    if rank == 1:
        local = [a + 0.125 for a in local]
    desync = jax.make_array_from_single_device_arrays(
        w.shape, w.sharding,
        [jax.device_put(a, s.device)
         for a, s in zip(local, w.addressable_shards)])
    net.params["fc1"]["wmat"] = desync
    diff, worst = net.check_replica_consistency()
    print("CONSISTENCY_DESYNC rank%d %.3g %s" % (rank, diff, worst))

    # permutation divergence: starting from the CLEAN weights again,
    # rank 1 reverses its rows — sum and sum-of-squares are preserved
    # exactly, so only the order-sensitive CRC channel can catch it
    # (reported as a tiny positive diff)
    local = [np.asarray(s.data) for s in w.addressable_shards]
    if rank == 1:
        local = [a[::-1].copy() for a in local]
    perm = jax.make_array_from_single_device_arrays(
        w.shape, w.sharding,
        [jax.device_put(a, s.device)
         for a, s in zip(local, w.addressable_shards)])
    net.params["fc1"]["wmat"] = perm
    diff, worst = net.check_replica_consistency()
    print("CONSISTENCY_PERM rank%d %.3g %s" % (rank, diff, worst))

    # ZeRO-3 across processes: params shard over the 4-device data axis
    # spanning BOTH hosts; one train step must run, and save_model must
    # gather the non-addressable shards (Net._fetch process_allgather)
    # into a full checkpoint identical on both ranks
    net3 = Net(tokenize(CONF))
    net3.set_param("shard_optimizer", "3")
    net3.init_model()
    net3.update(rank_shard(*batches[0]))
    w = net3.params["fc1"]["wmat"]
    assert not w.is_fully_addressable, "ZeRO-3 should span hosts"
    path3 = os.path.join(outdir, "zero3_rank%d.model" % rank)
    net3.save_model(path3)
    print("ZERO3_SAVED rank%d %d bytes" % (rank, os.path.getsize(path3)))

    # hybrid parallelism across the process boundary: dp ACROSS the two
    # gloo processes x tensor parallelism WITHIN each process's 2 local
    # devices (mesh data=2 over processes, model=2 within) — the
    # 2-process x 4-device composition the dryrun tail references
    net4 = Net(tokenize(CONF))
    net4.set_param("model_parallel", "2")
    net4.init_model()
    assert net4.mesh.shape["data"] == 2 and net4.mesh.shape["model"] == 2
    w4 = net4.params["fc1"]["wmat"]
    assert any(ax == "model" for ax in tuple(w4.sharding.spec) if ax), \
        "fc1 weight should be tensor-parallel in the hybrid"
    # the placement claim itself: each data row (= one tp group of 2
    # model shards) maps entirely to ONE process, i.e. tp runs within a
    # process and dp runs across them
    dev_rows = net4.mesh.devices.reshape(net4.mesh.shape["data"], -1)
    for row in dev_rows:
        assert len({d.process_index for d in row}) == 1, dev_rows
    assert {row[0].process_index for row in dev_rows} == {0, 1}, dev_rows
    for xb, yb in batches:
        net4.update(rank_shard(xb, yb))
    hyb = {"%s/%s" % (l, t): net4.get_weight(l, t)
           for l in ("fc1", "fc2") for t in ("wmat", "bias")}
    np.savez(os.path.join(outdir, "hybrid_rank%d.npz" % rank), **hyb)
    print("HYBRID_OK rank%d" % rank)
    print("rank", rank, "done")


def _xproc_segments(rank: int, outdir: str) -> None:
    """Round 4: cross-process collective topologies. Each of the seq,
    expert, and pipe axes is 4-wide over the 2x2-device process grid, so
    the axis SPANS the process boundary: ring attention's K/V ppermute
    (sp4), the MoE dispatch all-to-all (ep4), and gpipe's activation
    ppermute (pp4) all execute over gloo — the paths the single-process
    dryrun matrix cannot exercise."""
    import os
    import numpy as np
    from cxxnet_tpu import Net
    from cxxnet_tpu.models import transformer_config
    from cxxnet_tpu.utils.config import tokenize
    from tests.test_multihost import SEQ_KW, flat_params, make_seq_batches

    for tag, kw, extra in (
            ("sp4", dict(seq_parallel=4), ""),
            ("ep4", dict(moe_experts=4), "expert_parallel = 4\n"),
            ("pp4", dict(pipeline_parallel=4, nblock=4), "")):
        cfg = transformer_config(**dict(SEQ_KW, **kw)) + extra
        netx = Net(tokenize(cfg))
        netx.set_param("seed", "11")
        netx.init_model()
        ax = {"sp4": "seq", "ep4": "expert", "pp4": "pipe"}[tag]
        assert netx.mesh.shape[ax] == 4
        # the 4-wide axis must span both processes
        procs_on_axis = {d.process_index for d in netx.mesh.devices.ravel()}
        assert procs_on_axis == {0, 1}, (tag, procs_on_axis)
        for xb, yb in make_seq_batches():

            class SB:
                data, label, extra_data = xb, yb, []
                num_batch_padd = 0

            netx.update(SB)      # replicated feed: full batch on each rank
        # params shard across processes (expert/pipe axes span them):
        # get_weight gathers the full tensors on every rank
        gathered = {"%s/%s" % (k, t): netx._fetch(netx.params[k][t])
                    for k, tags in netx.params.items() for t in tags}
        np.savez(os.path.join(outdir, "%s_rank%d.npz" % (tag, rank)),
                 **gathered)
        print("%s_OK rank%d" % (tag.upper(), rank))


def _dp4_segment(rank: int, outdir: str) -> None:
    """4 processes x 1 device: plain dp4 with rank-sharded feed."""
    import numpy as np
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize
    from tests.test_multihost import CONF, make_batches, flat_params

    net = Net(tokenize(CONF))
    net.init_model()
    for xb, yb in make_batches():
        lo, hi = rank * 4, (rank + 1) * 4

        class B:
            data, label, extra_data = xb[lo:hi], yb[lo:hi], []
            num_batch_padd = 0

        net.update(B)
    np.savez(os.path.join(outdir, "dp4_rank%d.npz" % rank),
             **flat_params(net))
    print("DP4_OK rank%d" % rank)


if __name__ == "__main__":
    main()
