"""Data-plane tests: BinaryPage format, decoders (native vs PIL differential),
im2bin tool, imgbin/img iterators, augmentation, attachtxt."""

import io
import os
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io import create_iterator
from cxxnet_tpu.io.binpage import (BinaryPage, BinaryPageWriter, K_PAGE_BYTES,
                                   iter_pages)
from cxxnet_tpu.io.decoder import decode_image_chw, decode_jpeg_hwc, have_native
from cxxnet_tpu.io.augment import AugmentIterator, ImageAugmenter
from cxxnet_tpu.io.data import DataInst, IIterator


def make_jpeg(rng, w=32, h=24, gray=False, quality=95):
    from PIL import Image
    arr = (rng.rand(h, w) * 255 if gray else rng.rand(h, w, 3) * 255) \
        .astype(np.uint8)
    img = Image.fromarray(arr, mode="L" if gray else "RGB")
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


# ------------------------------------------------------------ binary page
def test_binary_page_roundtrip():
    page = BinaryPage()
    objs = [b"hello", b"x" * 1000, b"", b"world"]
    for o in objs:
        assert page.push(o)
    raw = page.tobytes()
    assert len(raw) == K_PAGE_BYTES
    page2 = BinaryPage(raw)
    assert page2.size == 4
    assert [bytes(page2[i]) for i in range(4)] == objs


def test_binary_page_disk_format():
    # verify the exact reference layout: int32 count, cumulative end-offsets,
    # payloads packed backward from the page end (io.h:254-326)
    page = BinaryPage()
    page.push(b"abc")
    page.push(b"de")
    raw = page.tobytes()
    head = np.frombuffer(raw, "<i4", count=4)
    assert list(head) == [2, 0, 3, 5]
    assert raw[K_PAGE_BYTES - 3:] == b"abc"
    assert raw[K_PAGE_BYTES - 5:K_PAGE_BYTES - 3] == b"de"


def test_binary_page_writer_multi_page(tmp_path):
    path = str(tmp_path / "multi.bin")
    big = b"B" * (K_PAGE_BYTES // 2 - 100)
    with BinaryPageWriter(path) as w:
        for _ in range(5):
            w.push(big)
    pages = list(iter_pages(path))
    assert sum(p.size for p in pages) == 5
    assert len(pages) == 3
    assert os.path.getsize(path) == 3 * K_PAGE_BYTES


def test_native_im2bin_matches_python(imgbin_dataset, tmp_path):
    """The C++ im2bin tool (native/im2bin.cpp) must emit byte-identical
    .bin output to tools/im2bin.py on the same .lst."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    exe = os.path.join(root, "native", "im2bin")
    try:
        # always invoke make: its dependency tracking rebuilds a stale binary
        # and no-ops when current
        r = subprocess.run(["make", "-C", os.path.join(root, "native"),
                            "im2bin"], capture_output=True, text=True)
    except OSError as e:
        pytest.skip("no make for native im2bin: %s" % e)
    if r.returncode != 0 or not os.path.exists(exe):
        pytest.skip("no toolchain for native im2bin: %s" % r.stderr[-300:])
    d = imgbin_dataset
    out = str(tmp_path / "native.bin")
    rc = subprocess.call([exe, str(d / "train.lst"), str(d), out])
    assert rc == 0
    with open(out, "rb") as fa, open(d / "train.bin", "rb") as fb:
        assert fa.read() == fb.read()

    # whitespace-separated .lst (parse_list_line fallback) must agree too
    with open(d / "train.lst") as f:
        ws_lines = [l.replace("\t", " ") for l in f]
    with open(tmp_path / "ws.lst", "w") as f:
        f.writelines(ws_lines)
    out_ws = str(tmp_path / "native_ws.bin")
    rc = subprocess.call([exe, str(tmp_path / "ws.lst"), str(d), out_ws])
    assert rc == 0
    with open(out_ws, "rb") as fa, open(d / "train.bin", "rb") as fb:
        assert fa.read() == fb.read()


# ------------------------------------------------------------ decoder
@pytest.fixture(scope="session")
def native_lib():
    """Build the native data-plane library from source (it is not checked in)
    and skip native-path tests where the toolchain can't produce it."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not have_native():
        try:
            r = subprocess.run(["make", "-C", os.path.join(root, "native")],
                               capture_output=True, text=True)
        except OSError as e:
            pytest.skip("no native toolchain (make): %s" % e)
        # toolchain present but the build broke: that is a failure, not a skip
        assert r.returncode == 0, \
            "native/libcxnetdata.so failed to build:\n%s" % r.stderr
        # reset the module-level load cache so the fresh build is picked up
        import cxxnet_tpu.io.decoder as dec
        dec._LIB_TRIED = False
        dec._LIB = None
    if not have_native():
        pytest.skip("native libcxnetdata.so unavailable")


def test_native_decoder_available(native_lib):
    assert have_native()


def test_decode_native_matches_pil(rng, native_lib):
    buf = make_jpeg(rng)
    native = decode_jpeg_hwc(buf)            # native path when available
    from PIL import Image
    pil = np.asarray(Image.open(io.BytesIO(buf)), np.uint8)
    # independent libjpeg decoders may differ by a few ULP of IDCT rounding
    assert native.shape == pil.shape
    diff = np.abs(native.astype(int) - pil.astype(int))
    assert diff.mean() < 1.0 and diff.max() <= 2


def test_decode_chw_gray_replication(rng):
    buf = make_jpeg(rng, gray=True)
    chw = decode_image_chw(buf, gray_to_rgb=True)
    assert chw.shape[0] == 3
    np.testing.assert_allclose(chw[0], chw[1])
    chw1 = decode_image_chw(buf, gray_to_rgb=False)
    assert chw1.shape[0] == 1


# ------------------------------------------------------------ im2bin + imgbin
@pytest.fixture(scope="module")
def imgbin_dataset(tmp_path_factory):
    """3-class dataset where class = dominant channel; 64 jpegs."""
    d = tmp_path_factory.mktemp("imgbin")
    rng = np.random.RandomState(3)
    from PIL import Image
    lines = []
    os.makedirs(d / "img", exist_ok=True)
    for i in range(64):
        cls = i % 3
        arr = (rng.rand(32, 32, 3) * 60).astype(np.uint8)
        arr[:, :, cls] += 180
        Image.fromarray(arr, "RGB").save(d / "img" / ("%03d.jpg" % i),
                                         quality=95)
        lines.append("%d\t%d\timg/%03d.jpg\n" % (i, cls, i))
    with open(d / "train.lst", "w") as f:
        f.writelines(lines)
    rc = subprocess.call([sys.executable,
                          os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "im2bin.py"),
                          str(d / "train.lst"), str(d) + os.sep,
                          str(d / "train.bin")])
    assert rc == 0
    return d


def test_imgbin_iterator(imgbin_dataset):
    d = imgbin_dataset
    it = create_iterator([
        ("iter", "imgbin"),
        ("image_list", str(d / "train.lst")),
        ("image_bin", str(d / "train.bin")),
        ("input_shape", "3,28,28"),
        ("batch_size", "16"),
        ("rand_crop", "1"),
        ("rand_mirror", "1"),
        ("silent", "1"),
    ])
    batches = list(it)
    assert len(batches) == 4
    b0 = batches[0]
    assert b0.data.shape == (16, 3, 28, 28)
    assert b0.label.shape == (16, 1)
    assert b0.data.max() > 100      # 0..255 scale before divideby
    # labels follow the lst: class = dominant channel of the decoded image
    for i in range(16):
        dom = np.argmax(b0.data[i].mean(axis=(1, 2)))
        assert dom == int(b0.label[i, 0])
    # second epoch works
    assert len(list(it)) == 4


def test_imgbin_shuffle_and_threadbuffer(imgbin_dataset):
    d = imgbin_dataset
    it = create_iterator([
        ("iter", "imgbin"),
        ("iter", "threadbuffer"),
        ("image_list", str(d / "train.lst")),
        ("image_bin", str(d / "train.bin")),
        ("input_shape", "3,32,32"),
        ("batch_size", "16"),
        ("shuffle", "1"),
        ("silent", "1"),
    ])
    b1 = [b.inst_index.copy() for b in it]
    b2 = [b.inst_index.copy() for b in it]
    assert not all(np.array_equal(a, b) for a, b in zip(b1, b2)), \
        "shuffle should change instance order between epochs"
    assert sorted(np.concatenate(b1).tolist()) == list(range(64))


def test_img_iterator(imgbin_dataset):
    d = imgbin_dataset
    it = create_iterator([
        ("iter", "img"),
        ("image_list", str(d / "train.lst")),
        ("image_root", str(d) + os.sep),
        ("input_shape", "3,32,32"),
        ("batch_size", "32"),
        ("silent", "1"),
    ])
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data.shape == (32, 3, 32, 32)


def test_imgbin_round_batch_tail(imgbin_dataset):
    d = imgbin_dataset
    it = create_iterator([
        ("iter", "imgbin"),
        ("image_list", str(d / "train.lst")),
        ("image_bin", str(d / "train.bin")),
        ("input_shape", "3,32,32"),
        ("batch_size", "48"),
        ("round_batch", "1"),
        ("silent", "1"),
    ])
    batches = list(it)
    assert len(batches) == 2
    assert batches[1].num_batch_padd == 32      # 64 = 48 + 16 (+32 wrapped)
    assert batches[1].pad_mode == "wrap"


def _imgbin_cfg(d, **over):
    cfg = dict([("image_list", str(d / "train.lst")),
                ("image_bin", str(d / "train.bin")),
                ("input_shape", "3,32,32"), ("batch_size", "16"),
                ("silent", "1")])
    cfg.update(over)
    return [("iter", "imgbin")] + list(cfg.items())


def test_imgbin_partial_consume_close(imgbin_dataset):
    """A partially-consumed iterator must tear down its producer thread and
    decode pool on close() (it used to leak both forever)."""
    import threading
    import time
    before = set(threading.enumerate())
    it = create_iterator(_imgbin_cfg(imgbin_dataset))
    it.before_first()
    assert it.next()
    it.close()
    deadline = time.time() + 6
    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t not in before and t.is_alive()
                 and "ThreadPoolExecutor" not in t.name]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, "leaked producer threads: %r" % alive


def test_imgbin_fresh_rewind_is_noop(imgbin_dataset):
    """Rewinding an epoch that has been queued but not consumed must not
    discard it (a drain-and-requeue costs a full decode pass)."""
    it = create_iterator(_imgbin_cfg(imgbin_dataset))
    it.before_first()
    it.before_first()
    n = sum(1 for _ in iter(it.next, False))
    assert n == 4
    it.close()


def test_threadbuffer_error_propagates():
    """A base iterator raising mid-epoch must surface in the consumer's
    next() rather than leaving it blocked on the queue forever."""
    from cxxnet_tpu.io.batch import ThreadBufferIterator

    class Boom(IIterator):
        def before_first(self):
            pass

        def next(self):
            raise RuntimeError("boom")

    it = ThreadBufferIterator(Boom())
    it.init()
    with pytest.raises(RuntimeError, match="boom"):
        while it.next():
            pass
    it.close()


def test_mean_image_with_membuffer(imgbin_dataset, tmp_path):
    """membuffer never rewinds its base, so augment must leave the base
    rewound after generating the mean image (regression: empty dataset)."""
    mean = str(tmp_path / "mean.npy")
    it = create_iterator(_imgbin_cfg(imgbin_dataset)
                         + [("iter", "membuffer"), ("image_mean", mean)])
    batches = list(it)
    assert os.path.exists(mean)
    assert len(batches) == 4
    it.close()


# ------------------------------------------------------------ augmentation
class _ListInstIterator(IIterator):
    def __init__(self, insts):
        self.insts = insts
        self.loc = 0

    def before_first(self):
        self.loc = 0

    def next(self):
        if self.loc >= len(self.insts):
            return False
        self._v = self.insts[self.loc]
        self.loc += 1
        return True

    def value(self):
        return self._v


def _augment(params, insts):
    it = AugmentIterator(_ListInstIterator(insts))
    for k, v in params:
        it.set_param(k, v)
    it.init()
    return list(it)


def test_augment_center_crop_and_scale(rng):
    data = np.arange(3 * 8 * 8, dtype=np.float32).reshape(3, 8, 8)
    out = _augment([("input_shape", "3,4,4"), ("divideby", "2"),
                    ("silent", "1")],
                   [DataInst(data, np.zeros(1, np.float32), 0)])
    np.testing.assert_allclose(out[0].data, data[:, 2:6, 2:6] / 2.0)


def test_augment_fixed_crop_and_mirror(rng):
    data = np.arange(1 * 4 * 6, dtype=np.float32).reshape(1, 4, 6)
    out = _augment([("input_shape", "1,4,4"), ("crop_x_start", "0"),
                    ("mirror", "1"), ("silent", "1")],
                   [DataInst(data, np.zeros(1, np.float32), 0)])
    np.testing.assert_allclose(out[0].data, data[:, :, 0:4][:, :, ::-1])


def test_augment_mean_value(rng):
    data = np.full((3, 4, 4), 100.0, np.float32)
    out = _augment([("input_shape", "3,4,4"),
                    ("mean_value", "10,20,30"), ("silent", "1")],
                   [DataInst(data, np.zeros(1, np.float32), 0)])
    np.testing.assert_allclose(out[0].data[0], 90.0)
    np.testing.assert_allclose(out[0].data[1], 80.0)
    np.testing.assert_allclose(out[0].data[2], 70.0)


def test_augment_mean_image_generation(tmp_path, rng):
    meanfile = str(tmp_path / "mean.npy")
    insts = [DataInst(np.full((3, 4, 4), float(v), np.float32),
                      np.zeros(1, np.float32), i)
             for i, v in enumerate([10, 20, 30])]
    out = _augment([("input_shape", "3,4,4"), ("image_mean", meanfile),
                    ("silent", "1")], insts)
    assert os.path.exists(meanfile)
    mean = np.load(meanfile)
    np.testing.assert_allclose(mean, 20.0)
    np.testing.assert_allclose(out[0].data, -10.0)


def test_affine_rotate_180(rng):
    aug = ImageAugmenter()
    aug.set_param("input_shape", "3,8,8")
    aug.set_param("rotate", "180")
    aug.set_param("max_rotate_angle", "1")   # activates need_process
    data = np.zeros((3, 8, 8), np.float32)
    data[:, 0, 0] = 200.0
    out = aug.process(data, np.random.RandomState(0))
    assert out.shape == (3, 8, 8)
    # the hot corner moved to the opposite corner (within interpolation blur)
    assert out[0, -2:, -2:].max() > 50
    assert out[0, :2, :2].max() < 50


def test_attachtxt(imgbin_dataset, tmp_path):
    d = imgbin_dataset
    attach = tmp_path / "extra.txt"
    with open(attach, "w") as f:
        f.write("4\n")
        for i in range(64):
            f.write("%d %d %d %d %d\n" % (i, i, i + 1, i + 2, i + 3))
    it = create_iterator([
        ("iter", "imgbin"),
        ("iter", "attachtxt"),
        ("image_list", str(d / "train.lst")),
        ("image_bin", str(d / "train.bin")),
        ("filename", str(attach)),
        ("input_shape", "3,32,32"),
        ("batch_size", "16"),
        ("silent", "1"),
    ])
    b = next(iter(it))
    assert len(b.extra_data) == 1
    assert b.extra_data[0].shape == (16, 1, 1, 4)
    for row in range(16):
        i = int(b.inst_index[row])
        np.testing.assert_allclose(b.extra_data[0][row, 0, 0],
                                   [i, i + 1, i + 2, i + 3])


def test_databatch_sparse_csr():
    """Surface parity for the CSR fields (data.h:96-180) — carried but not
    consumed by the dense path, same as the reference."""
    from cxxnet_tpu.io.data import DataBatch
    b = DataBatch(np.zeros((3, 1, 1, 4), np.float32),
                  np.zeros((3, 1), np.float32))
    values = np.array([1.0, 2.0, 3.0], np.float32)
    indices = np.array([0, 2, 1], np.int64)
    indptr = np.array([0, 2, 2, 3], np.int64)
    b.set_sparse(values, indices, indptr)
    idx, val = b.sparse_row(0)
    np.testing.assert_array_equal(idx, [0, 2])
    np.testing.assert_array_equal(val, [1.0, 2.0])
    idx, val = b.sparse_row(1)
    assert idx.size == 0
    idx, val = b.sparse_row(2)
    np.testing.assert_array_equal(val, [3.0])


def test_data_dtype_bfloat16_pipeline(imgbin_dataset):
    """`data_dtype = bfloat16` packs batch data in the compute dtype inside
    the pipeline (producer thread under threadbuffer); labels stay f32."""
    import ml_dtypes

    d = imgbin_dataset
    it = create_iterator([
        ("iter", "imgbin"),
        ("image_list", str(d / "train.lst")),
        ("image_bin", str(d / "train.bin")),
        ("input_shape", "3,28,28"),
        ("batch_size", "16"),
        ("data_dtype", "bfloat16"),
        ("iter", "threadbuffer"),
        ("silent", "1"),
    ])
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data.dtype == ml_dtypes.bfloat16
    assert batches[0].label.dtype == np.float32
    it.close()

    with pytest.raises(ValueError):
        create_iterator([
            ("iter", "imgbin"),
            ("image_list", str(d / "train.lst")),
            ("image_bin", str(d / "train.bin")),
            ("input_shape", "3,28,28"),
            ("batch_size", "16"),
            ("data_dtype", "float16"),
        ])


def test_pred_excludes_tail_padding(imgbin_dataset, tmp_path):
    """The tail batch is padded to batch_size; task=pred must write one
    line per real instance (cxxnet_main.cpp:276-277), and task=extract one
    row per real instance — 64 images at batch 24 = 2 full batches plus a
    tail of 16 real instances padded with 8 duplicates."""
    from cxxnet_tpu.cli import LearnTask

    d = imgbin_dataset
    conf = tmp_path / "c.conf"
    conf.write_text("""
data = train
iter = imgbin
    image_list = "%(d)s/train.lst"
    image_bin = "%(d)s/train.bin"
iter = end
netconfig=start
layer[+1] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
layer[+0] = softmax
netconfig=end
input_shape = 3,32,32
batch_size = 24
dev = cpu
num_round = 1
max_round = 1
model_dir = %(md)s
pred = %(out)s
iter = imgbin
    image_list = "%(d)s/train.lst"
    image_bin = "%(d)s/train.bin"
iter = end
""" % {"d": d, "md": tmp_path, "out": tmp_path / "out.txt"})
    assert LearnTask().run([str(conf)]) == 0
    assert LearnTask().run([str(conf), "task=pred",
                            "model_in=%s" % (tmp_path / "0001.model")]) == 0
    preds = np.loadtxt(tmp_path / "out.txt")
    assert preds.shape[0] == 64          # not 72 (3 x 24)

    assert LearnTask().run([str(conf), "task=extract",
                            "extract_node_name=top[-1]",
                            "model_in=%s" % (tmp_path / "0001.model")]) == 0
    feats = np.loadtxt(tmp_path / "out.txt")
    assert feats.shape == (64, 3)


def test_cifar_iterator(tmp_path):
    """CIFAR-10 binary format (documented `iter = cifar`, doc/io.md:4):
    1 label byte + 3072 CHW uint8 bytes per record; multi-file loads,
    shuffle determinism, bf16 option, and the CIFAR-100 2-byte label mode."""
    rs = np.random.RandomState(7)
    labels = rs.randint(0, 10, 50).astype(np.uint8)
    imgs = rs.randint(0, 255, (50, 3, 32, 32)).astype(np.uint8)
    recs = np.concatenate([labels[:, None], imgs.reshape(50, -1)], axis=1)
    (tmp_path / "b1.bin").write_bytes(recs[:30].tobytes())
    (tmp_path / "b2.bin").write_bytes(recs[30:].tobytes())

    it = create_iterator([
        ("iter", "cifar"),
        ("path_data", "%s,%s" % (tmp_path / "b1.bin", tmp_path / "b2.bin")),
        ("batch_size", "16"),
        ("silent", "1"),
    ])
    batches = list(it)
    assert len(batches) == 3                       # 50 // 16, tail dropped
    assert batches[0].data.shape == (16, 3, 32, 32)
    np.testing.assert_allclose(np.asarray(batches[0].label[:, 0], np.uint8),
                               labels[:16])
    np.testing.assert_allclose(batches[0].data[0],
                               imgs[0].astype(np.float32) / 256.0, rtol=1e-6)

    # shuffle is deterministic per seed and a permutation of the data
    it2 = create_iterator([
        ("iter", "cifar"),
        ("path_data", str(tmp_path / "b1.bin")),
        ("batch_size", "30"), ("shuffle", "1"), ("silent", "1"),
    ])
    it3 = create_iterator([
        ("iter", "cifar"),
        ("path_data", str(tmp_path / "b1.bin")),
        ("batch_size", "30"), ("shuffle", "1"), ("silent", "1"),
    ])
    assert it2.next() and it3.next()
    np.testing.assert_array_equal(it2.value().label, it3.value().label)
    assert sorted(it2.value().label[:, 0]) == sorted(labels[:30])

    # bf16 pipeline dtype
    import ml_dtypes
    it4 = create_iterator([
        ("iter", "cifar"), ("path_data", str(tmp_path / "b1.bin")),
        ("batch_size", "8"), ("data_dtype", "bfloat16"), ("silent", "1"),
    ])
    assert it4.next()
    assert it4.value().data.dtype == ml_dtypes.bfloat16

    # CIFAR-100 style: coarse+fine label bytes, fine label (last) is used
    recs100 = np.concatenate([labels[:10, None] // 2, labels[:10, None],
                              imgs[:10].reshape(10, -1)], axis=1)
    (tmp_path / "c100.bin").write_bytes(recs100.tobytes())
    it5 = create_iterator([
        ("iter", "cifar"), ("path_data", str(tmp_path / "c100.bin")),
        ("label_bytes", "2"), ("batch_size", "10"), ("silent", "1"),
    ])
    assert it5.next()
    np.testing.assert_allclose(np.asarray(it5.value().label[:, 0], np.uint8),
                               labels[:10])

    # corrupt size -> clear error
    (tmp_path / "bad.bin").write_bytes(b"123")
    with pytest.raises(ValueError):
        create_iterator([("iter", "cifar"),
                         ("path_data", str(tmp_path / "bad.bin")),
                         ("batch_size", "1")])


def test_inmem_iterator_requires_batch_size(tmp_path):
    """batch_size=0 previously made next() return an empty batch forever
    (an infinite loop for any consumer); init must reject it."""
    rs = np.random.RandomState(9)
    imgs = rs.randint(0, 256, size=(4, 3, 32, 32), dtype=np.uint8)
    labels = rs.randint(0, 10, size=4).astype(np.uint8)
    recs = np.concatenate([labels[:, None], imgs.reshape(4, -1)], axis=1)
    (tmp_path / "nb.bin").write_bytes(recs.tobytes())
    with pytest.raises(ValueError, match="batch_size"):
        create_iterator([("iter", "cifar"),
                         ("path_data", str(tmp_path / "nb.bin")),
                         ("silent", "1")])


def test_native_png_decode_matches_pil():
    """PNG is lossless: the native libpng path and PIL must agree exactly
    (rgb and grayscale)."""
    from cxxnet_tpu.io import decoder
    if not decoder.have_native():
        pytest.skip("native library not built")
    import io as _io
    from PIL import Image
    rs = np.random.RandomState(3)
    for mode, shape in (("RGB", (21, 17, 3)), ("L", (14, 9, 1))):
        arr = rs.randint(0, 256, size=shape, dtype=np.uint8)
        img = Image.fromarray(arr[:, :, 0] if mode == "L" else arr, mode)
        buf = _io.BytesIO()
        img.save(buf, format="PNG")
        got = decoder.decode_png_hwc(buf.getvalue())
        np.testing.assert_array_equal(got, arr)
        # and through the full decode_image_chw dispatch
    chw = decoder.decode_image_chw(buf.getvalue())
    assert chw.shape[0] == 3      # gray replicated


def test_native_affine_warp_matches_pil():
    """The native bicubic warp and PIL's BICUBIC AFFINE transform agree
    to ~1 gray level in the interior (boundary fill blending differs)."""
    from cxxnet_tpu.io import decoder
    if not decoder.have_native():
        pytest.skip("native library not built")
    import ctypes
    lib = decoder._find_native()
    if not hasattr(lib, "cxn_affine_warp_u8"):
        pytest.skip("old native build without the warp")
    from PIL import Image
    rs = np.random.RandomState(5)
    hwc = rs.randint(0, 256, size=(32, 40, 3), dtype=np.uint8)
    # mild rotation+shear inverse map
    inv = (0.95, 0.1, 1.5, -0.08, 1.02, -0.7)
    native = decoder.affine_warp_hwc(hwc, (36, 30), inv, 128)
    img = Image.fromarray(hwc)
    pil = np.asarray(img.transform((36, 30), Image.AFFINE, inv,
                                   resample=Image.BICUBIC,
                                   fillcolor=(128,) * 3), np.uint8)
    interior = (slice(3, -3), slice(3, -3))
    diff = np.abs(native[interior].astype(int) - pil[interior].astype(int))
    # a=-1 kernel + center convention matches PIL to sub-level mean even
    # on white noise (worst case for subpixel differences)
    assert diff.mean() < 1.5 and np.percentile(diff, 99) <= 8.0, \
        (diff.mean(), diff.max())


def test_pipeline_prefetch_hides_decode(imgbin_dataset):
    """The threadbuffer prefetcher must hide decode behind consumer work:
    with a consumer that takes ~2x the decode time per batch, the
    measured data-wait fraction stays small (VERDICT r1: pin data-wait
    ~ 0 at a feedable rate)."""
    import time as _time
    from cxxnet_tpu.utils.profiler import StepStats
    d = imgbin_dataset
    it = create_iterator([
        ("iter", "imgbin"),
        ("image_list", str(d / "train.lst")),
        ("image_bin", str(d / "train.bin")),
        ("input_shape", "3,28,28"), ("rand_crop", "1"),
        ("decode_threads", "2"),
        ("iter", "threadbuffer"),
        ("batch_size", "16"), ("round_batch", "1"), ("silent", "1"),
    ])
    # calibrate decode cost per batch (no consumer work)
    it.before_first()
    t0 = _time.perf_counter()
    n = 0
    while it.next():
        n += 1
    per_batch = (_time.perf_counter() - t0) / max(n, 1)
    stats = StepStats(batch_size=16)
    it.before_first()
    while True:
        with stats.phase("data"):
            if not it.next():
                break
        with stats.phase("step"):
            _time.sleep(per_batch * 3)     # consumer well below decode rate
        stats.end_step()
    totals = stats.phase_totals()
    data_s = totals["data"]
    step_s = totals["step"]
    # generous bound: under full-suite load on a single-core host the
    # decode pool competes with everything else; the property pinned is
    # "prefetch overlaps decode", not an exact ratio
    assert data_s < 0.7 * step_s, \
        "prefetch failed to hide decode: data %.3fs vs step %.3fs" \
        % (data_s, step_s)


def test_gz_compressed_lst_and_bin(imgbin_dataset, tmp_path):
    """gz-compressed .lst and .bin inputs read transparently — the
    reference's GzFile stream (io.h:152-180) generalized to every
    dataset input, not just the mnist idx files."""
    import gzip
    import shutil
    d = imgbin_dataset
    for name in ("train.lst", "train.bin"):
        with open(d / name, "rb") as fin, \
                gzip.open(tmp_path / (name + ".gz"), "wb") as fout:
            shutil.copyfileobj(fin, fout)
    it = create_iterator([
        ("iter", "imgbin"),
        ("image_list", str(tmp_path / "train.lst.gz")),
        ("image_bin", str(tmp_path / "train.bin.gz")),
        ("input_shape", "3,24,24"), ("rand_crop", "1"),
        ("iter", "threadbuffer"),
        ("batch_size", "16"), ("round_batch", "1"), ("silent", "1"),
    ])
    it.before_first()
    assert it.next()
    b = it.value()
    assert b.data.shape == (16, 3, 24, 24)
    assert b.data.max() > 1.0          # real decoded pixels


def test_imgbin_chain_with_affine_augmentation(imgbin_dataset, native_lib):
    """The full kaggle_bowl-style chain — imgbin decode -> affine warp
    (rotation+shear, native kernel) -> crop/mirror -> batch — produces
    well-formed batches (the warp path changed to native C in r2; the
    native_lib fixture guarantees the C kernel, not the PIL fallback,
    is what runs)."""
    d = imgbin_dataset
    it = create_iterator([
        ("iter", "imgbin"),
        ("image_list", str(d / "train.lst")),
        ("image_bin", str(d / "train.bin")),
        ("input_shape", "3,24,24"),
        ("rand_crop", "1"), ("rand_mirror", "1"),
        ("max_rotate_angle", "30"), ("max_shear_ratio", "0.2"),
        ("fill_value", "127"),
        ("iter", "threadbuffer"),
        ("batch_size", "16"), ("round_batch", "1"), ("silent", "1"),
    ])
    it.before_first()
    n = 0
    while it.next():
        b = it.value()
        assert b.data.shape == (16, 3, 24, 24)
        assert np.isfinite(b.data).all()
        assert b.data.max() > 1.0 and b.data.min() >= 0.0
        n += 1
    assert n == 4                      # 64 images / 16


# ---------------------------------------------------------- decode-at-scale
def _jpeg_bytes(rs, h=256, w=256):
    import io as _io
    from PIL import Image
    arr = rs.randint(0, 256, (h, w, 3), dtype=np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=90)
    return buf.getvalue()


def test_decode_at_scale_dims_and_native_pil_agree():
    """min_hw picks the coarsest power-of-two libjpeg scale covering the
    target; the native scaled path and the PIL draft fallback are the
    same libjpeg reduction and must agree pixel-exactly."""
    from cxxnet_tpu.io import decoder
    rs = np.random.RandomState(0)
    buf = _jpeg_bytes(rs, 256, 256)
    cases = [((112, 112), 128), ((227, 227), 256), ((64, 64), 64),
             ((20, 20), 32)]
    for min_hw, want in cases:
        out = decoder.decode_jpeg_hwc(buf, min_hw=min_hw)
        assert out.shape[:2] == (want, want), (min_hw, out.shape)
        pil = decoder._pil_decode_hwc(buf, min_hw=min_hw)
        assert pil.shape == out.shape
        if decoder.have_native():
            np.testing.assert_array_equal(out, pil)
    # sources that are NOT multiples of the reduction step: the native
    # path scales by ceil(dim*n/8) while PIL draft picks its reduction
    # from the requested size — the floor-dims request keeps them equal
    for h, w in ((255, 255), (250, 198), (257, 131)):
        buf = _jpeg_bytes(rs, h, w)
        out = decoder.decode_jpeg_hwc(buf, min_hw=(64, 64))
        pil = decoder._pil_decode_hwc(buf, min_hw=(64, 64))
        assert out.shape == pil.shape, (h, w, out.shape, pil.shape)
        assert out.shape[0] < h, "scaling should have engaged"
        if decoder.have_native():
            np.testing.assert_array_equal(out, pil)


def test_decode_at_scale_default_full_size():
    from cxxnet_tpu.io import decoder
    rs = np.random.RandomState(1)
    buf = _jpeg_bytes(rs, 200, 300)
    out = decoder.decode_jpeg_hwc(buf)
    assert out.shape[:2] == (200, 300)


def test_imgbin_decode_at_scale_chain(tmp_path):
    """imgbin with decode_at_scale=1 feeds the crop path from the scaled
    frame; warp-family params must disable it (full-size decode)."""
    import io as _io
    from PIL import Image
    from cxxnet_tpu.io import create_iterator
    from cxxnet_tpu.io.binpage import BinaryPageWriter
    rs = np.random.RandomState(2)
    lst = tmp_path / "t.lst"
    binp = tmp_path / "t.bin"
    with open(lst, "w") as f, BinaryPageWriter(str(binp)) as w:
        for i in range(8):
            arr = rs.randint(0, 256, (256, 256, 3), dtype=np.uint8)
            b = _io.BytesIO()
            Image.fromarray(arr).save(b, format="JPEG", quality=90)
            w.push(b.getvalue())
            f.write("%d\t%d\t%06d.jpg\n" % (i, i % 3, i))

    def chain(extra):
        return create_iterator([
            ("iter", "imgbin"),
            ("image_list", str(lst)), ("image_bin", str(binp)),
            ("input_shape", "3,112,112"), ("rand_crop", "1"),
            ("decode_at_scale", "1"), ("silent", "1"),
        ] + extra + [("iter", "threadbuffer"), ("batch_size", "4"),
                     ("round_batch", "1")])

    it = chain([])
    it.before_first()
    assert it.next()
    batch = it.value()
    assert batch.data.shape == (4, 3, 112, 112)
    if hasattr(it, "close"):
        it.close()

    # warp param present -> decode_at_scale must be ignored (the warp
    # geometry is defined on the full source frame): output still valid
    it2 = chain([("max_rotate_angle", "10")])
    it2.before_first()
    assert it2.next()
    assert it2.value().data.shape == (4, 3, 112, 112)
    if hasattr(it2, "close"):
        it2.close()
