"""Config tokenizer tests (reference grammar: src/utils/config.h)."""

import pytest

from cxxnet_tpu.utils.config import ConfigError, tokenize


def test_basic_pairs():
    assert tokenize("a = 1\nb=2\n c =3") == [("a", "1"), ("b", "2"), ("c", "3")]


def test_comments():
    text = "# header\na = 1  # trailing\n# full line\nb = 2"
    assert tokenize(text) == [("a", "1"), ("b", "2")]


def test_quoted_values():
    assert tokenize('p = "./data/x y.gz"') == [("p", "./data/x y.gz")]
    assert tokenize("p = 'a=b # not comment'") == [("p", "a=b # not comment")]


def test_multiline_quoted():
    assert tokenize("p = 'line1\nline2'") == [("p", "line1\nline2")]


def test_escapes():
    assert tokenize(r'p = "a\"b\n"') == [("p", 'a"b\n')]


def test_layer_decl_keys():
    pairs = tokenize("layer[+1:fc1] = fullc:fc1\n  nhidden = 100")
    assert pairs == [("layer[+1:fc1]", "fullc:fc1"), ("nhidden", "100")]


def test_ordered_not_deduped():
    assert tokenize("metric = error\nmetric = logloss") == [
        ("metric", "error"), ("metric", "logloss")]


def test_missing_equals():
    with pytest.raises(ConfigError):
        tokenize("novalue\n")


def test_unterminated_quote():
    with pytest.raises(ConfigError):
        tokenize("a = 'oops")


def test_with_lines_triples():
    triples = tokenize("a = 1\n# comment\nb = 2\np = 'x\ny'\nc = 3",
                       with_lines=True)
    assert triples == [("a", "1", 1), ("b", "2", 3), ("p", "x\ny", 4),
                       ("c", "3", 6)]


def test_unterminated_quote_carries_line():
    with pytest.raises(ConfigError) as ei:
        tokenize("a = 1\nb = 2\npath = 'oops")
    assert ei.value.line == 3
    assert "line 3" in str(ei.value)


def test_missing_equals_carries_line():
    with pytest.raises(ConfigError) as ei:
        tokenize("a = 1\n\nnovalue\n")
    assert ei.value.line == 3
