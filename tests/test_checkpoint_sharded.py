"""Sharded (orbax-backed) checkpointing of mesh-distributed state, including
resharding restores — the §5.4 upgrade for the GPT flagship."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_init, gpt_place, make_train_step
from cxxnet_tpu.parallel.mesh import make_mesh
from cxxnet_tpu.utils import checkpoint

CFG = GPTConfig(vocab_size=32, seq_len=16, n_layer=2, n_head=4, feat=32,
                n_microbatch=1)


def _tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip_sharded(tmp_path):
    mesh = make_mesh("cpu:0-7", model_parallel=2, seq_parallel=2)
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), CFG), mesh)
    checkpoint.save(tmp_path / "ckpt", params)
    back = checkpoint.restore(tmp_path / "ckpt", like=params)
    _tree_equal(params, back)
    # restored leaves keep the live shardings
    leaf = back["blocks"]["w_q"]
    assert leaf.sharding == params["blocks"]["w_q"].sharding


def test_reshard_on_restore(tmp_path):
    """Save from a tp2 x sp2 mesh, restore onto a pure-dp mesh and onto a
    tp4 mesh — values identical, placement follows the target."""
    mesh_a = make_mesh("cpu:0-7", model_parallel=2, seq_parallel=2)
    params = gpt_place(gpt_init(jax.random.PRNGKey(1), CFG), mesh_a)
    checkpoint.save(tmp_path / "c", params)

    mesh_b = make_mesh("cpu:0-7")                      # dp8
    target_b = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh_b)
    back_b = checkpoint.restore(tmp_path / "c", like=target_b)
    _tree_equal(params, back_b)

    mesh_c = make_mesh("cpu:0-7", model_parallel=4)    # dp2 x tp4
    target_c = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh_c)
    back_c = checkpoint.restore(tmp_path / "c", like=target_c)
    _tree_equal(params, back_c)
    assert back_c["blocks"]["w_q"].sharding == \
        target_c["blocks"]["w_q"].sharding


def test_training_resumes_identically(tmp_path):
    """Train 2 steps, checkpoint, train 2 more; reload and re-train the same
    2 — losses must match exactly (determinism across save/restore)."""
    mesh = make_mesh("cpu:0-7", model_parallel=2)
    params = gpt_place(gpt_init(jax.random.PRNGKey(2), CFG), mesh)
    mom = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh)
    step = make_train_step(CFG, mesh)
    rs = np.random.RandomState(0)
    ids = [jnp.asarray(rs.randint(0, 32, (8, CFG.seq_len)).astype(np.int32))
           for _ in range(4)]
    for i in range(2):
        params, mom, _ = step(params, mom, ids[i])
    checkpoint.save(tmp_path / "s", {"params": params, "mom": mom})
    ref_losses = []
    for i in range(2, 4):
        params, mom, loss = step(params, mom, ids[i])
        ref_losses.append(float(loss))

    state = checkpoint.restore(tmp_path / "s",
                               like={"params": params, "mom": mom})
    p2, m2 = state["params"], state["mom"]
    for i in range(2, 4):
        p2, m2, loss = step(p2, m2, ids[i])
        assert float(loss) == ref_losses[i - 2]


def test_restore_without_target_is_replicated(tmp_path):
    mesh = make_mesh("cpu:0-7", model_parallel=2)
    params = gpt_place(gpt_init(jax.random.PRNGKey(3), CFG), mesh)
    checkpoint.save(tmp_path / "r", params)
    back = checkpoint.restore(tmp_path / "r")
    _tree_equal(params, back)
