"""Top-k / top-p sampling (ops/sampling.py) and its decode-path wiring —
one filter implementation for the offline (gpt_decode) and serving
(serve/engine.py) surfaces, seeded-reproducible on both."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops.sampling import filter_logits, sample_rows

CFG_KW = dict(vocab_size=32, seq_len=24, n_layer=2, n_head=4, feat=32,
              n_microbatch=1)


def test_filter_topk_keeps_k_highest():
    logits = jnp.asarray([[1.0, 4.0, 2.0, 3.0, 0.0]])
    out = np.asarray(filter_logits(logits, top_k=2))
    np.testing.assert_array_equal(
        out[0], [-np.inf, 4.0, -np.inf, 3.0, -np.inf])


def test_filter_topk_zero_and_topp_one_are_noops():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(3, 16).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(filter_logits(logits)),
                                  np.asarray(logits))


def test_filter_topp_keeps_smallest_prefix():
    # softmax of [3, 2, 0, -1] ~ [.69, .25, .034, .013]: p=.8 keeps the
    # first two (cum .69 then .94 — the .94 entry is the nucleus edge)
    logits = jnp.asarray([[3.0, 2.0, 0.0, -1.0]])
    out = np.asarray(filter_logits(logits, top_p=0.8))
    np.testing.assert_array_equal(out[0], [3.0, 2.0, -np.inf, -np.inf])
    # p large enough keeps everything
    out = np.asarray(filter_logits(logits, top_p=0.999))
    assert np.isfinite(out).all()


def test_filter_topp_renormalized_after_topk():
    """Sequential semantics: the nucleus is measured on the top-k
    SURVIVORS' renormalized mass. Full dist [.5,.25,.15,.1]: p=0.6 over
    the raw mass would keep {0,1}; after top_k=2 the survivors
    renormalize to [2/3, 1/3], so 0 alone already covers p=0.6."""
    logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.15, 0.1]]))
    out = np.asarray(filter_logits(logits, top_k=2, top_p=0.6))
    np.testing.assert_array_equal(np.isfinite(out)[0],
                                  [True, False, False, False])


def test_filter_always_keeps_argmax():
    logits = jnp.asarray([[0.1, 5.0, 0.2]])
    for kw in (dict(top_k=1), dict(top_p=1e-6), dict(top_k=1, top_p=1e-6)):
        out = np.asarray(filter_logits(logits, **kw))
        np.testing.assert_array_equal(out[0], [-np.inf, 5.0, -np.inf])


def test_filter_per_row_params():
    """Per-row top_k arrays (the serving tick's case) apply row-wise."""
    logits = jnp.asarray([[1.0, 2.0, 3.0], [1.0, 2.0, 3.0]])
    out = np.asarray(filter_logits(logits, top_k=jnp.asarray([1, 0]),
                                   top_p=jnp.asarray([1.0, 1.0])))
    np.testing.assert_array_equal(out[0], [-np.inf, -np.inf, 3.0])
    np.testing.assert_array_equal(out[1], [1.0, 2.0, 3.0])


def test_sample_rows_restricted_and_greedy_mix():
    """Draws land inside the top-k set; temperature-0 rows take argmax."""
    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.randn(2, 16).astype(np.float32))
    top3 = set(np.argsort(np.asarray(logits)[0])[-3:].tolist())
    for s in range(20):
        keys = jnp.stack([jax.random.PRNGKey(s), jax.random.PRNGKey(s)])
        toks = np.asarray(sample_rows(
            logits, keys, jnp.asarray([1.0, 0.0]), jnp.asarray([3, 0]),
            jnp.asarray([1.0, 1.0])))
        assert int(toks[0]) in top3
        assert int(toks[1]) == int(np.argmax(np.asarray(logits)[1]))


def _decode_setup(seed=7):
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    cfg = GPTConfig(**CFG_KW)
    params = gpt_init(jax.random.PRNGKey(seed), cfg)
    prompt = jnp.asarray(np.zeros((2, 4), np.int32))
    return cfg, params, prompt


def test_decode_topk1_matches_greedy():
    """top_k=1 at any temperature collapses to the greedy stream — the
    filter is pinned against the decode path's own argmax."""
    from cxxnet_tpu.models.gpt import gpt_decode
    cfg, params, prompt = _decode_setup()
    greedy = np.asarray(gpt_decode(params, prompt, 6, cfg))
    k1 = np.asarray(gpt_decode(params, prompt, 6, cfg, temperature=1.0,
                               rng=jax.random.PRNGKey(0), top_k=1))
    np.testing.assert_array_equal(greedy, k1)
    tiny_p = np.asarray(gpt_decode(params, prompt, 6, cfg, temperature=1.0,
                                   rng=jax.random.PRNGKey(0), top_p=1e-6))
    np.testing.assert_array_equal(greedy, tiny_p)


def test_decode_topk_topp_seeded_reproducible():
    from cxxnet_tpu.models.gpt import gpt_decode
    cfg, params, prompt = _decode_setup()
    kw = dict(temperature=0.9, top_k=5, top_p=0.9)
    a = np.asarray(gpt_decode(params, prompt, 6, cfg,
                              rng=jax.random.PRNGKey(3), **kw))
    b = np.asarray(gpt_decode(params, prompt, 6, cfg,
                              rng=jax.random.PRNGKey(3), **kw))
    np.testing.assert_array_equal(a, b)
    c = np.asarray(gpt_decode(params, prompt, 6, cfg,
                              rng=jax.random.PRNGKey(4), **kw))
    assert not np.array_equal(a, c)     # a different seed actually samples


def test_decode_validates_sampling_params():
    from cxxnet_tpu.models.gpt import gpt_decode
    cfg, params, prompt = _decode_setup()
    with pytest.raises(ValueError, match="top_k"):
        gpt_decode(params, prompt, 2, cfg, top_k=-1)
    with pytest.raises(ValueError, match="top_p"):
        gpt_decode(params, prompt, 2, cfg, top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        gpt_decode(params, prompt, 2, cfg, top_p=1.5)


def _filtered_probs(logits, temperature, top_k, top_p):
    """Host-side expected distribution: the filtered softmax the
    speculative accept/residual pair must preserve."""
    filt = np.asarray(filter_logits(
        jnp.asarray(logits[None] / temperature, jnp.float32),
        top_k=top_k, top_p=top_p))[0]
    e = np.where(np.isfinite(filt), np.exp(filt - np.nanmax(
        np.where(np.isfinite(filt), filt, np.nan))), 0.0)
    return e / e.sum()


def _chi2(counts, probs, n):
    keep = probs > 0
    exp = probs[keep] * n
    return float(((counts[keep] - exp) ** 2 / exp).sum()), int(keep.sum())


def test_speculative_rejection_matches_direct_distribution():
    """The satellite's chi-squared check: emitting via the speculative
    accept/residual pair (accept the deterministic draft with prob
    p(draft), else sample the draft-excluded renormalized residual) must
    reproduce the SAME distribution as a direct sample_rows draw under
    top-k/top-p filters. Small vocab, many trials, generous chi-squared
    bound (p ~ 1e-4 rejection at the pinned df)."""
    from cxxnet_tpu.ops.sampling import (accept_draft_rows,
                                         residual_sample_rows)
    rs = np.random.RandomState(0)
    logits = rs.randn(8).astype(np.float32) * 2.0
    temperature, top_k, top_p = 0.9, 5, 0.9
    probs = _filtered_probs(logits, temperature, top_k, top_p)
    draft = int(np.argsort(probs)[-2])      # a plausible (2nd best) draft
    n = 4000
    # the rows APIs batch over independent requests, so the n trials
    # run as one n-row call each instead of an n-iteration host loop
    lrows = jnp.tile(jnp.asarray(logits)[None], (n, 1))
    t_rows = jnp.full((n,), temperature, jnp.float32)
    k_rows = jnp.full((n,), top_k, jnp.int32)
    p_rows = jnp.full((n,), top_p, jnp.float32)
    drafts = jnp.full((n,), draft, jnp.int32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n, dtype=jnp.uint32))
    k1 = jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys)
    k2 = jax.vmap(lambda k: jax.random.fold_in(k, 2))(keys)
    k3 = jax.vmap(lambda k: jax.random.fold_in(k, 3))(keys)
    acc = np.asarray(accept_draft_rows(lrows, drafts, k1, t_rows, k_rows,
                                       p_rows))
    resid = np.asarray(residual_sample_rows(lrows, drafts, k2, t_rows,
                                            k_rows, p_rows))
    spec_toks = np.where(acc, draft, resid)
    direct_toks = np.asarray(sample_rows(lrows, k3, t_rows, k_rows,
                                         p_rows))
    spec_counts = np.bincount(spec_toks, minlength=8).astype(float)
    direct_counts = np.bincount(direct_toks, minlength=8).astype(float)
    # the filters must actually bite in this setup (df > 1, < vocab)
    kept = int((probs > 0).sum())
    assert 2 <= kept < 8
    stat_spec, df = _chi2(spec_counts, probs, n)
    stat_direct, _ = _chi2(direct_counts, probs, n)
    # chi-squared 99.99% quantiles for df-1 in [1, 7]
    crit = {1: 15.1, 2: 18.4, 3: 21.1, 4: 23.5, 5: 25.7, 6: 27.9,
            7: 29.9}[df - 1]
    assert stat_spec < crit, (stat_spec, spec_counts, probs * n)
    assert stat_direct < crit, (stat_direct, direct_counts, probs * n)
    # no mass may leak outside the filtered candidate set
    assert spec_counts[probs == 0].sum() == 0


def test_speculative_greedy_accept_and_residual_rules():
    """Greedy rows: accept iff draft == argmax; the emitted token on a
    rejection is the plain argmax (the solo path's pick), never
    affected by the exclusion."""
    from cxxnet_tpu.ops.sampling import (accept_draft_rows,
                                         residual_sample_rows)
    logits = jnp.asarray([[1.0, 4.0, 2.0, 3.0],
                          [1.0, 4.0, 2.0, 3.0]])
    keys = jnp.stack([jax.random.PRNGKey(0), jax.random.PRNGKey(1)])
    zeros = jnp.zeros(2, jnp.float32)
    acc = np.asarray(accept_draft_rows(
        logits, jnp.asarray([1, 3]), keys, zeros,
        jnp.zeros(2, jnp.int32), jnp.ones(2, jnp.float32)))
    np.testing.assert_array_equal(acc, [True, False])
    out = np.asarray(residual_sample_rows(
        logits, jnp.asarray([3, 1]), keys, zeros,
        jnp.zeros(2, jnp.int32), jnp.ones(2, jnp.float32)))
    np.testing.assert_array_equal(out, [1, 1])


def test_residual_excludes_draft_in_sampled_rows():
    """Sampled rejection rows never re-emit the rejected draft token."""
    from cxxnet_tpu.ops.sampling import residual_sample_rows
    rs = np.random.RandomState(2)
    logits = jnp.asarray(rs.randn(1, 6).astype(np.float32))
    draft = int(np.argmax(np.asarray(logits)[0]))    # exclude the mode
    for s in range(50):
        tok = int(np.asarray(residual_sample_rows(
            logits, jnp.asarray([draft]), jax.random.PRNGKey(s)[None],
            jnp.asarray([1.0], jnp.float32), jnp.asarray([0], jnp.int32),
            jnp.asarray([1.0], jnp.float32)))[0])
        assert tok != draft


def test_net_generate_topk_through_config_surface():
    """generate_topk/generate_topp reach the decode from the Net surface
    (wrapper + nnet.lm), reproducibly for a fixed seed."""
    from cxxnet_tpu import wrapper
    from cxxnet_tpu.models import gpt_lm_config

    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=4, dev="cpu:0")
    net = wrapper.Net(cfg=cfg)
    net.init_model()
    prompt = np.zeros((2, 4), np.int32)
    a = net.generate(prompt, max_new=3, temperature=0.8, seed=5, top_k=4,
                     top_p=0.9)
    b = net.generate(prompt, max_new=3, temperature=0.8, seed=5, top_k=4,
                     top_p=0.9)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 7)
