"""Paged KV cache (serve/paged.py, serve/engine.py paged programs,
serve/prefix_cache.py:PagedPrefixCache, scheduler preemption/swap).

The load-bearing invariants:

1. **bit identity** — the paged engine's served tokens equal the dense
   engine's AND the solo ``gpt_decode`` oracle for every workload shape
   (chunked, non-multiple lengths, prefix hits, recycled rows,
   speculative, sampled);
2. **copy-on-write** — a write into a shared block faults a private
   copy; the shared block's bytes are untouched;
3. **no leaks** — every block returns to the free list at drain
   (refcount accounting is exact);
4. **preempt -> swap -> resume identity** — a row swapped to host and
   resumed later produces the same tokens as an undisturbed run, and a
   pool several times smaller than the working set still finishes every
   request;
5. **one compiled signature per paged program** across mixed prompt
   lengths, occupancy, and block placement (RecompileGuard-pinned), and
   the compiled-step audit passes with the block pool fully
   donation-aliased.
"""

import time

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (BlockPoolExhausted, DecodeEngine,
                              InferenceServer, auto_num_blocks)

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


def _chunked_admit(eng, slot, prompt, key, temp=0.0, top_k=0, top_p=1.0):
    """Drive a paged engine's chunk prefill by hand (reserve + chunk
    windows); returns the first sampled token."""
    n = len(prompt)
    tok = None
    for start in range(0, n, eng.chunk):
        end = min(start + eng.chunk, n)
        eng.reserve_window(slot, start, start + eng.chunk)
        buf = np.zeros(eng.chunk, np.int32)
        buf[:end - start] = prompt[start:end]
        tok = eng.prefill_chunk(slot, buf, start, end - start, key, temp,
                                top_k, top_p)
    return int(tok)


# ------------------------------------------------------- token identity
def test_paged_vs_dense_bit_identity_mixed_workload():
    """The tentpole invariant: the same mixed workload — non-multiple
    prompt lengths, mixed sampling params, shared prefixes, more
    requests than slots (recycled rows) — served by the paged and the
    dense engine produces IDENTICAL tokens, both equal to the solo
    gpt_decode oracle."""
    rs = np.random.RandomState(0)
    shared = _prompt(rs, 12)
    cases = [
        dict(p=_prompt(rs, 3), max_tokens=5),
        dict(p=_prompt(rs, 9), max_tokens=6, temperature=0.8, top_k=5,
             top_p=0.9, seed=7),
        dict(p=np.concatenate([shared, _prompt(rs, 3)]), max_tokens=5,
             temperature=0.7, seed=2),
        dict(p=np.concatenate([shared, _prompt(rs, 5)]), max_tokens=5,
             temperature=0.7, seed=9),
        dict(p=_prompt(rs, 13), max_tokens=5),
        dict(p=_prompt(rs, 8), max_tokens=4, temperature=1.2, top_k=3,
             seed=11),
    ]
    outs = {}
    for paged in (True, False):
        with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                             prefill_chunk=4, paged=paged) as srv:
            hs = [srv.submit(c["p"], **{k: v for k, v in c.items()
                                        if k != "p"}) for c in cases]
            outs[paged] = [srv.result(h, timeout=300) for h in hs]
            m = srv.metrics()
        assert all(r.status == "ok" for r in outs[paged])
        if paged:
            assert m["prefix_cache"]["hits"] >= 1   # zero-copy hits ran
            assert m["paged"]["blocks"]["free"] > 0
    for c, rp, rd in zip(cases, outs[True], outs[False]):
        kw = {k: v for k, v in c.items() if k not in ("p", "max_tokens")}
        ref = _ref(c["p"], c["max_tokens"], **kw)
        np.testing.assert_array_equal(rp.tokens, ref)
        np.testing.assert_array_equal(rp.tokens, rd.tokens)


def test_paged_speculative_identity():
    """Greedy speculative serving over the paged engine stays
    bit-identical to the solo oracle (the verify window's blocks are
    reserved — never COW-faulted on rollback — before each forward)."""
    rs = np.random.RandomState(3)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base, base])     # n-gram bait
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="ngram", spec_len=3) as srv:
        res = srv.result(srv.submit(prompt, max_tokens=8), timeout=300)
        m = srv.metrics()
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(prompt, 8))
    assert m["paged"] is not None and m["spec_forwards"] >= 1


# ---------------------------------------------------------------- COW
def test_cow_fault_preserves_shared_block():
    """Writing into a window that overlaps a SHARED block faults a
    private copy first: the shared block's bytes are bit-unchanged, the
    write lands in the copy, and the row's table points at the copy."""
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30)
    rs = np.random.RandomState(1)
    prompt = _prompt(rs, 8)
    key = np.asarray(jax.random.PRNGKey(0), np.uint32)
    _chunked_admit(eng, 0, prompt, key)
    m = eng.manager
    b0 = int(m.table[0, 0])
    m.incref(b0)                        # a second owner (as a trie node
    #                                     or another row's table would)
    snap_k = np.asarray(eng.cache_k[:, b0]).copy()
    snap_v = np.asarray(eng.cache_v[:, b0]).copy()
    eng.reserve_window(0, 0, 1)         # window overlaps shared block
    assert m.cow_faults == 1
    priv = int(m.table[0, 0])
    assert priv != b0 and m.ref[b0] == 1 and m.ref[priv] == 1
    np.testing.assert_array_equal(np.asarray(eng.cache_k[:, b0]), snap_k)
    np.testing.assert_array_equal(np.asarray(eng.cache_v[:, b0]), snap_v)
    # the private copy carries the same prefix K/V, so attention through
    # the new table is unchanged
    np.testing.assert_array_equal(np.asarray(eng.cache_k[:, priv]),
                                  snap_k)
    m.decref(b0)
    eng.close()


def test_reserve_is_all_or_nothing_on_exhaustion():
    """A reserve that cannot fit raises BEFORE mutating anything: the
    free list, refcounts and tables are exactly as before, so the
    scheduler can evict/preempt and retry safely."""
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=13)          # 12 usable + garbage
    m = eng.manager
    eng.reserve_window(0, 0, 44)               # 11 of 12 usable blocks
    free_before = m.free_count
    with pytest.raises(BlockPoolExhausted) as e:
        eng.reserve_window(1, 0, 8)            # needs 2, only 1 free
    assert e.value.short == 1
    assert m.free_count == free_before and m.nblocks[1] == 0
    eng.close()


# ------------------------------------------------------------- leaks
def test_every_block_freed_at_drain():
    """Refcount/leak accounting: after serving shared-prefix traffic
    (trie donations, zero-copy hits, recycled rows) and draining, every
    block is back on the free list — free_count == num_blocks - 1 (all
    but the reserved garbage block)."""
    rs = np.random.RandomState(4)
    shared = _prompt(rs, 8)
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=16, prefill_chunk=4,
                          prefix_mb=1.0)
    prompts = [np.concatenate([shared, _prompt(rs, k)])
               for k in (3, 5, 2, 7, 4)]
    hs = [srv.submit(p, max_tokens=4) for p in prompts]
    assert all(srv.result(h, timeout=300).status == "ok" for h in hs)
    eng = srv._engine
    m = eng.manager
    # mid-life: the trie retains blocks (ref >= 1), rows are drained
    assert srv.metrics()["prefix_cache"]["hits"] >= 1
    srv.shutdown(drain=True)
    assert m.free_count == eng.num_blocks - 1, m.counts()
    assert int((m.ref[1:] != 0).sum()) == 0


# --------------------------------------------------- preemption / swap
def test_preempt_swap_resume_identity_under_tiny_pool():
    """A block pool ~2x smaller than the concurrent working set forces
    preemption: rows are swapped to host, resumed later, and every
    request still produces the oracle's exact tokens. The swap counters
    and the cxn_blocks_*/cxn_swap_* metric families record it."""
    rs = np.random.RandomState(6)
    prompts = [_prompt(rs, 6) for _ in range(3)]
    # peak need: 3 rows x ceil((6+20)/4)=7 blocks = 21; pool holds 14
    srv = InferenceServer(CFG, PARAMS, slots=3, queue=8, prefill_chunk=4,
                          prefix_mb=0.0, num_blocks=15)
    hs = [srv.submit(p, max_tokens=20) for p in prompts]
    res = [srv.result(h, timeout=300) for h in hs]
    m = srv.metrics()
    text = srv.metrics_text()
    srv.shutdown()
    assert [r.status for r in res] == ["ok"] * 3
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, 20))
    assert m["paged"]["swaps_out"] >= 1, m["paged"]
    assert m["paged"]["swaps_in"] >= 1
    assert m["paged"]["swapped_pending"] == 0
    assert m["paged"]["swap_host_bytes"] == 0       # all resumed
    for name in ("cxn_blocks_free", "cxn_blocks_shared",
                 "cxn_blocks_private", "cxn_swap_out_total",
                 "cxn_swap_in_total", "cxn_cow_faults_total",
                 "cxn_serve_kv_utilization", "cxn_swap_host_bytes"):
        assert "# TYPE %s " % name in text, name
    # the ledger publishes the pool + host pools under cxn_device_bytes
    assert 'cxn_device_bytes{pool="kv_blocks"}' in text
    assert 'cxn_device_bytes{pool="swap_host"}' in text


def test_capacity_beyond_dense_equivalent_budget():
    """The acceptance geometry: a pool holding ~2 dense rows' worth of
    KV serves 8 CONCURRENT short requests (dense would cap at 2), all
    bit-identical to the oracle — occupancy scales with tokens in
    flight, not rows."""
    rs = np.random.RandomState(7)
    # 8 requests x (6 prompt + 6 gen = 12 tokens -> 3 blocks) = 24
    # blocks at peak; dense-2-slot equivalent is 2 * 48 / 4 = 24 + 1
    srv = InferenceServer(CFG, PARAMS, slots=8, queue=16, prefill_chunk=4,
                          prefix_mb=0.0, num_blocks=25)
    prompts = [_prompt(rs, 6) for _ in range(8)]
    hs = [srv.submit(p, max_tokens=6) for p in prompts]
    res = [srv.result(h, timeout=300) for h in hs]
    m = srv.metrics()
    srv.shutdown()
    assert [r.status for r in res] == ["ok"] * 8
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r.tokens, _ref(p, 6))
    # pool bytes = what TWO dense rows (+1 block) would pin, yet the
    # batch efficiency shows rows actually ran concurrently
    eng_bytes = m["kv_cache_bytes"]
    dense8_bytes = 2 * CFG.n_layer * 8 * CFG.n_head * 48 \
        * (CFG.feat // CFG.n_head) * 4
    assert eng_bytes < dense8_bytes / 3
    assert m["batch_efficiency"] > 0.25 or m["paged"]["swaps_out"] > 0


def test_live_prefix_sharing_between_concurrent_rows():
    """Donation happens at prefill COMPLETION, so a second request hits
    the first one's blocks while the first is still decoding — live-row
    sharing, no retire needed."""
    rs = np.random.RandomState(8)
    prompt = _prompt(rs, 9)
    with InferenceServer(CFG, PARAMS, slots=2, queue=8,
                         prefill_chunk=4, prefix_mb=1.0) as srv:
        ha = srv.submit(prompt, max_tokens=30)
        deadline = time.time() + 60
        while srv._sched.requests_prefilled < 1 \
                and time.time() < deadline:
            time.sleep(0.005)
        hb = srv.submit(prompt, max_tokens=4)
        res_b = srv.result(hb, timeout=300)
        b_hit = srv.metrics()["prefix_cache"]["hit_tokens"]
        res_a = srv.result(ha, timeout=300)
    assert res_a.status == "ok" and res_b.status == "ok"
    np.testing.assert_array_equal(res_a.tokens, _ref(prompt, 30))
    np.testing.assert_array_equal(res_b.tokens, _ref(prompt, 4))
    # b restored the shared chunks (8 tokens: cap excludes the final
    # token's chunk) — from a LIVE row's table, zero copies
    assert b_hit >= 8, b_hit


# ------------------------------------------- compiled-program hygiene
def test_one_compiled_signature_across_mixed_lengths_and_occupancy():
    """30 mixed-length requests through a strict RecompileGuard: the
    paged chunk program, the batched tick, and the verify program each
    hold exactly ONE compiled signature (the acceptance bound)."""
    rs = np.random.RandomState(9)
    with InferenceServer(CFG, PARAMS, slots=3, queue=64, prefill_chunk=4,
                         recompile_limit=1, recompile_strict=True,
                         spec_mode="ngram", spec_len=2) as srv:
        hs = [srv.submit(_prompt(rs, 1 + (i * 7) % 20), max_tokens=3)
              for i in range(30)]
        assert all(srv.result(h, timeout=300).status == "ok"
                   for h in hs)
        eng = srv._engine
        assert len(eng.prefill_signatures) == 1, eng.prefill_signatures
        assert len(eng.tick_signatures) == 1, eng.tick_signatures
        assert len(eng.verify_signatures) <= 1


def test_paged_audit_fully_aliased():
    """cxn-lint pass 2 on the paged engine: chunk/verify/tick programs
    with abstract block-table inputs, both pool buffers donation-
    aliased end to end (pinned with donate=True on the CPU mesh)."""
    from cxxnet_tpu.analysis import audit_serve_engine
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, spec_len=2)
    report, infos = audit_serve_engine(eng, donate=True)
    assert report.ok(), report.format()
    labels = [i["label"] for i in infos]
    assert labels == ["serve_prefill_chunk", "serve_verify_chunk",
                      "serve_tick"]
    for info in infos:
        assert info["donated"] == 2 and info["aliased"] == 2, info
    eng.close()


def test_paged_abstract_engine_audits_without_allocation():
    """The lint tool's path: abstract=True builds ShapeDtypeStruct
    pools — lint_specs rows exist, nothing was allocated."""
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, spec_len=2, abstract=True)
    labels = [row[0] for row in eng.lint_specs(donate=True)]
    assert labels == ["serve_prefill_chunk", "serve_verify_chunk",
                      "serve_tick"]
    assert isinstance(eng.cache_k, jax.ShapeDtypeStruct)


# ------------------------------------------------------- validation
def test_validation_errors():
    with pytest.raises(ValueError, match="divide"):
        DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=4, num_blocks=20,
                     block_size=3)
    with pytest.raises(ValueError, match="cannot hold one full row"):
        DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=4, num_blocks=4)
    with pytest.raises(ValueError, match="chunked prefill"):
        DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=0, num_blocks=20)
    with pytest.raises(ValueError, match="cannot hold one full row"):
        # a kv_mb budget too small for one row fails loudly, not subtly
        InferenceServer(CFG, PARAMS, slots=1, queue=2, prefill_chunk=4,
                        kv_mb=0.001)


def test_auto_sizing_formula():
    """auto_num_blocks: dense-equivalent rows + capped trie headroom +
    garbage; an explicit kv_mb budget wins."""
    nb = auto_num_blocks(CFG, slots=2, prefill_chunk=4, prefix_mb=0.0)
    assert nb == 2 * 12 + 1                     # bpr = 48 / 4 = 12
    nb_budget = auto_num_blocks(CFG, slots=2, prefill_chunk=4,
                                kv_mb=1.0)
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=nb_budget)
    assert abs(eng.cache_bytes() - (1 << 20)) < eng.block_bytes()
    eng.close()


def test_sub_chunk_block_size_identity():
    """block_size < chunk (finer occupancy granularity) keeps identity:
    chunk windows span several blocks per scatter."""
    rs = np.random.RandomState(10)
    prompt = _prompt(rs, 9)
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=8,
                         block_size=4) as srv:
        res = srv.result(srv.submit(prompt, max_tokens=6), timeout=300)
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(prompt, 6))
