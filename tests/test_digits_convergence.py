"""Real-data convergence: the example MNIST recipes on REAL scanned
digits (UCI handwritten digits via scikit-learn — this sandbox cannot
download MNIST itself), end to end through the CLI.

This is the accuracy-parity complement of test_train_e2e's synthetic
smoke run (VERDICT r1: "convergence test bar is too low"): a separable
synthetic set catches total breakage, while these runs catch
optimizer/BN/init math drift — the traces are recorded in
example/MNIST/README.md. ~2 min of CPU; the slowest tests in the suite.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

EXDIR = os.path.join(os.path.dirname(__file__), "..", "example", "MNIST")


@pytest.fixture(scope="module")
def digits_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("digits")
    sys.path.insert(0, EXDIR)
    try:
        from digits_data import write_idx
    finally:
        sys.path.pop(0)
    write_idx(str(d / "data-digits"))
    return d


def _final_eval_error(conf: str, workdir: str) -> float:
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.abspath(os.path.join(EXDIR, "..", ".."))]
        + [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
           if p and ".axon_site" not in p]),
        JAX_PLATFORMS="cpu")
    # single-device run: the configs' batch 100 (reference parity) does
    # not divide the suite's virtual 8-device mesh
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu",
         os.path.join(EXDIR, conf)],
        cwd=workdir, env=env, capture_output=True, text=True, timeout=1200)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stderr.splitlines() if l.startswith("[30]")]
    assert lines, "no round-30 eval line:\n" + r.stderr[-2000:]
    return float(lines[-1].split("test-error:")[1].split()[0])


def test_mlp_converges_on_real_digits(digits_dir):
    # recorded trace lands 4.0%; threshold leaves noise headroom
    err = _final_eval_error("DIGITS.conf", str(digits_dir))
    assert err <= 0.07, "MLP real-digits error %.3f > 7%%" % err


def test_conv_converges_on_real_digits(digits_dir):
    # recorded trace lands 6.0%
    err = _final_eval_error("DIGITS_CONV.conf", str(digits_dir))
    assert err <= 0.10, "conv real-digits error %.3f > 10%%" % err
