"""Transformer encoder through the config DSL, incl. sequence parallelism.

The toy task: classify which token id dominates a random sequence — linearly
separable through attention pooling, so a 2-block encoder reaches ~0 error in
a few steps. The sequence-parallel run must track the single-shard run
(differential testing, SURVEY §4.1 spirit).
"""

import jax
import numpy as np
import pytest

from cxxnet_tpu import Net
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models import transformer_config
from cxxnet_tpu.utils.config import tokenize

SEQ, VOCAB, NCLS = 32, 16, 4


def _batch(seed, n=16):
    rs = np.random.RandomState(seed)
    cls = rs.randint(0, NCLS, n)
    ids = rs.randint(NCLS, VOCAB, (n, SEQ))
    # majority token = class id: overwrite half the positions
    for i in range(n):
        pos = rs.choice(SEQ, SEQ // 2, replace=False)
        ids[i, pos] = cls[i]
    x = ids.astype(np.float32).reshape(n, 1, 1, SEQ)
    y = cls.astype(np.float32).reshape(n, 1)
    return DataBatch(x, y)


def _make_net(**kw):
    cfg = transformer_config(seq_len=SEQ, vocab_size=VOCAB, feat=32, nhead=4,
                             nblock=2, num_classes=NCLS, batch_size=16, **kw)
    net = Net(tokenize(cfg))
    net.set_param("seed", "7")
    net.init_model()
    return net


def _train(net, steps=30):
    for i in range(steps):
        net.update(_batch(i))
    return net


def test_transformer_learns():
    net = _train(_make_net(dev="cpu:0"))
    b = _batch(999)
    pred = net.predict(b)
    err = float((pred != b.label[:, 0]).mean())
    assert err <= 0.25, "toy transformer failed to learn (err=%.2f)" % err


def test_seq_parallel_matches_single_device():
    ref = _train(_make_net(dev="cpu:0"), steps=5)
    net = _train(_make_net(dev="cpu:0-7", seq_parallel=4), steps=5)
    assert net.mesh.shape["seq"] == 4
    ra = jax.tree.leaves(jax.tree.map(np.asarray, ref.params))
    rb = jax.tree.leaves(jax.tree.map(np.asarray, net.params))
    for a, b in zip(ra, rb):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-4)


def test_causal_transformer_trains():
    net = _make_net(dev="cpu:0-7", seq_parallel=2, model_parallel=2, causal=1)
    before = [np.asarray(t).copy() for t in jax.tree.leaves(net.params)]
    net.update(_batch(0))
    after = [np.asarray(t) for t in jax.tree.leaves(net.params)]
    assert any(np.abs(a - b).sum() > 0 for a, b in zip(after, before))


def test_transformer_ulysses_matches_ring():
    """Same net, same seed: sp2 training with ulysses attention must land
    on the same params as ring attention (both equal the exact math)."""
    import jax
    from cxxnet_tpu.models import transformer_config

    def run(mode):
        cfg = transformer_config(seq_len=16, vocab_size=16, feat=16,
                                 nhead=2, nblock=1, num_classes=4,
                                 batch_size=16, dev="cpu:0-7",
                                 seq_parallel=2, causal=1,
                                 seq_parallel_mode=mode)
        net = Net(tokenize(cfg))
        net.init_model()
        rs = np.random.RandomState(0)
        for i in range(3):
            ids = rs.randint(0, 16, (16, 1, 1, 16)).astype(np.float32)
            lab = rs.randint(0, 4, (16, 1)).astype(np.float32)
            net.update(DataBatch(ids, lab))
        return {"%s/%s" % (l, t): np.asarray(w)
                for l, tags in net.params.items()
                for t, w in tags.items()}

    ring = run("ring")
    uly = run("ulysses")
    assert ring.keys() == uly.keys()
    for k in ring:
        np.testing.assert_allclose(uly[k], ring[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)
