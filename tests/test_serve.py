"""The continuous-batching serving subsystem (cxxnet_tpu/serve/):
scheduler correctness pinned against the offline decode path, admission
semantics (FIFO + deadline + bounded-queue backpressure), lifecycle
(timeout, drain/shutdown, no leaked slots or threads), and the CLI /
wrapper surfaces. The load-bearing invariant everywhere: a request
served from ANY slot — fresh or recycled, alone or interleaved with
mixed-length neighbours — produces tokens identical to running it alone
through gpt_decode with the same sampling params and seed."""

import threading
import time

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (AdmissionError, InferenceServer,
                              QueueFullError, SamplingParams)

CFG = GPTConfig(vocab_size=32, seq_len=40, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    """The offline oracle: the same request run alone through
    gpt_decode."""
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


def test_concurrent_mixed_requests_match_offline_path():
    """The acceptance invariant: N concurrent mixed-length requests with
    mixed sampling params each reproduce their solo gpt_decode run."""
    rs = np.random.RandomState(0)
    cases = [
        dict(n=4, max_tokens=6),
        dict(n=7, max_tokens=5, temperature=1.0, seed=3),
        dict(n=3, max_tokens=8, temperature=0.8, top_k=5, top_p=0.9,
             seed=7),
        dict(n=5, max_tokens=4),
        dict(n=6, max_tokens=7, temperature=1.2, top_k=3, seed=11),
    ]
    with InferenceServer(CFG, PARAMS, slots=3, queue=16) as srv:
        handles = []
        for c in cases:
            c = dict(c)
            c["prompt"] = _prompt(rs, c.pop("n"))
            handles.append((c, srv.submit(c["prompt"],
                                          **{k: v for k, v in c.items()
                                             if k != "prompt"})))
        for c, h in handles:
            res = srv.result(h, timeout=300)
            assert res.status == "ok", (res.status, res.error)
            kw = {k: v for k, v in c.items() if k not in ("prompt",
                                                          "max_tokens")}
            np.testing.assert_array_equal(
                res.tokens, _ref(c["prompt"], c["max_tokens"], **kw))
            assert res.ttft_ms > 0


def test_recycled_slot_matches_fresh_decode():
    """Slot-reuse correctness: with ONE slot, the second request lands in
    the slot the first just vacated — its tokens must equal a fresh solo
    decode (prefill must fully evict the previous occupant's KV rows)."""
    rs = np.random.RandomState(1)
    a, b = _prompt(rs, 6), _prompt(rs, 9)
    with InferenceServer(CFG, PARAMS, slots=1, queue=8) as srv:
        ha = srv.submit(a, max_tokens=8, temperature=0.7, seed=2)
        hb = srv.submit(b, max_tokens=8, temperature=0.7, seed=9)
        res_a = srv.result(ha, timeout=300)
        res_b = srv.result(hb, timeout=300)
        assert hb.slot == ha.slot == 0
    np.testing.assert_array_equal(
        res_a.tokens, _ref(a, 8, temperature=0.7, seed=2))
    np.testing.assert_array_equal(
        res_b.tokens, _ref(b, 8, temperature=0.7, seed=9))


def test_eos_retires_early_and_frees_slot():
    """A request whose eos token appears stops there (eos included), and
    the freed slot admits the next queued request."""
    rs = np.random.RandomState(2)
    p = _prompt(rs, 5)
    full = _ref(p, 10)
    gen = full[len(p):]
    # first generated token that did not already occur earlier in the
    # stream (greedy streams repeat; an earlier duplicate would stop the
    # served request sooner than the slice below expects)
    i = next((j for j in range(1, len(gen))
              if int(gen[j]) not in gen[:j].tolist()), 0)
    eos = int(gen[i])
    with InferenceServer(CFG, PARAMS, slots=1, queue=4) as srv:
        h = srv.submit(p, max_tokens=10, eos=eos)
        res = srv.result(h, timeout=300)
        h2 = srv.submit(p, max_tokens=2)        # slot must be free again
        assert srv.result(h2, timeout=300).status == "ok"
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, full[:len(p) + i + 1])
    assert int(res.tokens[-1]) == eos


def test_fifo_admission_order_with_deadline_skips():
    """Admission is FIFO over non-expired requests: with one slot held by
    a long request, a queued request whose deadline lapses is skipped
    (finishing as timeout) while later submissions keep their order."""
    rs = np.random.RandomState(3)
    with InferenceServer(CFG, PARAMS, slots=1, queue=8) as srv:
        # 30-tick holder vs a 1 ms deadline: >= 15 ms of occupancy even
        # with every program warm, so hb's expiry cannot race the slot
        # freeing up (same margin pattern as the timeout test below)
        ha = srv.submit(_prompt(rs, 4), max_tokens=30)      # occupies slot
        hb = srv.submit(_prompt(rs, 4), max_tokens=2, timeout_ms=1.0)
        hc = srv.submit(_prompt(rs, 4), max_tokens=2)
        hd = srv.submit(_prompt(rs, 4), max_tokens=2)
        res_b = srv.result(hb, timeout=300)
        for h in (ha, hc, hd):
            assert srv.result(h, timeout=300).status == "ok"
        order = list(srv._sched.admit_order)
    assert res_b.status == "timeout"
    assert "ms in queue" in res_b.error
    assert order == [ha.rid, hc.rid, hd.rid]


def test_queue_full_rejection_with_reason():
    rs = np.random.RandomState(4)
    with InferenceServer(CFG, PARAMS, slots=1, queue=2) as srv:
        slow = srv.submit(_prompt(rs, 4), max_tokens=12)
        # wait until it is admitted so the queue is truly empty
        deadline = time.time() + 60
        while slow.status == "queued" and time.time() < deadline:
            time.sleep(0.01)
        q1 = srv.submit(_prompt(rs, 4), max_tokens=2)
        q2 = srv.submit(_prompt(rs, 4), max_tokens=2)
        with pytest.raises(QueueFullError, match="admission queue full"):
            srv.submit(_prompt(rs, 4), max_tokens=2)
        assert srv.metrics()["requests"]["rejected"] == 1
        for h in (slow, q1, q2):
            assert srv.result(h, timeout=300).status == "ok"


def test_unservable_prompts_rejected():
    with InferenceServer(CFG, PARAMS, slots=1, queue=2) as srv:
        with pytest.raises(AdmissionError, match="empty"):
            srv.submit(np.zeros((0,), np.int32))
        with pytest.raises(AdmissionError, match="no room"):
            srv.submit(np.zeros((CFG.seq_len,), np.int32))
        with pytest.raises(AdmissionError, match="max_tokens"):
            srv.submit(np.zeros((4,), np.int32), max_tokens=0)


def test_timeout_expires_while_slots_busy():
    """A queued request past its deadline times out even though no slot
    ever frees for it (the scheduler expires deadlines every pass, not
    only at admission)."""
    rs = np.random.RandomState(5)
    with InferenceServer(CFG, PARAMS, slots=1, queue=8,
                         timeout_ms=30.0) as srv:
        # the slot holder carries NO deadline (explicit params) and runs
        # ~30 ticks — far longer than the waiter's 2 ms budget even with
        # every program warm
        long = srv.submit(_prompt(rs, 4),
                          params=SamplingParams(max_tokens=30))
        h = srv.submit(_prompt(rs, 4), max_tokens=2, timeout_ms=2.0)
        res = srv.result(h, timeout=300)
        assert res.status == "timeout"
        assert res.tokens.size == 0
        assert srv.result(long, timeout=300).status == "ok"
        assert srv.metrics()["requests"]["timeout"] == 1


def test_drain_shutdown_finishes_work_and_frees_everything():
    rs = np.random.RandomState(6)
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8)
    handles = [srv.submit(_prompt(rs, 4 + i), max_tokens=4)
               for i in range(5)]
    srv.shutdown(drain=True)
    for h in handles:
        assert srv.result(h, timeout=1).status == "ok"
    assert srv._sched.active == 0
    assert srv._sched.free_slots == 2
    assert srv._engine.cache_k is None          # buffers dropped
    assert not srv._thread.is_alive()
    srv.shutdown()                              # idempotent
    with pytest.raises(AdmissionError, match="shutting down"):
        srv.submit(_prompt(rs, 4))


def test_abort_shutdown_cancels_queued_and_active():
    rs = np.random.RandomState(7)
    srv = InferenceServer(CFG, PARAMS, slots=1, queue=8)
    handles = [srv.submit(_prompt(rs, 4), max_tokens=25)
               for _ in range(3)]
    srv.shutdown(drain=False)
    statuses = [srv.result(h, timeout=5).status for h in handles]
    assert "cancelled" in statuses              # queued ones for sure
    assert all(s in ("ok", "cancelled") for s in statuses)
    assert srv._sched.active == 0
    assert srv._sched.free_slots == 1
    assert not srv._thread.is_alive()


def test_blocking_submit_applies_backpressure():
    """submit(block=True) waits for queue space instead of rejecting (the
    CLI stdin loop's mode)."""
    rs = np.random.RandomState(8)
    with InferenceServer(CFG, PARAMS, slots=1, queue=1) as srv:
        handles = [srv.submit(_prompt(rs, 4), max_tokens=3, block=True)
                   for _ in range(4)]
        assert [srv.result(h, timeout=300).status
                for h in handles] == ["ok"] * 4
        assert srv.metrics()["requests"]["rejected"] == 0


def test_serve_metrics_shape():
    rs = np.random.RandomState(9)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4) as srv:
        for h in [srv.submit(_prompt(rs, 4), max_tokens=3)
                  for _ in range(3)]:
            srv.result(h, timeout=300)
        m = srv.metrics()
    assert m["requests"]["completed"] == 3
    assert m["tokens_generated"] == 9
    for key in ("ttft_ms", "token_ms", "queue_wait_ms", "prefill_ms",
                "decode_tick_ms"):
        assert set(m[key]) == {"p50", "p95", "p99"}, key
    assert m["ttft_ms"]["p95"] >= m["ttft_ms"]["p50"] > 0
    assert 0 < m["batch_efficiency"] <= 1
    assert m["kv_cache_bytes"] > 0


def test_wrapper_serve_api():
    """The reference-style surface: Net.serve_* against a config-DSL net,
    pinned token-identical to Net.generate on the same request."""
    from cxxnet_tpu import wrapper
    from cxxnet_tpu.models import gpt_lm_config

    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=4, dev="cpu:0")
    net = wrapper.Net(cfg=cfg)
    net.init_model()
    prompt = np.arange(4, dtype=np.int32) % 32
    want = net.generate(prompt[None], max_new=5, temperature=0.9, seed=3)
    net.serve_start(slots=2, queue=4, max_tokens=5)
    try:
        h = net.serve_submit(prompt, temperature=0.9, seed=3)
        res = net.serve_result(h, timeout=300)
        assert res.status == "ok"
        np.testing.assert_array_equal(res.tokens, want[0])
        assert net.serve_metrics()["requests"]["completed"] == 1
        with pytest.raises(RuntimeError, match="already running"):
            net.serve_start()
    finally:
        net.serve_stop()
    with pytest.raises(RuntimeError, match="no server"):
        net.serve_submit(prompt)
    net.serve_stop()                            # idempotent


def test_cli_task_serve(tmp_path, capfd, monkeypatch):
    """task=serve end to end: train a tiny net via the CLI, snapshot,
    then serve prompts from stdin — outputs in submission order and
    token-identical to task=generate on the same snapshot."""
    import io as _io

    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.models import gpt_lm_config

    corpus = tmp_path / "corpus.bin"
    toks = np.tile(np.arange(16, dtype=np.uint16), 40)
    corpus.write_bytes(toks.tobytes())
    conf = tmp_path / "gpt.conf"
    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=8, dev="cpu:0", eta=0.2)
    conf.write_text("""
data = train
iter = lm
    path_data = "%s"
    token_dtype = uint16
    seq_len = 16
    stride = 8
iter = end
%s
num_round = 1
save_model = 1
model_dir = %s
""" % (corpus, cfg, tmp_path / "models"))
    assert LearnTask().run([str(conf)]) == 0
    model = tmp_path / "models" / "0001.model"

    # offline reference for the same prompts (equal lengths required by
    # generate, so reference them one line at a time)
    prompts = tmp_path / "p.txt"
    gen_out = tmp_path / "g.txt"
    want = []
    for line in ("0 1 2 3", "4 5 6 7 8"):
        prompts.write_text(line + "\n")
        assert LearnTask().run([
            str(conf), "task=generate", "model_in=%s" % model,
            "prompt_file=%s" % prompts, "num_gen=4",
            "generate_out=%s" % gen_out]) == 0
        want.append(gen_out.read_text().split())
    capfd.readouterr()

    # a malformed line and an oversized prompt must each get their ERR
    # output slot (in order) without taking down the serving loop
    monkeypatch.setattr("sys.stdin", _io.StringIO(
        "0 1 2 3\nnot a prompt\n%s\n4 5 6 7 8\n"
        % " ".join("1" for _ in range(16))))
    assert LearnTask().run([
        str(conf), "task=serve", "model_in=%s" % model, "num_gen=4",
        "serve_slots=2", "serve_queue=4"]) == 0
    out, err = capfd.readouterr()
    rows = [l.split() for l in out.strip().splitlines()
            if l and (l[0].isdigit() or l.startswith("ERR"))]
    assert len(rows) == 4
    assert rows[0] == want[0] and rows[3] == want[1]
    assert rows[1][:2] == ["ERR", "rejected:"] and "unparseable" in rows[1]
    assert rows[2][:2] == ["ERR", "rejected:"] and "no" in rows[2]
    assert "serve:" in err and "batch efficiency" in err


@pytest.mark.slow
def test_soak_continuous_batching_beats_sequential():
    """Mixed-length soak (the bench cell's shape at test scale): the
    slot scheduler serving 16 mixed requests concurrently must beat the
    same request set generated one-at-a-time through gpt_decode, wall
    clock, with both paths warm. Sequential gets its best case — each
    signature's program compiled ahead, no arrival gaps. A larger model
    than the unit tests' so per-token compute (which batching shares
    across slots) dominates per-call dispatch (which it cannot)."""
    cfg = GPTConfig(vocab_size=64, seq_len=64, n_layer=4, n_head=4,
                    feat=256, n_microbatch=1)
    params = gpt_init(jax.random.PRNGKey(8), cfg)
    rs = np.random.RandomState(10)
    reqs = [(rs.randint(0, 64, (int(n),)).astype(np.int32), int(m))
            for n, m in zip(rs.choice([4, 6, 8], 16),
                            rs.choice([16, 24], 16))]

    def ref(p, m):
        return np.asarray(gpt_decode(params, p[None], m, cfg))[0]

    # warm + time the sequential path (second pass is the warm one)
    for _ in range(2):
        t0 = time.perf_counter()
        for p, m in reqs:
            np.asarray(gpt_decode(params, p[None], m, cfg))
        seq_wall = time.perf_counter() - t0

    with InferenceServer(cfg, params, slots=8, queue=16) as srv:
        for h in [srv.submit(p, max_tokens=m) for p, m in reqs]:
            srv.result(h, timeout=600)          # warm pass
        srv.reset_metrics()
        t0 = time.perf_counter()
        handles = [srv.submit(p, max_tokens=m) for p, m in reqs]
        results = [srv.result(h, timeout=600) for h in handles]
        serve_wall = time.perf_counter() - t0
        eff = srv.metrics()["batch_efficiency"]

    assert all(r.status == "ok" for r in results)
    # every request still token-identical to its solo run, under load
    for (p, m), r in zip(reqs, results):
        np.testing.assert_array_equal(r.tokens, ref(p, m))
    assert eff > 0.4, eff
    assert serve_wall < seq_wall, (serve_wall, seq_wall)
