"""Observability subsystem (cxxnet_tpu/obs/): the unified metrics
registry (Counter/Gauge/Histogram with Prometheus exposition and
mergeable fixed-bucket percentiles), the request-scoped span tracer
(bounded ring, Chrome-trace export, slow-request exemplars), the export
plumbing (JSONL flusher, end-of-task dumps, tools/cxn_trace.py), and the
serving integration — a scripted mixed workload (chunked prefill +
prefix hit + speculative) must leave one complete, schema-valid span
tree per request, and expired/rejected requests must contribute to the
queue-wait distribution instead of silently dropping out of it."""

import importlib.util
import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.obs import (Counter, Gauge, Histogram, MetricsFlusher,
                            Registry, TIME_BUCKETS, export_run)
from cxxnet_tpu.obs.trace import (REQ_TID_BASE, TID_ENGINE, Tracer,
                                  get_tracer, request_tid)
from cxxnet_tpu.serve import AdmissionError, InferenceServer
from cxxnet_tpu.utils import profiler

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _cxn_trace_mod():
    spec = importlib.util.spec_from_file_location(
        "cxn_trace", os.path.join(_REPO, "tools", "cxn_trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- metrics
def test_counter_monotonic_and_callback():
    r = Registry()
    c = r.counter("t_total", "help")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    live = [7]
    cb = r.counter("t_live_total", fn=lambda: live[0])
    assert cb.value == 7
    with pytest.raises(RuntimeError):
        cb.inc()


def test_gauge_set_inc_and_dead_callback_nan():
    r = Registry()
    g = r.gauge("t_gauge")
    g.set(4.0)
    g.inc(-1.5)
    assert g.value == 2.5

    def dead():
        raise RuntimeError("provider gone")

    bad = r.gauge("t_dead", fn=dead)
    with pytest.raises(RuntimeError):
        bad.set(1.0)                    # callback gauge: read-only
    with pytest.raises(RuntimeError):
        bad.inc()
    assert np.isnan(bad.value)          # a dead provider must not
    #                                     kill the scrape...
    assert "t_dead NaN" in r.to_prometheus()    # ...nor the exposition
    snap = r.snapshot()
    assert snap["t_dead"] is None       # nor poison the JSONL stream
    json.dumps(snap, allow_nan=False)   # strict-JSON-clean


def test_registry_reregister_rebinds_callback_and_pins_buckets():
    """Re-registering a callback metric rebinds it to the NEW provider
    (a restarted server sharing a registry must not leave the exported
    names reading its dead predecessor), and re-registering a histogram
    with different buckets is an error, never a silent keep."""
    r = Registry()
    a = [1]
    r.counter("t_live_total", fn=lambda: a[0])
    b = [7]
    c = r.counter("t_live_total", fn=lambda: b[0])
    assert c.value == 7                 # latest provider wins
    lab = r.gauge("t_lab", labelnames=("k",), fn=lambda: a[0])
    lab.labels("x")
    r.gauge("t_lab", labelnames=("k",), fn=lambda: b[0])
    assert lab.labels("x").value == 7   # existing children rebound
    assert lab.labels("y").value == 7   # new children use the new fn
    r.histogram("t_h", buckets=(1.0, 2.0))
    r.histogram("t_h", buckets=(1.0, 2.0))      # same geometry: fine
    with pytest.raises(ValueError):
        r.histogram("t_h", buckets=(5.0, 6.0))


def test_registry_freeze_releases_owner_and_keeps_values():
    """Registry.freeze: callback metrics become their terminal values
    (the honest drained state keeps exporting) and the provider object
    is RELEASED — a stopped server must not be pinned by its registry."""
    import gc
    import weakref

    class Owner:
        def __init__(self):
            self.n = 5

    r = Registry()
    owner = Owner()
    ref = weakref.ref(owner)
    r.counter("t_owned_total", fn=lambda: owner.n)
    r.gauge("t_owned_gauge", fn=lambda: owner.n * 2)
    r.freeze(["t_owned_total", "t_owned_gauge", "t_absent"])
    del owner
    gc.collect()
    assert ref() is None                # closure dropped
    snap = r.snapshot()
    assert snap["t_owned_total"] == 5   # terminal values survive
    assert snap["t_owned_gauge"] == 10


def test_shared_registry_server_restart_reads_live_server():
    """The rebind end to end: server B re-registering into A's registry
    takes over every callback metric instead of exporting A's frozen
    state."""
    reg = Registry()
    with InferenceServer(CFG, PARAMS, slots=1, queue=4, prefill_chunk=4,
                         tracer=Tracer(enabled=False),
                         registry=reg) as a:
        h = a.submit(np.arange(4, dtype=np.int32), max_tokens=2)
        assert a.result(h, timeout=300).status == "ok"
        assert "cxn_serve_submitted_total 1" in a.metrics_text()
    # A's shutdown froze its callbacks at their terminal values: the
    # post-shutdown scrape reports the honest drained state without
    # evaluating (or pinning) the dead server
    after = reg.snapshot()
    assert after["cxn_serve_submitted_total"] == 1
    assert after["cxn_serve_slot_occupancy"] == 0.0
    with InferenceServer(CFG, PARAMS, slots=1, queue=4, prefill_chunk=4,
                         tracer=Tracer(enabled=False),
                         registry=reg) as b:
        assert b.registry is reg
        assert "cxn_serve_submitted_total 0" in b.metrics_text()


def test_registry_get_or_create_and_kind_mismatch():
    r = Registry()
    a = r.counter("shared_total")
    b = r.counter("shared_total")
    assert a is b                       # two subsystems share one
    with pytest.raises(ValueError):
        r.gauge("shared_total")
    lab = r.counter("lab_total", labelnames=("k",))
    lab.labels("x").inc()
    assert lab.labels("x") is lab.labels("x")
    with pytest.raises(ValueError):
        lab.labels("x", "y")            # arity mismatch
    with pytest.raises(ValueError):
        lab.default                     # labeled family has no default


def test_histogram_buckets_deterministic_and_strict():
    # the mergeability precondition: every process computes the SAME
    # bounds (pure function of constants)
    from cxxnet_tpu.obs.metrics import _log_spaced
    assert TIME_BUCKETS == _log_spaced(1e-5, 100.0, 4)
    assert list(TIME_BUCKETS) == sorted(TIME_BUCKETS)
    with pytest.raises(ValueError):
        Histogram(buckets=(1.0, 1.0, 2.0))
    with pytest.raises(ValueError):
        Histogram(buckets=(2.0, 1.0))


def test_histogram_merge_equals_combined():
    """The router property: merging replicas then asking for p95 equals
    observing everything in one histogram."""
    rs = np.random.RandomState(0)
    xs = rs.exponential(0.01, 200)
    ys = rs.exponential(0.10, 100)
    a, b, both = Histogram(), Histogram(), Histogram()
    for x in xs:
        a.observe(x)
        both.observe(x)
    for y in ys:
        b.observe(y)
        both.observe(y)
    a.merge(b)
    assert a.count == both.count == 300
    assert a.counts() == both.counts()
    for q in (0.5, 0.95, 0.99):
        assert a.percentile(q) == both.percentile(q)
    with pytest.raises(ValueError):
        a.merge(Histogram(buckets=(1.0, 2.0)))


def test_merged_prometheus_union_and_replica_labels():
    """obs/metrics.py:merged_prometheus — the router's scrape payload:
    per-replica series gain a replica= label under the UNCHANGED metric
    names, and every histogram additionally emits an aggregate series
    whose buckets equal one histogram that observed the union of the
    replicas' observations (Histogram.merge end to end)."""
    from cxxnet_tpu.obs.metrics import Registry, merged_prometheus
    rs = np.random.RandomState(3)
    regs = {str(i): Registry() for i in range(2)}
    union = Histogram()
    for i, reg in enumerate(regs.values()):
        reg.counter("cxn_serve_completed_total", "done").inc(10 + i)
        reg.gauge("cxn_serve_queue_depth", "depth").set(i)
        h = reg.histogram("cxn_serve_ttft_seconds", "ttft")
        ph = reg.histogram("cxn_serve_phase_seconds", "phases",
                           labelnames=("phase",))
        for x in rs.exponential(0.01 * (i + 1), 50):
            h.observe(x)
            union.observe(x)
            ph.labels("decode_tick").observe(x)
    txt = merged_prometheus(regs)
    # per-replica series under the original names
    assert 'cxn_serve_completed_total{replica="0"} 10' in txt
    assert 'cxn_serve_completed_total{replica="1"} 11' in txt
    assert 'cxn_serve_queue_depth{replica="1"} 1' in txt
    assert ('cxn_serve_phase_seconds_count{phase="decode_tick",'
            'replica="0"} 50') in txt
    # the aggregate histogram equals the union of observations: its
    # rendered bucket lines match a single all-observing histogram's
    one = Registry()
    agg = one.histogram("cxn_serve_ttft_seconds", "ttft")
    agg.merge(union)
    want = [l for l in one.to_prometheus().splitlines()
            if l.startswith("cxn_serve_ttft_seconds_bucket{le=")]
    got = [l for l in txt.splitlines()
           if l.startswith("cxn_serve_ttft_seconds_bucket{le=")]
    assert got == want
    assert "cxn_serve_ttft_seconds_count 100" in txt
    # a kind mismatch across replicas is skipped loudly, not rendered
    regs["0"].counter("cxn_oops_total")
    regs["1"].gauge("cxn_oops_total")
    txt2 = merged_prometheus(regs)
    assert "cxn_oops_total skipped" in txt2


def test_router_merged_payload_equals_union_of_replicas():
    """End-to-end: a 2-replica ServeRouter's metrics_text() aggregate
    TTFT histogram equals the union of the replicas' observations, and
    the per-replica cxn_serve_* series carry replica= labels without
    breaking any existing scrape name."""
    import jax

    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.obs.metrics import Histogram as H
    from cxxnet_tpu.serve import ServeRouter
    cfg = GPTConfig(vocab_size=32, seq_len=32, n_layer=1, n_head=2,
                    feat=8, n_microbatch=1)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(5)
    with ServeRouter(cfg, params, replicas=2, slots=2, queue=8,
                     prefill_chunk=4) as rt:
        hs = [rt.submit(rs.randint(0, 32, (n,)).astype(np.int32),
                        max_tokens=4) for n in (5, 9, 3, 7)]
        for h in hs:
            assert rt.result(h, timeout=300).status == "ok"
        txt = rt.metrics_text()
        union = H()
        per = 0
        for s in rt.servers:
            child = s.registry.get("cxn_serve_ttft_seconds").default
            union.merge(child)
            per += child.count
    # aggregate series == union of the two replicas' observations
    assert per == 4
    assert "cxn_serve_ttft_seconds_count %d" % union.count in txt
    assert ("cxn_serve_ttft_seconds_sum %s"
            % ("%r" % union.sum if union.sum != int(union.sum)
               else str(int(union.sum)))) in txt
    # every replica serves under its own label, names unchanged
    for i in range(2):
        assert 'cxn_serve_state{replica="%d"} 0' % i in txt
        assert 'cxn_serve_tp{replica="%d"} 1' % i in txt


def test_histogram_percentile_bucket_resolution_and_empty():
    h = Histogram()
    assert h.percentile(0.5) == 0.0     # empty window -> 0, not NaN
    h.observe(float("nan"))             # poison dropped
    h.observe(float("inf"))
    assert h.count == 0
    for v in (0.001,) * 99 + (1.0,):
        h.observe(v)
    p50, p99 = h.percentile(0.50), h.percentile(0.995)
    assert 0.001 <= p50 <= 0.002        # within one log-bucket
    assert p99 >= 1.0


def test_prometheus_exposition_schema():
    r = Registry()
    r.counter("cxn_x_total", "things done").inc(3)
    r.gauge("cxn_g", "a level").set(1.5)
    h = r.histogram("cxn_d_seconds", "latency")
    h.observe(0.001)
    h.observe(0.5)
    lab = r.counter("cxn_l_total", "labeled", labelnames=("k",))
    lab.labels("a").inc()
    text = r.to_prometheus()
    lines = text.strip().splitlines()
    assert "# TYPE cxn_x_total counter" in lines
    assert "# HELP cxn_x_total things done" in lines
    assert "cxn_x_total 3" in lines
    assert "# TYPE cxn_g gauge" in lines
    assert "cxn_g 1.5" in lines
    assert 'cxn_l_total{k="a"} 1' in lines
    # histogram: cumulative buckets, +Inf == _count, sum present
    buckets = [l for l in lines if l.startswith("cxn_d_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
    assert counts == sorted(counts)     # cumulative -> monotone
    assert buckets[-1].startswith('cxn_d_seconds_bucket{le="+Inf"}')
    assert counts[-1] == 2
    assert any(l.startswith("cxn_d_seconds_sum ") for l in lines)
    assert "cxn_d_seconds_count 2" in lines
    snap = r.snapshot()
    assert snap["cxn_x_total"] == 3
    assert snap["cxn_d_seconds"]["count"] == 2
    assert snap['cxn_l_total{k="a"}'] == 1


# -------------------------------------------------------------- tracer
def test_ring_eviction_bound_pinned():
    tr = Tracer(capacity=16)
    for i in range(100):
        tr.add("s%d" % i, float(i), 1.0, TID_ENGINE)
    assert len(tr) == 16                # memory bound holds
    assert tr.dropped == 84
    names = [s.name for s in tr.spans()]
    assert names[0] == "s84" and names[-1] == "s99"   # newest retained
    tr.clear()
    assert len(tr) == 0 and tr.dropped == 0


def test_zero_span_export_is_valid_json(tmp_path):
    tr = Tracer()
    doc = json.loads(json.dumps(tr.chrome_trace()))
    assert doc["traceEvents"] == []
    assert doc["otherData"]["format"] == "cxxnet_tpu.obs.trace/1"
    path = tr.write_chrome(str(tmp_path / "empty.trace.json"))
    with open(path) as f:
        assert json.load(f)["traceEvents"] == []
    assert tr.dump_jsonl(str(tmp_path / "empty.spans.jsonl")) == 0


def _validate_chrome(doc):
    """Chrome-trace JSON schema the satellite pins: every event is a
    complete ("X") or metadata ("M") record with the fields Perfetto
    needs, timestamps rebased near zero in microseconds."""
    assert isinstance(doc["traceEvents"], list)
    tids_meta = set()
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "M"), ev
        assert isinstance(ev["name"], str) and "pid" in ev and "tid" in ev
        if ev["ph"] == "M":
            assert ev["name"] == "thread_name"
            tids_meta.add(ev["tid"])
        else:
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert ev["cat"]
    # every track that has spans is named
    assert {e["tid"] for e in doc["traceEvents"]
            if e["ph"] == "X"} <= tids_meta
    return doc


def test_chrome_trace_schema_and_track_names():
    tr = Tracer()
    t0 = time.perf_counter()
    tr.add("decode_tick", t0, 0.001, TID_ENGINE, cat="serve",
           args={"decoding": 2})
    tr.add("queue_wait", t0, 0.002, request_tid(3), cat="serve")
    doc = _validate_chrome(tr.chrome_trace())
    names = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert names[TID_ENGINE] == "engine"
    assert names[request_tid(3)] == "request 3"


def test_sampling_knob_and_disabled_tracer():
    tr = Tracer(sample=2)
    assert tr.should_sample(0) and tr.should_sample(4)
    assert not tr.should_sample(1) and not tr.should_sample(3)
    tr.enabled = False
    assert not tr.should_sample(0)
    tr.add("x", 0.0, 1.0, TID_ENGINE)
    with tr.span("y", TID_ENGINE):
        pass
    assert len(tr) == 0                 # disabled -> nothing recorded
    tr.configure(enabled=True, capacity=4, sample=1)
    for i in range(8):
        tr.instant("s%d" % i, TID_ENGINE)
    assert len(tr) == 4
    tr.configure(capacity=2)            # resize keeps the newest
    assert [s.name for s in tr.spans()] == ["s6", "s7"]


def test_note_slow_exemplar(tmp_path, capfd):
    tr = Tracer(slow_dir=str(tmp_path / "slow"))
    assert tr.note_slow(5, "never recorded") is None
    tid = request_tid(5)
    t0 = time.perf_counter()
    tr.add("queue_wait", t0, 0.01, tid, cat="serve")
    tr.add("request", t0, 0.02, tid, cat="serve", args={"rid": 5})
    doc = tr.note_slow(5, "ttft over threshold")
    assert doc["otherData"]["slow_reason"] == "ttft over threshold"
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 2
    with open(tmp_path / "slow" / "slow-req-5.trace.json") as f:
        _validate_chrome(json.load(f))
    assert (5, "ttft over threshold", doc) in list(tr.exemplars)
    assert "[WARN]" in capfd.readouterr().err


# ---------------------------------------------------- profiler surface
def test_log_levels(capfd):
    profiler.log("plain line")
    profiler.warn("scary line")
    err = capfd.readouterr().err
    lines = err.strip().splitlines()
    assert len(lines) == 2
    assert lines[0].startswith("[") and "plain line" in lines[0]
    assert "[WARN]" not in lines[0]
    assert "[WARN] scary line" in lines[1]
    with pytest.raises(ValueError):
        profiler.log("x", level="debug")


def test_stepstats_observer_feeds_registry():
    h = Registry().histogram("t_phase_seconds", labelnames=("phase",))
    st = profiler.StepStats(
        observer=lambda name, s: h.labels(name).observe(s))
    st.record(profiler.QUEUE_WAIT, 0.002)
    with st.phase(profiler.DECODE_TICK):
        pass
    st.end_step()
    assert h.labels(profiler.QUEUE_WAIT).count == 1
    assert h.labels(profiler.DECODE_TICK).count == 1
    assert st.samples(profiler.QUEUE_WAIT) == [0.002]
    assert st.samples("never_ran") == []


# ------------------------------------------------------------- export
def test_metrics_flusher_jsonl_and_clean_shutdown(tmp_path):
    r = Registry()
    c = r.counter("t_total")
    path = str(tmp_path / "m.jsonl")
    with pytest.raises(ValueError):
        MetricsFlusher(r, path, interval_s=0)
    with pytest.raises(OSError):        # fail fast on the caller's
        MetricsFlusher(r, str(tmp_path / "no_dir" / "m.jsonl"),
                       interval_s=0.05)  # thread, not one interval in
    fl = MetricsFlusher(r, path, interval_s=0.05,
                        extra=lambda: {"task": "test"})
    assert any(t.name.startswith("cxn-obs-flusher")
               for t in threading.enumerate())
    c.inc(2)
    deadline = time.time() + 5
    while fl.flushes < 2 and time.time() < deadline:
        time.sleep(0.02)
    fl.close()
    fl.close()                          # idempotent
    assert not any(t.name.startswith("cxn-obs-flusher")
                   for t in threading.enumerate())
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) >= 2
    for line in lines:
        assert line["task"] == "test" and "ts" in line
    assert lines[-1]["metrics"]["t_total"] == 2   # final flush ran


def test_export_run_writes_all_three(tmp_path):
    r = Registry()
    r.counter("t_total").inc()
    tr = Tracer()
    tr.instant("x", TID_ENGINE)
    prefix = str(tmp_path / "run")
    paths = export_run(prefix, r, tr)
    assert sorted(os.path.basename(p) for p in paths) == [
        "run.prom", "run.spans.jsonl", "run.trace.json"]
    with open(prefix + ".trace.json") as f:
        _validate_chrome(json.load(f))
    assert "t_total 1" in open(prefix + ".prom").read()
    assert len(open(prefix + ".spans.jsonl").readlines()) == 1


def test_cxn_trace_export_and_summary(tmp_path, capsys):
    tr = Tracer()
    t0 = time.perf_counter()
    for rid, dur in ((0, 0.05), (1, 0.20), (2, 0.01)):
        tid = request_tid(rid)
        tr.add("queue_wait", t0, dur / 10, tid, cat="serve")
        tr.add("request", t0, dur, tid, cat="serve",
               args={"rid": rid, "status": "ok", "prompt_tokens": 4,
                     "tokens": 8})
    tr.add("decode_tick", t0, 0.002, TID_ENGINE, cat="serve")
    raw = str(tmp_path / "run.spans.jsonl")
    assert tr.dump_jsonl(raw) == 7
    mod = _cxn_trace_mod()
    out = str(tmp_path / "out.trace.json")
    assert mod.main(["export", raw, "-o", out]) == 0
    with open(out) as f:
        doc = _validate_chrome(json.load(f))
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 7
    # idempotent: exporting the Chrome form passes through unchanged
    assert mod.main(["export", out, "-o",
                     str(tmp_path / "again.trace.json")]) == 0
    capsys.readouterr()
    assert mod.main(["summary", raw, "--top", "2"]) == 0
    text = capsys.readouterr().out
    assert "7 spans, 3 requests" in text
    # top-2 slowest: rid 1 (200 ms) then rid 0 (50 ms); rid 2 cut
    pos1, pos0 = text.find("200.0"), text.find("50.0")
    assert 0 < pos1 < pos0 and "10.0" not in text.split("breakdown")[0]
    assert "queue_wait" in text and "decode_tick" in text


# ------------------------------------------- serving span-tree workload
def _spans_by_name(spans):
    out = {}
    for s in spans:
        out.setdefault(s.name, []).append(s)
    return out


def test_scripted_workload_span_tree_deterministic(tmp_path):
    """The satellite's scripted 3-request mixed workload: chunked
    prefill (A), prefix hit (B, shares A's first 2 chunks), speculative
    (C, repetitive prompt for the ngram drafter). Run sequentially so
    the span tree per request is deterministic; every request must
    leave one COMPLETE tree — queue_wait -> (prefix_restore) ->
    prefill_chunk* -> decode -> (spec_verify) -> retire under a single
    request root — and the Chrome export must validate."""
    rs = np.random.RandomState(0)
    a = rs.randint(0, CFG.vocab_size, (13,)).astype(np.int32)
    b = np.concatenate([a[:8],
                        rs.randint(0, CFG.vocab_size,
                                   (5,)).astype(np.int32)])
    c = np.asarray([1, 2, 3, 4] * 3, np.int32)       # ngram bait
    tr = Tracer()
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         prefix_mb=8.0, spec_mode="ngram", spec_len=2,
                         tracer=tr) as srv:
        ha = srv.submit(a, max_tokens=5, spec_mode="off")
        ra = srv.result(ha, timeout=300)
        hb = srv.submit(b, max_tokens=4, spec_mode="off")
        rb = srv.result(hb, timeout=300)
        hc = srv.submit(c, max_tokens=6)
        rc = srv.result(hc, timeout=300)
        for r in (ra, rb, rc):
            assert r.status == "ok", (r.status, r.error)
        spec_forwards = srv.metrics()["spec_forwards"]
    # shutdown joined the scheduler thread: the ring is final now
    # (rids come from the handles — they are process-global, not 0/1/2)
    ta = _spans_by_name(tr.spans_for_request(ha.rid))
    tb = _spans_by_name(tr.spans_for_request(hb.rid))
    tc = _spans_by_name(tr.spans_for_request(hc.rid))

    # A: 13-token prompt, chunk 4 -> 4 chunk steps, no prefix to hit
    assert len(ta["prefill_chunk"]) == 4
    assert [s.args["start"] for s in ta["prefill_chunk"]] == [0, 4, 8, 12]
    assert "prefix_restore" not in ta or \
        ta["prefix_restore"][0].args["restored_tokens"] == 0
    # B: A's retired row cached its chunks -> first 2 chunks restored,
    # prefill resumes at token 8 (2 more chunk steps: 8..12, 12..13)
    assert tb["prefix_restore"][0].args["restored_tokens"] == 8
    assert [s.args["start"] for s in tb["prefill_chunk"]] == [8, 12]
    # C: the drafter ran -> per-request verify spans with the accept
    # counts the registry saw
    assert spec_forwards > 0
    assert len(tc["spec_verify"]) == spec_forwards
    assert sum(s.args["drafted"] for s in tc["spec_verify"]) \
        == srv.registry.snapshot()["cxn_serve_spec_drafted_total"]

    for rid, t, req_prompt, res in ((ha.rid, ta, a, ra),
                                    (hb.rid, tb, b, rb),
                                    (hc.rid, tc, c, rc)):
        root, = t["request"]
        assert root.args["status"] == "ok" and root.args["rid"] == rid
        assert root.args["prompt_tokens"] == len(req_prompt)
        assert root.args["tokens"] == len(res.tokens) - len(req_prompt)
        decode, = t["decode"]
        assert decode.args["tokens"] == root.args["tokens"]
        assert len(t["queue_wait"]) == 1 and len(t["retire"]) == 1
        # time containment: every child lies inside the request root
        # (the nesting Perfetto renders), modulo clock-read jitter
        eps = 1e-4
        for name, spans in t.items():
            if name == "request":
                continue
            for s in spans:
                assert s.ts >= root.ts - eps
                assert s.ts + s.dur <= root.ts + root.dur + eps
    # shared engine track: batched ticks + drafter passes, never
    # per-request
    eng = _spans_by_name(tr.spans(TID_ENGINE))
    assert len(eng["decode_tick"]) > 0
    assert len(eng["spec_draft"]) > 0
    _validate_chrome(tr.chrome_trace())
    # and the whole ring round-trips through the offline tool
    raw = str(tmp_path / "wl.spans.jsonl")
    tr.dump_jsonl(raw)
    mod = _cxn_trace_mod()
    assert mod.main(["export", raw]) == 0
    # default out strips the .spans.jsonl suffix (no wl.spans.trace.json)
    with open(str(tmp_path / "wl.trace.json")) as f:
        _validate_chrome(json.load(f))


def test_slow_request_exemplar_via_server(tmp_path):
    """obs_slow_ms end to end: any served request outlasts a 0.001 ms
    threshold, so its span tree is dumped at completion."""
    tr = Tracer(slow_dir=str(tmp_path))
    with InferenceServer(CFG, PARAMS, slots=1, queue=4, prefill_chunk=4,
                         tracer=tr, slow_ms=0.001) as srv:
        h = srv.submit(np.arange(5, dtype=np.int32), max_tokens=3)
        assert srv.result(h, timeout=300).status == "ok"
    assert tr.exemplars
    rid, reason, doc = tr.exemplars[0]
    # rids are process-global (span tracks must not collide across
    # servers), so pin against the handle, not a literal
    assert rid == h.rid and "over obs_slow_ms" in reason
    with open(tmp_path / ("slow-req-%d.trace.json" % rid)) as f:
        doc = _validate_chrome(json.load(f))
    assert {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"} \
        >= {"queue_wait", "decode", "retire", "request"}


# ------------------------------------- overload accounting (satellite)
def test_expired_request_contributes_queue_wait():
    """A request that expires in the queue must still contribute its
    full wait to the queue-wait distribution (and count as expired) —
    otherwise overload reads as LOW queue-wait percentiles because only
    the admitted survivors report."""
    tr = Tracer()
    with InferenceServer(CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
                         tracer=tr, slow_ms=0.5) as srv:
        hold = srv.submit(np.arange(4, dtype=np.int32), max_tokens=30)
        doomed = srv.submit(np.arange(6, dtype=np.int32), max_tokens=2,
                            timeout_ms=1.0)
        res = srv.result(doomed, timeout=300)
        assert res.status == "timeout" and "expired" in res.error
        srv.result(hold, timeout=300)
        snap = srv.registry.snapshot()
        m = srv.metrics()
    assert snap["cxn_serve_expired_total"] == 1
    assert snap["cxn_serve_timeout_total"] == 1
    assert m["requests"]["expired"] == 1
    # its >= 1 ms wait landed in both the StepStats window and the
    # registry histogram
    assert m["queue_wait_ms"]["p99"] >= 1.0
    h = snap['cxn_serve_phase_seconds{phase="queue_wait"}']
    assert h["count"] >= 2 and h["sum"] >= 1e-3
    # and it left a span tree: queue_wait + a terminal root marked
    # expired, nothing else (it never got a slot)
    t = _spans_by_name(tr.spans_for_request(doomed.rid))
    assert set(t) == {"queue_wait", "request"}
    assert t["request"][0].args["expired"] is True
    assert t["queue_wait"][0].dur >= 1e-3
    # the worst offenders must not dodge the slow-exemplar hook just
    # because they expired in the queue instead of retiring from a slot
    assert doomed.rid in {rid for rid, _, _ in tr.exemplars}


def test_rejected_request_counted_with_zero_wait():
    """A queue-FULL shed observes a ZERO queue-wait sample (turned away
    at the door by load = shortest possible wait — dropping it would
    bias the distribution the other way under overload), but a
    bad-params rejection contributes NOTHING: it never interacted with
    the queue, and a client spamming invalid requests must not flood
    the wait histogram with zeros."""
    from cxxnet_tpu.serve import QueueFullError
    with InferenceServer(CFG, PARAMS, slots=1, queue=1,
                         prefill_chunk=4, tracer=Tracer(enabled=False)) \
            as srv:
        with pytest.raises(AdmissionError):
            srv.submit(np.zeros((0,), np.int32))     # bad params
        h = srv.registry.snapshot()[
            'cxn_serve_phase_seconds{phase="queue_wait"}']
        assert h["count"] == 0                       # no sample
        hold = srv.submit(np.arange(4, dtype=np.int32), max_tokens=30)
        deadline = time.time() + 60
        while srv.queue_depth() > 0 and time.time() < deadline:
            time.sleep(0.005)       # wait for hold to occupy the slot
        filler = srv.submit(np.arange(4, dtype=np.int32), max_tokens=2)
        with pytest.raises(QueueFullError):
            srv.submit(np.arange(4, dtype=np.int32), max_tokens=2)
        snap = srv.registry.snapshot()
        assert snap["cxn_serve_rejected_total"] == 2
        h = snap['cxn_serve_phase_seconds{phase="queue_wait"}']
        assert h["count"] >= 1 and h["p50"] <= TIME_BUCKETS[0]  # the shed
        srv.result(hold, timeout=300)
        srv.result(filler, timeout=300)


# ------------------------------------------------- exposition coverage
def test_metrics_text_covers_all_families():
    """The acceptance catalog: one exposition carries serving,
    prefix-cache, speculative, and recompile-guard metrics."""
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         prefix_mb=8.0, spec_mode="ngram", spec_len=2,
                         recompile_limit=8, tracer=Tracer(enabled=False)) \
            as srv:
        h = srv.submit(np.asarray([1, 2, 3, 4] * 3, np.int32),
                       max_tokens=5)
        assert srv.result(h, timeout=300).status == "ok"
        text = srv.metrics_text()
    for name in ("cxn_serve_submitted_total", "cxn_serve_completed_total",
                 "cxn_serve_expired_total", "cxn_serve_queue_depth",
                 "cxn_serve_slot_occupancy", "cxn_serve_batch_efficiency",
                 "cxn_serve_kv_cache_bytes", "cxn_serve_ttft_seconds",
                 "cxn_serve_token_gap_seconds", "cxn_serve_phase_seconds",
                 "cxn_prefix_hits_total", "cxn_prefix_evictions_total",
                 "cxn_prefix_cache_bytes", "cxn_serve_spec_forwards_total",
                 "cxn_serve_spec_accepted_total",
                 "cxn_serve_spec_backoffs_total",
                 "cxn_recompile_trips_total"):
        assert "# TYPE %s " % name in text, name
    assert 'cxn_recompile_trips_total{fn="serve_prefill"} 0' in text
    assert 'cxn_recompile_trips_total{fn="serve_verify_chunk"} 0' in text
    assert "cxn_serve_submitted_total 1" in text
    assert "cxn_serve_completed_total 1" in text
    # two servers get DISTINCT registries: gauges cannot fight
    with InferenceServer(CFG, PARAMS, slots=1, queue=2, prefill_chunk=4,
                         tracer=Tracer(enabled=False)) as other:
        assert other.registry is not srv.registry
        assert "cxn_serve_submitted_total 0" in other.metrics_text()


def test_offline_speculative_records_engine_spans():
    """gpt_decode(speculative=...) shows up on the engine track too:
    the offline decoder mirrors the scheduler's shared-span
    discipline."""
    tr = get_tracer()
    tr.clear()
    prompt = np.asarray([[1, 2, 3, 4] * 3], np.int32)
    stats = {}
    out = gpt_decode(PARAMS, jax.numpy.asarray(prompt), 6, CFG,
                     speculative={"mode": "ngram", "spec_len": 2,
                                  "stats": stats})
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(gpt_decode(
            PARAMS, jax.numpy.asarray(prompt), 6, CFG)))
    eng = _spans_by_name(tr.spans(TID_ENGINE))
    tr.clear()
    assert stats["forwards"] > 0
    assert len(eng.get("spec_verify", [])) == stats["forwards"]
    assert len(eng.get("spec_draft", [])) > 0
    assert len(eng.get("decode_tick", [])) == stats["ticks"]


# ------------------------------------------------------------ CLI e2e
def test_cli_serve_obs_export(tmp_path, capfd, monkeypatch):
    """The acceptance run: task=serve with obs_trace=1 + obs_export
    writes a Perfetto-loadable Chrome trace with one complete span tree
    per request, periodic JSONL metric snapshots, and a final
    Prometheus exposition covering the serving catalog."""
    import io as _io

    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.models import gpt_lm_config

    corpus = tmp_path / "corpus.bin"
    corpus.write_bytes(np.tile(np.arange(16, dtype=np.uint16),
                               40).tobytes())
    conf = tmp_path / "gpt.conf"
    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=8, dev="cpu:0", eta=0.2)
    conf.write_text("""
data = train
iter = lm
    path_data = "%s"
    token_dtype = uint16
    seq_len = 16
    stride = 8
iter = end
%s
num_round = 1
save_model = 1
model_dir = %s
""" % (corpus, cfg, tmp_path / "models"))
    assert LearnTask().run([str(conf)]) == 0
    capfd.readouterr()
    get_tracer().clear()                # only this run's spans below
    prefix = str(tmp_path / "obs")
    monkeypatch.setattr("sys.stdin",
                        _io.StringIO("0 1 2 3\n4 5 6 7 8\n"))
    assert LearnTask().run([
        str(conf), "task=serve",
        "model_in=%s" % (tmp_path / "models" / "0001.model"),
        "num_gen=4", "serve_slots=2", "serve_queue=4",
        "obs_trace=1", "obs_export=%s" % prefix,
        "obs_export_interval_s=0.1"]) == 0
    out, err = capfd.readouterr()
    assert "obs: telemetry written to" in err
    with open(prefix + ".trace.json") as f:
        doc = _validate_chrome(json.load(f))
    roots = [e for e in doc["traceEvents"]
             if e["ph"] == "X" and e["name"] == "request"]
    assert len(roots) == 2              # one complete tree per request
    for root in roots:
        assert root["args"]["status"] == "ok"
        tid = root["tid"]
        names = {e["name"] for e in doc["traceEvents"]
                 if e["ph"] == "X" and e["tid"] == tid}
        assert names >= {"queue_wait", "decode", "retire", "request"}
        assert any(n.startswith("prefill") for n in names)
    prom = open(prefix + ".prom").read()
    assert "cxn_serve_completed_total 2" in prom
    assert "cxn_serve_ttft_seconds_bucket" in prom
    lines = [json.loads(l) for l in open(prefix + ".metrics.jsonl")]
    assert lines and lines[-1]["task"] == "serve"
    assert lines[-1]["metrics"]["cxn_serve_completed_total"] == 2
    # tracer leaves no state behind for the next test
    get_tracer().clear()
