"""Model-zoo configs build, shape-infer, and (for a small inception-style
block) train — integration coverage for split/ch_concat/batch_norm graphs."""

import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import Net
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models.alexnet import alexnet_config
from cxxnet_tpu.models.inception_bn import inception_bn_config
from cxxnet_tpu.models.vgg import vgg16_config
from cxxnet_tpu.utils.config import tokenize


def build_graph_only(cfg_text, batch=8):
    net = Net(tokenize(cfg_text))
    net.set_param("batch_size", str(batch))
    net.set_param("dev", "cpu:0")
    net._build()
    return net


def test_alexnet_shapes():
    net = build_graph_only(alexnet_config(dev=""))
    # conv1: (227-11)/4+1 = 55
    c1 = net.graph.layers[0].outputs[0]
    assert net.node_shapes[c1] == (96, 55, 55)
    out = net.node_shapes[net._out_node]
    assert out == (1, 1, 1000)


def test_vgg16_shapes():
    net = build_graph_only(vgg16_config(dev=""))
    assert net.node_shapes[net._out_node] == (1, 1, 1000)
    # 5 pooling halvings: 224 -> 7
    p5 = net.graph.node_map["p5"]
    assert net.node_shapes[p5] == (512, 7, 7)


def test_inception_bn_shapes():
    net = build_graph_only(inception_bn_config(dev=""))
    assert net.node_shapes[net._out_node] == (1, 1, 1000)
    gap = net.graph.node_map["gap"]
    assert net.node_shapes[gap][1:] == (1, 1)


MINI_INCEPTION = """
netconfig=start
layer[0->s1,s2,s3] = split
layer[s1->b1] = conv:c1
  kernel_size = 1
  nchannel = 8
  random_type = xavier
  no_bias = 1
layer[b1->b1] = batch_norm:bn1
layer[b1->b1] = relu
layer[s2->b2] = conv:c2
  kernel_size = 3
  pad = 1
  nchannel = 8
  random_type = xavier
layer[b2->b2] = relu
layer[s3->b3] = max_pooling
  kernel_size = 3
  pad = 1
  stride = 1
layer[b1,b2,b3->cat] = ch_concat
layer[cat->pool] = avg_pooling
  kernel_size = 16
  stride = 1
layer[pool->flat] = flatten
layer[flat->out] = fullc:fc
  nhidden = 5
  init_sigma = 0.1
layer[out->out] = softmax
netconfig=end
input_shape = 4,16,16
batch_size = 16
dev = cpu
eta = 0.1
momentum = 0.9
metric = error
"""


def test_mini_inception_trains():
    net = Net(tokenize(MINI_INCEPTION))
    net.init_model()
    # ch_concat output: 8 + 8 + 4 channels
    cat = net.graph.node_map["cat"]
    assert net.node_shapes[cat] == (20, 16, 16)
    rs = np.random.RandomState(0)
    losses = []
    for i in range(30):
        x = rs.randn(16, 4, 16, 16).astype(np.float32)
        y = (x[:, 0].mean(axis=(1, 2)) > 0).astype(np.float32)
        net.update(DataBatch(x, y.reshape(16, 1)))
        losses.append(float(net._last_loss))
    assert losses[-1] < losses[0], "loss did not decrease: %s" % losses[:3]


def test_pairtest_layer_runs():
    cfg = """
netconfig=start
layer[0->1] = pairtest-conv-conv:pt1
  kernel_size = 3
  pad = 1
  nchannel = 8
  init_sigma = 0.05
layer[1->2] = flatten
layer[2->3] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[3->3] = softmax
netconfig=end
input_shape = 2,8,8
batch_size = 8
dev = cpu
eta = 0.1
metric = error
"""
    net = Net(tokenize(cfg))
    net.init_model()
    rs = np.random.RandomState(0)
    x = rs.randn(8, 2, 8, 8).astype(np.float32)
    y = rs.randint(0, 4, (8, 1)).astype(np.float32)
    net.update(DataBatch(x, y))   # identical impls -> no diff report, no crash


def test_pairtest_checkpoint_roundtrip(tmp_path):
    cfg = """
netconfig=start
layer[0->1] = pairtest-fullc-fullc:pt1
  nhidden = 4
  init_sigma = 0.1
layer[1->1] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 8
dev = cpu
eta = 0.1
metric = error
"""
    from cxxnet_tpu.utils.config import tokenize as tk
    net = Net(tk(cfg))
    net.init_model()
    path = str(tmp_path / "pt.model")
    net.save_model(path)
    net2 = Net(tk(cfg))
    net2.load_model(path)     # regression: pairtest survives the roundtrip
    np.testing.assert_allclose(net2.get_weight("pt1", "wmat"),
                               net.get_weight("pt1", "wmat"))


def test_pairtest_rejects_loss_layers():
    from cxxnet_tpu.utils.config import ConfigError, tokenize as tk
    cfg = """
netconfig=start
layer[+1:a] = fullc:fc
  nhidden = 4
layer[+0] = pairtest-softmax-softmax
netconfig=end
input_shape = 1,1,8
batch_size = 8
dev = cpu
"""
    net = Net(tk(cfg))
    with pytest.raises(ConfigError, match="loss"):
        net.init_model()


def test_clip_norm_and_adamw_train():
    """clip_norm + updater=adamw wired through the trainer: loss decreases
    and no step produces non-finite params."""
    cfg = MINI_INCEPTION + "\nclip_norm = 1.0\nupdater = adamw\nwd = 0.01\n"
    net = Net(tokenize(cfg))
    net.init_model()
    rs = np.random.RandomState(1)
    losses = []
    for i in range(20):
        x = rs.randn(16, 4, 16, 16).astype(np.float32)
        y = (x[:, 0].mean(axis=(1, 2)) > 0).astype(np.float32)
        net.update(DataBatch(x, y.reshape(16, 1)))
        losses.append(float(net._last_loss))
    assert losses[-1] < losses[0], "loss did not decrease: %s" % losses
    for tags in net.params.values():
        for w in tags.values():
            assert bool(jnp.isfinite(w).all())


def test_resnet50_builds():
    """ResNet-50 builds from the config DSL (residual add joins, projection
    shortcuts, moving-average BN): canonical stage shapes + param count.
    (Build-only — training coverage comes from the narrow residual net
    below; a full 224² depth-50 train step costs ~80s of CPU compile.)"""
    from cxxnet_tpu.models import resnet_config

    net = Net(tokenize(resnet_config(depth=50, batch_size=8, dev="",
                                     precision="float32")))
    net.init_model()
    # stage outputs: (256,56,56) -> (512,28,28) -> (1024,14,14) -> (2048,7,7)
    assert net.node_shapes[net.graph.node_map["s2r3"]] == (256, 56, 56)
    assert net.node_shapes[net.graph.node_map["s5r3"]] == (2048, 7, 7)
    assert net.node_shapes[net.graph.node_map["gap"]] == (2048, 1, 1)
    n_params = sum(int(np.prod(w.shape)) for t in net.params.values()
                   for w in t.values())
    assert 25.5e6 < n_params < 25.8e6, n_params   # ResNet-50 ~25.6M


MINI_RESNET = """
netconfig=start
layer[0->c1] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 8
  random_type = kaiming
  no_bias = 1
layer[c1->c1] = batch_norm:bn1
  moving_average = 1
layer[c1->c1] = relu
layer[c1->c2] = conv:c2
  kernel_size = 3
  pad = 1
  nchannel = 8
  random_type = kaiming
  no_bias = 1
layer[c2->c2] = batch_norm:bn2
  moving_average = 1
layer[c2,c1->res] = add
layer[res->res] = relu
layer[res->flat] = flatten
layer[flat->fc] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[fc->fc] = softmax
netconfig=end
input_shape = 2,8,8
batch_size = 16
eta = 0.05
momentum = 0.9
metric = error
"""


def test_mini_residual_net_trains():
    """The residual-net ingredients (add join + BN fused stats) train."""
    net = Net(tokenize(MINI_RESNET))
    net.init_model()
    rs = np.random.RandomState(2)
    losses = []
    for i in range(25):
        x = rs.randn(16, 2, 8, 8).astype(np.float32)
        y = (x[:, 0].mean(axis=(1, 2)) > 0).astype(np.float32)
        net.update(DataBatch(x, y.reshape(16, 1)))
        losses.append(float(net._last_loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()
