"""Speculative decoding (serve/speculative.py + engine verify program +
scheduler interleaving + the offline ``gpt_decode(speculative=...)``
path). The load-bearing invariants: (1) GREEDY speculative output is
bit-identical to the solo ``gpt_decode`` run — for chunked, prefix-hit,
and recycled-slot admissions, with both the n-gram and the draft-model
drafter, because acceptance is argmax-prefix matching against logits
that are themselves bit-identical to the tick's; (2) ``spec_mode=off``
is a TRUE no-op on the existing serve path (the verify program is never
even fetched); (3) mixed draft hit lengths compile exactly ONE verify
signature (RecompileGuard-pinned), and a drifting ``spec_len`` trips
CXN205 naming it; (4) the verify executable keeps both donated caches
aliased."""

import time

import jax
import numpy as np
import pytest

from cxxnet_tpu.analysis.findings import LintError
from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (DecodeEngine, InferenceServer, ModelDrafter,
                              NgramDrafter)

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)
DCFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=1, n_head=2, feat=16,
                 n_microbatch=1)
DPARAMS = gpt_init(jax.random.PRNGKey(7), DCFG)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    """The offline oracle: the same request run alone through
    gpt_decode (non-speculative)."""
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


# ------------------------------------------------------------ drafters
def test_ngram_drafter_prompt_lookup():
    """The drafter proposes the continuation of the most recent earlier
    occurrence of the trailing n-gram, longest n-gram first, and returns
    empty when the suffix never occurred before."""
    d = NgramDrafter(spec_len=4, max_ngram=3)
    ctx = np.asarray([1, 2, 3, 9, 8, 1, 2, 3], np.int32)
    # trailing 3-gram (1,2,3) occurred at 0 -> propose what followed: 9,8,1,2
    np.testing.assert_array_equal(d.draft_one(ctx, 4), [9, 8, 1, 2])
    # shorter draft window truncates
    np.testing.assert_array_equal(d.draft_one(ctx, 2), [9, 8])
    # most RECENT match wins: (7,) last occurred at index 4 -> proposes 5
    ctx2 = np.asarray([7, 1, 7, 2, 7, 5, 6, 7], np.int32)
    np.testing.assert_array_equal(d.draft_one(ctx2, 3), [5, 6, 7])
    # unseen suffix -> no proposal
    assert d.draft_one(np.asarray([1, 2, 3, 4], np.int32), 4).size == 0
    # degenerate contexts never crash
    assert d.draft_one(np.asarray([5], np.int32), 4).size == 0
    assert d.draft_one(np.zeros(0, np.int32), 4).size == 0


def test_greedy_prefix_accept_property():
    """The verify program's acceptance rule, driven directly: ANY draft
    that is a prefix of the target's greedy (argmax) continuation is
    fully accepted, and the first divergence is replaced by the target's
    own pick — so the emitted window is always exactly the next
    ``n_acc + 1`` tokens of the solo greedy stream."""
    rs = np.random.RandomState(3)
    p = _prompt(rs, 6)
    full = _ref(p, 10)
    gen = full[len(p):]                     # the greedy continuation
    K = 4
    key = np.asarray(jax.random.PRNGKey(0), np.uint32)
    for n_good in range(K + 1):             # drafts agreeing for n_good
        eng = DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=0,
                           spec_len=K)
        tok0 = eng.prefill(0, p, key, 0.0, 0, 1.0)
        assert tok0 == int(gen[0])
        draft = list(gen[1:1 + n_good])
        while len(draft) < K:               # diverge, then pad
            draft.append(int(gen[len(draft) + 1] + 1) % CFG.vocab_size)
        buf = np.asarray([tok0] + draft, np.int32)
        n_acc, emit = eng.verify_chunk(0, buf, len(p), K, key, 1,
                                       0.0, 0, 1.0)
        assert n_acc == n_good, (n_good, n_acc)
        assert emit == int(gen[1 + n_good]), (n_good, emit)
        eng.close()


# ----------------------------------------------------- serving identity
def test_spec_ngram_chunked_matches_offline_path():
    """The acceptance invariant: chunked admissions (prompt lengths that
    are and are not chunk multiples) with spec_mode=ngram reproduce the
    solo gpt_decode stream bit for bit, and the server actually ran
    verify forwards to get there."""
    rs = np.random.RandomState(0)
    prompts = [_prompt(rs, n) for n in (3, 4, 9, 13, 8)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=16, prefill_chunk=4,
                         spec_mode="ngram", spec_len=4) as srv:
        handles = [srv.submit(p, max_tokens=8) for p in prompts]
        res = [srv.result(h, timeout=300) for h in handles]
        m = srv.metrics()
    for p, r in zip(prompts, res):
        assert r.status == "ok", (r.status, r.error)
        np.testing.assert_array_equal(r.tokens, _ref(p, 8))
    assert m["spec_forwards"] > 0
    assert 0.0 <= m["accept_rate"] <= 1.0
    assert m["spec_tokens_per_forward"] >= 1.0
    assert set(m["spec_verify_ms"]) == {"p50", "p95", "p99"}


def test_spec_model_drafter_matches_offline_path():
    """spec_mode=model: a SMALLER draft GPT (its own slot pool, its own
    cache machinery) proposes, the target verifies — output still
    bit-identical to solo gpt_decode no matter how bad the drafter is
    (these two random inits disagree almost always)."""
    rs = np.random.RandomState(1)
    prompts = [_prompt(rs, n) for n in (5, 11, 7)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="model", spec_len=3,
                         spec_model=(DCFG, DPARAMS)) as srv:
        handles = [srv.submit(p, max_tokens=7) for p in prompts]
        res = [srv.result(h, timeout=300) for h in handles]
        m = srv.metrics()
    for p, r in zip(prompts, res):
        assert r.status == "ok", (r.status, r.error)
        np.testing.assert_array_equal(r.tokens, _ref(p, 7))
    assert m["spec_forwards"] > 0
    assert 0.0 <= m["spec_rollback_rate"] <= 1.0


def test_model_drafter_catch_up_stays_aligned():
    """A draft model IDENTICAL to the target is a perfect drafter: its
    greedy proposals must equal the target's own greedy continuation on
    every draft call, including later calls whose catch-up starts at a
    chunk-UNALIGNED synced offset. Regression: the catch-up used to
    issue its chunk-wide cache write at the raw synced offset, which
    can run past row_len where dynamic_update_slice start-clamping
    silently shifts the write onto earlier live draft K/V — drafts
    after the first call became garbage (identity unaffected, accept
    rate silently collapsed)."""
    d = ModelDrafter(CFG, PARAMS, slots=1, target_cfg=CFG)
    try:
        assert d.engine.chunk > 1     # unaligned growth must be possible
        rs = np.random.RandomState(9)
        ctx = _prompt(rs, 7)
        K = 4
        for _ in range(3):
            want = _ref(ctx, K)[len(ctx):]
            got = d.draft({0: ctx}, {0: K})[0]
            np.testing.assert_array_equal(got, want)
            # grow by the true greedy continuation to an offset that is
            # NOT a chunk multiple, then draft again from the same row
            ctx = np.concatenate([ctx, want[:3]])
            assert len(ctx) % d.engine.chunk
    finally:
        d.close()


def test_model_drafter_caps_draft_at_position_table():
    """A context near the sequence end caps the draft: draft positions
    run len(ctx) .. len(ctx) + k - 1 and must stay inside the draft
    model's own position table (the ctor only requires seq_len >= the
    target's), so a request asking for more gets a SHORTER draft — and
    a perfect (same-model) drafter's shortened proposal still matches
    the target's greedy continuation exactly."""
    d = ModelDrafter(CFG, PARAMS, slots=1, target_cfg=CFG)
    try:
        rs = np.random.RandomState(11)
        ctx = _ref(_prompt(rs, 5), CFG.seq_len - 7)     # len = seq - 2
        assert len(ctx) == CFG.seq_len - 2
        got = d.draft({0: ctx}, {0: 4})[0]
        assert 1 <= len(got) <= 2                       # 2 positions left
        np.testing.assert_array_equal(
            got, _ref(ctx, 2)[len(ctx):][:len(got)])
    finally:
        d.close()


def test_spec_recycled_slot_matches_fresh_decode():
    """One slot, back-to-back speculative requests: the second lands in
    the recycled slot (stale verify rows included) and must match its
    solo run."""
    rs = np.random.RandomState(2)
    a, b = _prompt(rs, 6), _prompt(rs, 9)
    with InferenceServer(CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
                         prefix_mb=0.0, spec_mode="ngram",
                         spec_len=4) as srv:
        ha = srv.submit(a, max_tokens=8)
        hb = srv.submit(b, max_tokens=8)
        res_a = srv.result(ha, timeout=300)
        res_b = srv.result(hb, timeout=300)
        assert hb.slot == ha.slot == 0
    np.testing.assert_array_equal(res_a.tokens, _ref(a, 8))
    np.testing.assert_array_equal(res_b.tokens, _ref(b, 8))


def test_spec_prefix_hit_matches_cold_path():
    """Prefix-cache hit + speculation: request b restores a's cached
    prompt chunks AND speculates — still bit-identical to both the cold
    path and the solo run."""
    rs = np.random.RandomState(4)
    shared = _prompt(rs, 12)
    a = np.concatenate([shared, _prompt(rs, 3)])
    b = np.concatenate([shared, _prompt(rs, 5)])
    with InferenceServer(CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
                         spec_mode="ngram", spec_len=4) as srv:
        res_a = srv.result(srv.submit(a, max_tokens=6), timeout=300)
        res_b = srv.result(srv.submit(b, max_tokens=6), timeout=300)
        m = srv.metrics()
    np.testing.assert_array_equal(res_a.tokens, _ref(a, 6))
    np.testing.assert_array_equal(res_b.tokens, _ref(b, 6))
    assert m["prefix_cache"]["hits"] == 1       # the reuse still engaged


def test_spec_eos_mid_window_truncates():
    """EOS landing inside an accepted speculative window retires the
    request THERE — tokens after it are discarded, exactly like the
    tick-by-tick path."""
    rs = np.random.RandomState(6)
    p = _prompt(rs, 5)
    full = _ref(p, 10)
    gen = full[len(p):]
    i = next((j for j in range(1, len(gen))
              if int(gen[j]) not in gen[:j].tolist()), 0)
    eos = int(gen[i])
    with InferenceServer(CFG, PARAMS, slots=1, queue=4, prefill_chunk=4,
                         spec_mode="ngram", spec_len=4) as srv:
        res = srv.result(srv.submit(p, max_tokens=10, eos=eos),
                         timeout=300)
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, full[:len(p) + i + 1])
    assert int(res.tokens[-1]) == eos


# ------------------------------------------------- mode plumbing / off
def test_spec_off_is_true_noop():
    """spec_mode=off must leave the serve path untouched: the verify
    program is never fetched (a poisoned verify_chunk proves it), spec
    gauges stay at their consistent zeros, and tokens match."""
    rs = np.random.RandomState(7)
    p = _prompt(rs, 6)
    with InferenceServer(CFG, PARAMS, slots=2, queue=8) as srv:
        def boom(*a, **kw):
            raise AssertionError("verify_chunk fetched with spec off")
        srv._engine.verify_chunk = boom
        assert srv._engine.spec_len == 0        # no verify program built
        res = srv.result(srv.submit(p, max_tokens=6), timeout=300)
        m = srv.metrics()
    np.testing.assert_array_equal(res.tokens, _ref(p, 6))
    assert m["spec_forwards"] == 0
    assert m["accept_rate"] == 0.0
    assert m["spec_tokens_per_forward"] == 0.0
    assert m["spec_rollback_rate"] == 0.0


def test_spec_per_request_override_and_validation():
    """Per-request spec_mode overrides: off-on-a-spec-server and
    ngram-on-a-model-server both serve identically; an unavailable mode
    is rejected at submit with a reason."""
    from cxxnet_tpu.serve import AdmissionError
    rs = np.random.RandomState(8)
    a, b, c = _prompt(rs, 7), _prompt(rs, 9), _prompt(rs, 5)
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="model", spec_len=3,
                         spec_model=(DCFG, DPARAMS)) as srv:
        h1 = srv.submit(a, max_tokens=6, spec_mode="off")
        h2 = srv.submit(b, max_tokens=6, spec_mode="ngram")
        h3 = srv.submit(c, max_tokens=6, spec_len=2)    # tighter window
        for h, p in ((h1, a), (h2, b), (h3, c)):
            r = srv.result(h, timeout=300)
            assert r.status == "ok"
            np.testing.assert_array_equal(r.tokens, _ref(p, 6))
    with InferenceServer(CFG, PARAMS, slots=1, queue=4, prefill_chunk=4,
                         spec_mode="ngram", spec_len=4) as srv:
        with pytest.raises(AdmissionError, match="not available"):
            srv.submit(a, max_tokens=4, spec_mode="model")
        assert srv.metrics()["requests"]["rejected"] == 1
    # a spec-off server rejects explicit spec requests too
    with InferenceServer(CFG, PARAMS, slots=1, queue=4) as srv:
        with pytest.raises(AdmissionError, match="not available"):
            srv.submit(a, max_tokens=4, spec_mode="ngram")


def test_spec_sampled_seeded_reproducible():
    """Sampled speculative serving: distribution-level (not bit-pinned
    to the solo run), but the same seed on the same single-slot server
    reproduces the same stream — the fold_in schedule still consumes
    one index per emitted token."""
    rs = np.random.RandomState(9)
    p = _prompt(rs, 9)

    def run():
        with InferenceServer(CFG, PARAMS, slots=1, queue=4,
                             prefill_chunk=4, spec_mode="ngram",
                             spec_len=4) as srv:
            return srv.result(srv.submit(p, max_tokens=8, temperature=0.9,
                                         top_k=5, seed=3), timeout=300)
    r1, r2 = run(), run()
    assert r1.status == r2.status == "ok"
    np.testing.assert_array_equal(r1.tokens, r2.tokens)
    assert ((0 <= r1.tokens) & (r1.tokens < CFG.vocab_size)).all()


# ------------------------------------------- compiled-program bounding
def test_verify_one_signature_across_mixed_requests():
    """The acceptance bound: >= 30 mixed-length speculative requests
    (mixed draft hit lengths included) compile exactly ONE verify
    signature, enforced by the engine's RecompileGuard (limit 1 would
    trip on the second signature — it never does)."""
    rs = np.random.RandomState(10)
    with InferenceServer(CFG, PARAMS, slots=4, queue=40, prefill_chunk=4,
                         prefix_mb=0.0, recompile_limit=4,
                         spec_mode="ngram", spec_len=4) as srv:
        handles = [srv.submit(
            np.tile(_prompt(rs, 4), 4)[:n].astype(np.int32), max_tokens=6)
            for n in range(2, 32)]          # 30 distinct lengths
        for h in handles:
            assert srv.result(h, timeout=300).status == "ok"
        vsigs = srv._engine.verify_signatures
        forwards = srv.metrics()["spec_forwards"]
    assert forwards > 0
    assert len(vsigs) == 1, vsigs


def test_verify_guard_trips_naming_spec_len():
    """A drifting verify window is a compile-per-shape bug: the guard
    trips CXN205 with the drifting dimension named (spec_len)."""
    eng = DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=0, spec_len=4,
                       recompile_limit=1)
    rs = np.random.RandomState(11)
    key = np.asarray(jax.random.PRNGKey(0), np.uint32)
    tok0 = eng.prefill(0, _prompt(rs, 4), key, 0.0, 0, 1.0)
    eng.verify_chunk(0, np.asarray([tok0, 1, 2], np.int32), 4, 2, key, 1,
                     0.0, 0, 1.0)
    with pytest.raises(LintError, match="spec_len"):
        eng.verify_chunk(0, np.asarray([tok0, 1, 2, 3], np.int32), 4, 3,
                         key, 1, 0.0, 0, 1.0)
    eng.close()


# --------------------------------------------------------- step audit
def test_verify_lint_specs_fully_aliased():
    """lint_specs grows the serve_verify_chunk row when the engine
    carries a spec_len, and its executable keeps both donated caches
    aliased (pinned with donate=True on the CPU mesh)."""
    from cxxnet_tpu.analysis import audit_serve_engine
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4, spec_len=4)
    report, infos = audit_serve_engine(eng, n_prompt=5, donate=True)
    assert report.ok(), report.format()
    labels = [i["label"] for i in infos]
    assert labels == ["serve_prefill", "serve_prefill_chunk",
                      "serve_verify_chunk", "serve_tick"]
    for info in infos:
        assert info["donated"] == 2 and info["aliased"] == 2, info
    eng.close()


def test_cxn_lint_compile_audits_verify_program(tmp_path, capsys):
    """tools/cxn_lint.py --compile with spec_mode enabled audits the
    verify program alongside prefill/chunk/tick for a GPT-shaped
    config."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import cxn_lint
    finally:
        sys.path.pop(0)
    from cxxnet_tpu.models import gpt_lm_config
    conf = tmp_path / "gpt.conf"
    conf.write_text(gpt_lm_config(seq_len=16, vocab_size=32, feat=16,
                                  nhead=2, nblock=2, batch_size=4,
                                  dev="cpu:0"))
    rc = cxn_lint.lint_one(str(conf), [("spec_mode", "ngram"),
                                       ("spec_len", "3")], do_compile=True)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "serve_verify_chunk" in out


# ------------------------------------------------------- offline path
def test_gpt_decode_speculative_greedy_identity():
    """gpt_decode(speculative=...) greedy output is bit-identical to the
    plain scan for both drafters and for batch > 1; the stats out-dict
    reports the forwards/accept accounting."""
    rs = np.random.RandomState(12)
    prompt = np.asarray([_prompt(rs, 7), np.tile(_prompt(rs, 7)[:4], 2)[:7]],
                        np.int32)
    ref = np.asarray(gpt_decode(PARAMS, prompt, 16, CFG))
    st = {}
    out = np.asarray(gpt_decode(PARAMS, prompt, 16, CFG,
                                speculative={"mode": "ngram",
                                             "spec_len": 4, "stats": st}))
    np.testing.assert_array_equal(ref, out)
    assert st["tokens"] == 32 and st["forwards"] >= 0
    assert 0.0 <= st["accept_rate"] <= 1.0
    out_m = np.asarray(gpt_decode(
        PARAMS, prompt, 16, CFG,
        speculative={"mode": "model", "spec_len": 3,
                     "model": (DCFG, DPARAMS)}))
    np.testing.assert_array_equal(ref, out_m)
    # the int shorthand selects the ngram drafter
    out_i = np.asarray(gpt_decode(PARAMS, prompt, 16, CFG, speculative=4))
    np.testing.assert_array_equal(ref, out_i)


def test_gpt_decode_speculative_accepts_int8():
    """The speculative + int8_weights combination is COMPOSABLE since
    the quantized-serving round (it used to raise): the verify/tick
    programs stream the per-out-column int8 weights, and the call
    returns the right shape (the identity-vs-own-int8-stream pin lives
    in tests/test_serve_int8.py)."""
    rs = np.random.RandomState(13)
    p = _prompt(rs, 4)[None]
    out = np.asarray(gpt_decode(PARAMS, p, 4, CFG, int8_weights=True,
                                speculative=4))
    assert out.shape == (1, 8)


def test_wrapper_generate_speculative():
    """Net.generate(speculative=...) through the config surface stays
    identical to the non-speculative call."""
    from cxxnet_tpu import wrapper
    from cxxnet_tpu.models import gpt_lm_config

    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=4, dev="cpu:0")
    net = wrapper.Net(cfg=cfg)
    net.init_model()
    prompt = (np.arange(8, dtype=np.int32) % 4).reshape(1, 8)
    want = net.generate(prompt, max_new=6)
    got = net.generate(prompt, max_new=6, speculative=3)
    np.testing.assert_array_equal(want, got)


def test_wrapper_serve_spec_api():
    """Net.serve_start(spec_mode=...) with a wrapper.Net draft model:
    tokens stay pinned to Net.generate on the same request."""
    from cxxnet_tpu import wrapper
    from cxxnet_tpu.models import gpt_lm_config

    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=4, dev="cpu:0")
    net = wrapper.Net(cfg=cfg)
    net.init_model()
    draft = wrapper.Net(cfg=gpt_lm_config(seq_len=16, vocab_size=32,
                                          feat=16, nhead=2, nblock=2,
                                          batch_size=4, dev="cpu:0"))
    draft.init_model()
    prompt = np.arange(4, dtype=np.int32) % 32
    want = net.generate(prompt[None], max_new=5)
    net.serve_start(slots=2, queue=4, max_tokens=5, spec_mode="model",
                    spec_len=3, spec_model=draft)
    try:
        res = net.serve_result(net.serve_submit(prompt), timeout=300)
        assert res.status == "ok"
        np.testing.assert_array_equal(res.tokens, want[0])
        m = net.serve_metrics()
        assert "accept_rate" in m and "spec_rollback_rate" in m
    finally:
        net.serve_stop()


# ------------------------------------------------------------ CLI path
def test_cli_task_serve_speculative(tmp_path, capfd, monkeypatch):
    """task=serve with spec_mode=ngram end to end: outputs stay
    token-identical to task=generate on the same snapshot, and the
    stats line reports the speculative gauges."""
    import io as _io

    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.models import gpt_lm_config

    corpus = tmp_path / "corpus.bin"
    toks = np.tile(np.arange(16, dtype=np.uint16), 40)
    corpus.write_bytes(toks.tobytes())
    conf = tmp_path / "gpt.conf"
    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=8, dev="cpu:0", eta=0.2)
    conf.write_text("""
data = train
iter = lm
    path_data = "%s"
    token_dtype = uint16
    seq_len = 16
    stride = 8
iter = end
%s
num_round = 1
save_model = 1
model_dir = %s
""" % (corpus, cfg, tmp_path / "models"))
    assert LearnTask().run([str(conf)]) == 0
    model = tmp_path / "models" / "0001.model"

    prompts = tmp_path / "p.txt"
    gen_out = tmp_path / "g.txt"
    prompts.write_text("0 1 2 3 0 1 2 3\n")
    assert LearnTask().run([
        str(conf), "task=generate", "model_in=%s" % model,
        "prompt_file=%s" % prompts, "num_gen=4",
        "generate_out=%s" % gen_out]) == 0
    want = gen_out.read_text().split()
    # the speculative offline CLI path writes the same tokens
    gen_spec = tmp_path / "gs.txt"
    assert LearnTask().run([
        str(conf), "task=generate", "model_in=%s" % model,
        "prompt_file=%s" % prompts, "num_gen=4", "spec_mode=ngram",
        "spec_len=3", "generate_out=%s" % gen_spec]) == 0
    assert gen_spec.read_text().split() == want
    capfd.readouterr()

    monkeypatch.setattr("sys.stdin", _io.StringIO("0 1 2 3 0 1 2 3\n"))
    assert LearnTask().run([
        str(conf), "task=serve", "model_in=%s" % model, "num_gen=4",
        "serve_slots=2", "serve_queue=4", "spec_mode=ngram",
        "spec_len=3"]) == 0
    out, err = capfd.readouterr()
    rows = [l.split() for l in out.strip().splitlines()
            if l and l[0].isdigit()]
    assert rows and rows[0] == want
    assert "speculative ngram x3" in err
    assert "spec accept" in err


# ------------------------------------------------------------- metrics
def test_spec_metrics_zero_window_consistent():
    """A speculative server that never ran a verify forward (no traffic)
    reports consistent finite zeros — no NaN, no raise (the empty-window
    contract of the satellite task)."""
    import math
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         spec_mode="ngram", spec_len=4) as srv:
        m = srv.metrics()
    assert m["spec_forwards"] == 0
    assert m["accept_rate"] == 0.0
    assert m["spec_tokens_per_forward"] == 0.0
    assert m["spec_rollback_rate"] == 0.0
    for key in ("spec_draft_ms", "spec_verify_ms", "ttft_ms"):
        assert all(math.isfinite(v) and v == 0.0 for v in m[key].values())


# ----------------------------------------------------------- slow soak
@pytest.mark.slow
def test_soak_mixed_spec_nonspec_identity():
    """Mixed speculative / non-speculative concurrent load: every greedy
    request — spec ngram, spec model, and spec off, interleaved on the
    same slots — stays bit-identical to its solo gpt_decode run, and
    sampled spec-off requests stay pinned too."""
    rs = np.random.RandomState(20)
    cases = []
    for i in range(18):
        n = int(rs.choice([4, 7, 11, 14]))
        p = _prompt(rs, n)
        if i % 3 == 0:
            p = np.tile(p, 3)[:n + 6].astype(np.int32)  # repetitive-ish
        mode = ("ngram", "model", "off")[i % 3]
        kw = {"max_tokens": int(rs.choice([6, 10, 14]))}
        if mode == "off" and i % 2:
            kw.update(temperature=0.8, top_k=5, seed=int(i))
        cases.append((p, mode, kw))
    with InferenceServer(CFG, PARAMS, slots=4, queue=32, prefill_chunk=4,
                         spec_mode="model", spec_len=4,
                         spec_model=(DCFG, DPARAMS)) as srv:
        handles = [srv.submit(p, spec_mode=mode, **kw)
                   for p, mode, kw in cases]
        res = [srv.result(h, timeout=600) for h in handles]
        m = srv.metrics()
    assert all(r.status == "ok" for r in res)
    for (p, mode, kw), r in zip(cases, res):
        ref_kw = {k: v for k, v in kw.items() if k != "max_tokens"}
        np.testing.assert_array_equal(
            r.tokens, _ref(p, kw["max_tokens"], **ref_kw))
    assert m["spec_forwards"] > 0
