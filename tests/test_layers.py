"""Layer numerics — differential tests against independent oracles (numpy /
torch-cpu), the moral equivalent of the reference's PairTestLayer harness
(SURVEY §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.graph import LayerSpec
from cxxnet_tpu.layers import create_layer
from cxxnet_tpu.layers.base import ApplyContext


def make_layer(ltype, cfg, inputs=(0,), outputs=(1,), name="t"):
    spec = LayerSpec(ltype, name, list(inputs), list(outputs))
    spec.cfg = list(cfg)
    return create_layer(spec, [])


def ctx_train(rng_seed=0, labels=None, batch_size=4):
    return ApplyContext(train=True, rng=jax.random.PRNGKey(rng_seed),
                        labels=labels or {}, batch_size=batch_size)


def ctx_eval():
    return ApplyContext(train=False, rng=None)


# ---------------------------------------------------------------- fullc
def test_fullc_matmul(rng):
    layer = make_layer("fullc", [("nhidden", "8"), ("init_sigma", "0.1")])
    assert layer.infer_shapes([(1, 1, 16)]) == [(1, 1, 8)]
    params = layer.init_params(jax.random.PRNGKey(0), [(1, 1, 16)])
    x = rng.randn(4, 1, 1, 16).astype(np.float32)
    out = layer.apply(params, [jnp.asarray(x)], ctx_eval())[0]
    expected = x.reshape(4, 16) @ np.asarray(params["wmat"]).T \
        + np.asarray(params["bias"])
    np.testing.assert_allclose(np.asarray(out).reshape(4, 8), expected,
                               rtol=1e-5)


def test_fullc_no_bias():
    layer = make_layer("fullc", [("nhidden", "8"), ("no_bias", "1")])
    layer.infer_shapes([(1, 1, 16)])
    params = layer.init_params(jax.random.PRNGKey(0), [(1, 1, 16)])
    assert "bias" not in params


# ---------------------------------------------------------------- conv vs torch
@pytest.mark.parametrize("groups,pad,stride", [(1, 0, 1), (1, 1, 2), (2, 2, 1)])
def test_conv_matches_torch(rng, groups, pad, stride):
    torch = pytest.importorskip("torch")
    cin, cout, k = 4, 6, 3
    layer = make_layer("conv", [("nchannel", str(cout)), ("kernel_size", str(k)),
                                ("pad", str(pad)), ("stride", str(stride)),
                                ("ngroup", str(groups))])
    out_shape = layer.infer_shapes([(cin, 9, 9)])[0]
    params = layer.init_params(jax.random.PRNGKey(1), [(cin, 9, 9)])
    x = rng.randn(2, cin, 9, 9).astype(np.float32)

    x_nhwc = jnp.asarray(x.transpose(0, 2, 3, 1))
    out = layer.apply(params, [x_nhwc], ctx_eval())[0]
    out_nchw = np.asarray(out).transpose(0, 3, 1, 2)
    assert out_nchw.shape[1:] == out_shape

    w = np.asarray(params["wmat"])          # HWIO
    w_oihw = w.transpose(3, 2, 0, 1)        # OIHW for torch
    tout = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w_oihw),
        torch.from_numpy(np.asarray(params["bias"])),
        stride=stride, padding=pad, groups=groups)
    np.testing.assert_allclose(out_nchw, tout.numpy(), rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------- pooling
def test_max_pooling_matches_torch(rng):
    torch = pytest.importorskip("torch")
    layer = make_layer("max_pooling", [("kernel_size", "3"), ("stride", "2")])
    out_shape = layer.infer_shapes([(2, 7, 7)])[0]
    x = rng.randn(2, 2, 7, 7).astype(np.float32)
    out = layer.apply({}, [jnp.asarray(x.transpose(0, 2, 3, 1))], ctx_eval())[0]
    out_nchw = np.asarray(out).transpose(0, 3, 1, 2)
    # ceil-mode pooling with partial edge windows == torch ceil_mode=True
    tout = torch.nn.functional.max_pool2d(
        torch.from_numpy(x), 3, stride=2, ceil_mode=True)
    assert out_nchw.shape == tuple(tout.shape)
    assert out_nchw.shape[1:] == out_shape
    np.testing.assert_allclose(out_nchw, tout.numpy(), rtol=1e-6)


def test_avg_pooling_divides_by_full_window(rng):
    # reference avg pooling always divides by ky*kx, even for partial
    # edge windows (pooling_layer-inl.hpp:33-86)
    layer = make_layer("avg_pooling", [("kernel_size", "2"), ("stride", "2")])
    layer.infer_shapes([(1, 3, 3)])
    x = np.ones((1, 1, 3, 3), np.float32)
    out = layer.apply({}, [jnp.asarray(x.transpose(0, 2, 3, 1))], ctx_eval())[0]
    out = np.asarray(out).transpose(0, 3, 1, 2)
    # edge windows see a single 1 but still divide by 4
    np.testing.assert_allclose(out[0, 0], [[1.0, 0.5], [0.5, 0.25]])


def test_padded_max_pooling_no_inf(rng):
    # regression: ceil-mode + symmetric pad must never create windows that
    # cover only padding (whose max would be the -inf identity)
    layer = make_layer("max_pooling", [("kernel_size", "2"), ("stride", "2"),
                                       ("pad", "1")])
    out_shape = layer.infer_shapes([(1, 3, 3)])[0]
    x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
    out = np.asarray(layer.apply({}, [jnp.asarray(x.transpose(0, 2, 3, 1))],
                                 ctx_eval())[0])
    assert np.isfinite(out).all()
    assert out_shape == (1, 2, 2)


def test_same_size_padded_pooling():
    # k3 s1 pad1 keeps spatial dims (inception 'same' pooling branch)
    layer = make_layer("max_pooling", [("kernel_size", "3"), ("stride", "1"),
                                       ("pad", "1")])
    assert layer.infer_shapes([(4, 14, 14)]) == [(4, 14, 14)]


def test_sum_pooling(rng):
    layer = make_layer("sum_pooling", [("kernel_size", "2"), ("stride", "1")])
    layer.infer_shapes([(1, 3, 3)])
    x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
    out = layer.apply({}, [jnp.asarray(x.transpose(0, 2, 3, 1))], ctx_eval())[0]
    out = np.asarray(out).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(out[0, 0], [[8, 12], [20, 24]])


# ---------------------------------------------------------------- activations
def test_activations(rng):
    x = rng.randn(3, 1, 1, 5).astype(np.float32)
    xj = jnp.asarray(x)
    assert np.allclose(
        np.asarray(make_layer("relu", []).apply({}, [xj], ctx_eval())[0]),
        np.maximum(x, 0))
    assert np.allclose(
        np.asarray(make_layer("sigmoid", []).apply({}, [xj], ctx_eval())[0]),
        1 / (1 + np.exp(-x)), rtol=1e-5)
    assert np.allclose(
        np.asarray(make_layer("tanh", []).apply({}, [xj], ctx_eval())[0]),
        np.tanh(x), rtol=1e-5)
    # xelu: a>0 ? a : a/b
    out = make_layer("xelu", [("b", "4.0")]).apply({}, [xj], ctx_eval())[0]
    assert np.allclose(np.asarray(out), np.where(x > 0, x, x / 4.0), rtol=1e-6)


def test_insanity_eval_uses_mean_divisor(rng):
    x = rng.randn(3, 1, 1, 5).astype(np.float32)
    layer = make_layer("insanity", [("lb", "4"), ("ub", "8")])
    out = layer.apply({}, [jnp.asarray(x)], ctx_eval())[0]
    assert np.allclose(np.asarray(out), np.where(x > 0, x, x / 6.0), rtol=1e-6)


def test_prelu(rng):
    layer = make_layer("prelu", [("init_slope", "0.3")])
    layer.infer_shapes([(4, 3, 3)])
    params = layer.init_params(jax.random.PRNGKey(0), [(4, 3, 3)])
    assert params["bias"].shape == (4,)
    x = rng.randn(2, 3, 3, 4).astype(np.float32)    # NHWC
    out = layer.apply(params, [jnp.asarray(x)], ctx_eval())[0]
    assert np.allclose(np.asarray(out), np.where(x > 0, x, 0.3 * x), rtol=1e-6)


# ---------------------------------------------------------------- dropout
def test_dropout_train_scaling(rng):
    spec_in_out = ((1,), (1,))
    layer = make_layer("dropout", [("threshold", "0.5")],
                       inputs=(1,), outputs=(1,))
    layer.infer_shapes([(1, 1, 1000)])
    x = np.ones((2, 1, 1, 1000), np.float32)
    out = np.asarray(layer.apply({}, [jnp.asarray(x)], ctx_train())[0])
    kept = out != 0
    assert 0.3 < kept.mean() < 0.7
    assert np.allclose(out[kept], 2.0)
    # eval = identity
    oute = np.asarray(layer.apply({}, [jnp.asarray(x)], ctx_eval())[0])
    assert np.allclose(oute, x)


# ---------------------------------------------------------------- lrn vs torch
def test_lrn_matches_torch(rng):
    torch = pytest.importorskip("torch")
    layer = make_layer("lrn", [("local_size", "5"), ("alpha", "0.001"),
                               ("beta", "0.75"), ("knorm", "1.0")])
    layer.infer_shapes([(8, 6, 6)])
    x = rng.randn(2, 8, 6, 6).astype(np.float32)
    out = layer.apply({}, [jnp.asarray(x.transpose(0, 2, 3, 1))], ctx_eval())[0]
    out_nchw = np.asarray(out).transpose(0, 3, 1, 2)
    tout = torch.nn.functional.local_response_norm(
        torch.from_numpy(x), 5, alpha=0.001, beta=0.75, k=1.0)
    np.testing.assert_allclose(out_nchw, tout.numpy(), rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("nsize,c_dim", [(3, 7), (4, 8), (5, 5), (5, 96)])
def test_lrn_band_matmul_matches_reduce_window(rng, monkeypatch, nsize, c_dim):
    """The MXU band-matmul windowed sum must agree with the reduce_window
    formulation it replaced (both paths stay selectable; conv.py:apply)."""
    x = rng.randn(2, 4, 4, c_dim).astype(np.float32)

    def run():
        layer = make_layer("lrn", [("local_size", str(nsize)),
                                   ("alpha", "0.001"), ("beta", "0.75")])
        layer.infer_shapes([(c_dim, 4, 4)])
        return np.asarray(layer.apply({}, [jnp.asarray(x)], ctx_eval())[0])

    monkeypatch.delenv("CXN_PALLAS_LRN", raising=False)
    monkeypatch.delenv("CXN_LRN_REDUCE_WINDOW", raising=False)
    out_mm = run()
    monkeypatch.setenv("CXN_LRN_REDUCE_WINDOW", "1")
    out_rw = run()
    np.testing.assert_allclose(out_mm, out_rw, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------- batch norm
def test_batch_norm_normalizes(rng):
    layer = make_layer("batch_norm", [])
    layer.infer_shapes([(4, 5, 5)])
    params = layer.init_params(jax.random.PRNGKey(0), [(4, 5, 5)])
    x = (rng.randn(8, 5, 5, 4) * 3 + 7).astype(np.float32)
    out = np.asarray(layer.apply(params, [jnp.asarray(x)], ctx_train())[0])
    assert np.allclose(out.mean(axis=(0, 1, 2)), 0, atol=1e-4)
    assert np.allclose(out.std(axis=(0, 1, 2)), 1, atol=1e-3)
    # reference quirk: eval also uses batch stats
    oute = np.asarray(layer.apply(params, [jnp.asarray(x)], ctx_eval())[0])
    assert np.allclose(oute.mean(axis=(0, 1, 2)), 0, atol=1e-4)


def test_batch_norm_fc_mode(rng):
    layer = make_layer("batch_norm", [])
    layer.infer_shapes([(1, 1, 16)])
    assert layer.channel == 16


# ---------------------------------------------------------------- structural
def test_flatten_concat_split(rng):
    x = rng.randn(2, 3, 4, 5).astype(np.float32)   # NHWC: (b,y=3? ...)
    flat = make_layer("flatten", [])
    flat.infer_shapes([(5, 3, 4)])
    out = flat.apply({}, [jnp.asarray(x)], ctx_eval())[0]
    assert out.shape == (2, 1, 1, 60)

    sp = make_layer("split", [], outputs=(1, 2))
    assert sp.infer_shapes([(5, 3, 4)]) == [(5, 3, 4)] * 2

    cc = make_layer("concat", [], inputs=(1, 2), outputs=(3,))
    assert cc.infer_shapes([(1, 1, 4), (1, 1, 6)]) == [(1, 1, 10)]
    a = rng.randn(2, 1, 1, 4).astype(np.float32)
    b = rng.randn(2, 1, 1, 6).astype(np.float32)
    out = cc.apply({}, [jnp.asarray(a), jnp.asarray(b)], ctx_eval())[0]
    assert np.allclose(np.asarray(out), np.concatenate([a, b], axis=-1))

    ch = make_layer("ch_concat", [], inputs=(1, 2), outputs=(3,))
    assert ch.infer_shapes([(3, 5, 5), (2, 5, 5)]) == [(5, 5, 5)]


def test_bias_layer(rng):
    layer = make_layer("bias", [("init_bias", "0.5")])
    layer.infer_shapes([(1, 1, 6)])
    params = layer.init_params(jax.random.PRNGKey(0), [(1, 1, 6)])
    x = rng.randn(2, 1, 1, 6).astype(np.float32)
    out = layer.apply(params, [jnp.asarray(x)], ctx_eval())[0]
    assert np.allclose(np.asarray(out), x + 0.5)


# ---------------------------------------------------------------- losses
def test_softmax_loss_grad_is_p_minus_onehot(rng):
    layer = make_layer("softmax", [], inputs=(1,), outputs=(1,))
    layer.infer_shapes([(1, 1, 5)])
    x = rng.randn(4, 1, 1, 5).astype(np.float32)
    labels = {"label": jnp.asarray(rng.randint(0, 5, (4, 1)).astype(np.float32))}

    def loss_fn(xj):
        ctx = ApplyContext(train=True, rng=None, labels=labels, batch_size=4)
        layer.apply({}, [xj], ctx)
        return ctx.losses[0]

    g = np.asarray(jax.grad(loss_fn)(jnp.asarray(x))).reshape(4, 5)
    p = np.exp(x.reshape(4, 5))
    p /= p.sum(axis=1, keepdims=True)
    onehot = np.eye(5)[np.asarray(labels["label"])[:, 0].astype(int)]
    # reference grad: (p - onehot) * grad_scale / batch_size
    np.testing.assert_allclose(g, (p - onehot) / 4.0, rtol=1e-4, atol=1e-6)


def test_l2_loss_grad(rng):
    layer = make_layer("l2_loss", [], inputs=(1,), outputs=(1,))
    layer.infer_shapes([(1, 1, 3)])
    x = rng.randn(4, 1, 1, 3).astype(np.float32)
    y = rng.randn(4, 3).astype(np.float32)
    labels = {"label": jnp.asarray(y)}

    def loss_fn(xj):
        ctx = ApplyContext(train=True, rng=None, labels=labels, batch_size=4)
        layer.apply({}, [xj], ctx)
        return ctx.losses[0]

    g = np.asarray(jax.grad(loss_fn)(jnp.asarray(x))).reshape(4, 3)
    np.testing.assert_allclose(g, (x.reshape(4, 3) - y) / 4.0, rtol=1e-5)


def test_multi_logistic_grad(rng):
    layer = make_layer("multi_logistic", [], inputs=(1,), outputs=(1,))
    layer.infer_shapes([(1, 1, 3)])
    x = rng.randn(4, 1, 1, 3).astype(np.float32)
    y = rng.randint(0, 2, (4, 3)).astype(np.float32)
    labels = {"label": jnp.asarray(y)}

    def loss_fn(xj):
        ctx = ApplyContext(train=True, rng=None, labels=labels, batch_size=4)
        layer.apply({}, [xj], ctx)
        return ctx.losses[0]

    g = np.asarray(jax.grad(loss_fn)(jnp.asarray(x))).reshape(4, 3)
    sig = 1 / (1 + np.exp(-x.reshape(4, 3)))
    np.testing.assert_allclose(g, (sig - y) / 4.0, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("k,s,pad,h", [(11, 4, 0, 227), (7, 2, 3, 224),
                                       (5, 2, 2, 33)])
def test_conv_space_to_depth_matches_direct(rng, monkeypatch, k, s, pad, h):
    """The low-channel strided-conv space-to-depth rewrite is exact."""
    layer = make_layer("conv", [("kernel_size", str(k)), ("stride", str(s)),
                                ("pad", str(pad)), ("nchannel", "16"),
                                ("random_type", "gaussian"),
                                ("init_sigma", "0.1")])
    layer.infer_shapes([(3, h, h)])
    params = layer.init_params(jax.random.PRNGKey(0), [(3, h, h)])
    x = jnp.asarray(rng.randn(2, h, h, 3).astype(np.float32))

    from cxxnet_tpu.layers.conv import ConvLayer
    calls = []
    real = ConvLayer.__dict__["_space_to_depth_conv"].__func__
    monkeypatch.setattr(
        ConvLayer, "_space_to_depth_conv",
        staticmethod(lambda *a: (calls.append(1), real(*a))[1]))
    monkeypatch.setenv("CXN_S2D", "1")
    out_s2d = np.asarray(layer.apply(params, [x], ctx_eval())[0])
    assert calls, "space-to-depth path was not taken (guard regressed?)"
    monkeypatch.delenv("CXN_S2D", raising=False)
    out_dir = np.asarray(layer.apply(params, [x], ctx_eval())[0])
    assert len(calls) == 1, "direct path unexpectedly used the rewrite"
    assert out_s2d.shape == out_dir.shape
    np.testing.assert_allclose(out_s2d, out_dir, rtol=1e-5, atol=1e-5)


# ------------------------------------------------- backward vs torch oracles

@pytest.mark.parametrize("groups,pad,stride", [(1, 0, 1), (2, 1, 2)])
def test_conv_backward_matches_torch(rng, groups, pad, stride):
    """dX, dW, db against torch autograd (the reference validated its conv
    backprop the same way, via pairtest vs caffe/cudnn)."""
    torch = pytest.importorskip("torch")
    cin, cout, k = 4, 6, 3
    layer = make_layer("conv", [("nchannel", str(cout)),
                                ("kernel_size", str(k)), ("pad", str(pad)),
                                ("stride", str(stride)),
                                ("ngroup", str(groups))])
    layer.infer_shapes([(cin, 9, 9)])
    params = layer.init_params(jax.random.PRNGKey(1), [(cin, 9, 9)])
    x = rng.randn(2, cin, 9, 9).astype(np.float32)
    x_nhwc = jnp.asarray(x.transpose(0, 2, 3, 1))

    def f(p, a):
        return layer.apply(p, [a], ctx_eval())[0].astype(jnp.float32).sum()

    (dp, dx) = jax.grad(f, argnums=(0, 1))(params, x_nhwc)

    xt = torch.from_numpy(x).requires_grad_(True)
    wt = torch.from_numpy(
        np.asarray(params["wmat"]).transpose(3, 2, 0, 1)).requires_grad_(True)
    bt = torch.from_numpy(np.asarray(params["bias"])).requires_grad_(True)
    torch.nn.functional.conv2d(xt, wt, bt, stride=stride, padding=pad,
                               groups=groups).sum().backward()
    np.testing.assert_allclose(np.asarray(dx).transpose(0, 3, 1, 2),
                               xt.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dp["wmat"]).transpose(3, 2, 0, 1),
                               wt.grad.numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dp["bias"]), bt.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_max_pooling_backward_matches_torch(rng):
    # 8x8 input: ceil-mode emits a partial edge window (last window starts
    # at row 6, covering one padded row) — the gradient path where the
    # -inf padding could plausibly diverge from torch
    torch = pytest.importorskip("torch")
    layer = make_layer("max_pooling", [("kernel_size", "3"), ("stride", "2")])
    layer.infer_shapes([(4, 8, 8)])
    x = rng.randn(2, 4, 8, 8).astype(np.float32)
    x_nhwc = jnp.asarray(x.transpose(0, 2, 3, 1))

    dx = jax.grad(lambda a: layer.apply({}, [a], ctx_eval())[0]
                  .astype(jnp.float32).sum())(x_nhwc)

    xt = torch.from_numpy(x).requires_grad_(True)
    torch.nn.functional.max_pool2d(xt, 3, stride=2,
                                   ceil_mode=True).sum().backward()
    np.testing.assert_allclose(np.asarray(dx).transpose(0, 3, 1, 2),
                               xt.grad.numpy(), rtol=1e-5, atol=1e-6)


def test_lrn_backward_matches_torch(rng):
    torch = pytest.importorskip("torch")
    n, alpha, beta, knorm = 5, 1e-4, 0.75, 1.0
    layer = make_layer("lrn", [("local_size", str(n)), ("alpha", str(alpha)),
                               ("beta", str(beta)), ("knorm", str(knorm))])
    layer.infer_shapes([(8, 5, 5)])
    x = rng.randn(2, 8, 5, 5).astype(np.float32)
    x_nhwc = jnp.asarray(x.transpose(0, 2, 3, 1))

    dx = jax.grad(lambda a: layer.apply({}, [a], ctx_eval())[0]
                  .astype(jnp.float32).sum())(x_nhwc)

    xt = torch.from_numpy(x).requires_grad_(True)
    torch.nn.functional.local_response_norm(
        xt, n, alpha=alpha, beta=beta, k=knorm).sum().backward()
    np.testing.assert_allclose(np.asarray(dx).transpose(0, 3, 1, 2),
                               xt.grad.numpy(), rtol=1e-4, atol=1e-5)
