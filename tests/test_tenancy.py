"""Multi-tenant SLO enforcement (serve/tenancy.py + wiring): policy
grammar, token-bucket determinism, quota accounting exactness,
priority admission/preemption order, tenant-aware shedding (rungs 3/4),
quota-aware router spill with aggregated retry hints, the `admit`
chaos point, recovery-replay preservation of per-tenant counters, and
the pinned untenanted no-op (serve_tenants unset touches nothing).
"""

import threading
import time

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (AdmissionError, DecodeEngine,
                              InferenceServer, QueueFullError,
                              QuotaExceededError, Request,
                              SamplingParams, ServeRouter, SlotScheduler,
                              TenantRegistry, TokenBucket)
from cxxnet_tpu.serve.resilience import DegradationLadder

# the test_resilience geometry: the jitted serve programs are
# module-level lru caches keyed by config, so reusing it costs no
# extra compiles in a shared pytest process
CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)

TEN = "gold:prio=G;std:prio=S;free:prio=B"


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


@pytest.fixture(scope="module", autouse=True)
def _warm_programs():
    rs = np.random.RandomState(99)
    with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                         prefill_chunk=4) as srv:
        h = srv.submit(_prompt(rs, 6), max_tokens=4)
        assert srv.result(h, timeout=300).status == "ok"


# ----------------------------------------------------------- unit: spec
def test_tenant_spec_grammar():
    reg = TenantRegistry.from_spec(
        "gold:prio=G,blocks=40%,qps=50,burst=8;std:prio=standard,"
        "timeout_ms=250;free:prio=B,queue=4,slots=1,blocks=6")
    gold = reg.policy_for("gold")
    assert gold.priority == "guaranteed" and gold.rank == 0
    assert gold.blocks_frac == 0.4 and gold.block_limit(100) == 40
    assert gold.qps == 50.0 and gold.burst == 8.0
    assert reg.policy_for("std").timeout_ms == 250.0
    free = reg.policy_for("free")
    assert free.priority == "best_effort" and free.rank == 2
    assert free.queue == 4 and free.slots == 1
    assert free.block_limit(100) == 6
    # unknown tenants resolve to the implicit default (standard, no
    # quotas); a spec naming `default` overrides it
    assert reg.resolve("nobody") == "default"
    assert reg.policy_for("nobody").priority == "standard"
    reg2 = TenantRegistry.from_spec("default:prio=B,qps=5")
    assert reg2.policy_for("anything").priority == "best_effort"
    assert sorted(reg.label_names()) == ["default", "free", "gold",
                                         "std"]
    # empty spec = NO registry (the pinned no-op); a registry instance
    # passes through
    assert TenantRegistry.from_spec("") is None
    assert TenantRegistry.from_spec("  ") is None
    assert TenantRegistry.from_spec(reg) is reg


def test_tenant_spec_errors():
    with pytest.raises(ValueError, match="unknown priority"):
        TenantRegistry.from_spec("a:prio=platinum")
    with pytest.raises(ValueError, match="unknown field"):
        TenantRegistry.from_spec("a:qqs=5")
    with pytest.raises(ValueError, match="malformed"):
        TenantRegistry.from_spec("noseparator")
    with pytest.raises(ValueError, match="duplicate"):
        TenantRegistry.from_spec("a:prio=G;a:prio=B")
    with pytest.raises(ValueError, match="percent"):
        TenantRegistry.from_spec("a:blocks=150%")


def test_token_bucket_deterministic_on_fake_clock():
    b = TokenBucket(rate=2.0, burst=2.0)
    # burst drains first, then strict refill arithmetic — every value
    # below is exact on the fake clock
    assert b.take(10.0) == (True, 0.0)
    assert b.take(10.0) == (True, 0.0)
    ok, retry = b.take(10.0)
    assert not ok and retry == pytest.approx(500.0)
    ok, retry = b.take(10.25)               # half a token refilled
    assert not ok and retry == pytest.approx(250.0)
    assert b.take(10.5) == (True, 0.0)      # exactly one token back
    # a clock that does not advance never refills; rate 0 = unlimited
    b2 = TokenBucket(rate=0.0)
    assert all(b2.take(0.0) == (True, 0.0) for _ in range(10))
    # identical call sequences are bit-identical
    x, y = TokenBucket(3.0, 1.0), TokenBucket(3.0, 1.0)
    seq = [0.0, 0.1, 0.5, 0.5, 1.7, 1.8]
    assert [x.take(t) for t in seq] == [y.take(t) for t in seq]


# --------------------------------------------------- untenanted no-op
def test_untenanted_server_is_a_pinned_noop():
    """serve_tenants unset: no registry, no tenant labels in the
    exposition, no accounting, and tokens equal the solo oracle — the
    whole layer is dark."""
    rs = np.random.RandomState(0)
    prompts = [_prompt(rs, n) for n in (5, 9, 3)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8,
                         prefill_chunk=4) as srv:
        assert srv.tenancy is None
        hs = [srv.submit(p, max_tokens=6) for p in prompts]
        for p, h in zip(prompts, hs):
            res = srv.result(h, timeout=300)
            assert res.status == "ok"
            np.testing.assert_array_equal(res.tokens, _ref(p, 6))
        assert all(h.tenant == "" for h in hs)
        text = srv.metrics_text()
        m = srv.metrics()
    assert "tenant=" not in text
    assert "cxn_serve_quota_rejections_total" not in text
    assert "cxn_serve_submitted_total 3" in text     # unlabeled series
    assert m["tenants"] is None
    assert "quota" not in m["requests"]
    assert srv._sched.tenant_slots == {} and srv._sched.tenant_blocks == {}
    assert srv.ladder.max_rung == DegradationLadder.MAX_RUNG


# ------------------------------------------------ accounting exactness
def test_tenant_accounting_exact_and_labels():
    """Per-tenant slot/block charges are applied at admit and returned
    at retire — zero residue after the traffic drains — and the
    request counters/histograms carry tenant= labels."""
    rs = np.random.RandomState(1)
    jobs = [("gold", _prompt(rs, 6), 5), ("free", _prompt(rs, 9), 4),
            ("gold", _prompt(rs, 4), 6), ("", _prompt(rs, 7), 3)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         tenants=TEN) as srv:
        hs = [srv.submit(p, max_tokens=m, tenant=t) for t, p, m in jobs]
        for (t, p, m), h in zip(jobs, hs):
            res = srv.result(h, timeout=300)
            assert res.status == "ok"
            # tenancy must never change WHAT is generated, only when
            np.testing.assert_array_equal(res.tokens, _ref(p, m))
        # the untenanted job resolved to the default policy
        assert hs[3].tenant == "default"
        mx = srv.metrics()
        text = srv.metrics_text()
        # exactness: every charge returned
        for t in ("gold", "free", "std", "default"):
            assert srv._sched.tenant_usage(t) == (0, 0), t
        assert mx["tenants"]["gold"]["requests"]["completed"] == 2
        assert mx["tenants"]["free"]["requests"]["completed"] == 1
        assert mx["tenants"]["default"]["requests"]["completed"] == 1
        assert mx["tenants"]["std"]["requests"]["completed"] == 0
    assert 'cxn_serve_completed_total{tenant="gold"} 2' in text
    assert 'cxn_serve_ttft_seconds_count{tenant="free"} 1' in text
    assert 'cxn_serve_tenant_slots{tenant="gold"} 0' in text


def test_tenant_accounting_returned_on_preempt():
    """A preempted (swapped-out) row returns its tenant's slot/block
    charge to the pot and re-charges at resume — driven through the
    real admit -> prefill -> preempt path on a paged engine."""
    rs = np.random.RandomState(2)
    eng = DecodeEngine(CFG, PARAMS, slots=3, prefill_chunk=4,
                       num_blocks=30)
    reg = TenantRegistry.from_spec(TEN)
    sched = SlotScheduler(eng, tenancy=reg)
    reqs = []
    for tenant, n in (("gold", 6), ("free", 6)):
        req = Request(len(reqs), _prompt(rs, n), SamplingParams(
            max_tokens=8), time.perf_counter(), tenant=tenant)
        sched.admit(req)
        reqs.append(req)
    while sched.prefill_step():
        pass
    gold_slots, gold_blocks = sched.tenant_usage("gold")
    assert gold_slots == 1 and gold_blocks > 0
    assert sched.tenant_usage("free")[0] == 1
    # preemption order is (priority class, age): the best-effort row
    # is the victim even though the gold row is younger by admit order
    assert sched._preempt_one(exclude=reqs[0].slot)
    assert reqs[1].status == "swapped"
    assert sched.tenant_usage("free") == (0, 0)
    assert sched.tenant_usage("gold") == (gold_slots, gold_blocks)
    # resume re-charges exactly what the preempt credited
    assert sched.resume_swapped() == 1
    assert sched.tenant_usage("free")[0] == 1
    sched.cancel_active()
    for t in ("gold", "free"):
        assert sched.tenant_usage(t) == (0, 0), t
    eng.close()


# ------------------------------------------------------------- quotas
def test_rate_limit_quota_typed_with_refill_hint():
    rs = np.random.RandomState(3)
    with InferenceServer(
            CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
            tenants="free:prio=B,qps=0.001,burst=1") as srv:
        h = srv.submit(_prompt(rs, 5), max_tokens=3, tenant="free")
        with pytest.raises(QuotaExceededError) as e:
            srv.submit(_prompt(rs, 5), max_tokens=3, tenant="free")
        assert e.value.kind == "rate" and e.value.tenant == "free"
        assert e.value.retry_after_ms > 0
        # the quota is the TENANT's, not the server's: other tenants
        # sail through
        h2 = srv.submit(_prompt(rs, 5), max_tokens=3, tenant="other")
        assert srv.result(h, timeout=300).status == "ok"
        assert srv.result(h2, timeout=300).status == "ok"
        m = srv.metrics()
        assert m["tenants"]["free"]["requests"]["quota"] == 1
        assert ('cxn_serve_quota_rejections_total{tenant="free",'
                'kind="rate"} 1') in srv.metrics_text()


def test_queue_quota_and_block_quota_typed():
    rs = np.random.RandomState(4)
    with InferenceServer(
            CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
            tenants="free:prio=B,queue=1,blocks=2") as srv:
        # occupy the single slot so later submits stay queued
        holder = srv.submit(_prompt(rs, 4), max_tokens=30,
                            tenant="gold")
        deadline = time.time() + 60
        while holder.status == "queued" and time.time() < deadline:
            time.sleep(0.002)
        q1 = srv.submit(_prompt(rs, 4), max_tokens=2, tenant="free")
        with pytest.raises(QuotaExceededError) as e:
            srv.submit(_prompt(rs, 4), max_tokens=2, tenant="free")
        assert e.value.kind == "queue"
        # a prompt that can NEVER fit the tenant's block quota is
        # rejected at the door, typed — not parked forever
        with pytest.raises(QuotaExceededError) as e2:
            srv.submit(_prompt(rs, 20), max_tokens=2, tenant="free")
        assert e2.value.kind == "blocks"
        assert srv.result(holder, timeout=300).status == "ok"
        assert srv.result(q1, timeout=300).status == "ok"


def test_slot_quota_skipped_without_blocking_peers():
    """A tenant at its slot quota parks ITS queue, not the server's:
    the best-effort tenant's second request must not head-of-line
    block the standard tenant queued behind it."""
    rs = np.random.RandomState(5)
    with InferenceServer(
            CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
            tenants="free:prio=B,slots=1") as srv:
        a1 = srv.submit(_prompt(rs, 4), max_tokens=25, tenant="free")
        a2 = srv.submit(_prompt(rs, 4), max_tokens=4, tenant="free")
        b = srv.submit(_prompt(rs, 4), max_tokens=4, tenant="std")
        ra1 = srv.result(a1, timeout=300)
        ra2 = srv.result(a2, timeout=300)
        rb = srv.result(b, timeout=300)
        assert [r.status for r in (ra1, ra2, rb)] == ["ok"] * 3
        # b was admitted into the second slot while a2 (same tenant as
        # the slot-quota'd a1) waited for a1 to retire
        assert b.first_token_t < a2.first_token_t
        assert srv._sched.tenant_usage("free") == (0, 0)


# ------------------------------------------------- ladder: rungs 3 / 4
def test_ladder_rung4_requires_protected_pressure():
    lad = DegradationLadder(up_hold=1,
                            max_rung=DegradationLadder.EMERGENCY_RUNG)
    be_only = {"guaranteed": 0.0, "standard": 0.0, "best_effort": 1.0}
    for _ in range(6):
        lad.evaluate(1.0, None, class_queue_frac=be_only)
    # a best-effort flood can reach shedding but never the emergency
    assert lad.rung == 3
    assert lad.shed_classes() == ("best_effort", "standard")
    hot_protected = {"guaranteed": 0.7, "standard": 0.3,
                     "best_effort": 0.0}
    lad.evaluate(1.0, None, class_queue_frac=hot_protected)
    assert lad.rung == 4
    assert lad.shed_classes() == ("best_effort", "standard",
                                  "guaranteed")
    assert DegradationLadder.classes_for(2) == ()
    # the emergency rung is HELD only under protected pressure: a
    # lingering best-effort flood (still globally hot) demotes back to
    # rung 3 immediately — guaranteed stops being sheddable the moment
    # the paying tenants' own pressure subsides
    lad.evaluate(1.0, None, class_queue_frac=be_only)
    assert lad.rung == 3
    # the untenanted ladder never grows the extra rung
    lad0 = DegradationLadder(up_hold=1)
    for _ in range(8):
        lad0.evaluate(1.0, None)
    assert lad0.rung == 3


def test_shed_walk_is_inverse_priority():
    """Scripted rung-3 overload: every queued request is deadline-
    doomed, but only best-effort and standard are shed — the
    guaranteed request survives rung 3 and falls only on rung 4."""
    rs = np.random.RandomState(6)
    srv = InferenceServer(CFG, PARAMS, slots=1, queue=16,
                          prefill_chunk=4, tenants=TEN)
    try:
        reqs = {}
        now = time.perf_counter()
        with srv._cond:
            for i, t in enumerate(("free", "gold", "std")):
                req = Request(1000 + i, _prompt(rs, 4), SamplingParams(
                    max_tokens=4, timeout_ms=1000.0), now, tenant=t)
                srv._queue.append(req)
                reqs[t] = req
            srv._ema_req_s = 100.0      # every ETA overruns deadlines
            srv._ladder.rung = 3
            shed3 = srv._shed_queued_locked(time.perf_counter())
        assert {r.tenant for r in shed3} == {"free", "std"}
        assert reqs["gold"].status == "queued"      # protected at rung 3
        assert all(r.retry_after_ms > 0 for r in shed3)
        with srv._cond:
            srv._ladder.rung = 4                    # emergency
            shed4 = srv._shed_queued_locked(time.perf_counter())
        assert [r.tenant for r in shed4] == ["gold"]
        srv._ema_req_s = 0.0
        text = srv.metrics_text()
        assert 'cxn_shed_requests_total{rung="3",tenant="free"} 1' \
            in text
        assert 'cxn_shed_requests_total{rung="4",tenant="gold"} 1' \
            in text
    finally:
        srv.shutdown(drain=False)


def test_door_check_protects_guaranteed_at_rung3():
    rs = np.random.RandomState(7)
    with InferenceServer(CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
                         tenants=TEN) as srv:
        srv._ema_req_s = 100.0          # hopeless ETA for any deadline
        srv._ladder.rung = 3
        # best-effort with a deadline is shed at the door...
        with pytest.raises(QueueFullError) as e:
            srv.submit(_prompt(rs, 4), max_tokens=2, timeout_ms=5.0,
                       tenant="free")
        assert "overload shed" in str(e.value)
        assert e.value.retry_after_ms > 0
        # ...the guaranteed tenant's identical request is ADMITTED
        srv._ladder.rung = 0            # let it actually run
        srv._ema_req_s = 0.0
        h = srv.submit(_prompt(rs, 4), max_tokens=2, timeout_ms=60000.0,
                       tenant="gold")
        assert srv.result(h, timeout=300).status == "ok"


# --------------------------------------------------- chaos: admit point
def test_admit_chaos_point_contained():
    rs = np.random.RandomState(8)
    with InferenceServer(CFG, PARAMS, slots=1, queue=4, prefill_chunk=4,
                         tenants=TEN, chaos="admit@1") as srv:
        with pytest.raises(AdmissionError, match="admit"):
            srv.submit(_prompt(rs, 5), max_tokens=3, tenant="gold")
        # containment: that ONE submit failed; the server serves on
        h = srv.submit(_prompt(rs, 5), max_tokens=3, tenant="gold")
        res = srv.result(h, timeout=300)
        assert res.status == "ok"
        assert srv.health()["state"] == "SERVING"
        m = srv.metrics()
        assert m["resilience"]["faults_injected"]["admit"] == 1
        assert m["resilience"]["restarts"] == 0
        assert m["tenants"]["gold"]["requests"]["rejected"] == 1


# ------------------------------------------------- recovery + failover
def test_recovery_replay_preserves_tenant_accounting():
    """An engine-fatal fault mid-stream: the rebuilt scheduler replays
    the journal through the normal admit path — per-tenant counters
    stay correct, streams stay bit-identical, and every charge is
    returned when the traffic drains."""
    rs = np.random.RandomState(9)
    jobs = [("gold", _prompt(rs, 5), 8), ("free", _prompt(rs, 9), 6),
            ("std", _prompt(rs, 6), 7)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         tenants=TEN, chaos="tick_raise@3") as srv:
        hs = [srv.submit(p, max_tokens=m, tenant=t) for t, p, m in jobs]
        for (t, p, m), h in zip(jobs, hs):
            res = srv.result(h, timeout=300)
            assert res.status == "ok"
            np.testing.assert_array_equal(res.tokens, _ref(p, m))
            assert h.tenant == t        # the label survived the replay
        mx = srv.metrics()
        assert mx["resilience"]["restarts"] == 1
        assert mx["resilience"]["replay_mismatches"] == 0
        for t, _, _ in jobs:
            assert mx["tenants"][t]["requests"]["completed"] == 1
            assert srv._sched.tenant_usage(t) == (0, 0)


def test_router_quota_spill_and_min_retry_hint():
    """A tenant-quota rejection spills to a peer replica (per-replica
    rate state) and, when EVERY replica rejects, the raised error
    carries the MINIMUM retry_after_ms across peers plus the replica
    id — typed QuotaExceededError end to end."""
    rs = np.random.RandomState(10)
    kw = dict(slots=1, queue=4, prefill_chunk=4,
              tenants="free:prio=B,qps=0.001,burst=1")
    with ServeRouter(CFG, PARAMS, replicas=2, **kw) as rt:
        p = _prompt(rs, 5)
        h1 = rt.submit(p, max_tokens=2, tenant="free")
        h2 = rt.submit(p, max_tokens=2, tenant="free")   # spilled
        assert {h1.replica, h2.replica} == {0, 1}
        assert rt.quota_spills >= 1
        # pin DISTINCT refill states so the minimum is unambiguous:
        # replica 0 would hint ~500 s, replica 1 ~100 s — the
        # aggregated error must carry replica 1's (the minimum), not
        # whichever peer answered last
        rt.servers[0].tenancy._buckets["free"].tokens = 0.5
        rt.servers[1].tenancy._buckets["free"].tokens = 0.9
        with pytest.raises(QuotaExceededError) as e:
            rt.submit(p, max_tokens=2, tenant="free")
        assert e.value.tenant == "free" and e.value.kind == "rate"
        assert "replica 1" in str(e.value)
        assert 0.9e5 < e.value.retry_after_ms < 1.1e5
        assert rt.result(h1, timeout=300).status == "ok"
        assert rt.result(h2, timeout=300).status == "ok"
        assert rt.metrics()["quota_spills"] >= 1


# ------------------------------------------------------------- the soak
@pytest.mark.slow
def test_tenant_chaos_soak_guaranteed_isolation():
    """Mixed-tenant traffic with every chaos point armed at low
    probability: every admitted request's stream is bit-identical to
    the oracle, per-tenant accounting drains to zero, and the server
    survives with restarts within budget."""
    rs = np.random.RandomState(11)
    jobs = []
    for i in range(24):
        t = ("gold", "std", "free")[i % 3]
        jobs.append((t, _prompt(rs, 3 + (i * 5) % 13), 4 + i % 7))
    srv = InferenceServer(
        CFG, PARAMS, slots=3, queue=32, prefill_chunk=4, prefix_mb=0.5,
        num_blocks=24, max_restarts=50, watchdog_ms=2000.0,
        tenants="gold:prio=G;std:prio=S;free:prio=B,slots=2",
        chaos="all:0.01,seed:23,hang_ms:400")
    try:
        hs = []
        for t, p, m in jobs:
            while True:
                try:
                    hs.append(srv.submit(p, max_tokens=m, tenant=t))
                    break
                except AdmissionError as e:
                    assert "admit" in str(e)    # injected; retry
        for (t, p, m), h in zip(jobs, hs):
            res = srv.result(h, timeout=600)
            assert res.status == "ok", (t, res.status, res.error)
            np.testing.assert_array_equal(res.tokens, _ref(p, m))
        m_ = srv.metrics()
        assert m_["resilience"]["restarts"] <= 50
        assert m_["resilience"]["replay_mismatches"] == 0
        for t in ("gold", "std", "free"):
            assert m_["tenants"][t]["requests"]["completed"] == 8
            assert srv._sched.tenant_usage(t) == (0, 0)
        eng, pc = srv._engine, srv._prefix
        eng.manager.check_consistency(trie_refs=pc.trie_refs())
    finally:
        srv.shutdown()


# ------------------------------------------------------- CLI: preemption
def test_cli_serve_sigterm_graceful_drain(tmp_path, capfd, monkeypatch):
    """task=serve honors save_on_preempt: SIGTERM mid-stream stops
    admission and DRAINS — the already-submitted request finishes (its
    line is printed) instead of dying mid-token, and the process exits
    0 with the preemption logged."""
    import os
    import signal

    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.models import gpt_lm_config

    corpus = tmp_path / "corpus.bin"
    toks = np.tile(np.arange(16, dtype=np.uint16), 40)
    corpus.write_bytes(toks.tobytes())
    conf = tmp_path / "gpt.conf"
    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=8, dev="cpu:0", eta=0.2)
    conf.write_text("""
data = train
iter = lm
    path_data = "%s"
    token_dtype = uint16
    seq_len = 16
    stride = 8
iter = end
%s
num_round = 1
save_model = 1
model_dir = %s
""" % (corpus, cfg, tmp_path / "models"))
    assert LearnTask().run([str(conf)]) == 0
    model = tmp_path / "models" / "0001.model"
    capfd.readouterr()

    class _Stdin:
        """Two lines, a SIGTERM between them: the handler raises out
        of the read loop before the second line is consumed."""

        def __iter__(self):
            yield "0 1 2 3\n"
            os.kill(os.getpid(), signal.SIGTERM)
            yield "4 5 6 7\n"           # unreachable: handler raised

    monkeypatch.setattr("sys.stdin", _Stdin())
    assert LearnTask().run([
        str(conf), "task=serve", "model_in=%s" % model, "num_gen=4",
        "serve_slots=2", "serve_queue=4", "serve_prefill_chunk=4",
        "serve_tenants=gold:prio=G"]) == 0
    out, err = capfd.readouterr()
    rows = [l for l in out.strip().splitlines()
            if l and l[0].isdigit()]
    assert len(rows) == 1               # the admitted request FINISHED
    assert len(rows[0].split()) == 4 + 4
    assert "graceful preemption" in err
    assert "tenants [default=S, gold=G]" in err
