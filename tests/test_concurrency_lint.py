"""cxn-lint pass 3: the CXN3xx host-concurrency rules (static AST
half) and the CXN_LOCK_WATCH runtime lock-order watchdog
(analysis/concurrency.py, doc/lint.md "Concurrency discipline").

Every rule CXN301-CXN305 gets one positive fixture the analyzer must
flag and one negative twin it must not; the watchdog tests seed a real
two-lock inversion and assert it raises BEFORE a deadlock is possible.
"""

import threading
import time

import pytest

from cxxnet_tpu.analysis import analyze_source
from cxxnet_tpu.analysis.concurrency import (LockOrderError, check,
                                             make_condition, make_lock,
                                             make_rlock, reset_watch,
                                             violations, watch_enabled)
from cxxnet_tpu.analysis.findings import LintReport


def rules(src, **kw):
    """The set of rule ids analyze_source raises on ``src``."""
    report = analyze_source(src, path="fix.py", **kw)
    return {f.rule for f in report.findings}


# ===================================================================
# CXN301: write to a guarded attribute outside `with <guard>:`
# ===================================================================
CXN301_POS = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0             # guarded_by: self._lock
        # guarded_by: self._lock
        self._items = []

    def bump(self):
        self._n += 1            # unguarded RMW

    def push(self, x):
        self._items.append(x)   # unguarded mutator
"""

CXN301_NEG = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0             # guarded_by: self._lock
        # guarded_by: self._lock
        self._items = []

    def bump(self):
        with self._lock:
            self._n += 1

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def _drain_locked(self):
        self._items.clear()     # caller-holds convention: _locked suffix

    def replay(self):
        \"\"\"Caller holds ``_lock`` around the whole replay pass.\"\"\"
        self._n += 1
"""


def test_cxn301_flags_unguarded_writes():
    report = analyze_source(CXN301_POS, path="fix.py")
    hits = [f for f in report.findings if f.rule == "CXN301"]
    assert len(hits) == 2
    assert {f.line for f in hits} == {12, 15}
    assert all(f.path == "fix.py" for f in hits)


def test_cxn301_quiet_under_lock_and_caller_holds():
    assert "CXN301" not in rules(CXN301_NEG)


def test_cxn301_annotation_does_not_bleed_to_next_line():
    # a trailing guarded_by on line N must not annotate line N+1's
    # attribute (regression: a real sweep briefly flagged the neighbor
    # of an annotated field) — only a comment-ONLY line above carries
    src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._a = 0             # guarded_by: self._lock
        self._b = 0

    def bump(self):
        self._b += 1
"""
    assert "CXN301" not in rules(src)


# ===================================================================
# CXN302: lock-acquisition-order cycle
# ===================================================================
CXN302_POS = """
import threading

_a = threading.Lock()
_b = threading.Lock()

def f():
    with _a:
        with _b:
            pass

def g():
    with _b:
        with _a:
            pass
"""

CXN302_NEG = """
import threading

_a = threading.Lock()
_b = threading.Lock()

def f():
    with _a:
        with _b:
            pass

def g():
    with _a:
        with _b:
            pass
"""


def test_cxn302_flags_inverted_nesting():
    assert "CXN302" in rules(CXN302_POS)


def test_cxn302_quiet_on_consistent_order():
    assert "CXN302" not in rules(CXN302_NEG)


# ===================================================================
# CXN303: blocking call while holding a lock
# ===================================================================
CXN303_POS = """
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = None

    def slow(self):
        with self._lock:
            time.sleep(0.5)

    def drain(self):
        with self._lock:
            item = self._q.get()
        return item
"""

CXN303_NEG = """
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = None

    def slow(self):
        time.sleep(0.5)
        with self._lock:
            pass

    def drain(self):
        with self._lock:
            item = self._q.get(timeout=1.0)
        return item
"""


def test_cxn303_flags_blocking_under_lock():
    report = analyze_source(CXN303_POS, path="fix.py")
    hits = [f for f in report.findings if f.rule == "CXN303"]
    assert len(hits) == 2           # the sleep and the untimed get


def test_cxn303_quiet_outside_lock_or_timed():
    assert "CXN303" not in rules(CXN303_NEG)


def test_cxn303_condition_wait_on_held_lock_is_exempt():
    # Condition.wait RELEASES its own lock while parked — waiting on
    # the condition you hold is the one "blocking" call that is fine
    src = """
import threading

class C:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def park(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()
"""
    assert "CXN303" not in rules(src)


# ===================================================================
# CXN304: threading.Thread without daemon= or a tracked join
# ===================================================================
CXN304_POS = """
import threading

def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
"""

CXN304_NEG = """
import threading

def spawn(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t

class Pool:
    def start(self, fn):
        self._t = threading.Thread(target=fn)
        self._t.start()

    def close(self):
        self._t.join()
"""


def test_cxn304_flags_untracked_thread():
    assert "CXN304" in rules(CXN304_POS)


def test_cxn304_quiet_with_daemon_or_join():
    assert "CXN304" not in rules(CXN304_NEG)


# ===================================================================
# CXN305: untimed Condition.wait outside a predicate while loop
# ===================================================================
CXN305_POS = """
import threading

class C:
    def __init__(self):
        self._cv = threading.Condition()

    def park(self):
        with self._cv:
            self._cv.wait()
"""

CXN305_NEG = """
import threading

class C:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def park(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()

    def poll(self):
        with self._cv:
            self._cv.wait(0.1)      # timed: a poll by construction
"""


def test_cxn305_flags_bare_wait():
    assert "CXN305" in rules(CXN305_POS)


def test_cxn305_quiet_in_while_or_timed():
    assert "CXN305" not in rules(CXN305_NEG)


# ===================================================================
# Suppression: per-line disable + lint_ignore plumbing
# ===================================================================
def test_inline_disable_suppresses_one_line():
    src = CXN305_POS.replace("self._cv.wait()",
                             "self._cv.wait()  # cxn-lint: disable=CXN305")
    assert "CXN305" not in rules(src)


def test_inline_disable_is_rule_scoped():
    # disabling a DIFFERENT rule on the line must not silence CXN305
    src = CXN305_POS.replace("self._cv.wait()",
                             "self._cv.wait()  # cxn-lint: disable=CXN301")
    assert "CXN305" in rules(src)


def test_lint_ignore_suppresses_family_rule():
    report = LintReport(suppress=frozenset({"CXN301"}))
    analyze_source(CXN301_POS, path="fix.py", report=report)
    assert not [f for f in report.findings if f.rule == "CXN301"]
    assert report.n_suppressed >= 2


# ===================================================================
# Runtime half: the lock-order watchdog
# ===================================================================
@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("CXN_LOCK_WATCH", "1")
    reset_watch()
    yield
    reset_watch()


def test_factories_plain_when_unarmed(monkeypatch):
    monkeypatch.delenv("CXN_LOCK_WATCH", raising=False)
    assert not watch_enabled()
    # the unwatched path hands back raw primitives: zero serving-path
    # overhead unless the env var arms the watchdog
    assert type(make_lock("x")) is type(threading.Lock())
    assert isinstance(make_condition("x"), threading.Condition)


def test_watchdog_detects_seeded_inversion(armed):
    a = make_lock("fix.A")
    b = make_lock("fix.B")
    with a:
        with b:                     # observe A -> B
            pass
    with pytest.raises(LockOrderError):
        with b:
            with a:                 # the inversion: B then A
                pass
    assert any("inversion" in v for v in violations())
    with pytest.raises(LockOrderError):
        check()


def test_watchdog_consistent_order_stays_silent(armed):
    a = make_lock("fix.C")
    b = make_lock("fix.D")
    for _ in range(3):
        with a:
            with b:
                pass
    assert violations() == []
    check()                         # must not raise


def test_watchdog_rlock_reentrance_is_not_a_cycle(armed):
    r = make_rlock("fix.R")
    with r:
        with r:                     # depth bump, never a self-edge
            pass
    assert violations() == []


def test_watchdog_condition_wait_releases_held_record(armed):
    # while parked in cv.wait() the thread does NOT hold the lock —
    # another thread taking an "inverted" lock order against the
    # parked thread's condition must stay silent
    cv = make_condition("fix.CV")
    lk = make_lock("fix.L")
    with cv:
        with lk:                    # observe CV -> L
            pass
    woke = []

    def waker():
        time.sleep(0.05)
        with lk:                    # L with CV *parked*: no inversion
            pass
        with cv:
            woke.append(True)
            cv.notify_all()

    t = threading.Thread(target=waker, daemon=True)
    t.start()
    with cv:
        while not woke:
            cv.wait(timeout=2.0)
    t.join(timeout=5)
    assert woke and violations() == []


def test_watchdog_hold_budget_records_without_raising(monkeypatch):
    monkeypatch.setenv("CXN_LOCK_WATCH", "1")
    monkeypatch.setenv("CXN_LOCK_HOLD_MS", "1")
    reset_watch()
    try:
        lk = make_lock("fix.H")     # budget read at creation
        with lk:
            time.sleep(0.02)        # breach the 1 ms budget, no raise
        assert any("budget" in v for v in violations())
    finally:
        reset_watch()


def test_watchdog_survives_respawned_instances(armed):
    # the graph keys on the creation-site NAME: a respawned worker's
    # fresh lock objects inherit the fleet's observed ordering
    with make_lock("fix.S1"):
        with make_lock("fix.S2"):
            pass
    with pytest.raises(LockOrderError):
        with make_lock("fix.S2"):
            with make_lock("fix.S1"):
                pass


# ===================================================================
# The swept tree itself
# ===================================================================
def test_package_is_clean():
    from cxxnet_tpu.analysis import lint_threads
    report = lint_threads(report=LintReport())
    assert report.ok(), report.format()
