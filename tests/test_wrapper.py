"""Wrapper API parity tests (reference wrapper/cxxnet.py:64-307 semantics)
plus the C ABI smoke test (native/capi_test.c) when a toolchain is present.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu import wrapper

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NET_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 32
eta = 0.2
momentum = 0.9
dev = cpu:0
"""


def _xy(seed, n=32):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 2, n)
    x = (2.0 * y[:, None] - 1.0) + rs.randn(n, 8) * 0.5
    return x.astype(np.float32), y.astype(np.float32)


def test_net_numpy_update_predict_weights(tmp_path):
    net = wrapper.Net(cfg=NET_CFG)
    net.init_model()
    for i in range(30):
        x, y = _xy(i)
        net.update(x, y)          # 2-D numpy auto-reshaped to (b,1,1,feat)
    x, y = _xy(999)
    pred = net.predict(x)
    assert (pred == y).mean() > 0.9

    w = net.get_weight("fc1", "wmat")
    assert w.shape == (32, 8)
    net.set_weight(np.zeros_like(w), "fc1", "wmat")
    assert np.all(net.get_weight("fc1", "wmat") == 0)

    # save/load round-trip through the wrapper facade
    p = str(tmp_path / "m.model")
    net.save_model(p)
    net2 = wrapper.Net(cfg=NET_CFG)
    net2.load_model(p)
    assert np.all(net2.get_weight("fc1", "wmat") == 0)


def test_train_loop_with_mnist_iter(tmp_path, synth_mnist=None):
    # synthetic idx.gz files via the e2e helpers
    sys.path.insert(0, os.path.join(ROOT, "tests"))
    from test_train_e2e import write_idx_images, write_idx_labels
    rs = np.random.RandomState(0)
    protos = rs.rand(4, 64) * 255
    lab = rs.randint(0, 4, 256)
    img = np.clip(protos[lab] + rs.randn(256, 64) * 10, 0, 255)
    write_idx_images(str(tmp_path / "img.gz"),
                     img.astype(np.uint8).reshape(-1, 8, 8))
    write_idx_labels(str(tmp_path / "lab.gz"), lab.astype(np.uint8))

    it_cfg = """
iter = mnist
    path_img = "%s"
    path_label = "%s"
    shuffle = 1
iter = end
batch_size = 32
input_flat = 1
""" % (tmp_path / "img.gz", tmp_path / "lab.gz")
    data = wrapper.DataIter(it_cfg)
    assert data.next()
    assert data.get_data().shape == (32, 1, 1, 64)
    assert data.get_label().shape[0] == 32

    net_cfg = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1] = sigmoid
layer[+1:fc2] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 32
dev = cpu:0
metric = error
"""
    net = wrapper.train(net_cfg, data, 6, {"eta": 0.25, "momentum": 0.9},
                        eval_data=data)
    pred = net.predict(data)
    assert pred.shape[0] == 256
    feats = net.extract(data, "top[-2]")
    assert feats.shape[0] == 256


@pytest.mark.skipif(shutil.which("g++") is None or shutil.which("cc") is None,
                    reason="no C toolchain")
def test_c_abi_end_to_end():
    native = os.path.join(ROOT, "native")
    r = subprocess.run(["make", "-C", native, "capi_test"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    # the embedded CPython must see THIS interpreter's packages (venv or
    # PYTHONPATH installs) — capi.cpp adopts the environment of the python
    # named by CXN_PYTHON
    env["CXN_PYTHON"] = sys.executable
    r = subprocess.run([os.path.join(native, "capi_test"), ROOT],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PASS" in r.stdout
