"""Quantized serving end-to-end (doc/serving.md "Quantized serving"):
int8 weight streaming through the serve programs + per-block-scaled
int8 KV pools.

The load-bearing invariants:

1. **pinned no-op when off** — the default engine/server holds plain
   compute-dtype pools and full-precision weights, byte-for-byte the
   pre-quantization programs (the whole bit-identity corpus of
   test_serve*/test_resilience/test_router keeps pinning that; here we
   pin the structural facts directly);
2. **the stored representation IS the int8 payload** — swap-out /
   checksum / swap-in round-trips bit-exactly, a COW fault copies the
   payload + scales without touching the donor, preempt->swap->resume
   is stream-identical to an undisturbed int8 run;
3. **accuracy under ONE contract** — ``kv_int8_tolerance()`` bounds the
   lockstep greedy divergence and the sampled-mode chi-squared, and
   nothing in this file invents its own ad-hoc tolerance;
4. **fused == gather under quantization** — the Pallas block-table-walk
   kernel's in-VMEM dequant is bit-exact against the XLA gather
   formulation in interpret mode, speculative verify included;
5. **hygiene** — int8 vs bf16 engines count DISTINCT single
   RecompileGuard signatures (the dtype is in the signature string,
   unlike the deliberately flag-free fused/gather bit), the quantized
   step audit keeps full donation aliasing with no silent f32
   promotion of int8 operands (CXN209), and ledger pool predictions
   stay exact under the quantized itemsize.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import DecodeEngine, InferenceServer, auto_num_blocks
from cxxnet_tpu.serve.engine import kv_int8_tolerance

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)
NB = auto_num_blocks(CFG, 2, 4)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _admit(eng, slot, prompt, key, temp=0.0):
    """Drive a paged engine's chunk prefill by hand (reserve + chunk
    windows); returns the first sampled token."""
    tok = None
    for start in range(0, len(prompt), eng.chunk):
        end = min(start + eng.chunk, len(prompt))
        eng.reserve_window(slot, start, start + eng.chunk)
        buf = np.zeros(eng.chunk, np.int32)
        buf[:end - start] = prompt[start:end]
        tok = eng.prefill_chunk(slot, buf, start, end - start, key, temp,
                                0, 1.0)
    return int(tok)


def _tick_one(eng, slot, tok, pos, fold, key=None, temp=0.0):
    """One batched tick advancing only ``slot`` (other rows parked)."""
    b = eng.slots
    t = np.zeros(b, np.int32)
    t[slot] = tok
    p = np.full(b, eng.row_len - 1, np.int32)
    p[slot] = pos
    keys = np.zeros((b, 2), np.uint32)
    if key is not None:
        keys[slot] = key
    f = np.zeros(b, np.int32)
    f[slot] = fold
    nxt = eng.tick(t, p, keys, f, np.full(b, temp, np.float32),
                   np.zeros(b, np.int32), np.ones(b, np.float32))
    return int(nxt[slot])


def _stream(eng, prompt, n, key=None, temp=0.0):
    """Greedy (or sampled) single-request stream through a paged
    engine: chunked admit + ticks, reserving every window."""
    key = np.zeros((2,), np.uint32) if key is None else key
    toks = [_admit(eng, 0, prompt, key, temp)]
    pos = len(prompt)
    for i in range(1, n):
        eng.reserve_window(0, pos, pos + 1)
        toks.append(_tick_one(eng, 0, toks[-1], pos, i, key, temp))
        pos += 1
    return toks


# --------------------------------------------------- pinned no-op (off)
def test_defaults_are_pinned_noop():
    """With the knobs unset the engine holds PLAIN compute-dtype pools
    (no (values, scales) pairs), full-precision weights, and the same
    block geometry as before the quantized round — the structural half
    of the no-op pin (the token-identity half is every pre-existing
    serve suite, which runs against exactly these defaults)."""
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB)
    assert not isinstance(eng.cache_k, tuple)
    assert not eng.kv_int8 and not eng.int8_weights
    assert eng.kv_dtype == "f32"
    assert "s_qkv" not in eng._blocks
    from cxxnet_tpu.serve.engine import _paged_geometry
    assert eng.block_bytes() == _paged_geometry(CFG, 4, 0)[4]
    assert eng._sig_suffix == ""
    with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                         prefill_chunk=4) as srv:
        m = srv.metrics()
    assert m["paged"]["kv_dtype"] == "f32"
    assert m["int8_weights"] is False


def test_kv_dtype_validation():
    with pytest.raises(ValueError, match="serve_kv_dtype"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                     kv_dtype="int4")
    # int8 KV is paged-only: the dense slot pool keeps the compute dtype
    with pytest.raises(ValueError, match="paged"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, kv_dtype="int8")
    # an explicit full-precision name must MATCH the compute dtype
    with pytest.raises(ValueError, match="COMPUTE"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                     kv_dtype="bf16")     # CFG is f32
    # matching spellings are accepted as the no-op they are
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                       kv_dtype="f32")
    assert not eng.kv_int8


def test_kv_int8_rejects_tp():
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 local devices for a model-axis mesh")
    from cxxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(devices=jax.devices()[:2], model_parallel=2)
    with pytest.raises(ValueError, match="serve_tp"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                     kv_dtype="int8", mesh=mesh)


# ------------------------------------------------- accuracy contract
def test_kv_int8_greedy_divergence_bounded():
    """Lockstep teacher-forced divergence: both engines fed the SAME
    context each step (the full-precision engine's greedy token), the
    fraction of steps where the int8-KV engine's argmax differs is
    bounded by the ONE contract, kv_int8_tolerance()['greedy_flip'].
    A plumbing bug (wrong scale axis, swapped K/V, garbage block read)
    flips essentially every step on this near-uniform tiny model."""
    rs = np.random.RandomState(1)
    prompt = _prompt(rs, 10)
    ref = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=4, num_blocks=NB)
    q = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=4, num_blocks=NB,
                     kv_dtype="int8")
    key = np.zeros((2,), np.uint32)
    t_ref = _admit(ref, 0, prompt, key)
    t_q = _admit(q, 0, prompt, key)
    steps = 24
    flips = int(t_ref != t_q)
    tok, pos = t_ref, len(prompt)
    for i in range(1, steps):
        ref.reserve_window(0, pos, pos + 1)
        q.reserve_window(0, pos, pos + 1)
        nxt_ref = _tick_one(ref, 0, tok, pos, i)
        nxt_q = _tick_one(q, 0, tok, pos, i)      # SAME forced context
        flips += int(nxt_ref != nxt_q)
        tok, pos = nxt_ref, pos + 1
    budget = kv_int8_tolerance()["greedy_flip"]
    assert flips / steps <= budget, (flips, steps, budget)


def _chi2_crit(df, z=3.09):
    """Wilson-Hilferty upper-tail chi-squared quantile (z=3.09 ~ the
    contract's chi2_sig=1e-3)."""
    return df * (1 - 2 / (9 * df) + z * (2 / (9 * df)) ** 0.5) ** 3


def test_kv_int8_sampled_chi_squared():
    """Sampled mode under int8 KV follows (statistically) the same
    first-token distribution as the full-precision engine at this
    sample size — the quantization perturbs logits by ~1%, far inside
    the two-sample chi-squared resolution, while a broken key schedule
    or scale application shifts whole modes and fails hard. Draws are
    repeated TICKS at a fixed position with varied request keys (each
    tick rewrites the same K/V deterministically, so only the sampling
    key varies)."""
    rs = np.random.RandomState(2)
    prompt = _prompt(rs, 9)
    n = 600
    counts = {}
    for kv in ("", "int8"):
        eng = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=4,
                           num_blocks=NB, kv_dtype=kv)
        _admit(eng, 0, prompt, np.zeros((2,), np.uint32))
        pos = len(prompt)
        eng.reserve_window(0, pos, pos + 1)
        c = np.zeros(CFG.vocab_size)
        for s in range(n):
            key = np.asarray(jax.random.PRNGKey(s), np.uint32)
            c[_tick_one(eng, 0, int(prompt[-1]), pos, 1, key,
                        temp=1.0)] += 1
        counts[kv] = c
    a, b = counts[""], counts["int8"]
    keep = (a + b) > 0
    stat = float((((a - b) ** 2)[keep] / (a + b)[keep]).sum())
    df = int(keep.sum()) - 1
    assert df >= 2
    assert stat < _chi2_crit(df), (stat, df, a, b)


# ------------------------------------------- stored-representation bits
def test_swap_roundtrip_bit_exact_and_checksummed():
    """Swap-out -> crc32 -> swap-in of an int8 row is bit-exact: the
    record carries the STORED representation (payload + scale planes),
    so re-swapping the resumed row reproduces the identical buffers and
    checksum; flipping one payload byte trips the typed corruption
    error BEFORE any allocation."""
    from cxxnet_tpu.serve.resilience import SwapCorruptionError
    rs = np.random.RandomState(3)
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                       kv_dtype="int8")
    _admit(eng, 0, _prompt(rs, 11), np.zeros((2,), np.uint32))
    rec = eng.swap_out_row(0)
    assert {"k", "ks", "v", "vs", "n", "nbytes", "crc"} <= set(rec)
    assert rec["k"].dtype == np.int8 and rec["v"].dtype == np.int8
    eng.swap_in_row(0, rec)
    rec2 = eng.swap_out_row(0)
    np.testing.assert_array_equal(rec["k"], rec2["k"])
    np.testing.assert_array_equal(rec["ks"], rec2["ks"])
    np.testing.assert_array_equal(rec["v"], rec2["v"])
    np.testing.assert_array_equal(rec["vs"], rec2["vs"])
    assert rec["crc"] == rec2["crc"]
    rec2["k"].view(np.uint8).flat[3] ^= 0xFF
    free_before = eng.manager.free_count
    with pytest.raises(SwapCorruptionError):
        eng.swap_in_row(0, rec2)
    assert eng.manager.free_count == free_before


def test_cow_fault_leaves_int8_donor_bit_unchanged():
    """A write into a shared int8 block faults a private copy; the
    donor block's stored payload AND scale plane are bit-untouched
    (the COW copy moves the stored representation, engine
    _copy_block_fn)."""
    rs = np.random.RandomState(4)
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                       kv_dtype="int8")
    prompt = _prompt(rs, 8)     # exactly 2 blocks at bs=4
    _admit(eng, 0, prompt, np.zeros((2,), np.uint32))
    donor_ids = eng.row_block_ids(0, 0, 2)
    # slot 1 shares both blocks (a prefix hit), then writes into them
    eng.attach_shared(1, donor_ids)
    kq, ks = eng.cache_k
    before_q = np.asarray(kq[:, donor_ids])
    before_s = np.asarray(ks[:, donor_ids])
    eng.reserve_window(1, 4, 12)        # COW-faults block 1, grows
    buf = np.zeros(eng.chunk, np.int32)
    buf[:] = _prompt(rs, 4)
    eng.prefill_chunk(1, buf, 4, 4, np.zeros((2,), np.uint32), 0.0,
                      0, 1.0)
    assert eng.manager.cow_faults >= 1
    kq2, ks2 = eng.cache_k
    np.testing.assert_array_equal(np.asarray(kq2[:, donor_ids]), before_q)
    np.testing.assert_array_equal(np.asarray(ks2[:, donor_ids]), before_s)


def test_preempt_swap_resume_identity_int8():
    """A pool several times smaller than the working set (forcing
    preempt -> swap -> resume) serves the same int8 token streams as a
    roomy pool — resume restores the stored int8 representation, never
    requantizes."""
    rs = np.random.RandomState(6)
    cases = [(_prompt(rs, 21), 8, 0.0, 0),
             (_prompt(rs, 19), 8, 0.9, 7),
             (_prompt(rs, 17), 8, 0.0, 0)]
    outs = {}
    # 13 blocks = one full row (bpr 12) + the garbage block: two live
    # rows' working sets (8 blocks each) cannot coexist, forcing
    # preempt -> swap -> resume in the tiny arm
    for nb in (NB, 13):
        with InferenceServer(CFG, PARAMS, slots=2, queue=8,
                             prefill_chunk=4, num_blocks=nb,
                             prefix_mb=0.0, kv_dtype="int8") as srv:
            hs = [srv.submit(p, max_tokens=m, temperature=t, seed=s)
                  for p, m, t, s in cases]
            outs[nb] = [srv.result(h, timeout=300) for h in hs]
            m_ = srv.metrics()
        assert all(r.status == "ok" for r in outs[nb])
    assert m_["paged"]["swaps_out"] >= 1       # the tiny pool really swapped
    for a, b in zip(outs[NB], outs[13]):
        np.testing.assert_array_equal(a.tokens, b.tokens)


# ------------------------------------------------ int8 weights + spec
def test_speculative_int8_weights_composes_offline():
    """gpt_decode(speculative=..., int8_weights=True) — the explicit
    rejection is gone — and its greedy stream is bit-identical to the
    SAME engine configuration decoded tick-by-tick (the verify logits
    ARE the int8 tick's logits, quantized weights included)."""
    rs = np.random.RandomState(3)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base, base])     # n-gram bait
    spec = {"mode": "ngram", "spec_len": 3, "stats": {}}
    out = np.asarray(gpt_decode(
        PARAMS, jnp.asarray(prompt)[None], 8, CFG, speculative=spec,
        int8_weights=True))[0]
    assert spec["stats"]["forwards"] >= 1
    eng = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=0,
                       int8_weights=True)
    key = np.zeros((2,), np.uint32)
    toks = [eng.prefill(0, prompt, key, 0.0, 0, 1.0)]
    pos = len(prompt)
    for i in range(1, 8):
        toks.append(_tick_one(eng, 0, toks[-1], pos, i))
        pos += 1
    assert list(out[len(prompt):]) == toks


def test_int8_weights_serving_identity_vs_own_oracle():
    """An int8-weights SERVER (paged, chunked, prefix cache on) is
    stream-identical to the offline speculative-int8 decode of the same
    request — the weight quantization is one engine-build-time
    transform, not a per-program reinterpretation."""
    rs = np.random.RandomState(8)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base])
    ref = np.asarray(gpt_decode(
        PARAMS, jnp.asarray(prompt)[None], 6, CFG, speculative=2,
        int8_weights=True))[0]
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         spec_mode="ngram", spec_len=2,
                         int8_weights=True) as srv:
        res = srv.result(srv.submit(prompt, max_tokens=6), timeout=300)
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, ref)


# ---------------------------------------------------- fused == gather
def test_fused_interpret_bit_identity_int8():
    """The Pallas block-table-walk kernel with scale operands is
    bit-exact against the XLA gather formulation in interpret mode —
    tick AND speculative verify — under the shared fused contract
    (exact on CPU/interpret; assert_fused_allclose's accelerator band
    would apply on a real TPU)."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    rs = np.random.RandomState(9)
    prompt = _prompt(rs, 10)
    old = pk._INTERPRET
    pk._INTERPRET = True
    try:
        streams = {}
        for fused in (True, False):
            eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4,
                               num_blocks=NB, kv_dtype="int8",
                               spec_len=3, fused_attn=fused)
            assert eng.fused_attn == fused
            toks = _stream(eng, prompt, 6)
            # one verify step rides along: draft the last token thrice
            pos = len(prompt) + 5
            eng.reserve_window(0, pos, pos + 4)
            buf = np.full(4, toks[-1], np.int32)
            n_acc, emit = eng.verify_chunk(
                0, buf, pos, 3, np.zeros((2,), np.uint32), 6, 0.0, 0,
                1.0)
            streams[fused] = (toks, n_acc, emit)
    finally:
        pk._INTERPRET = old
    assert streams[True] == streams[False]


# -------------------------------------------------------- hygiene pins
def test_recompile_signatures_distinct_per_dtype():
    """An int8 and a bf16 engine in one process are DISTINCT single
    signatures: the quantization dtypes ride in the signature string
    (/w=int8, /kv=int8) — unlike the fused/gather flag, which PR 10
    pinned flag-free, a dtype change IS a different abstract signature
    and must count as such. Each engine still holds exactly ONE
    signature across its own traffic."""
    rs = np.random.RandomState(10)
    engines = {
        "plain": DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4,
                              num_blocks=NB, recompile_limit=1),
        "quant": DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4,
                              num_blocks=NB, recompile_limit=1,
                              int8_weights=True, kv_dtype="int8"),
    }
    sigs = {}
    for name, eng in engines.items():
        for n in (5, 9):        # mixed lengths: still one signature
            slot = 0
            eng.release_row(slot)
            _admit(eng, slot, _prompt(rs, n), np.zeros((2,), np.uint32))
        assert len(eng.prefill_signatures) == 1
        sigs[name] = str(eng.prefill_signatures[0])
    assert sigs["plain"] != sigs["quant"]
    assert "/w=int8" in sigs["quant"] and "/kv=int8" in sigs["quant"]
    assert "int8" not in sigs["plain"]


def test_quantized_audit_clean_and_cxn209_detects():
    """The quantized serve programs (bf16 compute) audit with FULL
    donation aliasing and the int8=clean column — no silent f32
    promotion of int8 operands — while a deliberate i8->f32 convert
    trips CXN209."""
    from cxxnet_tpu.analysis import audit_serve_engine
    from cxxnet_tpu.analysis.step_audit import audit_jit
    bcfg = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2,
                     feat=16, n_microbatch=1, dtype="bfloat16")
    bparams = gpt_init(jax.random.PRNGKey(5), bcfg)
    eng = DecodeEngine(bcfg, bparams, 2, prefill_chunk=4, abstract=True,
                       num_blocks=auto_num_blocks(bcfg, 2, 4,
                                                  kv_dtype="int8"),
                       kv_dtype="int8", int8_weights=True, spec_len=3,
                       fused_attn=False)
    report, infos = audit_serve_engine(eng, donate=True)
    assert report.ok(), report.format()
    for info in infos:
        assert info["donated"] == info["aliased"] > 0
        assert info["int8_promotions"] == 0
    # negative control: int8 straight to f32 must be named
    bad = jax.jit(lambda a: a.astype(jnp.float32).sum())
    findings, info = audit_jit(
        bad, (jax.ShapeDtypeStruct((4,), jnp.int8),), "bad",
        check_int8=True)
    assert [f.rule for f in findings] == ["CXN209"]
    assert info["int8_promotions"] == 1


def test_auto_num_blocks_int8_sizes_by_quantized_itemsize():
    """The same serve_kv_mb budget buys ~2x the blocks under int8 (the
    dtype-aware geometry), and the ledger's kv_blocks prediction equals
    the pool's actual stored bytes — payload plus scale planes."""
    # realistic head_dim (64): value bytes dominate the scale overhead,
    # so the same MiB buys ~1.94x the blocks
    wide = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2,
                     feat=128, n_microbatch=1, dtype="bfloat16")
    nb_bf = auto_num_blocks(wide, 2, 4, kv_mb=1.0)
    nb_i8 = auto_num_blocks(wide, 2, 4, kv_mb=1.0, kv_dtype="int8")
    assert nb_i8 >= 1.8 * nb_bf
    # the exact stored-bytes formula, pinned against the live pool
    bcfg = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2,
                     feat=16, n_microbatch=1, dtype="bfloat16")
    bparams = gpt_init(jax.random.PRNGKey(5), bcfg)
    eng = DecodeEngine(bcfg, bparams, 2, prefill_chunk=4,
                       num_blocks=64, kv_dtype="int8")
    hd = bcfg.feat // bcfg.n_head
    expect = 2 * (bcfg.n_layer * 64 * bcfg.n_head * 4 * hd * 1
                  + bcfg.n_layer * 64 * bcfg.n_head * 4 * 2)
    assert eng.cache_bytes() == expect
    assert eng.block_bytes() * 64 == expect


def test_ledger_reconciles_under_int8():
    """cxn_device_bytes{pool=kv_blocks} prediction == the live pool's
    measured bytes under int8 (the formula follows the stored dtype),
    and the int8 pool at equal blocks is under ~60% of the bf16 pool."""
    from cxxnet_tpu.obs.metrics import Registry
    sizes = {}
    for kv in ("", "int8"):
        reg = Registry()
        with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                             prefill_chunk=4, num_blocks=NB,
                             kv_dtype=kv, registry=reg) as srv:
            res = srv.result(srv.submit(np.arange(6, dtype=np.int32),
                                        max_tokens=3), timeout=300)
            assert res.status == "ok"
            led = srv.metrics()["device_bytes"]
            eng = srv._engine
            assert led["pools"]["kv_blocks"] == eng.cache_bytes()
            leaves = []
            for c in (eng.cache_k, eng.cache_v):
                leaves += list(c) if isinstance(c, tuple) else [c]
            measured = sum(x.size * x.dtype.itemsize for x in leaves)
            assert led["pools"]["kv_blocks"] == measured
            sizes[kv] = measured
    assert sizes["int8"] < 0.6 * sizes[""]


# ----------------------------------------------------------- chaos soak
@pytest.mark.slow
def test_chaos_soak_with_quantization_armed():
    """The resilience chaos soak rides with quantization armed: every
    injection point firing at low probability over a mixed int8
    workload, every request completes, the streams stay bit-identical
    to an undisturbed int8 server (greedy replay pins the emitted
    prefix; int8 pools make the regeneration deterministic exactly
    like bf16 ones), and the block refcount audit stays clean."""
    rs = np.random.RandomState(11)
    cases = [dict(p=_prompt(rs, rs.randint(5, 14)),
                  max_tokens=int(rs.randint(4, 8)))
             for _ in range(12)]
    outs = {}
    for chaos in ("", "all:0.02,seed:3,hang_ms:50"):
        with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                             prefill_chunk=4, num_blocks=NB,
                             kv_dtype="int8", int8_weights=True,
                             spec_mode="ngram", spec_len=2,
                             chaos=chaos, max_restarts=50) as srv:
            hs = [srv.submit(c["p"], max_tokens=c["max_tokens"])
                  for c in cases]
            outs[chaos] = [srv.result(h, timeout=600) for h in hs]
            eng = srv._engine
            eng.manager.check_consistency(
                srv._prefix.trie_refs() if srv._prefix is not None else 0)
    for a, b in zip(outs[""], outs["all:0.02,seed:3,hang_ms:50"]):
        assert a.status == "ok" and b.status == "ok"
        np.testing.assert_array_equal(a.tokens, b.tokens)
